#!/bin/bash
# Round-5 follow-up chip chain: work discovered AFTER the r5 evidence
# ladder launched. Waits for the ladder (chip_jobs_r5.sh) to finish before
# touching the one-client tunnel, then runs, in order:
#   1 lm_flash_fixed   re-measure LM flash-vs-dense at T=1024 with the
#                      full-T fix (the ladder's lm_flash rung measured the
#                      pre-fix code, whose "flash" variant silently rode
#                      the dense fallback at t=1023 — both its columns are
#                      the dense path; parallel/tp_step.py fix, commit
#                      69ae479)
#   2 vote_exact       rep-resnet18 with --vote-check exact: the same-code
#                      same-round counterpart to the ladder's fingerprint
#                      row, settling the O(r·d)-vs-exact chip question
#                      without reaching across rounds
#   3 attn_tune_f32    flash block-size grid at T=2048 f32 (the shipped
#                      128x128 default was chosen for lowering safety;
#                      the attn_full rung shows jaxref 1.5x faster fwd —
#                      find whether bigger blocks close it)
#   4 attn_tune_bf16   same grid in bf16 (the LM training dtype; the MXU
#                      fast path changes the balance)
#
# Launch detached:
#   setsid nohup bash tools/chip_jobs_r5b.sh > baselines_out/chip_jobs_r5b.log 2>&1 &
# NEVER edit this file while it runs. Markers: baselines_out/.r5b_<rung>_done
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5b $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5b $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5b $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5b $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5b $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5b $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

tpu_up() {
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

ladder_running() {
  pgrep -f "bash tools/chip_jobs_r5.sh" > /dev/null 2>&1
}

# ---- wait for the main ladder to release the tunnel ----------------------
echo "[r5b $(stamp)] waiting for chip_jobs_r5.sh to finish"
while ladder_running; do
  sleep 60
done
echo "[r5b $(stamp)] ladder gone; proceeding"

ABORT_PASS=0
FAILURES=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5b_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5b $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5b $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    FAILURES=$((FAILURES + 1))
    if ! tpu_up; then
      echo "[r5b $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

all_done() {
  for m in lm_flash_fixed vote_exact attn_tune_f32 attn_tune_bf16; do
    [ -f "baselines_out/.r5b_${m}_done" ] || return 1
  done
  return 0
}

for outer in 1 2 3 4; do
  echo "[r5b $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5b $(stamp)] tunnel never came up this window"; continue; }
  FAILURES=0
  ABORT_PASS=0

  rung lm_flash_fixed "chip evidence: LM flash-vs-dense T=1024 remeasured with the full-T fix" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 \
      --variants lm_cyclic_s1_shared_bf16_flash,lm_cyclic_s1_shared_bf16 \
      --seq-len 1024 --batch-size 4 --remat \
      --out baselines_out/tpu_lm_perf_flash.json

  rung vote_exact "chip evidence: rep-resnet18 with vote_check=exact (same-round fingerprint counterpart)" \
    timeout -k 60 2400 python tools/run_baselines.py --max-steps 12 --protocol scan \
      --only rep-resnet18 --vote-check exact

  rung attn_tune_f32 "chip evidence: flash block-size grid T=2048 f32" \
    timeout -k 60 3600 python tools/tpu_attn_tune.py --seq-len 2048 \
      --dtype float32 --out baselines_out/tpu_attn_tune_f32.json

  rung attn_tune_bf16 "chip evidence: flash block-size grid T=2048 bf16" \
    timeout -k 60 3600 python tools/tpu_attn_tune.py --seq-len 2048 \
      --dtype bfloat16 --out baselines_out/tpu_attn_tune_bf16.json

  if all_done; then
    echo "[r5b $(stamp)] FOLLOW-UP COMPLETE"
    break
  fi
  echo "[r5b $(stamp)] incomplete ($FAILURES rung failures this pass); retrying"
  sleep 120
done
all_done && exit 0 || exit 1
