#!/usr/bin/env python
"""Fleet SLO study: the committed proof that the fleet observatory's
gates are live (ISSUE 19) — a scenario matrix of short REAL runs on
both production loops (coded-DP CNN Trainer, TransformerLM fold loop),
each folded through the ONE obs/fleet implementation:

  *_clean        no faults: every deterministic SLO must hold and the
                 run must burn ZERO error budget
  *_adversary    a live in-budget Byzantine episode: the detection SLO
                 must hold at precision == recall == 1.0 WITH a
                 nonzero adversary denominator (the Draco certificate
                 under fire, not vacuously)
  *_straggler    a sustained drop: the coded route rides through it
                 (zero burn) while the incident stream records the
                 straggle episode
  *_autopilot    adversary + closed-loop autopilot: the remediation is
                 attributed to its triggering incident and the run's
                 MTTR is FINITE (onset→remediation wall-clock joined
                 from the same incidents.jsonl stream)

The committed ``baselines_out/fleet_slo.json`` carries the per-cell
SLO verdicts + the fleet roll-up; ``tools/perf_watch.py`` pins the
verdict bools and zero-burn cells at tolerance 0 (MTTR at time
tolerance) and ``tools/check_artifacts.py`` re-verifies the artifact
jax-free via ``--check`` semantics (stale status schema refused).
Flipped-row control tests in tests/test_cli_tools.py prove every gate
fires both directions.

Usage (CPU, ~4 min):       python tools/fleet_study.py --cpu-mesh 8
Re-verify committed file:  python tools/fleet_study.py --check
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# jax-free imports only at module level: --check must run on a bare
# host (tools/check_artifacts.py re-uses verify_payload)
from draco_tpu.obs import fleet  # noqa: E402

NUM_WORKERS = 8
ADV_WORKER = 2
STRAGGLE_WORKER = 5
ADV_SPEC = f"adversary@5-20:w{ADV_WORKER}"
STRAGGLE_SPEC = f"straggle@10-30:w{STRAGGLE_WORKER}"
# boundary hysteresis tuned to the 64-step cell (same rationale as
# autopilot_study.POLICY); committed verbatim so the run is replayable
POLICY = "readmit_boundaries=6,dial_up_boundaries=3"

# cell -> (loop, scenario kind, extra TrainConfig kw)
CELLS = {
    "cnn_clean": ("cnn", "clean", {}),
    "cnn_adversary": ("cnn", "adversary", {"fault_spec": ADV_SPEC}),
    "cnn_straggler": ("cnn", "straggler",
                      {"fault_spec": STRAGGLE_SPEC}),
    "cnn_autopilot": ("cnn", "autopilot",
                      {"fault_spec": ADV_SPEC, "autopilot": "on",
                       "autopilot_policy": POLICY}),
    "lm_clean": ("lm", "clean", {}),
    "lm_adversary": ("lm", "adversary", {"fault_spec": ADV_SPEC}),
    "lm_straggler": ("lm", "straggler",
                     {"fault_spec": STRAGGLE_SPEC}),
    "lm_autopilot": ("lm", "autopilot",
                     {"fault_spec": ADV_SPEC, "autopilot": "on",
                      "autopilot_policy": POLICY}),
}


def _make_cfg(loop: str, name: str, train_dir: str, args, **kw):
    from draco_tpu.config import TrainConfig

    base = dict(
        approach="cyclic", worker_fail=1, adversary_count=0,
        redundancy="shared", batch_size=4, num_workers=NUM_WORKERS,
        max_steps=args.max_steps, eval_freq=8, train_dir=train_dir,
        log_every=1, steps_per_call=args.steps_per_call,
        step_guard="on", incident_watch="on", err_mode=args.err_mode,
        job_name=name,
    )
    if loop == "cnn":
        base.update(network="FC", dataset="synthetic-mnist", lr=0.012,
                    momentum=0.9)
    else:
        base.update(network="TransformerLM", dataset="synthetic-text",
                    seq_len=16, vocab=32, model_dim=32, model_heads=2,
                    model_layers=1, lr=0.05)
    base.update(kw)
    return TrainConfig(**base)


def run_cell(name: str, args, mesh, ds) -> "tuple[dict, object]":
    """Run one cell on its production loop, fold the run dir through
    obs/fleet, and return (row, RunSummary)."""
    loop, kind, kw = CELLS[name]
    d = tempfile.mkdtemp(prefix=f"fleet_{name}_")
    try:
        cfg = _make_cfg(loop, name, d, args, **kw)
        cfg.validate()
        t0 = time.perf_counter()
        if loop == "cnn":
            from draco_tpu.training.trainer import Trainer

            tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
            try:
                tr.run()
            finally:
                tr.close()
        else:
            from draco_tpu.parallel import make_mesh_2d
            from draco_tpu.parallel.sp_step import train_sp

            train_sp(cfg, make_mesh_2d(cfg.num_workers, 1),
                     quiet=True)
        wall_s = time.perf_counter() - t0

        summary = fleet.fold_run(d, tool="tools/fleet_study.py")
        results = fleet.evaluate_run(summary)
        row = {
            "cell": name, "loop": loop, "kind": kind,
            "run_id": summary.run_id, "job_name": summary.job_name,
            "state": summary.state, "steps": summary.steps_observed,
            "wall_s": round(wall_s, 3),
            "budget_burned": fleet.budget_burned(results),
            "notes": list(summary.notes),
            "slo": results,
        }
        row.update(_cell_verdict(row, kind))
        return row, summary
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _cell_verdict(row: dict, kind: str) -> dict:
    """The cell's acceptance bools — recomputed verbatim by --check on
    the committed artifact, so a hand-edited row cannot stay green."""
    slo = row["slo"]
    problems = []
    if row.get("state") != "done":
        problems.append(f"terminal state {row.get('state')!r}")
    if row.get("run_id") in (None, ""):
        problems.append("no run_id in status.json")
    for name in fleet.DETERMINISTIC_SLOS:
        res = slo.get(name)
        if res and res["verdict"] == "violated":
            problems.append(f"{name} violated: {res['detail']}")
    if row["budget_burned"] != 0.0:
        problems.append(
            f"burned {row['budget_burned']:g} of the deterministic "
            f"error budget")
    det = slo.get("detection_quality") or {}
    if kind in ("adversary", "autopilot"):
        if not det.get("evaluated"):
            problems.append("detection SLO not evaluated under a live "
                            "adversary")
        elif det.get("precision") != 1.0 or det.get("recall") != 1.0:
            problems.append(
                f"detection P/R {det.get('precision')}/"
                f"{det.get('recall')} != 1.0/1.0")
        elif not det.get("adv_total"):
            problems.append("adversary cell saw no adversarial rows "
                            "(vacuous certificate)")
    mttr = slo.get("incident_mttr") or {}
    if kind == "autopilot":
        mttr_s = mttr.get("mttr_s")
        if not mttr.get("evaluated") or mttr.get("verdict") != "ok":
            problems.append(f"incident_mttr not ok: "
                            f"{mttr.get('detail')}")
        elif mttr_s is None or not math.isfinite(mttr_s) \
                or mttr_s < 0:
            problems.append(f"MTTR not finite: {mttr_s!r}")
        elif mttr.get("unattributed"):
            problems.append(f"{mttr['unattributed']} unattributed "
                            f"remediation(s)")
    return {"ok": not problems, "problems": problems}


def verify_payload(payload: dict) -> list:
    """Jax-free re-verification of a committed fleet_slo.json — the
    same gate check_artifacts runs in CI. Returns problem strings
    ([] = good). A stale status schema is REFUSED: the artifact must
    be regenerated when the status contract moves."""
    problems = []
    if payload.get("status_schema") != fleet.STATUS_SCHEMA:
        problems.append(
            f"stale artifact: status_schema "
            f"{payload.get('status_schema')!r} != current "
            f"{fleet.STATUS_SCHEMA} — rerun tools/fleet_study.py")
    if payload.get("fleet_schema") != fleet.FLEET_SCHEMA:
        problems.append(
            f"fleet_schema {payload.get('fleet_schema')!r} != "
            f"{fleet.FLEET_SCHEMA}")
    rows = payload.get("rows") or []
    if len(rows) < 6:
        problems.append(f"only {len(rows)} cells (need >= 6)")
    loops = {r.get("loop") for r in rows}
    if not {"cnn", "lm"} <= loops:
        problems.append(f"cells cover loops {sorted(loops)} — need "
                        f"both production loops")
    for row in rows:
        cell = row.get("cell", "?")
        verdict = _cell_verdict(row, row.get("kind", "clean"))
        if not verdict["ok"]:
            problems.extend(f"{cell}: {p}" for p in verdict["problems"])
        if bool(row.get("ok")) != verdict["ok"]:
            problems.append(
                f"{cell}: committed ok={row.get('ok')} disagrees with "
                f"recomputed {verdict['ok']}")
    if rows and not payload.get("all_ok"):
        problems.append("all_ok is false")
    elif payload.get("all_ok") and any(not r.get("ok") for r in rows):
        problems.append("all_ok=true but some cell is not ok")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", type=str,
                    default=os.path.join("baselines_out",
                                         "fleet_slo.json"))
    ap.add_argument("--max-steps", type=int, default=64)
    ap.add_argument("--steps-per-call", type=int, default=4)
    ap.add_argument("--err-mode", type=str, default="rev_grad")
    ap.add_argument("--cells", type=str, default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--cpu-mesh", type=int, default=0, metavar="N",
                    help="force an N-device virtual CPU mesh")
    ap.add_argument("--check", action="store_true",
                    help="re-verify the committed artifact (jax-free) "
                         "instead of running the matrix")
    args = ap.parse_args(argv)

    if args.check:
        try:
            with open(args.out) as fh:
                payload = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"fleet_study --check: cannot read {args.out}: {e}")
            return 1
        problems = verify_payload(payload)
        for p in problems:
            print(f"fleet_study --check: {p}")
        print(f"fleet_study --check: {args.out} "
              f"{'FAILED' if problems else 'ok'} "
              f"({len(payload.get('rows') or [])} cells)")
        return 1 if problems else 0

    from draco_tpu.cli import maybe_force_cpu_mesh

    if args.cpu_mesh:
        maybe_force_cpu_mesh(args)

    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    cells = [c for c in args.cells.split(",") if c] or list(CELLS)
    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=128)
    mesh = make_mesh(NUM_WORKERS)
    rows, summaries = [], []
    for name in cells:
        row, summary = run_cell(name, args, mesh, ds)
        rows.append(row)
        summaries.append(summary)
        det = row["slo"].get("detection_quality") or {}
        print(f"fleet_study: {name:14s} -> ok={row['ok']} "
              f"burn={row['budget_burned']:g} "
              f"P/R={det.get('precision')}/{det.get('recall')} "
              f"({row['wall_s']}s)", flush=True)
        for p in row["problems"]:
            print(f"fleet_study:   problem: {p}", flush=True)

    payload = {
        "schema": 1,
        "tool": "tools/fleet_study.py",
        "fleet_schema": fleet.FLEET_SCHEMA,
        "status_schema": fleet.STATUS_SCHEMA,
        "num_workers": NUM_WORKERS,
        "max_steps": args.max_steps,
        "steps_per_call": args.steps_per_call,
        "err_mode": args.err_mode,
        "adv_spec": ADV_SPEC,
        "straggle_spec": STRAGGLE_SPEC,
        "policy": POLICY,
        "slo_table": fleet.slo_table(),
        "rows": rows,
        "fleet": fleet.fleet_fold(summaries),
        "all_ok": bool(rows) and all(r["ok"] for r in rows),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"fleet_study: {len(rows)} cells -> {args.out} "
          f"(all_ok={payload['all_ok']})")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
