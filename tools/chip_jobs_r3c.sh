#!/bin/bash
# Round-3 third chip chain: the remat utilization frontier (b256+ needs
# activation rematerialisation — the no-remat simulate path OOMs HBM at
# b256, PERF.md §1a) and the per-layer decode granularity row that the
# r3 outage killed. Runs after chip_jobs_r3b.sh.
set -u
cd "$(dirname "$0")/.."

tools/wait_tpu.sh 40 150 120 || exit 3

FAILURES=0
run() {
  echo "[chip_jobs_r3c] ===== $* ====="
  if ! "$@"; then
    echo "[chip_jobs_r3c] FAILED (continuing): $*"
    FAILURES=$((FAILURES + 1))
  fi
}

run python tools/tpu_sweep.py --remat --batches 128,256,512 \
  --dtypes bfloat16 --out baselines_out/tpu_sweep_remat.json
run python tools/decode_study.py --ns 8 --ss 1 \
  --out baselines_out/decode_study_granularity.json
echo "[chip_jobs_r3c] done ($FAILURES failures)"
exit $((FAILURES > 0 ? 1 : 0))
