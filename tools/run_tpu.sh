#!/usr/bin/env bash
# Canonical training launch — parity with the reference's run_pytorch.sh
# (reference: src/run_pytorch.sh:1-20 — FC/MNIST, per-worker batch 4,
# lr 0.01, momentum 0.9, cyclic code s=2, constant attack, compression on).
# On a pod slice, run via: python tools/tpu_pod.py train --name <pod> -- "$@"
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m draco_tpu.cli \
  --approach cyclic \
  --network FC \
  --dataset MNIST \
  --batch-size 4 \
  --lr 0.01 \
  --momentum 0.9 \
  --num-workers 8 \
  --worker-fail 2 \
  --err-mode constant \
  --eval-freq 50 \
  --train-dir ./train_out/ \
  "$@"
