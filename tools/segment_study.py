#!/usr/bin/env python
"""Segment study: the streaming segmented wire's committed pipeline
evidence (ISSUE 16).

The production chunked regime decodes a segmented wire IN-GRAPH
(coding/cyclic.decode_segments / coding/approx.decode_segments — one
jitted program per step, bounds from obs/numerics.cfg_segment_bounds).
What segmentation BUYS is at the seam the codewords physically cross in a
multi-host deployment: with the row split into S wire segments the
aggregator can decode segment ``j`` while segment ``j+1`` is still in
flight, hiding transfer wall under decode wall. This study measures that
seam with the decode-on-arrival driver (control/engine.SegmentPipeline)
over the sp LM route's REAL coded shape: the TransformerLM parameter
vector is raveled to its flat d, encoded under the production cyclic
(n, s) code, narrowed to the wire dtype (obs/numerics.narrow_wire_rows —
the same buffers the real narrow wire ships), segmented on the committed
bounds, and driven through per-segment host→device transfer + jitted
λ-regularized decode:

  * **pipelined** — decode ``j`` async-dispatches, transfer ``j+1`` rides
    under it, THEN ``j`` drains (decode-on-arrival);
  * **serial** — drain before the next transfer: the no-overlap control;
  * **S=1** — one transfer, one decode: today's wire, the ms/step base.

Each (dtype, S) cell records the median wire+decode ms/step of both
rails, the measured overlap fraction (transfer wall that landed inside a
decode's in-flight window, SegmentPipeline.overlap_us), and the ledger's
per-segment physical bytes (obs/numerics.wire_ledger ``segments`` block —
which must SUM to the per-step ledger, the satellite-3 pin). The winning
pipelined S>1 cell re-runs once under the span tracer + a jax profiler
capture and the two event streams merge onto one clock
(obs/device_attr.merge_timeline, the PR 9 machinery) — the
``merged_timeline`` block records the artifact written into the work dir.

``tools/perf_watch.py`` folds the committed artifact: the overlap
fraction and the ms/step win gate round-over-round; the segment counts
and per-segment bytes are pinned tolerance-0 in BOTH directions.
``--check`` re-verifies a committed artifact jax-free (segment-bytes
sums, bounds algebra, the overlap/win acceptance pins) — wired into
tools/check_artifacts.py.

Usage (CPU, ~2-4 min):
  python tools/segment_study.py
  python tools/segment_study.py --check
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_WORKERS = 8
S_FAULTS = 1
SEGMENTS = (1, 2, 4)
DTYPES = ("f32", "int8")
TRIALS = 5
SEED = 428


def _study_cfg(dtype: str, segments: int, args):
    """The sp-route TrainConfig the cells share: the ONE source of the
    committed bounds, ledger, and decode params (rel_tol, λ)."""
    from draco_tpu.config import TrainConfig

    return TrainConfig(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=N_WORKERS, approach="cyclic", redundancy="shared",
        worker_fail=S_FAULTS, err_mode="rev_grad",
        seq_len=64, vocab=args.vocab, model_dim=args.model_dim,
        model_heads=args.model_heads, model_layers=args.model_layers,
        max_steps=2, eval_freq=0, train_dir="", log_every=10 ** 9,
        wire_dtype=dtype, wire_segments=segments,
    )


def _lm_dim(args) -> int:
    """Flat parameter count of the sp route's TransformerLM at the study
    shape — the d the coded wire actually carries on that route."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from draco_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab=args.vocab, dim=args.model_dim,
                          heads=args.model_heads, layers=args.model_layers,
                          attn_fn=None, dtype=jnp.float32)
    params = model.init({"params": jax.random.key(SEED)},
                        jnp.zeros((1, 8), jnp.int32), train=True)["params"]
    flat, _ = ravel_pytree(params)
    return int(flat.size)


def _build_wire(code, d: int, dtype: str, block: int):
    """Host-side wire payloads: encoded rows (with one live rev_grad-style
    corrupt row, so the per-segment locators have something to locate),
    narrowed to the wire dtype — numpy, so every put() is a REAL copy."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import cyclic as cyclic_mod
    from draco_tpu.obs import numerics as nx

    rs = np.random.RandomState(SEED)
    g = rs.randn(code.n, d).astype(np.float32) * 0.05
    enc_re, enc_im = cyclic_mod.encode_shared(code, jnp.asarray(g))
    adv = jnp.zeros((code.n, 1), bool).at[0, 0].set(True)
    enc_re = jnp.where(adv, -100.0 * enc_re, enc_re)
    enc_im = jnp.where(adv, -100.0 * enc_im, enc_im)
    f = rs.randn(d).astype(np.float32)
    if dtype == "f32":
        return np.asarray(enc_re), np.asarray(enc_im), f
    buf_re = {k: np.asarray(v) for k, v in
              nx.narrow_wire_rows(enc_re, dtype, block).items()}
    buf_im = {k: np.asarray(v) for k, v in
              nx.narrow_wire_rows(enc_im, dtype, block).items()}
    return buf_re, buf_im, f


def _segment_payloads(bounds, wire_re, wire_im, f, dtype, block):
    """Slice the host buffers on the committed bounds — the narrow slices
    go through the same segment-offset entry point the kernels use
    (ops/decode_kernels.wire_slice_pair)."""
    from draco_tpu.ops import decode_kernels

    segs = []
    for a, b in zip(bounds[:-1], bounds[1:]):
        if dtype == "f32":
            segs.append((wire_re[:, a:b], wire_im[:, a:b], f[a:b]))
        else:
            _, sr, si, _ = decode_kernels.wire_slice_pair(
                (dtype, wire_re, wire_im, block), a, b)
            segs.append((sr, si, f[a:b]))
    return segs


def _make_decode(code, dtype, block, rel_tol, lam):
    import jax

    from draco_tpu.coding import cyclic as cyclic_mod
    from draco_tpu.obs import numerics as nx

    kw = {} if rel_tol is None else {"rel_tol": rel_tol}

    @jax.jit
    def dec(pr, pi, f_seg):
        if dtype == "f32":
            er, ei = pr, pi
        else:
            er = nx.widen_wire_rows(pr, dtype, block)
            ei = nx.widen_wire_rows(pi, dtype, block)
        return cyclic_mod.decode(code, er, ei, f_seg, with_health=True,
                                 lam=lam, **kw)

    return dec


def _drive(segs, dec, pipelined: bool, trials: int, tracer=None):
    """Median wall ms + overlap stats over ``trials`` (first run is the
    compile warmup and is discarded)."""
    import jax

    from draco_tpu.control.engine import SegmentPipeline
    from draco_tpu.obs.tracer import NULL_TRACER

    tracer = tracer or NULL_TRACER

    def put(j, seg):
        return jax.device_put(seg)

    def decode(j, dev):
        pr, pi, f_seg = dev
        return dec(pr, pi, f_seg)

    walls, ofracs = [], []
    for t in range(trials + 1):
        pipe = SegmentPipeline(tracer, put, decode, jax.block_until_ready,
                               pipelined=pipelined)
        t0 = time.perf_counter()
        pipe.run(segs)
        wall = time.perf_counter() - t0
        if t == 0:
            continue
        walls.append(wall * 1e3)
        o_us, infl_us = pipe.overlap_us()
        ofracs.append(o_us / infl_us if infl_us > 0 else 0.0)
    return (statistics.median(walls), statistics.median(ofracs))


def run_cell(code, d: int, dtype: str, segments: int, args) -> dict:
    from draco_tpu.obs import numerics as nx

    cfg = _study_cfg(dtype, segments, args)
    block = cfg.shadow_block if dtype == "int8" else 1
    bounds = nx.cfg_segment_bounds(cfg, d)
    ledger = nx.wire_ledger(cfg, d)
    rel_tol, lam = nx.wire_decode_params(cfg)
    wire_re, wire_im, f = _build_wire(code, d, dtype, block)
    segs = _segment_payloads(bounds, wire_re, wire_im, f, dtype, block)
    dec = _make_decode(code, dtype, block, rel_tol, lam)

    pipe_ms, ofrac = _drive(segs, dec, True, args.trials)
    serial_ms, _ = _drive(segs, dec, False, args.trials)

    seg_block = ledger["segments"]
    row = {
        "route": "sp_lm", "family": "cyclic", "dtype": dtype,
        "segments": segments, "d": d,
        "bounds_count": len(bounds) - 1,
        "ms_per_step": round(pipe_ms, 3),
        "ms_per_step_serial": round(serial_ms, 3),
        "overlap_frac": round(ofrac, 4),
        "wire": ledger,
    }
    # structural pins: the effective segment count is what the bounds
    # algebra says (small d collapses S), and the ledger's per-segment
    # physical bytes SUM to the per-step row — satellite 3's honesty pin
    sums_ok = (
        sum(seg_block["physical_bytes_per_worker"])
        == ledger["physical_bytes_per_worker"]
        and sum(seg_block["physical_bytes_per_step"])
        == ledger["physical_bytes_per_step"]
        and seg_block["count"] == len(bounds) - 1
        and seg_block["bounds"] == list(bounds))
    # a pipelined multi-segment run must measure overlap; single-segment
    # and serial rails must measure none (the control that proves the
    # overlap metric live)
    row["ok"] = bool(sums_ok and (ofrac > 0.0 if segments > 1
                                  and len(bounds) > 2 else ofrac == 0.0))
    return row


def capture_timeline(code, d: int, row: dict, args, work_dir: str) -> dict:
    """Re-run the winning pipelined cell once under the span tracer + a
    jax profiler capture; merge both event streams onto one clock
    (obs/device_attr.merge_timeline) into the work dir."""
    import gzip

    from draco_tpu.obs import device_attr, numerics as nx
    from draco_tpu.obs.profiling import ANCHOR_FILE, ProfilerWindow
    from draco_tpu.obs.tracer import make_tracer

    cfg = _study_cfg(row["dtype"], row["segments"], args)
    block = cfg.shadow_block if row["dtype"] == "int8" else 1
    bounds = nx.cfg_segment_bounds(cfg, d)
    rel_tol, lam = nx.wire_decode_params(cfg)
    wire_re, wire_im, f = _build_wire(code, d, row["dtype"], block)
    segs = _segment_payloads(bounds, wire_re, wire_im, f, row["dtype"],
                             block)
    dec = _make_decode(code, row["dtype"], block, rel_tol, lam)
    _drive(segs, dec, True, 1)  # compile outside the capture

    cell_dir = os.path.join(work_dir, "segment_pipeline")
    os.makedirs(cell_dir, exist_ok=True)
    tracer = make_tracer(cell_dir)
    win = ProfilerWindow(cell_dir, (0, 10 ** 9), tracer=tracer)
    win.maybe_start(0, first_step=0)
    try:
        _drive(segs, dec, True, 1, tracer=tracer)
    finally:
        win.stop()
        tracer.close()

    host = device_attr.load_json(os.path.join(cell_dir, "trace.json"))
    host_events = (host or {}).get("traceEvents") or []
    anchor = device_attr.load_json(os.path.join(cell_dir, ANCHOR_FILE))
    cap = device_attr.find_capture(cell_dir)
    dev_events = []
    if cap is not None:
        dev_events, _ = device_attr.load_trace(cap)
    merged = device_attr.merge_timeline(host_events, dev_events, None,
                                        anchor, max_device_events=50_000)
    out_path = os.path.join(cell_dir, "merged_timeline.json.gz")
    with gzip.open(out_path, "wt") as fh:
        json.dump(merged, fh)
    mt = merged["mergedTimeline"]
    seg_spans = sum(1 for e in host_events
                    if str(e.get("name", "")).startswith("segment_"))
    # path relative to the work dir (device_profile.py discipline: the
    # committed artifact must not embed a machine-local temp path)
    rel = os.path.join(os.path.basename(cell_dir.rstrip(os.sep)),
                       os.path.basename(out_path))
    return {"path": rel, "cell": f"{row['dtype']}.s{row['segments']}",
            "anchored": mt["anchored"], "anchor_kind": mt.get("anchor_kind"),
            "host_events": len(host_events), "segment_spans": seg_spans,
            "device_events": sum(1 for e in merged["traceEvents"]
                                 if e.get("cat") == "device")}


# --------------------------------------------------------------------------
# --check: jax-free artifact re-verification (tools/check_artifacts.py)
# --------------------------------------------------------------------------


def check_artifact(path: str) -> int:
    """Re-verify a committed segment_study.json: the per-row segment-bytes
    sums + bounds algebra, the S=1 base rows, the overlap/win acceptance
    pins (ISSUE 16), and the roll-up. Exits nonzero naming the first
    failure."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"segment_study --check: cannot read {path}: {e}")
        return 1
    rows = data.get("rows", [])
    want = {(dt, s) for dt in DTYPES for s in SEGMENTS}
    got = {(r.get("dtype"), r.get("segments")) for r in rows}
    if not want <= got:
        print(f"segment_study --check: missing cells {sorted(want - got)}")
        return 1
    for r in rows:
        cell = f"{r['dtype']}.s{r['segments']}"
        w = r.get("wire") or {}
        seg = w.get("segments") or {}
        bounds = seg.get("bounds") or []
        if seg.get("count") != len(bounds) - 1 or r.get("bounds_count") \
                != seg.get("count"):
            print(f"segment_study --check: {cell}: segment count "
                  f"{seg.get('count')} disagrees with bounds {bounds}")
            return 1
        if bounds[0] != 0 or bounds[-1] != w.get("dim") \
                or any(a >= b for a, b in zip(bounds[:-1], bounds[1:])):
            print(f"segment_study --check: {cell}: bounds not a monotone "
                  f"cover of [0, dim): {bounds}")
            return 1
        if sum(seg.get("physical_bytes_per_worker", [])) \
                != w.get("physical_bytes_per_worker"):
            print(f"segment_study --check: {cell}: per-segment worker "
                  f"bytes do not sum to the per-step ledger row")
            return 1
        if sum(seg.get("physical_bytes_per_step", [])) \
                != w.get("physical_bytes_per_step"):
            print(f"segment_study --check: {cell}: per-segment step bytes "
                  f"do not sum to the per-step ledger row")
            return 1
        if r["segments"] == 1 and r.get("overlap_frac") != 0.0:
            print(f"segment_study --check: {cell}: S=1 row measured "
                  f"nonzero overlap — the no-pipeline base is broken")
            return 1
        if not r.get("ok"):
            print(f"segment_study --check: {cell}: row not ok")
            return 1
    win = data.get("win") or {}
    if not (win.get("segments", 0) > 1 and win.get("overlap_frac", 0.0)
            > 0.0 and win.get("ms_per_step_win", 0.0) > 0.0):
        print(f"segment_study --check: no pipelined S>1 cell beats the "
              f"S=1 base with measured overlap (win={win}) — the ISSUE 16 "
              f"acceptance pin")
        return 1
    mt = data.get("merged_timeline") or {}
    if not mt.get("segment_spans", 0) > 0:
        print("segment_study --check: merged timeline carries no "
              "segment_* spans")
        return 1
    if not data.get("all_ok"):
        print("segment_study --check: all_ok is false")
        return 1
    print(f"segment_study --check: {len(rows)} cells verified ({path})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", type=str,
                    default=os.path.join("baselines_out",
                                         "segment_study.json"))
    ap.add_argument("--trials", type=int, default=TRIALS)
    ap.add_argument("--model-dim", type=int, default=256)
    ap.add_argument("--model-heads", type=int, default=4)
    ap.add_argument("--model-layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--work-dir", type=str, default="",
                    help="dir for the merged-timeline artifact "
                         "(default: a temp dir, printed at exit)")
    ap.add_argument("--check", action="store_true",
                    help="re-verify a committed artifact (jax-free)")
    ap.add_argument("--artifact", type=str, default="",
                    help="artifact path for --check (default --out)")
    args = ap.parse_args(argv)
    if args.check:
        return check_artifact(args.artifact or args.out)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from draco_tpu.coding import cyclic as cyclic_mod

    d = _lm_dim(args)
    code = cyclic_mod.build_cyclic_code(N_WORKERS, S_FAULTS)
    print(f"segment_study: sp LM route d={d} n={N_WORKERS} s={S_FAULTS}",
          flush=True)
    rows = []
    for dtype in DTYPES:
        for s in SEGMENTS:
            row = run_cell(code, d, dtype, s, args)
            rows.append(row)
            print(f"segment_study: {dtype:4s} S={s} -> "
                  f"pipelined={row['ms_per_step']:.1f}ms "
                  f"serial={row['ms_per_step_serial']:.1f}ms "
                  f"overlap={row['overlap_frac']:.3f} ok={row['ok']}",
                  flush=True)

    # the win block perf_watch gates: the best pipelined S>1 cell vs its
    # own dtype's S=1 base
    base = {r["dtype"]: r["ms_per_step"] for r in rows
            if r["segments"] == 1}
    best, best_win = None, 0.0
    for r in rows:
        if r["segments"] <= 1:
            continue
        w = base[r["dtype"]] - r["ms_per_step"]
        if w > best_win:
            best, best_win = r, w
    win = {}
    if best is not None:
        win = {"route": best["route"], "dtype": best["dtype"],
               "segments": best["segments"],
               "ms_per_step": best["ms_per_step"],
               "ms_per_step_s1": base[best["dtype"]],
               "ms_per_step_win": round(best_win, 3),
               "win_frac": round(best_win / base[best["dtype"]], 4),
               "overlap_frac": best["overlap_frac"]}
        print(f"segment_study: win {best['dtype']} S={best['segments']} "
              f"-> -{best_win:.1f}ms/step "
              f"({100 * win['win_frac']:.1f}%)", flush=True)

    work_dir = args.work_dir or tempfile.mkdtemp(prefix="segment_study_")
    merged = {}
    if best is not None:
        merged = capture_timeline(code, d, best, args, work_dir)
        print(f"segment_study: merged timeline -> "
              f"{os.path.join(work_dir, merged['path'])} "
              f"(anchored={merged['anchored']}, "
              f"{merged['segment_spans']} segment spans)", flush=True)

    payload = {
        "schema": 1,
        "tool": "tools/segment_study.py",
        "num_workers": N_WORKERS, "s": S_FAULTS, "d": d,
        "model": {"network": "TransformerLM", "dim": args.model_dim,
                  "heads": args.model_heads, "layers": args.model_layers,
                  "vocab": args.vocab},
        "trials": args.trials,
        "rows": rows,
        "win": win,
        "merged_timeline": merged,
        "all_ok": bool(rows) and all(r["ok"] for r in rows)
        and bool(win) and win["ms_per_step_win"] > 0.0
        and win["overlap_frac"] > 0.0
        and merged.get("segment_spans", 0) > 0,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"segment_study: {len(rows)} cells -> {args.out} "
          f"(all_ok={payload['all_ok']})")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
