#!/usr/bin/env python
"""Offline lowering audit + program-size evidence for scan_layers (round 5).

Every multi-variant attempt at the d≈159M LM point died in the tunnel's
remote-compile service with "Broken pipe" at ~27 min (PERF.md §4) — the
unrolled 12-layer remat program is ~12× the size it needs to be, and the
service ceiling is evidently program-size-shaped. ``scan_layers`` compiles
the layer stack as ONE nn.scan body over stacked weights (identical math:
tests/test_transformer_scan.py), shrinking the XLA program by ~layers×.

This tool proves, without a chip:
  1. the scan_layers variants of the exact lm_big rung shapes lower clean
     for platforms=["tpu"] (methodology: tools/tpu_lm_lowering_check.py,
     which pins the unrolled counterparts);
  2. the serialized StableHLO module is a fraction of the unrolled one —
     the quantity the compile service chokes on. Both sizes are recorded
     per variant so the chip rung's compile-odds argument is numbers-backed.

Configs are IMPORTED from tools/tpu_lm_perf.py (build_lm_variants with
scan_layers=True) and the shapes from tools/tpu_lm_lowering_check.py
(LM_BIG), so the audit lowers the same programs chain r5f times on chip.

  python tools/tpu_lm_scan_lowering_check.py \
      [--out baselines_out/tpu_lm_scan_lowering.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def lower_variant(name, cfg_kw, steps=2):
    """Returns (ok-row dict) with serialized-module byte size."""
    import jax
    import jax.export

    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.mesh import make_folded_wtp_mesh
    from draco_tpu.parallel.tp_step import build_tp_train_setup
    from tools.tpu_lm_perf import make_scan_loop, stage_scan_inputs

    cfg = TrainConfig(**cfg_kw)
    mesh = make_folded_wtp_mesh(cfg.num_workers)
    t0 = time.time()
    try:
        setup = build_tp_train_setup(cfg, mesh)
        xs, ms = stage_scan_inputs(cfg, steps)
        loop = make_scan_loop(setup)
        with mesh:
            exp = jax.export.export(jax.jit(loop), platforms=["tpu"])(
                setup.state, xs, ms)
        n_params = sum(x.size for x in jax.tree.leaves(setup.state.params))
        return {"variant": name, "ok": True, "params": int(n_params),
                "scan_layers": bool(cfg.scan_layers),
                "module_bytes": len(exp.mlir_module_serialized),
                "seconds": round(time.time() - t0, 1)}
    except Exception as e:
        return {"variant": name, "ok": False,
                "scan_layers": bool(cfg_kw.get("scan_layers", False)),
                "seconds": round(time.time() - t0, 1),
                "error": f"{type(e).__name__}: {str(e)[:400]}"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/tpu_lm_scan_lowering.json")
    args = ap.parse_args(argv)

    from tools._lowering_common import run_rows, setup_cpu_host

    setup_cpu_host(1)  # the chip's folded 1-device layout

    from tools.tpu_lm_lowering_check import (
        LM_BIG, LM_BIG_VARIANTS_B1, LM_BIG_VARIANTS_B2,
    )
    from tools.tpu_lm_perf import build_lm_variants

    rows = []
    for scan in (True, False):
        v_b2 = build_lm_variants(batch_size=2, scan_layers=scan, **LM_BIG)
        v_b1 = build_lm_variants(batch_size=1, scan_layers=scan, **LM_BIG)
        tag = "scan" if scan else "unroll"
        rows += [(f"{n}_{tag}", (lambda n=n, v=v_b2: lower_variant(n, v[n])))
                 for n in LM_BIG_VARIANTS_B2]
        rows += [(f"{n}_{tag}", (lambda n=n, v=v_b1: lower_variant(n, v[n])))
                 for n in LM_BIG_VARIANTS_B1]

    report = run_rows(
        args.out,
        "jax.export platforms=['tpu'] on the 1-virtual-device CPU host: "
        "d~159M lm_big rung shapes with scan_layers=True vs unrolled; "
        "module_bytes = serialized StableHLO size (the compile-service "
        "pressure metric). Configs from tools/tpu_lm_perf.py.",
        rows,
    )
    # headline ratio: shared-flash variant, scan vs unroll
    by = {r["variant"] + ("_scan" if r.get("scan_layers") else "_unroll"): r
          for r in report["rows"] if r.get("ok")}
    k = "lm_cyclic_s1_shared_bf16_flash"
    if f"{k}_scan" in by and f"{k}_unroll" in by:
        ratio = by[f"{k}_unroll"]["module_bytes"] / by[f"{k}_scan"]["module_bytes"]
        report["flash_module_shrink_x"] = round(ratio, 2)
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1)
    print(json.dumps({"all_ok": report["all_ok"],
                      "flash_module_shrink_x": report.get(
                          "flash_module_shrink_x")}))
    return 0 if report["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
