#!/usr/bin/env python
"""Offline lowering audit + program-size evidence for scan_layers (round 5).

Every multi-variant attempt at the d≈159M LM point died in the tunnel's
remote-compile service with "Broken pipe" at ~27 min (PERF.md §4) — the
unrolled 12-layer remat program is ~12× the size it needs to be, and the
service ceiling is evidently program-size-shaped. ``scan_layers`` compiles
the layer stack as ONE nn.scan body over stacked weights (identical math:
tests/test_transformer_scan.py), shrinking the XLA program by ~layers×.

This tool proves, without a chip:
  1. the scan_layers variants of the exact lm_big rung shapes lower clean
     for platforms=["tpu"] (methodology: tools/tpu_lm_lowering_check.py,
     which pins the unrolled counterparts);
  2. the serialized StableHLO module is a fraction of the unrolled one —
     the quantity the compile service chokes on. Both sizes are recorded
     per variant so the chip rung's compile-odds argument is numbers-backed;
  3. the PRODUCTION chunked token-loop program (train_token_many, K fused
     steps — parallel/common.py) lowers clean for platforms=["tpu"] AND its
     serialized module stays within ~2× of the eager single-step module:
     the token block and the adversary/straggler schedules enter as scan
     ARGUMENTS, so the 638 MB closed-over-constant regression (PERF.md §4)
     cannot reappear through them.

Configs are IMPORTED from tools/tpu_lm_perf.py (build_lm_variants with
scan_layers=True) and the shapes from tools/tpu_lm_lowering_check.py
(LM_BIG), so the audit lowers the same programs chain r5f times on chip.

  python tools/tpu_lm_scan_lowering_check.py \
      [--out baselines_out/tpu_lm_scan_lowering.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def lower_variant(name, cfg_kw, steps=2):
    """Returns (ok-row dict) with serialized-module byte size."""
    import jax
    import jax.export

    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.mesh import make_folded_wtp_mesh
    from draco_tpu.parallel.tp_step import build_tp_train_setup
    from tools.tpu_lm_perf import make_scan_loop, stage_scan_inputs

    cfg = TrainConfig(**cfg_kw)
    mesh = make_folded_wtp_mesh(cfg.num_workers)
    t0 = time.time()
    try:
        setup = build_tp_train_setup(cfg, mesh)
        xs, ms = stage_scan_inputs(cfg, steps)
        loop = make_scan_loop(setup)
        with mesh:
            exp = jax.export.export(jax.jit(loop), platforms=["tpu"])(
                setup.state, xs, ms)
        n_params = sum(x.size for x in jax.tree.leaves(setup.state.params))
        return {"variant": name, "ok": True, "params": int(n_params),
                "scan_layers": bool(cfg.scan_layers),
                "module_bytes": len(exp.mlir_module_serialized),
                "seconds": round(time.time() - t0, 1)}
    except Exception as e:
        return {"variant": name, "ok": False,
                "scan_layers": bool(cfg_kw.get("scan_layers", False)),
                "seconds": round(time.time() - t0, 1),
                "error": f"{type(e).__name__}: {str(e)[:400]}"}


CHUNK_RATIO_LIMIT = 2.0  # chunked module must stay within ~2x of eager step


def lower_chunked_variant(name, cfg_kw, k=4):
    """Export the eager single-step program AND the K-chunk
    ``train_token_many`` program for platforms=["tpu"]; ok requires both to
    lower clean and the chunked module to stay within CHUNK_RATIO_LIMIT of
    the eager step's serialized size (the closed-over-constant guard)."""
    import jax
    import jax.export
    import numpy as np

    from draco_tpu import rng as drng
    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.mesh import make_folded_wtp_mesh
    from draco_tpu.parallel.sp_step import synthetic_text
    from draco_tpu.parallel.tp_step import build_tp_train_setup

    cfg = TrainConfig(**dict(cfg_kw, steps_per_call=k))
    mesh = make_folded_wtp_mesh(cfg.num_workers)
    t0 = time.time()
    try:
        setup = build_tp_train_setup(cfg, mesh)
        adv = drng.adversary_schedule(cfg.seed, k + 1, cfg.num_workers,
                                      cfg.num_adversaries)
        toks1 = synthetic_text(cfg.seed, 1, cfg.num_workers, cfg.batch_size,
                               cfg.seq_len, cfg.vocab)
        blk = np.stack([
            synthetic_text(cfg.seed, s, cfg.num_workers, cfg.batch_size,
                           cfg.seq_len, cfg.vocab)
            for s in range(1, k + 1)
        ])
        with mesh:
            exp_step = jax.export.export(setup.train_step,
                                         platforms=["tpu"])(
                setup.state, toks1, np.asarray(adv[1]))
            exp_many = jax.export.export(setup.train_token_many,
                                         platforms=["tpu"])(
                setup.state, blk, np.asarray(adv[1 : k + 1]), None)
        step_bytes = len(exp_step.mlir_module_serialized)
        many_bytes = len(exp_many.mlir_module_serialized)
        ratio = many_bytes / max(step_bytes, 1)
        return {"variant": name, "ok": ratio <= CHUNK_RATIO_LIMIT,
                "steps_per_call": k,
                "scan_layers": bool(cfg.scan_layers),
                "eager_step_module_bytes": step_bytes,
                "chunked_module_bytes": many_bytes,
                "chunked_vs_eager_ratio": round(ratio, 3),
                "ratio_limit": CHUNK_RATIO_LIMIT,
                "seconds": round(time.time() - t0, 1)}
    except Exception as e:
        return {"variant": name, "ok": False, "steps_per_call": k,
                "seconds": round(time.time() - t0, 1),
                "error": f"{type(e).__name__}: {str(e)[:400]}"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/tpu_lm_scan_lowering.json")
    args = ap.parse_args(argv)

    from tools._lowering_common import run_rows, setup_cpu_host

    setup_cpu_host(1)  # the chip's folded 1-device layout

    from tools.tpu_lm_lowering_check import (
        LM_BIG, LM_BIG_VARIANTS_B1, LM_BIG_VARIANTS_B2,
    )
    from tools.tpu_lm_perf import build_lm_variants

    rows = []
    for scan in (True, False):
        v_b2 = build_lm_variants(batch_size=2, scan_layers=scan, **LM_BIG)
        v_b1 = build_lm_variants(batch_size=1, scan_layers=scan, **LM_BIG)
        tag = "scan" if scan else "unroll"
        rows += [(f"{n}_{tag}", (lambda n=n, v=v_b2: lower_variant(n, v[n])))
                 for n in LM_BIG_VARIANTS_B2]
        rows += [(f"{n}_{tag}", (lambda n=n, v=v_b1: lower_variant(n, v[n])))
                 for n in LM_BIG_VARIANTS_B1]
    # the production chunked token-loop program at the same rung shapes
    # (scan_layers, the chip layout): K=4 fused steps, token block and
    # schedules as arguments
    v_chunk = build_lm_variants(batch_size=2, scan_layers=True, **LM_BIG)
    rows += [(f"{n}_chunked_k4",
              (lambda n=n: lower_chunked_variant(n, v_chunk[n])))
             for n in ("lm_cyclic_s1_shared_bf16_flash", "lm_geomedian_bf16")]

    report = run_rows(
        args.out,
        "jax.export platforms=['tpu'] on the 1-virtual-device CPU host: "
        "d~159M lm_big rung shapes with scan_layers=True vs unrolled, plus "
        "the production chunked token-loop program (train_token_many, K=4) "
        "vs its eager single step; module_bytes = serialized StableHLO size "
        "(the compile-service pressure metric). Configs from "
        "tools/tpu_lm_perf.py.",
        rows,
    )
    # headline ratio: shared-flash variant, scan vs unroll
    by = {r["variant"] + ("_scan" if r.get("scan_layers") else "_unroll"): r
          for r in report["rows"]
          if r.get("ok") and "chunked_module_bytes" not in r}
    k = "lm_cyclic_s1_shared_bf16_flash"
    if f"{k}_scan" in by and f"{k}_unroll" in by:
        ratio = by[f"{k}_unroll"]["module_bytes"] / by[f"{k}_scan"]["module_bytes"]
        report["flash_module_shrink_x"] = round(ratio, 2)
    # keyed on steps_per_call (present on success AND error rows) so a
    # crashed chunked export can't vanish from the guard's verdict
    chunk_rows = [r for r in report["rows"] if "steps_per_call" in r]
    if chunk_rows:
        report["chunked_within_ratio_limit"] = all(
            r["ok"] for r in chunk_rows
        )
        ratios = [r["chunked_vs_eager_ratio"] for r in chunk_rows
                  if "chunked_vs_eager_ratio" in r]
        if ratios:
            report["chunked_vs_eager_ratio_max"] = max(ratios)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps({"all_ok": report["all_ok"],
                      "flash_module_shrink_x": report.get(
                          "flash_module_shrink_x"),
                      "chunked_vs_eager_ratio_max": report.get(
                          "chunked_vs_eager_ratio_max")}))
    return 0 if report["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
