#!/usr/bin/env python
"""Flagship-config utilization frontier: per-worker batch × dtype sweep.

VERDICT r2 item 4: the round-2 headline led with per-worker batch 32 / f32
(MFU 11.5%) with no evidence of where the flagship config's MFU tops out.
This sweep measures ms/step and MFU for the cyclic (simulate) flagship step —
ResNet-18 / CIFAR-10 shapes, n=8 coded workers, one rev_grad adversary — at
per-worker batch {32, 64, 128, 256} × {float32, bfloat16}, same
fetch-synchronised scanned protocol as bench.py.

The JSON is (re)written after every point, so a mid-run tunnel loss keeps
the completed points.

Usage: python tools/tpu_sweep.py [--batches 32,64,128,256]
       [--dtypes float32,bfloat16] [--remat] [--cpu-mesh 8] [--out PATH]
       (--out defaults to baselines_out/tpu_sweep.json, or
       tpu_sweep_remat.json under --remat so the two frontiers never
       clobber each other)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default=None,
                    help="default baselines_out/tpu_sweep.json, or "
                         "tpu_sweep_remat.json under --remat (so a remat "
                         "sweep never clobbers the no-remat frontier)")
    ap.add_argument("--network", type=str, default="ResNet18")
    ap.add_argument("--num-workers", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batches", type=str, default="32,64,128,256")
    ap.add_argument("--dtypes", type=str, default="float32,bfloat16")
    ap.add_argument("--redundancy", type=str, default="simulate")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialise activations (jax.checkpoint) — the "
                         "memory-for-FLOPs trade that unlocks b256+ (the "
                         "no-remat simulate path OOMs HBM there)")
    ap.add_argument("--cpu-mesh", type=int, default=0)
    args = ap.parse_args(argv)
    if args.out is None:
        args.out = ("baselines_out/tpu_sweep_remat.json" if args.remat
                    else "baselines_out/tpu_sweep.json")

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    import bench
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    ds = load_dataset("Cifar10", data_dir="./data")
    mesh = make_mesh(args.num_workers)
    dev = jax.devices()[0]
    device_kind = getattr(dev, "device_kind", dev.platform)
    peak = bench._peak_flops(device_kind)

    report = {
        "platform": dev.platform,
        "device_kind": device_kind,
        "network": args.network,
        "num_workers": args.num_workers,
        "redundancy": args.redundancy,
        "remat": args.remat,
        "mfu_note": ("mfu includes remat recompute FLOPs (hardware "
                     "utilization)" if args.remat else
                     "mfu is model-useful FLOPs / peak"),
        "steps_per_scan": args.steps,
        "peak_bf16_flops": peak,
        "points": [],
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)

    for dtype in args.dtypes.split(","):
        for bs in [int(b) for b in args.batches.split(",")]:
            kw = dict(
                network=args.network, dataset="Cifar10", batch_size=bs,
                lr=0.01, momentum=0.9, num_workers=args.num_workers,
                worker_fail=1, err_mode="rev_grad",
                approach="cyclic", redundancy=args.redundancy,
                compute_dtype=dtype, remat=args.remat,
                max_steps=args.steps + 1, eval_freq=0, train_dir="",
                log_every=10**9,
            )
            label = f"b{bs}_{dtype}" + ("_remat" if args.remat else "")
            print(f"[tpu_sweep] {label} ...", file=sys.stderr, flush=True)
            t0 = time.time()
            try:
                dt, loss, flops, compile_s = bench.run(kw, ds, mesh,
                                                       args.steps, warmup=1,
                                                       reps=2,
                                                       want_flops=True)
            except Exception as e:
                print(f"[tpu_sweep] {label} FAILED: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
                report["points"].append({"label": label, "batch": bs,
                                         "dtype": dtype,
                                         "error": f"{type(e).__name__}: {e}"[:300]})
                with open(args.out, "w") as fh:
                    json.dump(report, fh, indent=1)
                continue
            # NOTE under --remat the compiled program re-executes the
            # forward inside the backward, so flops (and hence this MFU)
            # include recompute — hardware utilization, not model-useful
            # utilization; the report carries a flag and best_point uses
            # throughput, which is comparable across remat settings
            mfu = (flops / dt / peak) if (flops and peak and dt > 0) else None
            pt = {
                "label": label, "batch": bs, "dtype": dtype,
                "step_ms": round(dt * 1e3, 3),
                "compile_ms": round(compile_s * 1e3, 1),
                "flops_per_step": flops,
                "mfu_vs_bf16_peak": round(mfu, 4) if mfu else None,
                "examples_per_s": round(bs * args.num_workers / dt, 1),
                "measure_s": round(time.time() - t0, 1),
            }
            report["points"].append(pt)
            print(f"[tpu_sweep] {label}: {pt['step_ms']} ms/step, "
                  f"MFU {pt['mfu_vs_bf16_peak']}", file=sys.stderr, flush=True)
            with open(args.out, "w") as fh:
                json.dump(report, fh, indent=1)

    best = max((p for p in report["points"] if p.get("examples_per_s")),
               key=lambda p: p["examples_per_s"], default=None)
    report["best_point"] = best and best["label"]
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
