#!/usr/bin/env python
"""Local multi-process cluster launcher — the ``mpirun -n P`` equivalent.

The reference trains multi-node by launching one MPI rank per host
(reference: src/README.md:10, tools/local_script.sh). The TPU-native
equivalent is one *JAX process* per host sharing a global device mesh via
``jax.distributed``; this script simulates that cluster on one machine:
it spawns N processes, each pinned to K virtual CPU devices, wired to a
shared coordinator — the same code path (gloo collectives over the
process boundary) a real multi-host TPU pod uses over DCN.

Usage:
  python tools/local_cluster.py -n 2 -d 4 -- \
      python -m draco_tpu.cli --approach cyclic --network LeNet \
        --dataset synthetic-mnist --num-workers 8 --worker-fail 1 \
        --max-steps 20 --cpu-mesh 4

Each child gets DRACO_COORDINATOR / DRACO_NUM_PROCESSES / DRACO_PROCESS_ID
(read by draco_tpu.runtime.init_distributed) and an XLA host-device count of
``-d``. Exit code is the first non-zero child exit code.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import tempfile


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def launch(num_processes: int, devices_per_process: int, cmd: list[str],
           env: dict | None = None, prefix_output: bool = True) -> int:
    port = _free_port()
    base = dict(os.environ, **(env or {}))
    base["DRACO_COORDINATOR"] = f"localhost:{port}"
    base["DRACO_NUM_PROCESSES"] = str(num_processes)
    base["XLA_FLAGS"] = (
        base.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_process}"
    ).strip()
    base.setdefault("JAX_PLATFORMS", "cpu")

    # Each child writes to its own temp file, never a pipe: collectives keep
    # all children in lock-step, so a child blocked on a full pipe buffer
    # stalls the whole cluster while the launcher drains children in pid
    # order — the classic launcher deadlock. Files have no backpressure.
    procs, logs = [], []
    for pid in range(num_processes):
        child_env = dict(base, DRACO_PROCESS_ID=str(pid))
        log = tempfile.TemporaryFile(mode="w+b", prefix=f"draco_proc{pid}_") if prefix_output else None
        logs.append(log)
        procs.append(
            subprocess.Popen(
                cmd, env=child_env,
                stdout=log if prefix_output else None,
                stderr=subprocess.STDOUT if prefix_output else None,
            )
        )
    rc = 0
    for pid, p in enumerate(procs):
        p.wait()
        if prefix_output:
            logs[pid].seek(0)
            # children can emit non-UTF-8 bytes (native/libtpu log garbage);
            # never let a decode error eat the other children's logs
            text = logs[pid].read().decode("utf-8", errors="replace")
            for line in text.splitlines():
                print(f"[proc {pid}] {line}", flush=True)
            logs[pid].close()
        if p.returncode != 0 and rc == 0:
            rc = p.returncode
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-processes", type=int, default=2)
    ap.add_argument("-d", "--devices-per-process", type=int, default=4)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run in every process (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given")
    return launch(args.num_processes, args.devices_per_process, cmd)


if __name__ == "__main__":
    raise SystemExit(main())
