#!/usr/bin/env python
"""Local multi-process cluster launcher — the ``mpirun -n P`` equivalent.

The reference trains multi-node by launching one MPI rank per host
(reference: src/README.md:10, tools/local_script.sh). The TPU-native
equivalent is one *JAX process* per host sharing a global device mesh via
``jax.distributed``; this script simulates that cluster on one machine:
it spawns N processes, each pinned to K virtual CPU devices, wired to a
shared coordinator — the same code path (gloo collectives over the
process boundary) a real multi-host TPU pod uses over DCN.

Usage:
  python tools/local_cluster.py -n 2 -d 4 -- \
      python -m draco_tpu.cli --approach cyclic --network LeNet \
        --dataset synthetic-mnist --num-workers 8 --worker-fail 1 \
        --max-steps 20 --cpu-mesh 4

Each child gets DRACO_COORDINATOR / DRACO_NUM_PROCESSES / DRACO_PROCESS_ID
(read by draco_tpu.runtime.init_distributed) and an XLA host-device count of
``-d``. Exit code is the first non-zero child exit code.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def launch(num_processes: int, devices_per_process: int, cmd: list[str],
           env: dict | None = None, prefix_output: bool = True) -> int:
    port = _free_port()
    base = dict(os.environ, **(env or {}))
    base["DRACO_COORDINATOR"] = f"localhost:{port}"
    base["DRACO_NUM_PROCESSES"] = str(num_processes)
    base["XLA_FLAGS"] = (
        base.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_process}"
    ).strip()
    base.setdefault("JAX_PLATFORMS", "cpu")

    procs = []
    for pid in range(num_processes):
        child_env = dict(base, DRACO_PROCESS_ID=str(pid))
        procs.append(
            subprocess.Popen(
                cmd, env=child_env,
                stdout=subprocess.PIPE if prefix_output else None,
                stderr=subprocess.STDOUT if prefix_output else None,
                text=prefix_output,
            )
        )
    rc = 0
    for pid, p in enumerate(procs):
        out, _ = p.communicate() if prefix_output else (None, None)
        if prefix_output and out:
            for line in out.splitlines():
                print(f"[proc {pid}] {line}", flush=True)
        if p.returncode != 0 and rc == 0:
            rc = p.returncode
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-processes", type=int, default=2)
    ap.add_argument("-d", "--devices-per-process", type=int, default=4)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run in every process (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given")
    return launch(args.num_processes, args.devices_per_process, cmd)


if __name__ == "__main__":
    raise SystemExit(main())
