#!/usr/bin/env python
"""Fold a fleet of run directories into the SLO dashboard (ISSUE 19).

The fleet observatory (draco_tpu/obs/fleet.py) rolls every run's
status.json + incidents.jsonl + metrics.jsonl tail into per-run SLO
verdicts with error budgets and a fleet-level roll-up: per-SLO
compliance counts, the cross-run worker trust table (a worker accused
in 3 of 4 runs outranks a one-run spike), and compute-to-target. This
tool prints the text dashboard and (``--json``) writes ``fleet.json``:

  python tools/fleet_report.py run_a/ run_b/            # explicit dirs
  python tools/fleet_report.py --runs-root train_out/   # discover
  python tools/fleet_report.py --runs-root . --watch 10 # poll forever

No jax import — runs on a bare checkout, on a laptop, against
artifacts scp'd from a chip job. Torn / empty / missing inputs degrade
with a visible per-run note (obs/replay tolerance rules), never a
traceback; a directory with no runs at all prints a note and exits 0.
``--strict`` exits 1 when any SLO is violated (CI mode).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# draco_tpu.obs is importable without jax — the registry/SLO fold is the
# ONE implementation this dashboard, fleet_study, and check_artifacts
# share, so the committed artifact cannot drift from the live report
from draco_tpu.obs import fleet  # noqa: E402

_SLO_ABBREV = {
    "step_availability": "avail",
    "detection_quality": "detect",
    "decode_health": "decode",
    "throughput": "thru",
    "incident_mttr": "mttr",
    "wire_bytes": "wire",
}
_MARK = {"ok": "ok", "violated": "VIOL", "not_evaluated": "-"}


def collect_run_dirs(paths, runs_root: str) -> list:
    dirs = list(paths)
    if runs_root:
        dirs.extend(fleet.RunRegistry.discover(runs_root))
    # stable order, no duplicates (a positional dir may also be under
    # --runs-root)
    seen, out = set(), []
    for d in dirs:
        key = os.path.normpath(d)
        if key not in seen:
            seen.add(key)
            out.append(d)
    return out


def make_fleet(run_dirs, thresholds: str = "",
               target_loss=None) -> dict:
    registry = fleet.RunRegistry(run_dirs, tool="tools/fleet_report.py")
    report = fleet.fleet_fold(registry.summaries, overrides=thresholds,
                              target_loss=target_loss)
    report["tool"] = "tools/fleet_report.py"
    report["run_dirs"] = list(run_dirs)
    return report


def print_report(report: dict, out=None) -> None:
    out = out if out is not None else sys.stdout  # resolve at call time
    runs = report["runs"]
    print(f"fleet: {len(runs)} run(s)   "
          f"all_ok={report['all_ok']}", file=out)
    if not runs:
        print("no run directories found (nothing holding a status.json "
              "or metrics.jsonl) — nothing to fold", file=out)
        return
    names = list(_SLO_ABBREV)
    hdr = f"{'run':<18}{'state':<10}{'steps':>6}{'burn':>6}  " + \
        "".join(f"{_SLO_ABBREV[n]:>8}" for n in names)
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    for r in runs:
        marks = "".join(
            f"{_MARK[r['slo'][n]['verdict']] if n in r['slo'] else '?':>8}"
            for n in names)
        state = r.get("state") or "?"
        print(f"{r['run'][:17]:<18}{state:<10}{r['steps']:>6}"
              f"{r['budget_burned']:>6g}  {marks}", file=out)
        for note in r["notes"]:
            print(f"    note: {note}", file=out)
        for n in names:
            res = r["slo"].get(n)
            if res and res["verdict"] == "violated":
                print(f"    {n}: {res['detail']}", file=out)
    comp = report["slo_compliance"]
    print("slo compliance (ok/violated/not_evaluated):", file=out)
    for n in names:
        c = comp[n]
        print(f"  {n:<20} {c['ok']}/{c['violated']}/"
              f"{c['not_evaluated']}", file=out)
    offenders = [w for w in report["workers"] if w["runs_accusing"]]
    if offenders:
        print("top offenders (cross-run):", file=out)
        for w in offenders:
            print(f"  worker {w['worker']}: accused in "
                  f"{w['runs_accusing']}/{w['runs_seen']} runs "
                  f"({w['accused_total']} accusations, min trust "
                  f"{w['min_trust']:.2f})", file=out)
    comp_roll = report["compute"]
    print(f"compute: {comp_roll['total_worker_steps']:g} worker-steps "
          f"across the fleet", file=out)
    if comp_roll["target_loss"] is not None:
        print(f"  to target loss {comp_roll['target_loss']:g}: "
              f"{comp_roll['runs_reaching_target']}/{len(runs)} runs, "
              f"{comp_roll['worker_steps_to_target_total'] or 0:g} "
              f"worker-steps", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dirs", nargs="*",
                    help="run directories (or metrics.jsonl paths)")
    ap.add_argument("--runs-root", default="",
                    help="discover every run dir under this root "
                         "(anything holding status.json/metrics.jsonl)")
    ap.add_argument("--slo-thresholds", default="",
                    help="SLO threshold overrides, comma-separated "
                         "'<slo>.<key>=<float>' (obs/fleet.slo_table "
                         "names the keys)")
    ap.add_argument("--target-loss", type=float, default=None,
                    help="fold compute-to-target at this loss")
    ap.add_argument("--json", default="",
                    help="write fleet.json here ('' = don't write)")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="poll every N seconds (dashboard mode)")
    ap.add_argument("--watch-count", type=int, default=0,
                    help="stop after N polls (0 = forever; for tests)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any SLO is violated (CI mode)")
    args = ap.parse_args(argv)

    polls = 0
    while True:
        run_dirs = collect_run_dirs(args.run_dirs, args.runs_root)
        report = make_fleet(run_dirs, args.slo_thresholds,
                            args.target_loss)
        if args.watch:
            print(f"\n--- fleet poll {polls + 1} "
                  f"@ {time.strftime('%H:%M:%S')} ---")
        print_report(report)
        if args.json:
            os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
            tmp = args.json + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(report, fh, indent=1)
            os.replace(tmp, args.json)
        polls += 1
        if not args.watch or (args.watch_count
                              and polls >= args.watch_count):
            break
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            break
    return 1 if (args.strict and not report["all_ok"]) else 0


if __name__ == "__main__":
    sys.exit(main())
