#!/bin/bash
# Round-3 follow-up chip chain: everything chip_jobs_r3.sh left failed or
# stale, in priority order. Safe to re-run; artifacts land in baselines_out/.
#
#   1. flash-attention hardware check with the FIXED kernel (the r3.sh run
#      recorded the pre-fix Mosaic tiling failure)
#   2. bench.py with a wide budget — warms the persistent compile cache so
#      the driver's own budget-280 run fits all three legs
#   3. bench.py at the driver budget (proof the warmed record lands whole)
#   4. LM perf with the flash variant on the training path
#   5. decode study n=32 rows (tunnel flapped during r3.sh)
#   6/7. TPU time-to-accuracy (skip if r3.sh already produced them)
set -u
cd "$(dirname "$0")/.."

tools/wait_tpu.sh 60 150 120 || exit 3

FAILURES=0
run() {
  echo "[chip_jobs_r3b] ===== $* ====="
  if ! "$@"; then
    echo "[chip_jobs_r3b] FAILED (continuing): $*"
    FAILURES=$((FAILURES + 1))
  fi
}

run python tools/tpu_attn_check.py --out baselines_out/tpu_attn.json
run python bench.py --budget 1200
run python bench.py --budget 280
run python tools/tpu_lm_perf.py --steps 4 \
  --variants lm_cyclic_s1_shared_bf16_flash,lm_cyclic_s1_shared_bf16 \
  --seq-len 1024 --batch-size 4 --remat \
  --out baselines_out/tpu_lm_perf_flash.json
run python tools/decode_study.py --ns 32 --out baselines_out/decode_study_n32.json
if [ ! -s baselines_out/tpu_tta_resnet_cyclic.json ]; then
  run python tools/time_to_acc.py --network ResNet18 --dataset Cifar10 \
    --approach cyclic --redundancy simulate --eval-every 5 --max-steps 300 \
    --target 0.9 --out baselines_out/tpu_tta_resnet_cyclic.json
fi
if [ ! -s baselines_out/tpu_tta_resnet_geomedian.json ]; then
  run python tools/time_to_acc.py --network ResNet18 --dataset Cifar10 \
    --approach baseline --mode geometric_median --eval-every 5 --max-steps 300 \
    --target 0.9 --out baselines_out/tpu_tta_resnet_geomedian.json
fi
run python tools/lm_time_to_loss.py --eval-every 10 --max-steps 100 \
  --out baselines_out/lm_time_to_loss.json \
  --variants lm_cyclic_s1_simulate,lm_geomedian,lm_mean_under_attack,lm_mean_no_attack
echo "[chip_jobs_r3b] done ($FAILURES failures)"
exit $((FAILURES > 0 ? 1 : 0))
