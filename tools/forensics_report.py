#!/usr/bin/env python
"""Fold a run's metrics.jsonl into a per-worker forensics report.

The coded training steps ship their per-worker accusation, presence, and
seeded-adversary masks as packed bitmask columns riding the metric block
(draco_tpu/obs/forensics.py, PERF.md §10). This tool replays the host
ledger over a run's ``metrics.jsonl`` — per-worker accusation counters,
detection precision/recall vs the seeded schedule, exponentially-weighted
trust, and attack **episodes** ("worker 3 was adversarial for steps
120..400") — prints the timeline table, and writes ``forensics.json`` next
to the metrics file (``--json`` overrides):

  python tools/forensics_report.py train_out/          # a train dir
  python tools/forensics_report.py path/to/metrics.jsonl --num-workers 8

No jax import — the packed words live in the JSONL as exact integers and
the ledger fold is pure host arithmetic (a sibling of trace_report.py,
usable on a laptop against artifacts scp'd from a chip job). It tolerates
the partial-artifact states a killed run leaves behind: a missing or empty
metrics.jsonl folds to an empty report, a torn JSONL tail line is skipped,
and records without forensics columns (baseline routes, eval records,
mixed-route train dirs) are ignored.

The worker count comes from ``--num-workers``, else the run's status.json
(schema >= 2 carries it in the ``forensics`` block), else the highest
worker ever marked present in the packed masks — the inference only
under-counts workers that never sent a single row, which contribute
nothing to any counter.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# draco_tpu.obs is importable without jax (packing imports it lazily and
# this tool never packs) — one ledger implementation for the live heartbeat
# and this offline fold, so the two cannot drift; the torn-tolerant JSONL
# reading is the shared replay scaffold (obs/replay.py, ISSUE 13 satellite)
from draco_tpu.obs import replay  # noqa: E402
from draco_tpu.obs.forensics import AccusationLedger  # noqa: E402


def load_records(path: str) -> list:
    """Train records from metrics.jsonl; blank/torn lines skipped, eval
    records dropped. [] when the file is missing or empty — a killed run
    must not take the report down with it (obs/replay.py). Mask-only
    records without a loss still fold (require_loss=False: the ledger
    ignores whatever lacks masks anyway)."""
    return replay.train_records(path, require_loss=False)


def infer_num_workers(records: list, status_path: str) -> int:
    """--num-workers fallback chain — the ONE shared implementation
    (obs/replay.infer_num_workers; incident_report uses it too)."""
    return replay.infer_num_workers(records, status_path,
                                    "tools/forensics_report.py")


def make_report(metrics_path: str, num_workers: int = 0) -> dict:
    records = load_records(metrics_path)
    n = num_workers or infer_num_workers(
        records, replay.find_run_files(metrics_path).status)
    # n > MAX_WORKERS raises the ledger's named bound — an explicit
    # --num-workers above it must error, not silently truncate the table
    ledger = AccusationLedger(n)
    folded = sum(ledger.observe(rec) for rec in records)
    report = ledger.to_dict()
    report.update({
        "tool": "tools/forensics_report.py",
        "metrics": metrics_path,
        "records_seen": len(records),
        "records_with_masks": int(folded),
    })
    return report


def print_table(report: dict, out=None) -> None:
    out = out if out is not None else sys.stdout  # resolve at call time
    print(f"forensics: {report['metrics']}   "
          f"{report['records_with_masks']}/{report['records_seen']} records "
          f"carried masks   workers: {report['num_workers']}", file=out)
    if not report["records_with_masks"]:
        print("no forensics columns found (baseline route, eval-only file, "
              "or a pre-forensics run)", file=out)
        return
    hdr = (f"{'worker':>6}{'present':>9}{'accused':>9}{'tp':>6}{'fp':>6}"
           f"{'fn':>6}{'precision':>11}{'recall':>9}{'trust':>8}"
           f"{'episodes':>10}")
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    for r in report["workers"]:
        print(f"{r['worker']:>6}{r['present']:>9}{r['accused']:>9}"
              f"{r['tp']:>6}{r['fp']:>6}{r['fn']:>6}"
              f"{r['precision']:>11.3f}{r['recall']:>9.3f}"
              f"{r['trust']:>8.3f}{r['episodes']:>10}", file=out)
    eps = report["episodes"]
    if eps:
        print(f"episodes ({len(eps)}):", file=out)
        for ep in eps:
            tail = "  (open)" if ep.get("open") else ""
            span = (f"step {ep['start']}" if ep["start"] == ep["end"]
                    else f"steps {ep['start']}-{ep['end']}")
            print(f"  worker {ep['worker']}: {span} "
                  f"({ep['steps']} accused){tail}", file=out)
    top = report["summary"]["top_suspects"]
    if top:
        sus = ", ".join(f"w{t['worker']} (accused {t['accused']}, trust "
                        f"{t['trust']:.2f})" for t in top)
        print(f"top suspects: {sus}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="metrics.jsonl, or a directory holding it")
    ap.add_argument("--num-workers", type=int, default=0,
                    help="worker count (default: status.json, else inferred "
                         "from the present masks)")
    ap.add_argument("--json", default="",
                    help="report output path (default: forensics.json next "
                         "to the metrics file)")
    args = ap.parse_args(argv)

    metrics_path = replay.find_run_files(args.path).metrics
    report = make_report(metrics_path, args.num_workers)
    print_table(report)
    out_path = args.json or os.path.join(os.path.dirname(metrics_path),
                                         "forensics.json")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
