#!/bin/bash
# Round-5 chain f: the d~159M LM point via scan_layers. Every unrolled
# attempt died in the tunnel's remote-compile service ("Broken pipe" at
# ~27 min — PERF.md §4, chains r5/r5c/r5e). scan_layers compiles the
# 12-layer stack as ONE nn.scan body (identical math —
# tests/test_transformer_scan.py; offline TPU lowering + program-size
# evidence — baselines_out/tpu_lm_scan_lowering.json), so the program the
# service sees is ~12x smaller. One variant per rung, headline first:
#   1 lm159scan_flash   cyclic shared + flash kernel, T=2048 b2 remat
#   2 lm159scan_geomed  geomedian, same shapes (the comparison column)
#   3 lm159scan_shared  cyclic shared dense, same shapes
#   4 lm159scan_sim     cyclic simulate (r=3 lanes), T=2048 b1 remat
# Rungs 1+2 give the decode-vs-geomedian claim at d~159M; 3 isolates the
# kernel's contribution; 4 prices reference-parity redundancy.
# Parks until chains r5/r5b/r5c/r5d/r5e are gone.
#
# Launch detached:
#   setsid nohup bash tools/chip_jobs_r5f.sh > baselines_out/chip_jobs_r5f.log 2>&1 &
# NEVER edit this file while it runs. Markers: baselines_out/.r5f_<rung>_done
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5f $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5f $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5f $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5f $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5f $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5f $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

tpu_up() {
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

others_running() {
  for s in chip_jobs_r5.sh chip_jobs_r5b.sh chip_jobs_r5c.sh \
           chip_jobs_r5d.sh chip_jobs_r5e.sh; do
    pgrep -f "bash tools/$s" > /dev/null 2>&1 && return 0
  done
  return 1
}

echo "[r5f $(stamp)] waiting for chains r5/r5b/r5c/r5d/r5e to finish"
while others_running; do
  sleep 60
done
echo "[r5f $(stamp)] predecessors gone; proceeding"

ABORT_PASS=0
FAILURES=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5f_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5f $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5f $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    FAILURES=$((FAILURES + 1))
    if ! tpu_up; then
      echo "[r5f $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

all_done() {
  for m in lm159scan_flash lm159scan_geomed lm159scan_shared lm159scan_sim; do
    [ -f "baselines_out/.r5f_${m}_done" ] || return 1
  done
  return 0
}

for outer in 1 2 3; do
  echo "[r5f $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5f $(stamp)] tunnel never came up this window"; continue; }
  FAILURES=0
  ABORT_PASS=0

  rung lm159scan_flash "chip evidence: d~159M LM cyclic+flash T=2048 via scan_layers" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 2 --remat --scan-layers \
      --variants lm_cyclic_s1_shared_bf16_flash \
      --out baselines_out/tpu_lm_perf_scan_flash.json

  rung lm159scan_geomed "chip evidence: d~159M LM geomedian T=2048 via scan_layers" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 2 --remat --scan-layers \
      --variants lm_geomedian_bf16 \
      --out baselines_out/tpu_lm_perf_scan_geomed.json

  rung lm159scan_shared "chip evidence: d~159M LM cyclic dense T=2048 via scan_layers" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 2 --remat --scan-layers \
      --variants lm_cyclic_s1_shared_bf16 \
      --out baselines_out/tpu_lm_perf_scan_shared.json

  rung lm159scan_sim "chip evidence: d~159M LM cyclic simulate (r=3) T=2048 b1 via scan_layers" \
    timeout -k 60 5400 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 1 --remat --scan-layers \
      --variants lm_cyclic_s1_simulate_bf16 \
      --out baselines_out/tpu_lm_perf_scan_sim.json

  if all_done; then
    echo "[r5f $(stamp)] D~159M SCAN EVIDENCE COMPLETE"
    break
  fi
  echo "[r5f $(stamp)] incomplete ($FAILURES rung failures this pass); retrying"
  sleep 120
done
all_done && exit 0 || exit 1
