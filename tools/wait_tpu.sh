#!/bin/bash
# Shared bounded-probe wait loop for the tunnel TPU (one source of truth
# for the tunnel discipline: an unbounded in-process jax.devices() blocks
# ~25 min inside the plugin's retry loop against a wedged lease, PERF.md §4).
#
# Usage: tools/wait_tpu.sh [attempts] [sleep_s] [probe_timeout_s]
# Exits 0 the moment a probe sees a non-cpu device; 3 after `attempts`
# failures.
ATTEMPTS=${1:-60}
SLEEP_S=${2:-150}
PROBE_S=${3:-120}
for attempt in $(seq 1 "$ATTEMPTS"); do
  if timeout -k 30 "$PROBE_S" python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
  then
    echo "[wait_tpu] TPU up (attempt $attempt)"
    exit 0
  fi
  echo "[wait_tpu] attempt $attempt/$ATTEMPTS: TPU still down"
  [ "$attempt" = "$ATTEMPTS" ] && break
  sleep "$SLEEP_S"
done
echo "[wait_tpu] giving up"
exit 3
