"""Shared scaffolding for the offline TPU-lowering audit tools (round 5).

Three tools prove chip-queued programs clean against the Pallas/StableHLO
TPU lowering stack without a chip (tpu_attn_lowering_check,
tpu_lm_lowering_check, tpu_parallel_lowering_check); the env bootstrap and
the incremental per-row report loop live here so a fix to the pattern is
made once. Methodology and the negative control proving the lowering
checks are actually exercised: tools/tpu_attn_lowering_check.py.
"""

from __future__ import annotations

import json
import os
import sys


def setup_cpu_host(device_count: int) -> None:
    """Force a CPU host with `device_count` virtual devices. MUST run
    before the first jax import in the process; jax_platforms is then
    latched via jax.config (the env var alone is read too late under this
    image's sitecustomize — .claude/skills/verify/SKILL.md)."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={device_count}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")


def run_rows(out_path: str, method: str, named_rows, extra=None):
    """Drive (name, thunk) pairs, rewriting the report after EVERY row so an
    interrupt keeps finished rows (the repo's incremental-artifact
    discipline). Each thunk returns a dict with at least {"ok": bool}.
    Returns the report; all_ok covers the rows run so far."""
    report = {"method": method, "all_ok": None, "rows": []}
    if extra:
        report.update(extra)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    for name, thunk in named_rows:
        try:
            row = thunk()
        except Exception as e:  # a row crash must not lose earlier rows
            row = {"ok": False,
                   "error": f"{type(e).__name__}: {str(e)[:400]}"}
        row = {"name": name, **row}
        report["rows"].append(row)
        report["all_ok"] = all(r["ok"] for r in report["rows"])
        with open(out_path, "w") as fh:
            json.dump(report, fh, indent=1)
        print(f"[lowering] {name}: "
              f"{'ok' if row['ok'] else row.get('error', '?')[:120]}",
              file=sys.stderr, flush=True)
    return report


def lint_row(program, extra_row=None, only=None):
    """Run the program-lint rules on a registered
    :class:`draco_tpu.analysis.LintProgram` and shape the result as a
    run_rows row: ``ok`` is the lint verdict, ``failed_rules``/``rules``
    carry the per-rule detail. ``only`` restricts to a subset of rule
    names (tools/program_lint.py --only). The three lowering-check tools
    build their rows through this helper so a chip-scale audit row always
    carries the same verdict fields as the CI artifact
    (baselines_out/program_lint.json)."""
    import time

    from draco_tpu.analysis import lint_program

    t0 = time.time()
    try:
        row = lint_program(program, only=only)
    except Exception as e:  # build/trace crash: report as a failed row
        return {"ok": False, "seconds": round(time.time() - t0, 1),
                "error": f"{type(e).__name__}: {str(e)[:400]}",
                **(extra_row or {})}
    row["seconds"] = round(time.time() - t0, 1)
    if extra_row:
        row.update(extra_row)
    return row
