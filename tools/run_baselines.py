#!/usr/bin/env python
"""Run the five BASELINE.json configurations end-to-end and record results.

  python tools/run_baselines.py --smoke            # short runs, any hardware
  python tools/run_baselines.py --max-steps 2000   # real grid

Writes one JSON line per config to stdout and baselines_out/results.jsonl.
Eager rows record per-step wall-clock + final loss/accuracy; scan rows
(accelerators) record per-step wall-clock + loss + analytic FLOPs — the
timed scan has no eval loop, so the accuracy axis comes from the eager
grid / tools/time_to_acc.py instead. --smoke shrinks steps and swaps in
synthetic data so the grid runs anywhere in minutes.

Timing protocol: on accelerators the per-step number comes from bench.run's
scanned-steps protocol (utils/timing.py — through the remote-dispatch tunnel
an eager loop times host dispatch, not the chip); on CPU the eager Trainer
loop is both honest and much faster than a scanned conv step
(PERF.md §4). --protocol overrides the auto choice.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--max-steps", type=int, default=50)
    ap.add_argument("--cpu-mesh", type=int, default=0)
    ap.add_argument("--out-dir", type=str, default="baselines_out")
    ap.add_argument("--fresh", action="store_true",
                    help="truncate results.jsonl first (default appends), so "
                         "stale rows from older code can't shadow a re-run")
    ap.add_argument("--protocol", choices=["auto", "eager", "scan"],
                    default="auto",
                    help="per-step timing: eager Trainer loop or scanned "
                         "steps (auto: scan on accelerators, eager on CPU)")
    ap.add_argument("--scan-steps", type=int, default=10,
                    help="steps folded into each timed scan (scan protocol)")
    ap.add_argument("--only", type=str, default="",
                    help="comma-separated subset of preset names to run "
                         "(default: all five)")
    ap.add_argument("--vote-check", type=str, default="",
                    choices=["", "fingerprint", "exact"],
                    help="override the maj_vote row-equality method for the "
                         "rep presets (empty: preset default) — lets the "
                         "chip decide fingerprint-vs-exact at equal config")
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)  # shared bootstrap: compile cache (+ cpu mesh)

    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.presets import PRESETS, get_preset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer

    os.makedirs(args.out_dir, exist_ok=True)
    results_path = os.path.join(args.out_dir, "results.jsonl")
    rc = 0
    names = list(PRESETS)
    if args.only:
        keep = {v.strip() for v in args.only.split(",") if v.strip()}
        unknown = keep - set(names)
        if unknown:
            raise SystemExit(f"unknown presets {sorted(unknown)}; have {names}")
        names = [n for n in names if n in keep]
    with open(results_path, "w" if args.fresh else "a") as fh:
        for name in names:
            overrides = dict(max_steps=args.max_steps, eval_freq=0,
                             train_dir="", log_every=10**9)
            if args.vote_check and name.startswith("rep-"):
                # only the rep presets run maj_vote; stamping the override
                # into other rows would split equal-config groupings on an
                # inert field
                overrides["vote_check"] = args.vote_check
            if args.smoke:
                overrides.update(
                    dataset="synthetic-mnist" if "lenet" in name else "synthetic-cifar10",
                    batch_size=4, max_steps=min(args.max_steps, 12),
                    # shared: algebraically identical to the r× redundant
                    # compute (see config.redundancy) at 1/r the FLOPs —
                    # keeps the smoke grid tractable on CPU
                    redundancy="shared",
                )
            cfg = get_preset(name, **overrides)
            ds = load_dataset(cfg.dataset, cfg.data_dir,
                              synthetic_train=1024, synthetic_test=128)
            try:
                import jax

                protocol = args.protocol
                if protocol == "auto":
                    protocol = (
                        "eager" if jax.devices()[0].platform == "cpu" else "scan"
                    )
                if protocol == "scan":
                    import bench as bench_mod

                    steps = min(args.scan_steps, cfg.max_steps)
                    dt, loss, flops, _compile_s = bench_mod.run(
                        dataclasses.asdict(cfg), ds, make_mesh(cfg.num_workers),
                        steps, warmup=1, reps=2, want_flops=True,
                    )
                    rec = {
                        "preset": name,
                        "steps": steps,
                        "ms_per_step": round(1000 * dt, 2),
                        "final_loss": round(loss, 4),
                        "flops_per_step": flops,
                        "protocol": "scan",
                        "dataset": ds.name,
                        "config": dataclasses.asdict(cfg),
                    }
                else:
                    tr = Trainer(cfg, mesh=make_mesh(cfg.num_workers),
                                 dataset=ds, quiet=True)
                    t0 = time.perf_counter()
                    last = tr.run()
                    wall = time.perf_counter() - t0
                    rec = {
                        "preset": name,
                        "steps": cfg.max_steps,
                        "ms_per_step": round(1000 * wall / cfg.max_steps, 2),
                        "final_loss": round(last.get("loss", float("nan")), 4),
                        "final_prec1": round(last.get("prec1", float("nan")), 4),
                        "protocol": "eager",
                        "dataset": ds.name,
                        "config": dataclasses.asdict(cfg),
                    }
                    tr.close()
            except Exception as e:  # record the failure, keep the grid going
                rec = {"preset": name, "error": repr(e)}
                rc = 1
            line = json.dumps(rec)
            print(line, flush=True)
            fh.write(line + "\n")
            fh.flush()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
