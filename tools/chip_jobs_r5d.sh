#!/bin/bash
# Round-5 chain d: re-measure the attention evidence at the TUNED kernel
# defaults (commit 822a588: blocks 512x1024 + divisor-aware shrink + causal
# fetch-clamp). The committed tpu_attn.json rows and the lm_flash rows were
# measured at the old 128x128 defaults (and, for the LM rows, partly with
# the pre-fix dense fallback); this chain replaces them with what actually
# ships:
#   1 attn_defaults    tpu_attn_check T=256..4096 at shipped defaults —
#                      parity + timings vs dense + jaxref (supersedes the
#                      r5-ladder attn_full rows; closes the r5 review's
#                      "evidence attests old defaults" finding on chip)
#   2 lm_flash_tuned   LM flash-vs-dense T=1024 remat with the tuned kernel
# Parks until chip_jobs_r5.sh, r5b.sh AND r5c.sh are gone.
#
# Launch detached:
#   setsid nohup bash tools/chip_jobs_r5d.sh > baselines_out/chip_jobs_r5d.log 2>&1 &
# NEVER edit this file while it runs. Markers: baselines_out/.r5d_<rung>_done
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5d $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5d $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5d $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5d $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5d $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5d $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

tpu_up() {
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

others_running() {
  pgrep -f "bash tools/chip_jobs_r5.sh" > /dev/null 2>&1 && return 0
  pgrep -f "bash tools/chip_jobs_r5b.sh" > /dev/null 2>&1 && return 0
  pgrep -f "bash tools/chip_jobs_r5c.sh" > /dev/null 2>&1 && return 0
  return 1
}

echo "[r5d $(stamp)] waiting for chip_jobs_r5/r5b/r5c to finish"
while others_running; do
  sleep 60
done
echo "[r5d $(stamp)] predecessors gone; proceeding"

ABORT_PASS=0
FAILURES=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5d_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5d $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5d $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    FAILURES=$((FAILURES + 1))
    if ! tpu_up; then
      echo "[r5d $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

all_done() {
  for m in attn_defaults lm_flash_tuned; do
    [ -f "baselines_out/.r5d_${m}_done" ] || return 1
  done
  return 0
}

for outer in 1 2 3; do
  echo "[r5d $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5d $(stamp)] tunnel never came up this window"; continue; }
  FAILURES=0
  ABORT_PASS=0

  rung attn_defaults "chip evidence: flash T=256..4096 vs dense/jaxref at tuned shipped defaults" \
    timeout -k 60 3600 python tools/tpu_attn_check.py --out baselines_out/tpu_attn.json

  rung lm_flash_tuned "chip evidence: LM flash-vs-dense T=1024 with tuned kernel defaults" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 \
      --variants lm_cyclic_s1_shared_bf16_flash,lm_cyclic_s1_shared_bf16 \
      --seq-len 1024 --batch-size 4 --remat \
      --out baselines_out/tpu_lm_perf_flash_tuned.json

  if all_done; then
    echo "[r5d $(stamp)] TUNED-DEFAULTS EVIDENCE COMPLETE"
    break
  fi
  echo "[r5d $(stamp)] incomplete ($FAILURES rung failures this pass); retrying"
  sleep 120
done
all_done && exit 0 || exit 1
