#!/bin/bash
# Round-4 second chip chain (run AFTER chip_jobs_r4.sh completes r3b+r3c):
# the scale-up evidence VERDICT r3 item 5 asks for — one LM perf point big
# enough that the decode-vs-geomedian gap and MFU are measured where they
# matter (d≈160M, T=2048, remat+flash), plus a long-context ring+flash row.
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

tools/wait_tpu.sh 60 150 120 || exit 3

FAILURES=0
run() {
  echo "[chip_jobs_r4b] ===== $* ====="
  if ! "$@"; then
    echo "[chip_jobs_r4b] FAILED (continuing): $*"
    FAILURES=$((FAILURES + 1))
  fi
}

# d ≈ 159M (dim 1024, 12 blocks, vocab 8192): the (8, d) f32 gradient stack
# is 5.1 GB, params+momentum 1.3 GB — fits 16G HBM with remat on.
run python tools/tpu_lm_perf.py --steps 4 --reps 2 \
  --model-dim 1024 --model-heads 16 --model-layers 12 \
  --seq-len 2048 --batch-size 2 --remat \
  --variants lm_cyclic_s1_shared_bf16_flash,lm_cyclic_s1_shared_bf16,lm_geomedian_bf16 \
  --out baselines_out/tpu_lm_perf_big.json

# same scale, reference-parity redundant compute (r=3 lanes): smaller batch
# to keep the 3x activation footprint inside HBM
run python tools/tpu_lm_perf.py --steps 4 --reps 2 \
  --model-dim 1024 --model-heads 16 --model-layers 12 \
  --seq-len 2048 --batch-size 1 --remat \
  --variants lm_cyclic_s1_simulate_bf16 \
  --out baselines_out/tpu_lm_perf_big_simulate.json

# re-time the maj_vote preset after the O(r·d) fingerprint-vote rewrite
# (r3 verdict weak #6: 40.0 ms with the O(r²·d) pairwise-equality vote)
run python tools/run_baselines.py --max-steps 12 --protocol scan \
  --only rep-resnet18

echo "[chip_jobs_r4b] done ($FAILURES failures)"
exit $((FAILURES > 0 ? 1 : 0))
