#!/usr/bin/env python
"""Decode-granularity and s-scaling study (VERDICT r2 item 7).

Two questions the round-2 evidence left at two data points:

1. How do the isolated encode / decode costs scale with the Byzantine
   budget s ∈ {1, 2, 3} and the worker count n ∈ {8, 16, 32} at the
   flagship gradient dimension — against the Weiszfeld geometric-median
   cost at the same (n, d)? (The "decode stays flat while Weiszfeld
   scales" claim.)
2. What does reference-parity per-layer decode granularity
   (cyclic_master.py:125-129, one locator per parameter tensor) cost vs
   the global one-locator decode, as a full train step?

Writes after every point; a mid-run tunnel loss keeps completed points.

ISSUE 17 additions:

  * ``--merge PATCH`` folds a partial re-run (e.g. the regenerated n=32
    rows measured after the PR 15 regularized locator landed) into the
    committed artifact: every (n, s) scaling row the patch carries
    WITHOUT an error replaces the main artifact's row, numeric
    granularity cells replace errored ones, and the merge provenance is
    recorded in the artifact ("merged_from");
  * ``--tree-fanout G`` measures, next to every flat (n, s) scaling row,
    the tree topology's per-node critical path at the same d (leaf
    decode at the (G, s_g) group code + per-level combine,
    coding/topology.py) and records the tree-vs-flat crossover column —
    the light companion of tools/tree_study.py;
  * ``--check`` re-verifies a committed artifact jax-free: NO scaling
    row may carry an error, granularity cells must be numeric, and every
    present tree column must agree with its own timings — wired into
    tools/check_artifacts.py.

Usage: python tools/decode_study.py [--out baselines_out/decode_study.json]
       [--d 11173962] [--cpu-mesh 8 for smoke]
       python tools/decode_study.py --merge baselines_out/decode_study_n32.json
       python tools/decode_study.py --check
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def geomedian_ms(n, d, iters=80, reps=10):
    """Isolated Weiszfeld cost at (n, d) under the chained-feedback timing
    protocol (utils/timing.py) — the PS-phase cost cyclic decode replaces."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu import aggregation
    from draco_tpu.utils.timing import timeit_chained

    r = np.random.RandomState(0)
    g = jnp.asarray(r.randn(n, d).astype(np.float32))

    def step(gc):
        med = aggregation.geometric_median(gc, iters=iters)
        return gc.at[0, 0].add(1e-30 * jnp.sum(med**2))

    return timeit_chained(step, g, reps=reps) * 1e3


def tree_phase_times(n, d, s, fanout, reps=10):
    """Per-node critical path of the tree topology at (n, d): the leaf
    decode at the (fanout, s_g) group code plus each combine level's
    fan-in partial sum (coding/topology.py algebra). Returns
    ``(critical_ms, leaf_ms, s_g, levels)`` or None when (n, fanout) has
    no valid tree (n % g != 0 or fewer than 2 groups)."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import cyclic as cyc
    from draco_tpu.coding import topology as topo
    from draco_tpu.utils.timing import timeit_chained

    if n % fanout != 0 or n // fanout < 2:
        return None
    plan = topo.tree_plan(n, fanout)
    s_g = topo.group_worker_fail(fanout, s)
    code = cyc.build_cyclic_code(fanout, s_g)
    r = np.random.RandomState(0)
    g = jnp.asarray(r.randn(fanout, d).astype(np.float32))
    rf = jnp.asarray(r.randn(d).astype(np.float32))
    e_re, e_im = cyc.encode_shared(code, g)

    def dec_step(carry, rf):
        er, ei = carry
        dec, _honest = cyc.decode(code, er, ei, rf)
        return (er.at[0, 0].add(1e-30 * jnp.sum(dec ** 2)), ei)

    leaf_ms = timeit_chained(dec_step, (e_re, e_im), (rf,), reps=reps) * 1e3
    combine_ms = 0.0
    for f in plan.level_fanouts:
        parts = jnp.asarray(r.randn(f, d).astype(np.float32))

        def node_step(pc):
            t = jnp.sum(pc, axis=0)
            return pc.at[0, 0].add(1e-30 * jnp.sum(t ** 2))

        combine_ms += timeit_chained(node_step, parts, reps=reps) * 1e3
    return leaf_ms + combine_ms, leaf_ms, s_g, plan.levels


def merge_artifact(out_path: str, patch_path: str) -> int:
    """Fold a partial re-run into the committed artifact: error-free
    (n, s) scaling rows from the patch replace the main artifact's rows
    (stale errors included), numeric granularity cells replace errored
    ones. Jax-free; records provenance under ``merged_from``."""
    try:
        with open(out_path) as fh:
            main_doc = json.load(fh)
        with open(patch_path) as fh:
            patch = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"decode_study --merge: cannot read artifacts: {e}")
        return 1
    by_key = {(r.get("n"), r.get("s")): r
              for r in patch.get("scaling", []) if "error" not in r}
    replaced = []
    rows = []
    for row in main_doc.get("scaling", []):
        key = (row.get("n"), row.get("s"))
        if key in by_key:
            rows.append(by_key.pop(key))
            replaced.append(key)
        else:
            rows.append(row)
    rows.extend(by_key.values())  # patch rows the main artifact lacked
    replaced.extend(by_key)
    main_doc["scaling"] = sorted(rows, key=lambda r: (r["n"], r["s"]))
    for gran, val in (patch.get("granularity") or {}).items():
        if isinstance(val, (int, float)):
            main_doc.setdefault("granularity", {})[gran] = val
    for meta in ("granularity_network", "granularity_batch_size"):
        if meta in patch:
            main_doc[meta] = patch[meta]
    main_doc["merged_from"] = {
        "patch": os.path.basename(patch_path),
        "replaced": sorted(f"n{n}s{s}" for n, s in replaced),
    }
    with open(out_path, "w") as fh:
        json.dump(main_doc, fh, indent=1)
    print(f"decode_study --merge: {len(replaced)} rows from {patch_path} "
          f"-> {out_path}")
    return 0


def check_artifact(path: str) -> int:
    """Re-verify a committed decode_study.json jax-free: no error rows
    anywhere (ISSUE 17 satellite — the stale n=32 tunnel failures must
    stay purged), numeric granularity cells, and any tree crossover
    columns consistent with their own timings."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"decode_study --check: cannot read {path}: {e}")
        return 1
    rows = data.get("scaling", [])
    if not rows:
        print(f"decode_study --check: no scaling rows in {path}")
        return 1
    for r in rows:
        cell = f"n{r.get('n')}s{r.get('s')}"
        if "error" in r:
            print(f"decode_study --check: {cell}: error row committed "
                  f"({r['error'][:80]}) — re-measure and --merge")
            return 1
        if "skipped" in r:
            continue  # n <= 4s existence gaps are honest, not stale
        for col in ("encode_ms", "decode_ms", "geomedian_ms_same_n"):
            if not isinstance(r.get(col), (int, float)):
                print(f"decode_study --check: {cell}: non-numeric {col}")
                return 1
        if isinstance(r.get("tree_critical_ms"), (int, float)):
            want = bool(r["tree_critical_ms"] < r["decode_ms"])
            if bool(r.get("tree_win")) != want:
                print(f"decode_study --check: {cell}: tree_win disagrees "
                      f"with its own timings")
                return 1
    for gran, val in (data.get("granularity") or {}).items():
        if not isinstance(val, (int, float)):
            print(f"decode_study --check: granularity[{gran}] is not a "
                  f"number: {str(val)[:80]}")
            return 1
    print(f"decode_study --check: {len(rows)} scaling rows clean ({path})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/decode_study.json")
    ap.add_argument("--merge", type=str, default="",
                    help="fold a partial re-run artifact into --out "
                         "(jax-free)")
    ap.add_argument("--check", action="store_true",
                    help="re-verify a committed artifact (jax-free)")
    ap.add_argument("--tree-fanout", type=int, default=0,
                    help="also measure the tree per-node critical path at "
                         "this fan-in next to every scaling row (0 = off)")
    ap.add_argument("--d", type=int, default=0,
                    help="gradient dimension (0 = flagship ResNet-18 dim)")
    ap.add_argument("--ns", type=str, default="8,16,32")
    ap.add_argument("--ss", type=str, default="1,2,3")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--skip-granularity", action="store_true")
    ap.add_argument("--gran-network", type=str, default="ResNet18",
                    help="model for the granularity full-step rows (smoke: "
                         "LeNet)")
    ap.add_argument("--gran-batch-size", type=int, default=32)
    ap.add_argument("--cpu-mesh", type=int, default=0)
    args = ap.parse_args(argv)
    if args.merge:
        return merge_artifact(args.out, args.merge)
    if args.check:
        return check_artifact(args.out)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    # resolves in both contexts: as tools.decode_study (tests) and as a
    # script (the sys.path.insert above puts the repo root first either way)
    from tools.tpu_perf import phase_times

    dev = jax.devices()[0]
    d = args.d
    if not d:
        # flagship dimension without building the model: ResNet-18/CIFAR-10
        # param count, pinned by tests (tests/test_models_optim_data.py)
        d = 11_173_962

    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "grad_dim": d,
        "geomedian_iters": 80,
        "scaling": [],
        # provenance for the full-step rows: a LeNet smoke must never be
        # mistakable for the flagship ResNet18/b32 evidence
        "granularity_network": args.gran_network,
        "granularity_batch_size": args.gran_batch_size,
        "granularity": {},
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)

    def flush():
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1)

    # ---- s / n scaling of isolated phases vs Weiszfeld --------------------
    for n in [int(x) for x in args.ns.split(",")]:
        gm = None
        for s in [int(x) for x in args.ss.split(",")]:
            if n <= 4 * s:  # cyclic existence condition
                report["scaling"].append({"n": n, "s": s,
                                          "skipped": "needs n > 4s"})
                flush()
                continue
            print(f"[decode_study] n={n} s={s} ...", file=sys.stderr,
                  flush=True)
            t0 = time.time()
            try:
                enc_ms, dec_ms = phase_times(n, d, s, reps=args.reps)
                if gm is None:
                    gm = geomedian_ms(n, d, reps=args.reps)
            except Exception as e:
                report["scaling"].append({"n": n, "s": s,
                                          "error": f"{type(e).__name__}: {e}"[:300]})
                flush()
                continue
            row = {
                "n": n, "s": s,
                "encode_ms": round(enc_ms, 3),
                "decode_ms": round(dec_ms, 3),
                "geomedian_ms_same_n": round(gm, 3),
                "decode_vs_geomedian": round(gm / dec_ms, 2),
                "measure_s": round(time.time() - t0, 1),
            }
            if args.tree_fanout:
                tp = tree_phase_times(n, d, s, args.tree_fanout,
                                      reps=args.reps)
                if tp is not None:
                    crit, leaf, s_g, levels = tp
                    row.update(
                        tree_fanout=args.tree_fanout, tree_s_g=s_g,
                        tree_levels=levels,
                        tree_leaf_ms=round(leaf, 3),
                        tree_critical_ms=round(crit, 3),
                        tree_win=bool(crit < dec_ms))
            report["scaling"].append(row)
            print(f"[decode_study] n={n} s={s}: enc {row['encode_ms']} ms, "
                  f"dec {row['decode_ms']} ms, geomed {row['geomedian_ms_same_n']} ms",
                  file=sys.stderr, flush=True)
            flush()

    # ---- decode granularity: global vs per-layer, full train step ---------
    if not args.skip_granularity:
        import bench
        from draco_tpu.data.datasets import load_dataset
        from draco_tpu.runtime import make_mesh

        ds = load_dataset("Cifar10", data_dir="./data")
        mesh = make_mesh(8)
        for gran in ("global", "layer"):
            kw = dict(
                network=args.gran_network, dataset="Cifar10",
                batch_size=args.gran_batch_size,
                lr=0.01, momentum=0.9, num_workers=8, worker_fail=1,
                err_mode="rev_grad", approach="cyclic",
                redundancy="simulate", decode_granularity=gran,
                max_steps=args.steps + 1, eval_freq=0, train_dir="",
                log_every=10**9,
            )
            print(f"[decode_study] granularity={gran} full step ...",
                  file=sys.stderr, flush=True)
            try:
                dt, _loss, _f, _c = bench.run(kw, ds, mesh, args.steps,
                                              warmup=1, reps=2)
                report["granularity"][gran] = round(dt * 1e3, 3)
            except Exception as e:
                report["granularity"][gran] = f"{type(e).__name__}: {e}"[:300]
            flush()

    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
