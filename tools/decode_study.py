#!/usr/bin/env python
"""Decode-granularity and s-scaling study (VERDICT r2 item 7).

Two questions the round-2 evidence left at two data points:

1. How do the isolated encode / decode costs scale with the Byzantine
   budget s ∈ {1, 2, 3} and the worker count n ∈ {8, 16, 32} at the
   flagship gradient dimension — against the Weiszfeld geometric-median
   cost at the same (n, d)? (The "decode stays flat while Weiszfeld
   scales" claim.)
2. What does reference-parity per-layer decode granularity
   (cyclic_master.py:125-129, one locator per parameter tensor) cost vs
   the global one-locator decode, as a full train step?

Writes after every point; a mid-run tunnel loss keeps completed points.

Usage: python tools/decode_study.py [--out baselines_out/decode_study.json]
       [--d 11173962] [--cpu-mesh 8 for smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def geomedian_ms(n, d, iters=80, reps=10):
    """Isolated Weiszfeld cost at (n, d) under the chained-feedback timing
    protocol (utils/timing.py) — the PS-phase cost cyclic decode replaces."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu import aggregation
    from draco_tpu.utils.timing import timeit_chained

    r = np.random.RandomState(0)
    g = jnp.asarray(r.randn(n, d).astype(np.float32))

    def step(gc):
        med = aggregation.geometric_median(gc, iters=iters)
        return gc.at[0, 0].add(1e-30 * jnp.sum(med**2))

    return timeit_chained(step, g, reps=reps) * 1e3


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/decode_study.json")
    ap.add_argument("--d", type=int, default=0,
                    help="gradient dimension (0 = flagship ResNet-18 dim)")
    ap.add_argument("--ns", type=str, default="8,16,32")
    ap.add_argument("--ss", type=str, default="1,2,3")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--skip-granularity", action="store_true")
    ap.add_argument("--gran-network", type=str, default="ResNet18",
                    help="model for the granularity full-step rows (smoke: "
                         "LeNet)")
    ap.add_argument("--gran-batch-size", type=int, default=32)
    ap.add_argument("--cpu-mesh", type=int, default=0)
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    # resolves in both contexts: as tools.decode_study (tests) and as a
    # script (the sys.path.insert above puts the repo root first either way)
    from tools.tpu_perf import phase_times

    dev = jax.devices()[0]
    d = args.d
    if not d:
        # flagship dimension without building the model: ResNet-18/CIFAR-10
        # param count, pinned by tests (tests/test_models_optim_data.py)
        d = 11_173_962

    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "grad_dim": d,
        "geomedian_iters": 80,
        "scaling": [],
        # provenance for the full-step rows: a LeNet smoke must never be
        # mistakable for the flagship ResNet18/b32 evidence
        "granularity_network": args.gran_network,
        "granularity_batch_size": args.gran_batch_size,
        "granularity": {},
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)

    def flush():
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1)

    # ---- s / n scaling of isolated phases vs Weiszfeld --------------------
    for n in [int(x) for x in args.ns.split(",")]:
        gm = None
        for s in [int(x) for x in args.ss.split(",")]:
            if n <= 4 * s:  # cyclic existence condition
                report["scaling"].append({"n": n, "s": s,
                                          "skipped": "needs n > 4s"})
                flush()
                continue
            print(f"[decode_study] n={n} s={s} ...", file=sys.stderr,
                  flush=True)
            t0 = time.time()
            try:
                enc_ms, dec_ms = phase_times(n, d, s, reps=args.reps)
                if gm is None:
                    gm = geomedian_ms(n, d, reps=args.reps)
            except Exception as e:
                report["scaling"].append({"n": n, "s": s,
                                          "error": f"{type(e).__name__}: {e}"[:300]})
                flush()
                continue
            row = {
                "n": n, "s": s,
                "encode_ms": round(enc_ms, 3),
                "decode_ms": round(dec_ms, 3),
                "geomedian_ms_same_n": round(gm, 3),
                "decode_vs_geomedian": round(gm / dec_ms, 2),
                "measure_s": round(time.time() - t0, 1),
            }
            report["scaling"].append(row)
            print(f"[decode_study] n={n} s={s}: enc {row['encode_ms']} ms, "
                  f"dec {row['decode_ms']} ms, geomed {row['geomedian_ms_same_n']} ms",
                  file=sys.stderr, flush=True)
            flush()

    # ---- decode granularity: global vs per-layer, full train step ---------
    if not args.skip_granularity:
        import bench
        from draco_tpu.data.datasets import load_dataset
        from draco_tpu.runtime import make_mesh

        ds = load_dataset("Cifar10", data_dir="./data")
        mesh = make_mesh(8)
        for gran in ("global", "layer"):
            kw = dict(
                network=args.gran_network, dataset="Cifar10",
                batch_size=args.gran_batch_size,
                lr=0.01, momentum=0.9, num_workers=8, worker_fail=1,
                err_mode="rev_grad", approach="cyclic",
                redundancy="simulate", decode_granularity=gran,
                max_steps=args.steps + 1, eval_freq=0, train_dir="",
                log_every=10**9,
            )
            print(f"[decode_study] granularity={gran} full step ...",
                  file=sys.stderr, flush=True)
            try:
                dt, _loss, _f, _c = bench.run(kw, ds, mesh, args.steps,
                                              warmup=1, reps=2)
                report["granularity"][gran] = round(dt * 1e3, 3)
            except Exception as e:
                report["granularity"][gran] = f"{type(e).__name__}: {e}"[:300]
            flush()

    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
