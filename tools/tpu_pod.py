#!/usr/bin/env python
"""TPU pod / multi-host cluster tooling — the reference's EC2 launcher,
re-targeted at Cloud TPU.

Parity with tools/pytorch_ec2.py (reference: 975 lines of boto3+paramiko:
``launch``, ``get_hosts``, ``run_ssh_commands_parallel``, ``kill_all_python``,
``terminate_all_instances``, NFS setup): each subcommand shells out to
``gcloud compute tpus tpu-vm`` (the supported control plane — no raw REST),
fans commands out to every pod worker with ``--worker=all``, and writes the
``hosts_address`` file the reference's scripts expect. ``--dry-run`` prints
every command instead of executing, so the control flow is testable without
GCP credentials.

Typical session:
  python tools/tpu_pod.py launch   --name draco-pod --type v5e-16
  python tools/tpu_pod.py hosts    --name draco-pod           # -> hosts_address
  python tools/tpu_pod.py push     --name draco-pod --src . --dst '~/draco_tpu'
  python tools/tpu_pod.py train    --name draco-pod -- --approach cyclic \
      --network ResNet18 --dataset Cifar10 --num-workers 16 --worker-fail 3
  python tools/tpu_pod.py kill     --name draco-pod
  python tools/tpu_pod.py terminate --name draco-pod

Multi-host wiring: on a TPU pod slice, JAX discovers the coordinator from the
TPU metadata — no DRACO_* env needed (draco_tpu.runtime.init_distributed is
a no-op and jax.distributed.initialize() auto-configures). The DRACO_* envs
exist for CPU simulation (tools/local_cluster.py) and non-TPU fleets.
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys

DEFAULTS = {
    "zone": "us-central2-b",
    "project": None,  # use gcloud's configured default
    "type": "v5litepod-16",
    "version": "tpu-ubuntu2204-base",
}


def _gcloud(args: argparse.Namespace, *sub: str) -> list[str]:
    cmd = ["gcloud", "compute", "tpus", "tpu-vm", *sub, "--zone", args.zone]
    if args.project:
        cmd += ["--project", args.project]
    return cmd


def _run(args: argparse.Namespace, cmd: list[str], capture: bool = False):
    print("+ " + " ".join(shlex.quote(c) for c in cmd), flush=True)
    if args.dry_run:
        return ""
    out = subprocess.run(cmd, check=True, text=True,
                         capture_output=capture)
    return out.stdout if capture else ""


def cmd_launch(args):
    """Create the pod slice (reference: pytorch_ec2.py `launch`)."""
    _run(args, _gcloud(args, "create", args.name) + [
        "--accelerator-type", args.type,
        "--version", args.version,
        *(["--spot"] if args.spot else []),
    ])


def cmd_hosts(args):
    """Write hosts_address (reference writes PS ip first; here all hosts are
    symmetric — there is no PS rank)."""
    out = _run(args, _gcloud(args, "describe", args.name) + [
        "--format", "value(networkEndpoints[].ipAddress)",
    ], capture=True)
    hosts = [h for h in out.replace(";", "\n").split() if h]
    if not args.dry_run:
        with open(args.hostfile, "w") as fh:
            fh.write("\n".join(hosts) + "\n")
        print(f"wrote {len(hosts)} hosts to {args.hostfile}")


def cmd_run(args):
    """Fan a shell command out to every pod worker (reference:
    run_ssh_commands_parallel)."""
    _run(args, _gcloud(args, "ssh", args.name) + [
        "--worker=all", "--command", args.command,
    ])


def cmd_push(args):
    """Copy the working tree to every worker (replaces the reference's
    NFS shared dir, pytorch_ec2.py setup_nfs)."""
    _run(args, _gcloud(args, "scp", "--recurse", args.src,
                       f"{args.name}:{args.dst}") + ["--worker=all"])


def cmd_train(args):
    """Start training on every worker; JAX auto-discovers the pod topology."""
    train_args = " ".join(shlex.quote(a) for a in args.train_args)
    inner = (
        f"cd {shlex.quote(args.dst)} && "
        f"nohup python -m draco_tpu.cli {train_args} "
        f"> train_$(hostname).log 2>&1 &"
    )
    _run(args, _gcloud(args, "ssh", args.name) + [
        "--worker=all", "--command", inner,
    ])


def cmd_kill(args):
    """Stop all python on the pod (reference: kill_all_python)."""
    _run(args, _gcloud(args, "ssh", args.name) + [
        "--worker=all", "--command", "pkill -9 -f python || true",
    ])


def cmd_terminate(args):
    """Delete the slice (reference: terminate_all_instances)."""
    _run(args, _gcloud(args, "delete", args.name) + ["--quiet"])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="draco_tpu pod tooling")
    ap.add_argument("--zone", default=DEFAULTS["zone"])
    ap.add_argument("--project", default=DEFAULTS["project"])
    ap.add_argument("--dry-run", action="store_true",
                    help="print gcloud commands without executing")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("launch", help=cmd_launch.__doc__)
    p.add_argument("--name", required=True)
    p.add_argument("--type", default=DEFAULTS["type"])
    p.add_argument("--version", default=DEFAULTS["version"])
    p.add_argument("--spot", action="store_true",
                   help="preemptible capacity (the reference used EC2 spot)")
    p.set_defaults(fn=cmd_launch)

    p = sub.add_parser("hosts", help=cmd_hosts.__doc__)
    p.add_argument("--name", required=True)
    p.add_argument("--hostfile", default="hosts_address")
    p.set_defaults(fn=cmd_hosts)

    p = sub.add_parser("run", help=cmd_run.__doc__)
    p.add_argument("--name", required=True)
    p.add_argument("--command", required=True)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("push", help=cmd_push.__doc__)
    p.add_argument("--name", required=True)
    p.add_argument("--src", default=".")
    p.add_argument("--dst", default="~/draco_tpu")
    p.set_defaults(fn=cmd_push)

    p = sub.add_parser("train", help=cmd_train.__doc__)
    p.add_argument("--name", required=True)
    p.add_argument("--dst", default="~/draco_tpu")
    p.add_argument("train_args", nargs=argparse.REMAINDER,
                   help="arguments forwarded to draco_tpu.cli (prefix with --)")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("kill", help=cmd_kill.__doc__)
    p.add_argument("--name", required=True)
    p.set_defaults(fn=cmd_kill)

    p = sub.add_parser("terminate", help=cmd_terminate.__doc__)
    p.add_argument("--name", required=True)
    p.set_defaults(fn=cmd_terminate)

    args = ap.parse_args(argv)
    if getattr(args, "train_args", None) and args.train_args[0] == "--":
        args.train_args = args.train_args[1:]
    try:
        args.fn(args)
    except subprocess.CalledProcessError as e:
        print(f"command failed with exit {e.returncode}", file=sys.stderr)
        return e.returncode
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
