#!/usr/bin/env python
"""Host-loop overhead microbench: eager per-step dispatch vs the scan-chunked
trainer (cfg.steps_per_call = K), measured on the PRODUCTION ``Trainer.run``
path — not a synthetic harness.

The eager loop pays, per step: one jitted dispatch, a per-metric device
fetch, a ``block_until_ready``, and a fresh device_put (PERF.md §0 documents
~70 ms of host/RTT cost per dispatch on the remote tunnel; on local CPU the
same costs are tens of microseconds but still per-step). The chunked loop
pays them once per K steps. This tool times both regimes over the same
config/seed/steps and emits a JSON artifact so the win (or the CPU caveat)
is recorded per-platform.

Model default is FC on synthetic MNIST: matmul-only, so XLA:CPU's
single-threaded scan-body conv execution (PERF.md §4) does not distort the
host-overhead comparison on the CPU mesh. Conv nets on CPU should keep
steps_per_call=1 regardless of what this tool reports for FC.

``--lm`` switches the measured loop to the production TransformerLM token
loop (parallel/token_loop.run_token_loop on the folded tp route): eager
per-step dispatch vs the scan-chunked ``train_token_many`` driver, same
config/seed/steps. TransformerLM is matmul-dominated like FC, so the
XLA:CPU scanned-conv caveat does not apply there either — the artifact
records that directly (chunked vs eager on the same CPU mesh).

Per K the artifact now records compile and steady-state wall SEPARATELY
(ISSUE 5): the warmup pass's executable-build cost (lower + backend
compile seconds observed via the compile sentinel's process-wide counters,
obs/compile_watch.py ``global_stats``) lands in
``compile_ms_by_steps_per_call`` while the timed pass remains pure
steady-state — and ``timed_builds_by_steps_per_call`` records how many
builds fired DURING the timed window (must be 0; anything else means the
timed number silently included a retrace). That split is what makes the
K-sweep comparable across rounds: tools/perf_watch.py diffs both series
against the committed snapshot.

Output: one JSON (default baselines_out/host_loop_overhead.json;
--lm defaults to baselines_out/host_loop_overhead_lm.json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_split(fn_warm, fn_timed):
    """Run warmup then the timed section, splitting executable-build cost
    (lower + backend compile seconds, process-wide jax.monitoring counters:
    obs/compile_watch.global_stats) out of each: returns
    ``(timed_result, {"compile_ms", "timed_builds", "timed_compile_ms"})``.
    ``timed_builds`` must be 0 — a build inside the timed window means the
    steady-state number silently absorbed a retrace."""
    from draco_tpu.obs.compile_watch import global_stats, install

    install()
    t_start = global_stats()
    fn_warm()
    t_mid = global_stats()
    result = fn_timed()
    t_end = global_stats()

    def cost_ms(a, b):
        return round((b["lower_s"] - a["lower_s"]
                      + b["compile_s"] - a["compile_s"]) * 1000.0, 1)

    return result, {
        "compile_ms": cost_ms(t_start, t_mid),
        "timed_builds": t_end["builds"] - t_mid["builds"],
        "timed_compile_ms": cost_ms(t_mid, t_end),
    }


def measure_loop(cfg_kwargs: dict, ds, mesh, warmup_steps: int,
                 timed_steps: int) -> "tuple[float, dict]":
    """(ms/step, compile split) of Trainer.run over ``timed_steps`` steps,
    after a warmup run that settles compilation (main chunk shape) and the
    prefetch pipeline."""
    import jax

    from draco_tpu.config import TrainConfig
    from draco_tpu.training.trainer import Trainer

    cfg = TrainConfig(**cfg_kwargs)
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    try:
        def warm():
            tr.run(max_steps=warmup_steps)
            jax.block_until_ready(tr.state.params)

        def timed():
            t0 = time.perf_counter()
            tr.run(max_steps=warmup_steps + timed_steps)
            jax.block_until_ready(tr.state.params)
            return (time.perf_counter() - t0) / timed_steps * 1000.0

        return _build_split(warm, timed)
    finally:
        tr.close()


def measure_lm_loop(cfg_kwargs: dict, mesh, warmup_steps: int,
                    timed_steps: int) -> "tuple[float, dict]":
    """(ms/step, compile split) of the production run_token_loop over
    ``timed_steps`` steps.

    A warmup pass on a deep-copied state settles compilation (the jitted
    programs are cached on the setup's callables, keyed by chunk shape), then
    the timed pass runs the setup's own state — train_step/train_token_many
    donate their carry, so each state tree drives at most one loop."""
    import jax
    import jax.numpy as jnp

    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.token_loop import run_token_loop
    from draco_tpu.parallel.tp_step import build_tp_train_setup

    cfg = TrainConfig(**cfg_kwargs)
    setup = build_tp_train_setup(cfg, mesh)
    warm_setup = setup._replace(state=jax.tree.map(jnp.copy, setup.state))

    def warm():
        st, _ = run_token_loop(warm_setup, cfg, steps=warmup_steps,
                               quiet=True)
        jax.block_until_ready(st.params)

    def timed():
        t0 = time.perf_counter()
        st, _ = run_token_loop(setup, cfg, steps=timed_steps, quiet=True)
        jax.block_until_ready(st.params)
        return (time.perf_counter() - t0) / timed_steps * 1000.0

    return _build_split(warm, timed)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="")
    ap.add_argument("--lm", action="store_true",
                    help="measure the TransformerLM token loop "
                         "(parallel/token_loop.py, folded tp route) instead "
                         "of the CNN Trainer")
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--model-dim", type=int, default=64)
    ap.add_argument("--model-heads", type=int, default=2)
    ap.add_argument("--model-layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--network", type=str, default="FC")
    ap.add_argument("--dataset", type=str, default="synthetic-mnist")
    ap.add_argument("--approach", type=str, default="cyclic")
    ap.add_argument("--worker-fail", type=int, default=1)
    ap.add_argument("--err-mode", type=str, default="rev_grad")
    ap.add_argument("--num-workers", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--steps", type=int, default=64,
                    help="timed steps per regime (each K must divide it)")
    ap.add_argument("--ks", type=str, default="1,8,16",
                    help="comma list of steps_per_call values; 1 = eager")
    ap.add_argument("--cpu-mesh", type=int, default=0)
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    ks = sorted({max(int(k), 1) for k in args.ks.split(",")})
    if 1 not in ks:
        ks = [1] + ks
    for k in ks:
        if args.steps % k:
            raise SystemExit(f"--steps {args.steps} must be divisible by K={k}")

    dev = jax.devices()[0]
    if args.lm:
        from draco_tpu.parallel.mesh import make_folded_wtp_mesh

        mesh = make_folded_wtp_mesh(args.num_workers)
        common = dict(
            network="TransformerLM", dataset="synthetic-text",
            approach=args.approach, worker_fail=args.worker_fail,
            err_mode=args.err_mode, num_workers=args.num_workers,
            batch_size=args.batch_size, lr=0.01, momentum=0.9,
            seq_len=args.seq_len, vocab=args.vocab,
            model_dim=args.model_dim, model_heads=args.model_heads,
            model_layers=args.model_layers,
            max_steps=2 * args.steps + max(ks), eval_freq=0, train_dir="",
            log_every=10**9,
        )
        cfg_report = {
            "network": "TransformerLM", "dataset": "synthetic-text",
            "loop": "parallel/token_loop.run_token_loop (folded tp route)",
            "approach": args.approach, "worker_fail": args.worker_fail,
            "err_mode": args.err_mode, "num_workers": args.num_workers,
            "batch_size_per_worker": args.batch_size,
            "seq_len": args.seq_len, "model_dim": args.model_dim,
            "model_heads": args.model_heads,
            "model_layers": args.model_layers, "vocab": args.vocab,
            "timed_steps": args.steps,
        }
    else:
        from draco_tpu.data.datasets import load_dataset
        from draco_tpu.runtime import make_mesh

        ds = load_dataset(args.dataset, synthetic_train=4096,
                          synthetic_test=128)
        mesh = make_mesh(args.num_workers)
        common = dict(
            network=args.network, dataset=args.dataset,
            approach=args.approach, worker_fail=args.worker_fail,
            err_mode=args.err_mode, num_workers=args.num_workers,
            batch_size=args.batch_size, lr=0.01, momentum=0.9,
            max_steps=2 * args.steps + max(ks), eval_freq=0, train_dir="",
            log_every=10**9,
        )
        cfg_report = {
            "network": args.network, "dataset": args.dataset,
            "approach": args.approach, "worker_fail": args.worker_fail,
            "err_mode": args.err_mode, "num_workers": args.num_workers,
            "batch_size_per_worker": args.batch_size,
            "timed_steps": args.steps,
        }

    rows, compile_rows, timed_builds = {}, {}, {}
    for k in ks:
        if args.lm:
            ms, split = measure_lm_loop(dict(common, steps_per_call=k), mesh,
                                        warmup_steps=k,
                                        timed_steps=args.steps)
        else:
            ms, split = measure_loop(dict(common, steps_per_call=k), ds,
                                     mesh, warmup_steps=k,
                                     timed_steps=args.steps)
        rows[str(k)] = round(ms, 4)
        compile_rows[str(k)] = split["compile_ms"]
        timed_builds[str(k)] = split["timed_builds"]
        print(f"K={k}: {ms:.3f} ms/step steady "
              f"(compile {split['compile_ms']:.0f} ms in warmup, "
              f"{split['timed_builds']} builds in the timed window)",
              flush=True)

    eager = rows["1"]
    big_ks = [k for k in ks if k >= 8]
    best_big = min((rows[str(k)] for k in big_ks), default=None)
    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "mode": "lm_token_loop" if args.lm else "cnn_trainer",
        "config": cfg_report,
        "ms_per_step_by_steps_per_call": rows,
        # compile vs steady-state split (ISSUE 5): warmup-pass executable
        # build cost per K, and builds observed during the timed window
        # (must be 0 — else ms/step silently absorbed a retrace); both are
        # perf_watch series
        "compile_ms_by_steps_per_call": compile_rows,
        "timed_builds_by_steps_per_call": timed_builds,
        "eager_ms_per_step": eager,
        "best_chunked_k8plus_ms_per_step": best_big,
        "overhead_saved_ms_per_step": (
            round(eager - best_big, 4) if best_big is not None else None
        ),
        "chunked_k8plus_lowers_overhead": (
            best_big is not None and best_big < eager
        ),
    }
    if not args.out:
        args.out = ("baselines_out/host_loop_overhead_lm.json" if args.lm
                    else "baselines_out/host_loop_overhead.json")
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
