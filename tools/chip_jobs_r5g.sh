#!/bin/bash
# Round-5 chain g: re-warm bench.py's programs after the in-graph
# projection change (commit 8fdd1f5 touched the cyclic training step, so
# the persistent compile cache is stale for bench's cyclic legs — the
# driver's end-of-round budget-280 bench must find warm programs or it
# eats cold compiles). Also records the warmed bench as evidence.
# Parks until chains r5/r5b/r5c/r5d/r5e/r5f are gone.
#
# Launch detached:
#   setsid nohup bash tools/chip_jobs_r5g.sh > baselines_out/chip_jobs_r5g.log 2>&1 &
# NEVER edit this file while it runs. Markers: baselines_out/.r5g_<rung>_done
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5g $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5g $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5g $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5g $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5g $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5g $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

tpu_up() {
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

others_running() {
  for s in chip_jobs_r5.sh chip_jobs_r5b.sh chip_jobs_r5c.sh \
           chip_jobs_r5d.sh chip_jobs_r5e.sh chip_jobs_r5f.sh \
           chip_jobs_r5h.sh chip_jobs_r5i.sh; do
    pgrep -f "bash tools/$s" > /dev/null 2>&1 && return 0
  done
  return 1
}

echo "[r5g $(stamp)] waiting for chains r5..r5f and r5h to finish"
while others_running; do
  sleep 60
done
echo "[r5g $(stamp)] predecessors gone; proceeding"

ABORT_PASS=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5g_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5g $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5g $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    if ! tpu_up; then
      echo "[r5g $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

all_done() {
  for m in bench_warm bench_280; do
    [ -f "baselines_out/.r5g_${m}_done" ] || return 1
  done
  return 0
}

bench_warm_rung() {
  timeout -k 60 1500 python bench.py --budget 1200 \
    > baselines_out/bench_warm_r5g.json
}

bench_280_rung() {
  timeout -k 60 400 python bench.py \
    > baselines_out/bench_280_r5g.json
}

for outer in 1 2 3; do
  echo "[r5g $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5g $(stamp)] tunnel never came up this window"; continue; }
  ABORT_PASS=0

  rung bench_warm "chip evidence: warmed driver bench after in-graph projection change" \
    bench_warm_rung

  rung bench_280 "chip evidence: budget-280 driver-format bench on warm cache (post-fix step)" \
    bench_280_rung

  if all_done; then
    echo "[r5g $(stamp)] BENCH RE-WARM COMPLETE"
    break
  fi
  echo "[r5g $(stamp)] incomplete; retrying"
  sleep 120
done
all_done && exit 0 || exit 1
