#!/usr/bin/env python
"""Transformer-scale time-to-target-loss under attack (the LM analogue of
tools/time_to_acc.py — VERDICT r3 evidence item: convergence curves at
ResNet-18/LM scale on TPU).

For each variant (cyclic simulate/shared, geo-median, mean under attack,
mean no-attack) the coded LM step (parallel/tp_step.py, n logical workers
vmapped over the available chips) trains on the deterministic synthetic
token stream, pausing every --eval-every steps to score a FIXED held-out
token set (disjoint seed namespace), until eval loss <= --target or
--max-steps. The reference's convergence oracle is held-out metrics from a
separate evaluator process (src/distributed_evaluator.py:92-110); here the
oracle is the same held-out principle at transformer scale.

Wall-clock: train blocks are ONE jitted lax.scan each (utils/timing.py
tunnel discipline), synced by a device->host loss fetch, RTT subtracted;
eval time is excluded from the train clock. Mean-under-attack is expected
NOT to reach the target — its curve records the damage an undefended
aggregator takes at LM scale.

Output JSON (--out): per-variant curves [(step, train_wall_s, eval_loss)],
reached/missed target, plus config. Rewritten after every variant so a
mid-run tunnel loss keeps finished variants.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EVAL_SEED_STRIDE = 999_983  # disjoint from every training (seed, step) pair


def run_variant(cfg_kwargs, mesh, args, rtt):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu import rng as drng
    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.sp_step import synthetic_text
    from draco_tpu.parallel.tp_step import build_tp_train_setup
    from draco_tpu.utils.timing import fetch_scalar

    cfg = TrainConfig(**cfg_kwargs)
    setup = build_tp_train_setup(cfg, mesh)
    # blocks are fixed-shape compiled scans, so the last block runs whole
    # even when max_steps isn't a multiple of eval_every (up to
    # eval_every-1 extra steps, reported in the curve); the schedule must
    # cover that overhang
    adv = drng.adversary_schedule(
        cfg.seed, args.max_steps + args.eval_every + 1,
        cfg.num_workers, cfg.num_adversaries)
    # held-out eval set: same distribution, disjoint seed namespace
    eval_toks = jnp.asarray(synthetic_text(
        cfg.seed + EVAL_SEED_STRIDE, 0, args.eval_batches, cfg.batch_size,
        cfg.seq_len, cfg.vocab))

    def loop(state, xs, ms):
        def body(st, batch):
            toks, mask = batch
            st, metrics = setup.train_step(st, toks, mask)
            return st, metrics["loss"]
        return jax.lax.scan(body, state, (xs, ms))

    block = args.eval_every

    def stage(lo):  # train batches for steps [lo, lo+block)
        xs = jnp.asarray(np.stack([
            synthetic_text(cfg.seed, s, cfg.num_workers, cfg.batch_size,
                           cfg.seq_len, cfg.vocab)
            for s in range(lo, lo + block)
        ]))
        ms = jnp.asarray(np.stack(
            [np.asarray(adv[s]) for s in range(lo, lo + block)]))
        return xs, ms

    with mesh:
        xs0, ms0 = stage(1)
        compiled = jax.jit(loop).lower(setup.state, xs0, ms0).compile()

    state = setup.state
    curve, wall, reached = [], 0.0, None
    e0 = float(setup.eval_step(state.params, eval_toks))
    curve.append({"step": 0, "train_wall_s": 0.0, "eval_loss": round(e0, 4)})
    step = 1
    while step <= args.max_steps:
        xs, ms = (xs0, ms0) if step == 1 else stage(step)
        jax.block_until_ready((xs, ms))  # stage off the timed path
        t0 = time.perf_counter()
        state, losses = compiled(state, xs, ms)
        fetch_scalar(losses)  # real completion barrier through the tunnel
        wall += max(time.perf_counter() - t0 - rtt, 0.0)
        hi = step + block - 1
        eloss = float(setup.eval_step(state.params, eval_toks))
        curve.append({"step": hi, "train_wall_s": round(wall, 3),
                      "eval_loss": round(eloss, 4)})
        if eloss <= args.target and reached is None:
            reached = curve[-1]
            break
        step = hi + 1
    return {"curve": curve, "reached": reached,
            "final_eval_loss": curve[-1]["eval_loss"],
            "train_wall_s": round(wall, 3)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/lm_time_to_loss.json")
    ap.add_argument("--num-workers", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--model-dim", type=int, default=768)
    ap.add_argument("--model-heads", type=int, default=12)
    ap.add_argument("--model-layers", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--target", type=float, default=1.0)
    ap.add_argument("--eval-every", type=int, default=10)
    ap.add_argument("--eval-batches", type=int, default=8)
    ap.add_argument("--max-steps", type=int, default=120)
    ap.add_argument("--variants", type=str, default="",
                    help="comma-separated subset to run")
    ap.add_argument("--cpu-mesh", type=int, default=0)
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    from draco_tpu.parallel.mesh import make_folded_wtp_mesh
    from draco_tpu.utils.timing import measure_rtt

    mesh = make_folded_wtp_mesh(args.num_workers)
    dev = jax.devices()[0]

    common = dict(
        network="TransformerLM", dataset="synthetic-text",
        batch_size=args.batch_size, lr=args.lr, momentum=0.9,
        num_workers=args.num_workers, worker_fail=1, err_mode="rev_grad",
        seq_len=args.seq_len, vocab=args.vocab, model_dim=args.model_dim,
        model_heads=args.model_heads, model_layers=args.model_layers,
        compute_dtype="bfloat16", max_steps=args.max_steps + 1, eval_freq=0,
        train_dir="", log_every=10**9,
    )
    variants = {
        "lm_cyclic_s1_simulate": dict(common, approach="cyclic",
                                      redundancy="simulate"),
        "lm_cyclic_s1_shared": dict(common, approach="cyclic",
                                    redundancy="shared"),
        "lm_geomedian": dict(common, approach="baseline",
                             mode="geometric_median"),
        "lm_mean_under_attack": dict(common, approach="baseline",
                                     mode="normal"),
        "lm_mean_no_attack": dict(common, approach="baseline", mode="normal",
                                  worker_fail=0),
    }
    if args.variants:
        keep = {v.strip() for v in args.variants.split(",")}
        variants = {k: v for k, v in variants.items() if k in keep}
        if not variants:
            raise SystemExit(f"no variants match {sorted(keep)}")

    rtt = 0.0 if dev.platform == "cpu" else measure_rtt()
    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "num_workers": args.num_workers,
        "batch_size_per_worker": args.batch_size,
        "seq_len": args.seq_len, "model_dim": args.model_dim,
        "model_layers": args.model_layers, "vocab": args.vocab,
        "target_eval_loss": args.target, "eval_every": args.eval_every,
        "rtt_s": round(rtt, 4),
        "variants": {},
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    rc = 0
    for name, kw in variants.items():
        print(f"[lm_tta] {name} ...", file=sys.stderr, flush=True)
        try:
            res = run_variant(kw, mesh, args, rtt)
        except Exception as e:
            res = {"error": f"{type(e).__name__}: {e}"[:300]}
            rc = 1
        print(f"[lm_tta] {name}: "
              f"{json.dumps({k: v for k, v in res.items() if k != 'curve'})}",
              file=sys.stderr, flush=True)
        report["variants"][name] = res
        with open(args.out, "w") as fh:  # keep finished variants on loss
            json.dump(report, fh, indent=1)
    print(json.dumps({k: v for k, v in report.items() if k != "variants"}))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
