#!/bin/bash
# Round-3 chip job chain: wait for the tunnel TPU, then run every pending
# hardware study in priority order (one client at a time per the tunnel
# discipline). Each step is independent — a failure or a mid-chain tunnel
# loss keeps earlier artifacts, but the exit code reflects any failure.
# Safe to re-run; artifacts land in baselines_out/.
#
# Priority order mirrors VERDICT r2 "Next round: do this":
#   1. bench.py sanity (the driver-captured headline must land)
#   2. flash-attention hardware check (item 2 — never Mosaic-compiled)
#   3. long-context remat LM run (item 2)
#   4. LM simulate-vs-shared at d~63M (item 6)
#   5. batch x dtype MFU sweep (item 4)
#   6. decode s/n scaling + per-layer granularity (item 7)
#   7. TPU time-to-accuracy: ResNet-18 cyclic vs geo-median, eval every 5
#      (item 3)
set -u
cd "$(dirname "$0")/.."

tools/wait_tpu.sh 60 150 120 || exit 3

FAILURES=0
run() {
  echo "[chip_jobs_r3] ===== $* ====="
  if ! "$@"; then
    echo "[chip_jobs_r3] FAILED (continuing): $*"
    FAILURES=$((FAILURES + 1))
  fi
}

run python bench.py --budget 280
run python tools/tpu_attn_check.py --out baselines_out/tpu_attn.json
run python tools/tpu_lm_perf.py --remat --batch-size 8 --seq-len 1024 --steps 3 \
  --variants lm_cyclic_s1_shared_bf16,lm_mean_no_attack_bf16 \
  --out baselines_out/tpu_lm_perf_long.json
run python tools/tpu_lm_perf.py --steps 4 \
  --variants lm_cyclic_s1_shared_bf16,lm_cyclic_s1_simulate_bf16,lm_geomedian_bf16 \
  --out baselines_out/tpu_lm_perf_simulate.json
run python tools/tpu_sweep.py --out baselines_out/tpu_sweep.json
run python tools/decode_study.py --out baselines_out/decode_study.json
run python tools/time_to_acc.py --network ResNet18 --dataset Cifar10 \
  --approach cyclic --redundancy simulate --eval-every 5 --max-steps 300 \
  --target 0.9 --out baselines_out/tpu_tta_resnet_cyclic.json
run python tools/time_to_acc.py --network ResNet18 --dataset Cifar10 \
  --approach baseline --mode geometric_median --eval-every 5 --max-steps 300 \
  --target 0.9 --out baselines_out/tpu_tta_resnet_geomedian.json
echo "[chip_jobs_r3] done ($FAILURES failures)"
exit $((FAILURES > 0 ? 1 : 0))
