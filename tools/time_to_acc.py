#!/usr/bin/env python
"""Time-to-accuracy measurement (BASELINE.md's second north-star axis).

The bench image has no real MNIST/CIFAR files and no network egress
(documented in PERF.md): the strongest available substitute is the
deterministic class-conditional synthetic sets (draco_tpu/data/datasets.py
``_synthetic`` — learnable, with a held-out test split), standing in for the
reference's convergence oracle (src/distributed_evaluator.py:92-110).

Trains a config, evaluating every ``--eval-every`` steps, until test top-1
reaches --target or --max-steps; records the (wall-clock, step, accuracy)
curve. Wall-clock covers train steps only (eval excluded), timed with the
fetch-synchronised protocol per eval block.

Output: one JSON (default baselines_out/time_to_acc.json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="baselines_out/time_to_acc.json")
    ap.add_argument("--network", type=str, default="LeNet")
    ap.add_argument("--dataset", type=str, default="synthetic-mnist")
    ap.add_argument("--approach", type=str, default="cyclic")
    ap.add_argument("--mode", type=str, default="normal",
                    help="aggregation for --approach baseline")
    ap.add_argument("--worker-fail", type=int, default=1)
    ap.add_argument("--err-mode", type=str, default="rev_grad")
    ap.add_argument("--adversarial", type=float, default=-100.0,
                    help="attack magnitude (reference default -100; alie/ipm "
                         "scale linearly relative to it)")
    ap.add_argument("--redundancy", type=str, default="simulate",
                    help="cyclic compute regime: simulate (reference-parity "
                         "2s+1 lanes) | shared (one-copy fast path)")
    ap.add_argument("--group-size", type=int, default=3,
                    help="repetition redundancy r for --approach maj_vote")
    ap.add_argument("--num-workers", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--target", type=float, default=0.98)
    ap.add_argument("--eval-every", type=int, default=50)
    ap.add_argument("--max-steps", type=int, default=1500)
    ap.add_argument("--steps-per-call", type=int, default=1,
                    help="K steps fused per device program (the production "
                         "scan-chunked loop); keep 1 on CPU (PERF.md §4)")
    ap.add_argument("--cpu-mesh", type=int, default=0)
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    from draco_tpu.config import TrainConfig
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer
    from draco_tpu.utils.timing import fetch_scalar, measure_rtt

    cfg = TrainConfig(
        network=args.network, dataset=args.dataset, approach=args.approach,
        mode=args.mode, redundancy=args.redundancy,
        group_size=args.group_size,
        batch_size=args.batch_size, lr=args.lr, momentum=0.9,
        num_workers=args.num_workers, worker_fail=args.worker_fail,
        err_mode=args.err_mode, adversarial=args.adversarial,
        max_steps=args.max_steps, eval_freq=0,
        steps_per_call=args.steps_per_call,
        train_dir="", log_every=10**9,
    )
    ds = load_dataset(cfg.dataset, cfg.data_dir)
    mesh = make_mesh(cfg.num_workers)
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    dev = jax.devices()[0]
    rtt = measure_rtt()

    curve = []
    train_s = 0.0
    reached = None
    step = 1
    try:
        while step <= args.max_steps:
            hi = min(step + args.eval_every - 1, args.max_steps)
            t0 = time.perf_counter()
            # run() advances its cursor on return, so successive calls train
            # blocks [step, hi] without retraining from step 1
            last = tr.run(max_steps=hi)
            fetch_scalar(tr.state.params)
            train_s += max(time.perf_counter() - t0 - rtt, 0.0)
            rec = tr.evaluate(hi)
            curve.append({
                "step": hi,
                "train_wall_s": round(train_s, 3),
                "prec1_test": round(rec["prec1_test"], 4),
                "loss": round(last.get("loss", float("nan")), 4),
            })
            if rec["prec1_test"] >= args.target and reached is None:
                reached = curve[-1]
                break
            step = hi + 1
    finally:
        tr.close()

    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "config": {
            "network": args.network, "dataset": ds.name,
            "approach": args.approach, "mode": args.mode,
            "redundancy": args.redundancy, "group_size": args.group_size,
            "worker_fail": args.worker_fail,
            "err_mode": args.err_mode, "adversarial": args.adversarial,
            "num_workers": args.num_workers,
            "batch_size_per_worker": args.batch_size, "lr": args.lr,
            "steps_per_call": args.steps_per_call,
        },
        "target_prec1": args.target,
        "reached": reached,
        "curve": curve,
        "real_data_available": not ds.synthetic,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps(report))
    return 0 if reached is not None else 1


if __name__ == "__main__":
    raise SystemExit(main())
