#!/usr/bin/env python
"""Wire study: what would a bf16/int8 worker→aggregator wire do to decode
error and Byzantine detection? — ISSUE 10's committed evidence, measured by
the shadow-quantized wire (obs/numerics.py) on the production chunked loop.

ROADMAP item 4 will narrow the coded wire; this study is the measurement
foundation it gets built and regression-gated on. Each cell trains the same
FC/synthetic-mnist workload under {cyclic, maj_vote, approx} ×
{bf16, int8} × K∈{1,4} with ``numerics_watch=on`` and ``shadow_wire`` set —
the f32 path alone updates params, the shadow decode of the quantized
codewords rides the same step body — and records, from the run's own
metrics.jsonl:

  shadow_err_max        worst-step relative L2 error of the shadow
                        aggregate vs the f32 aggregate — the end-to-end
                        cost of the narrow dtype
  shadow_residual_max   worst-step shadow decode-health residual
  shadow_flag_agree_min worst-step fraction of present workers whose
                        shadow detection flag equals the f32 flag — 1.0
                        means quantization changed NO accusation
  det_precision/recall (_shadow)
                        detection P/R vs the seeded schedules, on the f32
                        AND the shadow flag sets — the exact-code cells run
                        a LIVE rev_grad adversary, so "detection survives
                        the narrow wire" is measured, not assumed
  wire                  the logical bytes ledger (obs/numerics.wire_ledger)
                        — f32/bf16/int8 bytes per worker per step at the
                        program's registered shapes

``tools/perf_watch.py`` folds the committed artifact: the shadow residual /
flag-agreement columns gate round-over-round as pinned tolerance-0 kinds
(proven live by the flipped-row control in tests/test_cli_tools.py), the
detection bools at tolerance 0, wire bytes at the bytes tolerance.

``--check`` re-verifies a committed artifact jax-free (ledger arithmetic,
bf16 detection-preserved pins, all_ok roll-up) — wired into
tools/check_artifacts.py.

Usage (CPU, ~2 min):
  python tools/wire_study.py --cpu-mesh 8
  python tools/wire_study.py --check
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_WORKERS = 8
FAMILIES = {
    # live rev_grad adversary on both exact codes: the study must show
    # detection P/R under quantization, not just decode error
    "cyclic": dict(approach="cyclic", worker_fail=1, err_mode="rev_grad",
                   redundancy="shared"),
    "maj_vote": dict(approach="maj_vote", group_size=4, worker_fail=1,
                     err_mode="rev_grad"),
    # the approx family rejects live adversaries (no Byzantine
    # certificate); its fault axis is seeded drops inside the α budget
    "approx": dict(approach="approx", worker_fail=0, redundancy="shared",
                   code_redundancy=1.5, straggler_alpha=0.25,
                   straggle_mode="drop", straggle_count=1),
}
DTYPES = ("bf16", "int8")
KS = (1, 4)


def _fold_prec_recall(tp, flagged, adv):
    """Detection precision/recall with the empty-denominator healthy-state
    convention (obs/heartbeat.decode_health)."""
    return ((tp / flagged) if flagged else 1.0,
            (tp / adv) if adv else 1.0)


def run_cell(family: str, dtype: str, k: int, args, mesh, ds) -> dict:
    from draco_tpu.config import TrainConfig
    from draco_tpu.obs import numerics as numerics_mod
    from draco_tpu.training.trainer import Trainer

    d = tempfile.mkdtemp(prefix=f"wire_{family}_{dtype}_k{k}_")
    cfg = TrainConfig(
        network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.05,
        momentum=0.9, num_workers=NUM_WORKERS, max_steps=args.max_steps,
        eval_freq=0, train_dir=d, log_every=1, steps_per_call=k,
        step_guard="on", compile_guard="raise",
        numerics_watch="on", shadow_wire=dtype,
        shadow_round=args.shadow_round, **FAMILIES[family],
    )
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    try:
        tr.run()
        dim = tr.setup.dim
    finally:
        tr.close()
    recs = []
    with open(os.path.join(d, "metrics.jsonl")) as fh:
        for line in fh:
            r = json.loads(line)
            if "loss" in r and r.get("split") != "eval":
                recs.append(r)
    shutil.rmtree(d, ignore_errors=True)

    exact = family in ("cyclic", "maj_vote")
    flag_col = {"cyclic": "located_errors", "maj_vote": "det_flagged"}
    tp = sum(r.get("det_tp", 0.0) for r in recs)
    adv = sum(r.get("det_adv", 0.0) for r in recs)
    flagged = sum(r.get(flag_col.get(family, ""), 0.0) for r in recs)
    stp = sum(r["shadow_det_tp"] for r in recs)
    sflagged = sum(r["shadow_det_flagged"] for r in recs)
    prec, rec = _fold_prec_recall(tp, flagged, adv)
    sprec, srec = _fold_prec_recall(stp, sflagged, adv)
    row = {
        "family": family, "dtype": dtype, "k": k,
        "steps": len(recs),
        "shadow_err_max": round(max(r["shadow_err"] for r in recs), 6),
        "shadow_residual_max": round(
            max(r["shadow_residual"] for r in recs), 6),
        "shadow_flag_agree_min": round(
            min(r["shadow_flag_agree"] for r in recs), 6),
        "det_precision": round(prec, 6), "det_recall": round(rec, 6),
        "det_precision_shadow": round(sprec, 6),
        "det_recall_shadow": round(srec, 6),
        "adv_total": adv,
        "wire_absmax_max": round(
            max(r["nx_wire_absmax"] for r in recs), 6),
        "wire_uf_int8_max": round(
            max(r["nx_wire_uf_int8"] for r in recs), 6),
        "wire_of_bf16_max": round(
            max(r["nx_wire_of_bf16"] for r in recs), 6),
        "guard_trips_total": sum(r.get("guard_trips", 0.0) for r in recs),
        "loss_final": round(recs[-1]["loss"], 6),
        "wire": numerics_mod.wire_ledger(cfg, dim),
    }
    # detection survives the narrow wire: shadow P/R both 1.0 with a live
    # adversary (exact codes); the approx cells' surface is flag agreement
    row["det_preserved"] = bool(
        (not exact or (sprec == 1.0 and srec == 1.0 and adv > 0))
        and row["shadow_flag_agree_min"] == 1.0)
    # every shadow column stayed finite (the NaN sentinel is -1.0 — a
    # clean run must never produce it)
    clean = all(r["shadow_err"] >= 0 and r["shadow_residual"] >= 0
                and r["shadow_flag_agree"] >= 0 for r in recs)
    row["ok"] = bool(row["det_preserved"] and clean
                     and row["guard_trips_total"] == 0.0
                     and row["steps"] == args.max_steps)
    return row


# --------------------------------------------------------------------------
# --check: jax-free artifact re-verification (tools/check_artifacts.py)
# --------------------------------------------------------------------------


def check_artifact(path: str) -> int:
    """Re-verify a committed wire_study.json: the roll-up, the per-row
    detection pins, and the ledger arithmetic (bytes must match the
    recorded dim — a stale ledger would misreport the item-4 win). Exits
    nonzero naming the first failure."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"wire_study --check: cannot read {path}: {e}")
        return 1
    rows = data.get("rows", [])
    want_cells = {(f, dt, k) for f in FAMILIES for dt in DTYPES for k in KS}
    got_cells = {(r.get("family"), r.get("dtype"), r.get("k"))
                 for r in rows}
    if not want_cells <= got_cells:
        print(f"wire_study --check: missing cells "
              f"{sorted(want_cells - got_cells)}")
        return 1
    for r in rows:
        cell = f"{r['family']}.{r['dtype']}.k{r['k']}"
        w = r.get("wire") or {}
        rows_per = 2 if r["family"] == "cyclic" else 1
        dim = w.get("dim", 0)
        per = w.get("bytes_per_worker", {})
        if per.get("f32") != 4 * rows_per * dim \
                or per.get("bf16") != 2 * rows_per * dim:
            print(f"wire_study --check: {cell}: ledger bytes inconsistent "
                  f"with dim={dim} ({per})")
            return 1
        if not (per.get("int8", 0) < per.get("bf16", 0)
                < per.get("f32", 0)):
            print(f"wire_study --check: {cell}: dtype ordering broken "
                  f"({per})")
            return 1
        if r["dtype"] == "bf16" and not r.get("det_preserved"):
            print(f"wire_study --check: {cell}: bf16 shadow lost "
                  f"detection (det_preserved false) — the ISSUE 10 "
                  f"acceptance pin")
            return 1
        if not r.get("ok"):
            print(f"wire_study --check: {cell}: row not ok")
            return 1
    if not data.get("all_ok"):
        print("wire_study --check: all_ok is false")
        return 1
    print(f"wire_study --check: {len(rows)} cells verified ({path})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", type=str,
                    default=os.path.join("baselines_out", "wire_study.json"))
    ap.add_argument("--max-steps", type=int, default=12)
    ap.add_argument("--shadow-round", type=str, default="nearest",
                    choices=["nearest", "stochastic"])
    ap.add_argument("--families", type=str, default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--dtypes", type=str, default="",
                    help="comma-separated subset of bf16,int8")
    ap.add_argument("--ks", type=str, default="",
                    help="comma-separated subset of 1,4")
    ap.add_argument("--check", action="store_true",
                    help="re-verify a committed artifact (jax-free)")
    ap.add_argument("--artifact", type=str, default="",
                    help="artifact path for --check (default --out)")
    ap.add_argument("--cpu-mesh", type=int, default=0, metavar="N",
                    help="force an N-device virtual CPU mesh")
    args = ap.parse_args(argv)
    if args.check:
        return check_artifact(args.artifact or args.out)
    from draco_tpu.cli import maybe_force_cpu_mesh

    if args.cpu_mesh:
        maybe_force_cpu_mesh(args)

    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    families = [f for f in args.families.split(",") if f] or list(FAMILIES)
    dtypes = [d for d in args.dtypes.split(",") if d] or list(DTYPES)
    ks = [int(x) for x in args.ks.split(",") if x] or list(KS)
    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=128)
    mesh = make_mesh(NUM_WORKERS)
    rows = []
    for family in families:
        for dtype in dtypes:
            for k in ks:
                row = run_cell(family, dtype, k, args, mesh, ds)
                rows.append(row)
                print(f"wire_study: {family:8s} {dtype:4s} k={k} -> "
                      f"err_max={row['shadow_err_max']:.4g} "
                      f"agree_min={row['shadow_flag_agree_min']} "
                      f"det_shadow={row['det_precision_shadow']:.2f}/"
                      f"{row['det_recall_shadow']:.2f} ok={row['ok']}",
                      flush=True)

    payload = {
        "schema": 1,
        "tool": "tools/wire_study.py",
        "num_workers": NUM_WORKERS,
        "max_steps": args.max_steps,
        "shadow_round": args.shadow_round,
        "rows": rows,
        "all_ok": bool(rows) and all(r["ok"] for r in rows),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"wire_study: {len(rows)} cells -> {args.out} "
          f"(all_ok={payload['all_ok']})")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
