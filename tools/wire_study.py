#!/usr/bin/env python
"""Wire study: what does a bf16/int8 worker→aggregator wire do to decode
error and Byzantine detection? — ISSUE 10's shadow calibration matrix plus,
since ISSUE 15, the REAL narrow wire's committed evidence:

**Shadow rows** (the PR 10 matrix, unchanged): the f32 wire ships, the
shadow decode measures the candidate dtype alongside it.

**Real rows** (``"mode": "real"``): ``cfg.wire_dtype`` is SET — the
codewords physically cross the sharding boundary as bf16/int8 buffers and
the λ-regularized, quantization-aware decode is the only decode. Each cell
trains the same workload twice (narrow wire vs an f32 twin, identical
seeds) and records the end-to-end relative parameter error, detection P/R
on the narrow wire's OWN flag columns under a live adversary, guard
cleanliness, and the ledger's physical bytes/worker/step with the ratio vs
the f32 row — the ISSUE 15 acceptance pins (P/R 1.0 preserved, bytes ≤
0.50×/≈0.25×).

**Locator cells** (``"mode": "locator"``): the PR 10 blocker replayed at
n=32 s=3 — synthetic encodes quantized to the narrow dtype, decoded with
the UNREGULARIZED (λ=0) and the λ-regularized locator, recording the worst
honest-row deviation with no adversary (the rank-deficient amplification),
the margins with s live adversaries, and whether the committed
per-(n, s, dtype) threshold (obs/numerics.WIRE_REL_TOL_TABLE, committed
here as ``threshold_table``) separates them. λ=0 must reproduce the
blocker (NOT usable); λ must solve it.

ROADMAP item 4 will narrow the coded wire; this study is the measurement
foundation it gets built and regression-gated on. Each cell trains the same
FC/synthetic-mnist workload under {cyclic, maj_vote, approx} ×
{bf16, int8} × K∈{1,4} with ``numerics_watch=on`` and ``shadow_wire`` set —
the f32 path alone updates params, the shadow decode of the quantized
codewords rides the same step body — and records, from the run's own
metrics.jsonl:

  shadow_err_max        worst-step relative L2 error of the shadow
                        aggregate vs the f32 aggregate — the end-to-end
                        cost of the narrow dtype
  shadow_residual_max   worst-step shadow decode-health residual
  shadow_flag_agree_min worst-step fraction of present workers whose
                        shadow detection flag equals the f32 flag — 1.0
                        means quantization changed NO accusation
  det_precision/recall (_shadow)
                        detection P/R vs the seeded schedules, on the f32
                        AND the shadow flag sets — the exact-code cells run
                        a LIVE rev_grad adversary, so "detection survives
                        the narrow wire" is measured, not assumed
  wire                  the logical bytes ledger (obs/numerics.wire_ledger)
                        — f32/bf16/int8 bytes per worker per step at the
                        program's registered shapes

``tools/perf_watch.py`` folds the committed artifact: the shadow residual /
flag-agreement columns gate round-over-round as pinned tolerance-0 kinds
(proven live by the flipped-row control in tests/test_cli_tools.py), the
detection bools at tolerance 0, wire bytes at the bytes tolerance.

``--check`` re-verifies a committed artifact jax-free (ledger arithmetic
— including the ISSUE 16 pin that the ledger's per-segment physical bytes
sum exactly to the per-worker/per-step rows — bf16 detection-preserved
pins, all_ok roll-up) — wired into tools/check_artifacts.py.

Usage (CPU, ~2 min):
  python tools/wire_study.py --cpu-mesh 8
  python tools/wire_study.py --check
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_WORKERS = 8
FAMILIES = {
    # live rev_grad adversary on both exact codes: the study must show
    # detection P/R under quantization, not just decode error
    "cyclic": dict(approach="cyclic", worker_fail=1, err_mode="rev_grad",
                   redundancy="shared"),
    "maj_vote": dict(approach="maj_vote", group_size=4, worker_fail=1,
                     err_mode="rev_grad"),
    # the approx family rejects live adversaries (no Byzantine
    # certificate); its fault axis is seeded drops inside the α budget
    "approx": dict(approach="approx", worker_fail=0, redundancy="shared",
                   code_redundancy=1.5, straggler_alpha=0.25,
                   straggle_mode="drop", straggle_count=1),
}
DTYPES = ("bf16", "int8")
KS = (1, 4)

# real-wire acceptance bounds (ISSUE 15): end-to-end relative parameter
# error vs the f32 twin, and physical-bytes ratio vs the f32 ledger row.
# The int8 ratio is 0.25 + 1/64: one f32 scale per 256-element block — the
# committed ledger's own arithmetic, which the headline "0.25×" rounds.
REAL_ERR_MAX = {"bf16": 2e-2, "int8": 1e-1}
REAL_RATIO_MAX = {"bf16": 0.505, "int8": 0.26}

# the PR 10 blocker shape the locator cells replay
LOCATOR_SHAPE = (32, 3)
LOCATOR_TRIALS = 12
LOCATOR_D = 4096


def _fold_prec_recall(tp, flagged, adv):
    """Detection precision/recall with the empty-denominator healthy-state
    convention (obs/heartbeat.decode_health)."""
    return ((tp / flagged) if flagged else 1.0,
            (tp / adv) if adv else 1.0)


def run_cell(family: str, dtype: str, k: int, args, mesh, ds) -> dict:
    from draco_tpu.config import TrainConfig
    from draco_tpu.obs import numerics as numerics_mod
    from draco_tpu.training.trainer import Trainer

    d = tempfile.mkdtemp(prefix=f"wire_{family}_{dtype}_k{k}_")
    cfg = TrainConfig(
        network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.05,
        momentum=0.9, num_workers=NUM_WORKERS, max_steps=args.max_steps,
        eval_freq=0, train_dir=d, log_every=1, steps_per_call=k,
        step_guard="on", compile_guard="raise",
        numerics_watch="on", shadow_wire=dtype,
        shadow_round=args.shadow_round, **FAMILIES[family],
    )
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    try:
        tr.run()
        dim = tr.setup.dim
    finally:
        tr.close()
    recs = []
    with open(os.path.join(d, "metrics.jsonl")) as fh:
        for line in fh:
            r = json.loads(line)
            if "loss" in r and r.get("split") != "eval":
                recs.append(r)
    shutil.rmtree(d, ignore_errors=True)

    exact = family in ("cyclic", "maj_vote")
    flag_col = {"cyclic": "located_errors", "maj_vote": "det_flagged"}
    tp = sum(r.get("det_tp", 0.0) for r in recs)
    adv = sum(r.get("det_adv", 0.0) for r in recs)
    flagged = sum(r.get(flag_col.get(family, ""), 0.0) for r in recs)
    stp = sum(r["shadow_det_tp"] for r in recs)
    sflagged = sum(r["shadow_det_flagged"] for r in recs)
    prec, rec = _fold_prec_recall(tp, flagged, adv)
    sprec, srec = _fold_prec_recall(stp, sflagged, adv)
    row = {
        "family": family, "dtype": dtype, "k": k,
        "steps": len(recs),
        "shadow_err_max": round(max(r["shadow_err"] for r in recs), 6),
        "shadow_residual_max": round(
            max(r["shadow_residual"] for r in recs), 6),
        "shadow_flag_agree_min": round(
            min(r["shadow_flag_agree"] for r in recs), 6),
        "det_precision": round(prec, 6), "det_recall": round(rec, 6),
        "det_precision_shadow": round(sprec, 6),
        "det_recall_shadow": round(srec, 6),
        "adv_total": adv,
        "wire_absmax_max": round(
            max(r["nx_wire_absmax"] for r in recs), 6),
        "wire_uf_int8_max": round(
            max(r["nx_wire_uf_int8"] for r in recs), 6),
        "wire_of_bf16_max": round(
            max(r["nx_wire_of_bf16"] for r in recs), 6),
        "guard_trips_total": sum(r.get("guard_trips", 0.0) for r in recs),
        "loss_final": round(recs[-1]["loss"], 6),
        "wire": numerics_mod.wire_ledger(cfg, dim),
    }
    # detection survives the narrow wire: shadow P/R both 1.0 with a live
    # adversary (exact codes); the approx cells' surface is flag agreement
    row["det_preserved"] = bool(
        (not exact or (sprec == 1.0 and srec == 1.0 and adv > 0))
        and row["shadow_flag_agree_min"] == 1.0)
    # every shadow column stayed finite (the NaN sentinel is -1.0 — a
    # clean run must never produce it)
    clean = all(r["shadow_err"] >= 0 and r["shadow_residual"] >= 0
                and r["shadow_flag_agree"] >= 0 for r in recs)
    row["ok"] = bool(row["det_preserved"] and clean
                     and row["guard_trips_total"] == 0.0
                     and row["steps"] == args.max_steps)
    return row


# --------------------------------------------------------------------------
# real-wire cells (ISSUE 15)
# --------------------------------------------------------------------------


def _train(cfg, mesh, ds):
    """Run the production Trainer; return (flat params, train records,
    dim)."""
    import jax
    import numpy as np

    from draco_tpu.training.trainer import Trainer

    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    try:
        tr.run()
        dim = tr.setup.dim
        pv = np.concatenate([
            np.ravel(x)
            for x in jax.tree.leaves(jax.device_get(tr.state.params))])
    finally:
        tr.close()
    recs = []
    with open(os.path.join(cfg.train_dir, "metrics.jsonl")) as fh:
        for line in fh:
            r = json.loads(line)
            if "loss" in r and r.get("split") != "eval":
                recs.append(r)
    return pv, recs, dim


def run_real_cell(family: str, dtype: str, k: int, args, mesh, ds,
                  f32_twins: dict) -> dict:
    """One REAL-narrow-wire cell: train with cfg.wire_dtype=dtype, compare
    end-to-end against the cached f32 twin of the same (family, k), and
    score detection on the narrow wire's OWN flag columns."""
    import numpy as np

    from draco_tpu.config import TrainConfig
    from draco_tpu.obs import numerics as numerics_mod

    def mk(wire):
        d = tempfile.mkdtemp(prefix=f"wirereal_{family}_{wire}_k{k}_")
        return TrainConfig(
            network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.05,
            momentum=0.9, num_workers=NUM_WORKERS, max_steps=args.max_steps,
            eval_freq=0, train_dir=d, log_every=1, steps_per_call=k,
            step_guard="on", compile_guard="raise", numerics_watch="on",
            wire_dtype=wire, shadow_round=args.shadow_round,
            **FAMILIES[family],
        )

    twin_key = (family, k)
    if twin_key not in f32_twins:
        cfg0 = mk("f32")
        f32_twins[twin_key] = _train(cfg0, mesh, ds)
        shutil.rmtree(cfg0.train_dir, ignore_errors=True)
    pv0, recs0, _dim0 = f32_twins[twin_key]

    cfg = mk(dtype)
    pv, recs, dim = _train(cfg, mesh, ds)
    shutil.rmtree(cfg.train_dir, ignore_errors=True)

    exact = family in ("cyclic", "maj_vote")
    flag_col = {"cyclic": "located_errors", "maj_vote": "det_flagged"}
    tp = sum(r.get("det_tp", 0.0) for r in recs)
    adv = sum(r.get("det_adv", 0.0) for r in recs)
    flagged = sum(r.get(flag_col.get(family, ""), 0.0) for r in recs)
    prec, rec = _fold_prec_recall(tp, flagged, adv)
    err = float(np.linalg.norm(pv - pv0)
                / max(np.linalg.norm(pv0), 1e-30))
    ledger = numerics_mod.wire_ledger(cfg, dim)
    phys = ledger["physical_bytes_per_worker"]
    ratio = phys / ledger["bytes_per_worker"]["f32"]
    row = {
        "mode": "real", "family": family, "dtype": dtype, "k": k,
        "steps": len(recs),
        "end_to_end_err": round(err, 6),
        "det_precision": round(prec, 6), "det_recall": round(rec, 6),
        "adv_total": adv,
        "decode_residual_max": round(
            max(r.get("decode_residual", 0.0) for r in recs), 6),
        "guard_trips_total": sum(r.get("guard_trips", 0.0) for r in recs),
        "loss_final": round(recs[-1]["loss"], 6),
        "loss_final_f32": round(recs0[-1]["loss"], 6),
        "wire": ledger,
        "physical_ratio": round(ratio, 6),
    }
    # the ledger honesty pin (ISSUE 15 satellite): the materialized bytes
    # ARE the logical candidate row, by construction
    row["physical_matches_ledger"] = bool(
        phys == ledger["bytes_per_worker"][dtype]
        and ledger["wire_dtype"] == dtype)
    row["det_preserved"] = bool(
        not exact or (prec == 1.0 and rec == 1.0 and adv > 0))
    row["ok"] = bool(
        row["det_preserved"] and row["physical_matches_ledger"]
        and row["guard_trips_total"] == 0.0
        and row["steps"] == args.max_steps
        and err <= REAL_ERR_MAX[dtype]
        and ratio <= REAL_RATIO_MAX[dtype])
    return row


# --------------------------------------------------------------------------
# locator-margin cells (ISSUE 15): the PR 10 n=32 s=3 blocker, replayed
# --------------------------------------------------------------------------


def locator_cell(n: int, s: int, dtype: str, lam: float) -> dict:
    """Measure the narrow-wire locator margins at (n, s): worst honest-row
    relative deviation with NO adversary (the rank-deficient quantization
    amplification — the blocker), and the honest-max / adversary-min
    margins with s live rev_grad-magnitude adversaries. ``usable`` = the
    committed per-shape threshold separates the no-adversary honest band
    from the adversary band — the PR 10 blocker's certificate, and ONLY
    that: ``honest_dev_max_adv`` is recorded (not folded into ``usable``)
    because at the blocker shape it EXCEEDS the threshold — honest rows
    extrapolated under a live adversary cross the flag line, so detection
    RECALL holds (adv_dev_min > threshold) while flag PRECISION degrades
    in the adversary regime at large (n, s). A measured limit, documented
    in PERF.md §17 and the WIRE_REL_TOL_TABLE comment, not silently
    absorbed into the certificate."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import cyclic as cyclic_mod
    from draco_tpu.obs import numerics as numerics_mod

    code = cyclic_mod.build_cyclic_code(n, s)
    block = 256

    def margins(adv_rows):
        hmax, amin = 0.0, float("inf")
        for t in range(LOCATOR_TRIALS):
            rs = np.random.RandomState(100 + t)
            g = rs.randn(n, LOCATOR_D).astype(np.float32) * 0.05
            enc_re, enc_im = cyclic_mod.encode_shared(code, jnp.asarray(g))
            adv = np.zeros(n, bool)
            if adv_rows:
                adv[rs.choice(n, adv_rows, replace=False)] = True
                m = jnp.asarray(adv)[:, None]
                enc_re = jnp.where(m, -100.0 * enc_re, enc_re)
                enc_im = jnp.where(m, -100.0 * enc_im, enc_im)
            buf_re = numerics_mod.narrow_wire_rows(enc_re, dtype, block)
            buf_im = numerics_mod.narrow_wire_rows(enc_im, dtype, block)
            enc_re = numerics_mod.widen_wire_rows(buf_re, dtype, block)
            enc_im = numerics_mod.widen_wire_rows(buf_im, dtype, block)
            f = jnp.asarray(rs.randn(LOCATOR_D).astype(np.float32))
            _, _, h = cyclic_mod.decode(code, enc_re, enc_im, f,
                                        with_health=True, rel_tol=1e9,
                                        lam=lam)
            dev = np.asarray(h["dev_rel"])
            if adv_rows:
                amin = min(amin, float(dev[adv].min()))
                hmax = max(hmax, float(dev[~adv].max()))
            else:
                hmax = max(hmax, float(dev.max()))
        return hmax, amin

    noadv_hmax, _ = margins(0)
    adv_hmax, adv_min = margins(s)
    tol = numerics_mod.wire_rel_tol(n, s, dtype)
    usable = bool(noadv_hmax < tol < adv_min)
    return {
        "mode": "locator", "n": n, "s": s, "dtype": dtype,
        "lam": lam, "regularized": bool(lam > 0.0),
        "trials": LOCATOR_TRIALS, "d": LOCATOR_D,
        "honest_dev_max_noadv": round(noadv_hmax, 6),
        "honest_dev_max_adv": round(adv_hmax, 6),
        "adv_dev_min": round(adv_min, 6),
        "threshold": tol,
        "usable": usable,
        # the regularized cell must solve the blocker; the λ=0 cell must
        # REPRODUCE it (a blocker that stops reproducing means the λ=0
        # path changed — which it never may: it is the bitwise f32 path)
        "ok": usable if lam > 0.0 else not usable,
    }


# --------------------------------------------------------------------------
# --check: jax-free artifact re-verification (tools/check_artifacts.py)
# --------------------------------------------------------------------------


def check_artifact(path: str) -> int:
    """Re-verify a committed wire_study.json: the roll-up, the per-row
    detection pins, the ledger arithmetic (bytes must match the recorded
    dim — a stale ledger would misreport the item-4 win), and — ISSUE 15 —
    the real-wire rows' P/R + physical-bytes pins and the locator cells'
    blocker-solved certificate. Exits nonzero naming the first failure."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"wire_study --check: cannot read {path}: {e}")
        return 1
    rows = data.get("rows", [])
    shadow = [r for r in rows if r.get("mode", "shadow") == "shadow"]
    real = [r for r in rows if r.get("mode") == "real"]
    locator = [r for r in rows if r.get("mode") == "locator"]
    want_cells = {(f, dt, k) for f in FAMILIES for dt in DTYPES for k in KS}
    for label, rset in (("shadow", shadow), ("real", real)):
        got = {(r.get("family"), r.get("dtype"), r.get("k")) for r in rset}
        if not want_cells <= got:
            print(f"wire_study --check: missing {label} cells "
                  f"{sorted(want_cells - got)}")
            return 1
    for r in shadow + real:
        cell = f"{r.get('mode', 'shadow')}.{r['family']}.{r['dtype']}" \
               f".k{r['k']}"
        w = r.get("wire") or {}
        rows_per = 2 if r["family"] == "cyclic" else 1
        dim = w.get("dim", 0)
        per = w.get("bytes_per_worker", {})
        if per.get("f32") != 4 * rows_per * dim \
                or per.get("bf16") != 2 * rows_per * dim:
            print(f"wire_study --check: {cell}: ledger bytes inconsistent "
                  f"with dim={dim} ({per})")
            return 1
        if not (per.get("int8", 0) < per.get("bf16", 0)
                < per.get("f32", 0)):
            print(f"wire_study --check: {cell}: dtype ordering broken "
                  f"({per})")
            return 1
        # ISSUE 16: the ledger's per-segment physical bytes must SUM to
        # the per-worker/per-step rows exactly — a segment boundary can
        # never create or destroy wire bytes
        seg = w.get("segments")
        if not isinstance(seg, dict):
            print(f"wire_study --check: {cell}: ledger carries no "
                  f"segments block — regenerate with the segmented "
                  f"wire_ledger (ISSUE 16)")
            return 1
        bounds = seg.get("bounds") or []
        if (sum(seg.get("physical_bytes_per_worker", []))
                != w.get("physical_bytes_per_worker")
                or sum(seg.get("physical_bytes_per_step", []))
                != w.get("physical_bytes_per_step")
                or seg.get("count") != len(bounds) - 1
                or bounds[:1] != [0] or bounds[-1:] != [dim]):
            print(f"wire_study --check: {cell}: per-segment bytes do not "
                  f"sum to the per-step ledger row (segments={seg})")
            return 1
        if r["dtype"] == "bf16" and not r.get("det_preserved"):
            print(f"wire_study --check: {cell}: bf16 wire lost "
                  f"detection (det_preserved false) — the ISSUE 10/15 "
                  f"acceptance pin")
            return 1
        if not r.get("ok"):
            print(f"wire_study --check: {cell}: row not ok")
            return 1
    for r in real:
        cell = f"real.{r['family']}.{r['dtype']}.k{r['k']}"
        w = r.get("wire") or {}
        dtype = r["dtype"]
        # the ledger-honesty pin: physical == the logical candidate row
        if w.get("wire_dtype") != dtype or \
                w.get("physical_bytes_per_worker") \
                != (w.get("bytes_per_worker") or {}).get(dtype):
            print(f"wire_study --check: {cell}: materialized wire bytes "
                  f"disagree with the logical candidate row "
                  f"(wire_dtype={w.get('wire_dtype')})")
            return 1
        ratio = (w.get("physical_bytes_per_worker", 0)
                 / max(w.get("bytes_per_worker", {}).get("f32", 1), 1))
        if ratio > REAL_RATIO_MAX[dtype]:
            print(f"wire_study --check: {cell}: physical bytes ratio "
                  f"{ratio:.4f} exceeds the {dtype} pin "
                  f"{REAL_RATIO_MAX[dtype]} — the wire is not narrow")
            return 1
        if r.get("end_to_end_err", 1.0) > REAL_ERR_MAX[dtype]:
            print(f"wire_study --check: {cell}: end-to-end error "
                  f"{r.get('end_to_end_err')} exceeds {REAL_ERR_MAX[dtype]}")
            return 1
        if r["family"] in ("cyclic", "maj_vote") and not (
                r.get("det_precision") == 1.0
                and r.get("det_recall") == 1.0):
            print(f"wire_study --check: {cell}: detection P/R "
                  f"{r.get('det_precision')}/{r.get('det_recall')} != 1.0 "
                  f"on the real narrow wire — the ISSUE 15 acceptance pin")
            return 1
    # locator cells: the blocker must REPRODUCE at λ=0 and be SOLVED at λ
    n32, s32 = LOCATOR_SHAPE
    for dtype in DTYPES:
        cells = {bool(r.get("regularized")): r for r in locator
                 if r.get("dtype") == dtype and r.get("n") == n32
                 and r.get("s") == s32}
        if set(cells) != {False, True}:
            print(f"wire_study --check: locator cells missing for {dtype} "
                  f"at n={n32} s={s32} (need λ=0 and λ>0)")
            return 1
        if cells[False].get("usable"):
            print(f"wire_study --check: locator {dtype} λ=0 row claims "
                  f"usable — the PR 10 blocker stopped reproducing, which "
                  f"means the exact path changed")
            return 1
        reg = cells[True]
        if not reg.get("usable"):
            print(f"wire_study --check: locator {dtype} regularized row "
                  f"not usable — the blocker is back")
            return 1
        thr = reg.get("threshold")
        tbl = (data.get("threshold_table") or {}).get(
            f"{n32}:{s32}:{dtype}")
        if thr != tbl:
            print(f"wire_study --check: locator {dtype} threshold {thr} "
                  f"!= committed table entry {tbl}")
            return 1
        if not (reg.get("honest_dev_max_noadv", 1e9) < thr
                < reg.get("adv_dev_min", 0.0)):
            print(f"wire_study --check: locator {dtype} threshold {thr} "
                  f"does not separate the measured margins "
                  f"({reg.get('honest_dev_max_noadv')} .. "
                  f"{reg.get('adv_dev_min')})")
            return 1
    for r in locator:
        if not r.get("ok"):
            print(f"wire_study --check: locator row not ok: {r}")
            return 1
    if not data.get("all_ok"):
        print("wire_study --check: all_ok is false")
        return 1
    print(f"wire_study --check: {len(shadow)} shadow + {len(real)} real + "
          f"{len(locator)} locator cells verified ({path})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", type=str,
                    default=os.path.join("baselines_out", "wire_study.json"))
    ap.add_argument("--max-steps", type=int, default=12)
    ap.add_argument("--shadow-round", type=str, default="nearest",
                    choices=["nearest", "stochastic"])
    ap.add_argument("--families", type=str, default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--dtypes", type=str, default="",
                    help="comma-separated subset of bf16,int8")
    ap.add_argument("--ks", type=str, default="",
                    help="comma-separated subset of 1,4")
    ap.add_argument("--check", action="store_true",
                    help="re-verify a committed artifact (jax-free)")
    ap.add_argument("--artifact", type=str, default="",
                    help="artifact path for --check (default --out)")
    ap.add_argument("--cpu-mesh", type=int, default=0, metavar="N",
                    help="force an N-device virtual CPU mesh")
    args = ap.parse_args(argv)
    if args.check:
        return check_artifact(args.artifact or args.out)
    from draco_tpu.cli import maybe_force_cpu_mesh

    if args.cpu_mesh:
        maybe_force_cpu_mesh(args)

    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    families = [f for f in args.families.split(",") if f] or list(FAMILIES)
    dtypes = [d for d in args.dtypes.split(",") if d] or list(DTYPES)
    ks = [int(x) for x in args.ks.split(",") if x] or list(KS)
    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=128)
    mesh = make_mesh(NUM_WORKERS)
    rows = []
    for family in families:
        for dtype in dtypes:
            for k in ks:
                row = run_cell(family, dtype, k, args, mesh, ds)
                row["mode"] = "shadow"
                rows.append(row)
                print(f"wire_study: shadow {family:8s} {dtype:4s} k={k} -> "
                      f"err_max={row['shadow_err_max']:.4g} "
                      f"agree_min={row['shadow_flag_agree_min']} "
                      f"det_shadow={row['det_precision_shadow']:.2f}/"
                      f"{row['det_recall_shadow']:.2f} ok={row['ok']}",
                      flush=True)

    # REAL-wire cells (ISSUE 15): wire_dtype set, f32 twin per (family, k)
    f32_twins: dict = {}
    for family in families:
        for dtype in dtypes:
            for k in ks:
                row = run_real_cell(family, dtype, k, args, mesh, ds,
                                    f32_twins)
                rows.append(row)
                print(f"wire_study: real   {family:8s} {dtype:4s} k={k} -> "
                      f"err={row['end_to_end_err']:.4g} "
                      f"det={row['det_precision']:.2f}/"
                      f"{row['det_recall']:.2f} "
                      f"bytes_ratio={row['physical_ratio']:.4f} "
                      f"ok={row['ok']}", flush=True)

    # locator-margin cells: the PR 10 blocker shape, λ=0 (must reproduce
    # the blocker) and the committed λ (must solve it)
    from draco_tpu.obs.numerics import (WIRE_LOCATOR_LAMBDA,
                                        WIRE_REL_TOL_TABLE)

    n32, s32 = LOCATOR_SHAPE
    for dtype in dtypes:
        for lam in (0.0, WIRE_LOCATOR_LAMBDA[dtype]):
            row = locator_cell(n32, s32, dtype, lam)
            rows.append(row)
            print(f"wire_study: locator n={n32} s={s32} {dtype:4s} "
                  f"lam={lam:g} -> noadv_hmax="
                  f"{row['honest_dev_max_noadv']:.4g} "
                  f"adv_min={row['adv_dev_min']:.4g} "
                  f"usable={row['usable']} ok={row['ok']}", flush=True)

    payload = {
        "schema": 2,
        "tool": "tools/wire_study.py",
        "num_workers": NUM_WORKERS,
        "max_steps": args.max_steps,
        "shadow_round": args.shadow_round,
        # the committed per-(n, s, dtype) flag-threshold table the narrow
        # wire decodes with (obs/numerics.WIRE_REL_TOL_TABLE) + the
        # locator λ per dtype — re-verified against the locator cells'
        # measured margins by --check
        "threshold_table": {f"{n}:{s}:{dt}": tol for (n, s, dt), tol
                            in sorted(WIRE_REL_TOL_TABLE.items())},
        "locator_lambda": dict(WIRE_LOCATOR_LAMBDA),
        "rows": rows,
        "all_ok": bool(rows) and all(r["ok"] for r in rows),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"wire_study: {len(rows)} cells -> {args.out} "
          f"(all_ok={payload['all_ok']})")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
