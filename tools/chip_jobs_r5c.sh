#!/bin/bash
# Round-5 fallback chain for the d~159M LM point. The flagship lm_big rung
# (T=2048 b2 remat, 3 variants) died in the tunnel's remote-compile path
# ("Broken pipe" after ~28 min; same family as the remat-sweep b256/b512
# "tpu_compile_helper subprocess exit code 1" rows) — an infra limit on
# big-program compiles, not a chip or code limit (the programs lower clean
# offline: baselines_out/tpu_lm_big_lowering.json). The r5 ladder retries
# the flagship config once on its second pass; THIS chain lands the same
# d~159M decode-vs-geomedian comparison on progressively lighter programs
# so the scale point exists even if the flagship compile never fits:
#   1 lm_big_t1024     same ~159M params, T=1024 b4 remat (params are
#                      T-independent; activation graph and compile shrink)
#   2 lm_big_noremat   T=2048 b1, no remat (remat enlarges the autodiff
#                      graph the remote helper must chew)
#   3 lm_big_sim1024   simulate leg at T=1024 b2 (the r=2s+1 redundant-
#                      compute cost at scale)
# Parks until chip_jobs_r5.sh AND chip_jobs_r5b.sh are gone.
#
# Launch detached:
#   setsid nohup bash tools/chip_jobs_r5c.sh > baselines_out/chip_jobs_r5c.log 2>&1 &
# NEVER edit this file while it runs. Markers: baselines_out/.r5c_<rung>_done
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5c $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5c $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5c $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5c $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5c $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5c $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

tpu_up() {
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

others_running() {
  pgrep -f "bash tools/chip_jobs_r5.sh" > /dev/null 2>&1 && return 0
  pgrep -f "bash tools/chip_jobs_r5b.sh" > /dev/null 2>&1 && return 0
  return 1
}

echo "[r5c $(stamp)] waiting for chip_jobs_r5.sh and r5b.sh to finish"
while others_running; do
  sleep 60
done
echo "[r5c $(stamp)] predecessors gone; proceeding"

ABORT_PASS=0
FAILURES=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5c_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5c $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5c $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    FAILURES=$((FAILURES + 1))
    if ! tpu_up; then
      echo "[r5c $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

all_done() {
  for m in lm_big_t1024 lm_big_noremat lm_big_sim1024; do
    [ -f "baselines_out/.r5c_${m}_done" ] || return 1
  done
  return 0
}

for outer in 1 2 3; do
  echo "[r5c $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5c $(stamp)] tunnel never came up this window"; continue; }
  FAILURES=0
  ABORT_PASS=0

  rung lm_big_t1024 "chip evidence: d~159M LM at T=1024 remat (flash/shared/geomedian)" \
    timeout -k 60 5400 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 1024 --batch-size 4 --remat \
      --variants lm_cyclic_s1_shared_bf16_flash,lm_cyclic_s1_shared_bf16,lm_geomedian_bf16 \
      --out baselines_out/tpu_lm_perf_big_t1024.json

  rung lm_big_noremat "chip evidence: d~159M LM at T=2048 b1 no-remat (shared/geomedian)" \
    timeout -k 60 5400 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 1 \
      --variants lm_cyclic_s1_shared_bf16_flash,lm_cyclic_s1_shared_bf16,lm_geomedian_bf16 \
      --out baselines_out/tpu_lm_perf_big_noremat.json

  rung lm_big_sim1024 "chip evidence: d~159M LM simulate leg at T=1024 b2" \
    timeout -k 60 5400 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 1024 --batch-size 2 --remat \
      --variants lm_cyclic_s1_simulate_bf16 \
      --out baselines_out/tpu_lm_perf_big_sim1024.json

  if all_done; then
    echo "[r5c $(stamp)] FALLBACK COMPLETE"
    break
  fi
  echo "[r5c $(stamp)] incomplete ($FAILURES rung failures this pass); retrying"
  sleep 120
done
all_done && exit 0 || exit 1
