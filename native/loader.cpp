// Native prefetching batch-gather engine.
//
// TPU-native equivalent of the reference's vendored multiprocess DataLoader
// (reference: src/data_loader_ops/my_data_loader.py:137-319 — worker
// processes + index queues feeding the training loop). Here the dataset is a
// host-resident array; the per-step work is gathering B (or n*B) sample rows
// at arbitrary indices into a contiguous batch buffer. That gather runs on
// C++ threads fully outside the GIL, so the host prepares step k+1's batch
// while the device executes step k (the reference got this overlap from
// separate loader processes; we get it from a thread pool + ticket queue).
//
// API: submit(src rows, indices, dst) -> ticket; wait(ticket) blocks until
// the gather completed. Caller owns all buffers and must keep them alive
// until wait() returns (the Python wrapper pins them).

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_set>
#include <vector>

namespace {

struct Job {
  const uint8_t* src;
  long long row_bytes;
  std::vector<int64_t> indices;  // copied at submit
  uint8_t* dst;
  long long ticket;
};

struct Loader {
  std::vector<std::thread> threads;
  std::deque<Job> queue;
  std::unordered_set<long long> in_flight;  // submitted, not yet finished
  std::mutex mu;
  std::condition_variable cv_work;
  std::condition_variable cv_done;
  long long next_ticket = 1;
  bool stop = false;

  explicit Loader(int num_threads) {
    for (int t = 0; t < num_threads; ++t)
      threads.emplace_back([this] { worker(); });
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_work.notify_all();
    for (auto& t : threads) t.join();
  }

  void worker() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        job = std::move(queue.front());
        queue.pop_front();
      }
      for (size_t i = 0; i < job.indices.size(); ++i)
        std::memcpy(job.dst + (long long)i * job.row_bytes,
                    job.src + job.indices[i] * job.row_bytes,
                    (size_t)job.row_bytes);
      {
        std::lock_guard<std::mutex> lk(mu);
        in_flight.erase(job.ticket);
      }
      cv_done.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* draco_loader_create(int num_threads) {
  if (num_threads < 1) num_threads = 2;
  return new Loader(num_threads);
}

void draco_loader_destroy(void* h) { delete static_cast<Loader*>(h); }

// Gather `count` rows of `src` (each row_bytes long) at `indices` into `dst`.
// Returns a ticket (> 0) immediately; the copy happens on a pool thread.
long long draco_loader_submit(void* h, const uint8_t* src, long long row_bytes,
                              const int64_t* indices, long long count,
                              uint8_t* dst) {
  Loader* L = static_cast<Loader*>(h);
  Job job;
  job.src = src;
  job.row_bytes = row_bytes;
  job.indices.assign(indices, indices + count);
  job.dst = dst;
  long long ticket;
  {
    std::lock_guard<std::mutex> lk(L->mu);
    ticket = L->next_ticket++;
    job.ticket = ticket;
    L->in_flight.insert(ticket);
    L->queue.push_back(std::move(job));
  }
  L->cv_work.notify_one();
  return ticket;
}

// Block until the ticket's gather is complete. Returns 0.
int draco_loader_wait(void* h, long long ticket) {
  Loader* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_done.wait(lk, [&] { return L->in_flight.count(ticket) == 0; });
  return 0;
}

}  // extern "C"
