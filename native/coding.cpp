// Native cyclic-code decoder core.
//
// TPU-native re-design of the reference's native decoder (reference:
// src/c_coding.cpp:15-84 — pybind11/Eigen `solve_poly_a`): same algebra
// (syndrome -> Hankel system -> error-locator polynomial), but exposed as a
// plain C ABI (ctypes-loadable, no pybind11 in this image) and extended with
// a complete host-side decoder `draco_cyclic_decode` used as (a) the test
// oracle for the jit/Pallas decode path in draco_tpu/coding/cyclic.py and
// (b) a host fallback when no accelerator is attached.
//
// No Eigen: the systems are at most (n-2s)x(n-2s); hand-rolled complex
// Gaussian elimination with partial pivoting + a truncated-eigendecomposition
// pseudoinverse for the rank-deficiency-prone locator solve (mirroring the
// jnp path's handling, which in turn mirrors the reference's SVD
// least-squares, c_coding.cpp:81).

#include <algorithm>
#include <cmath>
#include <complex>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

using cd = std::complex<double>;
constexpr double kPi = 3.14159265358979323846;

// Solve A x = b in-place (m x m complex, Gaussian elimination, partial
// pivoting). Returns false if singular to working precision.
bool solve_ge(std::vector<cd>& a, std::vector<cd>& b, int m) {
  for (int col = 0; col < m; ++col) {
    int piv = col;
    double best = std::abs(a[col * m + col]);
    for (int r = col + 1; r < m; ++r) {
      double v = std::abs(a[r * m + col]);
      if (v > best) { best = v; piv = r; }
    }
    if (best < 1e-300) return false;
    if (piv != col) {
      for (int c = 0; c < m; ++c) std::swap(a[col * m + c], a[piv * m + c]);
      std::swap(b[col], b[piv]);
    }
    cd inv = 1.0 / a[col * m + col];
    for (int r = col + 1; r < m; ++r) {
      cd f = a[r * m + col] * inv;
      if (f == cd(0.0, 0.0)) continue;
      for (int c = col; c < m; ++c) a[r * m + c] -= f * a[col * m + c];
      b[r] -= f * b[col];
    }
  }
  for (int r = m - 1; r >= 0; --r) {
    cd acc = b[r];
    for (int c = r + 1; c < m; ++c) acc -= a[r * m + c] * b[c];
    b[r] = acc / a[r * m + r];
  }
  return true;
}

// Truncated-pseudoinverse least squares via eigendecomposition of the
// normal-equations gram: x = V f(Λ) V^T A^T b with 1/λ zeroed below
// (rcond·σmax)².  Matches draco_tpu.coding.cyclic._complex_solve's rcond
// branch (SVD-truncated lstsq, same relative singular-value threshold —
// the float64 gram here resolves σ down to ~1e-8·σmax, far below the
// cutoff): exact on full-rank systems, NaN-free min-norm solve on
// rank-deficient ones (fewer than s corrupt rows).  A is m x m complex,
// handled as the real symmetric 2m x 2m embedding; eigendecomposition by
// cyclic Jacobi (systems are tiny).
bool solve_trunc(const std::vector<cd>& a, const std::vector<cd>& b,
                 std::vector<cd>& x, int m, double rcond) {
  int d = 2 * m;
  std::vector<double> B(d * d), r(d);
  for (int i = 0; i < m; ++i) {
    for (int j = 0; j < m; ++j) {
      double re = a[i * m + j].real(), im = a[i * m + j].imag();
      B[i * d + j] = re;
      B[i * d + (m + j)] = -im;
      B[(m + i) * d + j] = im;
      B[(m + i) * d + (m + j)] = re;
    }
    r[i] = b[i].real();
    r[m + i] = b[i].imag();
  }
  std::vector<double> G(d * d), atb(d);
  for (int i = 0; i < d; ++i) {
    for (int j = 0; j < d; ++j) {
      double acc = 0.0;
      for (int k = 0; k < d; ++k) acc += B[k * d + i] * B[k * d + j];
      G[i * d + j] = acc;
    }
    double acc = 0.0;
    for (int k = 0; k < d; ++k) acc += B[k * d + i] * r[k];
    atb[i] = acc;
  }
  std::vector<double> V(d * d, 0.0);
  for (int i = 0; i < d; ++i) V[i * d + i] = 1.0;
  for (int sweep = 0; sweep < 64; ++sweep) {
    double off = 0.0;
    for (int p = 0; p < d; ++p)
      for (int q = p + 1; q < d; ++q) off += G[p * d + q] * G[p * d + q];
    if (off < 1e-28) break;
    for (int p = 0; p < d; ++p) {
      for (int q = p + 1; q < d; ++q) {
        double apq = G[p * d + q];
        if (std::abs(apq) < 1e-300) continue;
        double theta = (G[q * d + q] - G[p * d + p]) / (2.0 * apq);
        double t = (theta >= 0 ? 1.0 : -1.0) /
                   (std::abs(theta) + std::sqrt(theta * theta + 1.0));
        double c = 1.0 / std::sqrt(t * t + 1.0), sn = t * c;
        for (int k = 0; k < d; ++k) {
          double gkp = G[k * d + p], gkq = G[k * d + q];
          G[k * d + p] = c * gkp - sn * gkq;
          G[k * d + q] = sn * gkp + c * gkq;
        }
        for (int k = 0; k < d; ++k) {
          double gpk = G[p * d + k], gqk = G[q * d + k];
          G[p * d + k] = c * gpk - sn * gqk;
          G[q * d + k] = sn * gpk + c * gqk;
        }
        for (int k = 0; k < d; ++k) {
          double vkp = V[k * d + p], vkq = V[k * d + q];
          V[k * d + p] = c * vkp - sn * vkq;
          V[k * d + q] = sn * vkp + c * vkq;
        }
      }
    }
  }
  double wmax = 0.0;
  for (int i = 0; i < d; ++i) wmax = std::max(wmax, G[i * d + i]);
  // rcond is a relative *singular-value* cutoff (σ = sqrt λ of the gram);
  // squared here so the threshold matches the jit path's SVD lstsq rcond.
  double cutoff = rcond * rcond * std::max(wmax, 0.0);
  std::vector<double> tmp(d, 0.0), xr(d, 0.0);
  for (int i = 0; i < d; ++i) {
    double acc = 0.0;
    for (int k = 0; k < d; ++k) acc += V[k * d + i] * atb[k];
    double w = G[i * d + i];
    tmp[i] = (w > cutoff && w > 0.0) ? acc / w : 0.0;
  }
  for (int k = 0; k < d; ++k) {
    double acc = 0.0;
    for (int i = 0; i < d; ++i) acc += V[k * d + i] * tmp[i];
    xr[k] = acc;
  }
  x.resize(m);
  for (int i = 0; i < m; ++i) x[i] = cd(xr[i], xr[m + i]);
  return true;
}

// C[p][q] = exp(-2*pi*i*p*q/n)/sqrt(n) (draco_tpu.coding.cyclic._dft_c;
// reference builds the same matrix natively, c_coding.cpp:38-60).
std::vector<cd> dft_c(int n) {
  std::vector<cd> c(n * n);
  double scale = 1.0 / std::sqrt((double)n);
  for (int p = 0; p < n; ++p)
    for (int q = 0; q < n; ++q) {
      double ang = -2.0 * kPi * (double)((long long)p * q % n) / n;
      c[p * n + q] = cd(std::cos(ang) * scale, std::sin(ang) * scale);
    }
  return c;
}

// Error-locator coefficients alpha from the projected received column e
// (length n).  Mirrors c_coding.cpp:65-81: syndrome E2 = C2^H e, Hankel
// system A[i][j] = E2[s-1-i+j], rhs b[i] = E2[2s-1-i], ridge least squares.
bool locator_alpha(int n, int s, const cd* e, std::vector<cd>& alpha) {
  int m = n - 2 * s;  // C1 width; C2 = columns m..n-1
  std::vector<cd> c = dft_c(n);
  std::vector<cd> e2(2 * s);
  for (int r = 0; r < 2 * s; ++r) {
    cd acc(0.0, 0.0);
    for (int i = 0; i < n; ++i) acc += std::conj(c[i * n + (m + r)]) * e[i];
    e2[r] = acc;
  }
  double scale = 0.0;
  for (const cd& v : e2) scale = std::max(scale, std::abs(v));
  scale = std::max(scale, 1e-30);
  std::vector<cd> a(s * s);
  std::vector<cd> b(s);
  for (int i = 0; i < s; ++i) {
    for (int j = 0; j < s; ++j) a[i * s + j] = e2[s - 1 - i + j] / scale;
    b[i] = e2[2 * s - 1 - i] / scale;
  }
  // kept identical to draco_tpu.coding.cyclic.LOCATOR_RCOND so native and
  // jit decodes rank borderline (rank-deficient) rows the same way
  return solve_trunc(a, b, alpha, s, 1e-5);
}

}  // namespace

extern "C" {

// Reference-parity entry point (c_coding.cpp:15,91 `solve_poly_a`): e is the
// projected column (n complex values as separate re/im arrays); writes the s
// error-locator coefficients. Returns 0 on success.
int draco_solve_poly_a(int n, int s, const double* e_re, const double* e_im,
                       double* alpha_re, double* alpha_im) {
  if (n <= 4 * s || s <= 0) return 1;
  std::vector<cd> e(n);
  for (int i = 0; i < n; ++i) e[i] = cd(e_re[i], e_im[i]);
  std::vector<cd> alpha;
  if (!locator_alpha(n, s, e.data(), alpha)) return 2;
  for (int i = 0; i < s; ++i) { alpha_re[i] = alpha[i].real(); alpha_im[i] = alpha[i].imag(); }
  return 0;
}

// Full host decode (cyclic_master.py:152-173 semantics, matching the
// fixed-shape jnp decode in draco_tpu/coding/cyclic.py):
//   r_re/r_im: (n, d) row-major received rows, <= s arbitrarily corrupt.
//   rand_factor: (d,) projection.
//   present: optional (n,) 0/1 — 0 rows are erasures (known-missing,
//     zero-filled by the caller); pass null for all-present. Same budget as
//     the jit decode: erasure-only e <= 2s, or errors + erasures <= s.
//   out: (d,) = Re(v^T R) / n, i.e. the mean of the n batch gradients.
//   honest_out: (n,) 0/1 mask of rows the recombination used (may be null).
// Returns 0 on success.
int draco_cyclic_decode_present(int n, int s, long long d,
                                const float* r_re, const float* r_im,
                                const double* rand_factor,
                                const int32_t* present,
                                float* out, int32_t* honest_out,
                                int num_threads) {
  if (n <= 4 * s || s < 0 || d <= 0) return 1;
  int m = n - 2 * s;
  if (num_threads < 1) num_threads = (int)std::thread::hardware_concurrency();
  if (num_threads < 1) num_threads = 1;
  num_threads = std::min<long long>(num_threads, std::max<long long>(1, d / 4096 + 1));

  // 1. project e = R f (threaded over the d axis with partial sums)
  std::vector<cd> e(n, cd(0.0, 0.0));
  {
    std::vector<std::vector<cd>> partial(num_threads, std::vector<cd>(n, cd(0.0, 0.0)));
    std::vector<std::thread> ts;
    long long chunk = (d + num_threads - 1) / num_threads;
    for (int t = 0; t < num_threads; ++t) {
      ts.emplace_back([&, t] {
        long long lo = t * chunk, hi = std::min<long long>(d, lo + chunk);
        for (int i = 0; i < n; ++i) {
          double ar = 0.0, ai = 0.0;
          const float* rr = r_re + (long long)i * d;
          const float* ri = r_im + (long long)i * d;
          for (long long j = lo; j < hi; ++j) {
            ar += (double)rr[j] * rand_factor[j];
            ai += (double)ri[j] * rand_factor[j];
          }
          partial[t][i] = cd(ar, ai);
        }
      });
    }
    for (auto& th : ts) th.join();
    for (int t = 0; t < num_threads; ++t)
      for (int i = 0; i < n; ++i) e[i] += partial[t][i];
  }

  // 2-4. locator polynomial -> per-row magnitudes
  std::vector<double> mag(n, 1.0);
  if (s > 0) {
    std::vector<cd> alpha;
    if (!locator_alpha(n, s, e.data(), alpha)) return 2;
    // p(z) = z^s - sum_j alpha_j z^j on the grid z_t = exp(+2*pi*i*t/n)
    for (int t = 0; t < n; ++t) {
      double ang = 2.0 * kPi * t / n;
      cd z(std::cos(ang), std::sin(ang));
      cd zp(1.0, 0.0);
      cd val(0.0, 0.0);
      for (int j = 0; j < s; ++j) { val -= alpha[j] * zp; zp *= z; }
      val += zp;  // z^s
      mag[t] = std::norm(val);
    }
  }

  // Deterministic tie-break matching draco_tpu.coding.cyclic._locate_v:
  // index-monotone bias pins the honest-set choice when grid-symmetric rows
  // tie in exact arithmetic (must stay identical across jit/native paths).
  {
    double mean = 0.0;
    for (int i = 0; i < n; ++i) mean += mag[i];
    mean /= n;
    for (int i = 0; i < n; ++i) mag[i] += i * (1e-3 / n) * mean;
  }

  // 5. recombination v on the top n-2s rows by locator magnitude (corrupt
  //    rows are locator roots, so they rank in the bottom s; top-m selection
  //    stays full-rank even under fewer-than-s actual corruptions — same
  //    policy as the jit decode), solve C1[idx]^T v = e1. honest_out marks
  //    exactly the rows used. Absent rows are never eligible.
  if (present)
    for (int i = 0; i < n; ++i)
      if (!present[i]) mag[i] = -1.0;
  std::vector<int> order(n);
  for (int i = 0; i < n; ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(),
                   [&](int a, int b) { return mag[a] > mag[b]; });
  std::vector<int> idx(order.begin(), order.begin() + m);
  std::sort(idx.begin(), idx.end());
  if (honest_out) {
    for (int i = 0; i < n; ++i) honest_out[i] = 0;
    for (int i : idx) honest_out[i] = 1;
  }
  std::vector<cd> c = dft_c(n);
  std::vector<cd> a(m * m);  // a[k][j] = C1[idx[j]][k]  (the transpose)
  for (int k = 0; k < m; ++k)
    for (int j = 0; j < m; ++j) a[k * m + j] = c[idx[j] * n + k];
  std::vector<cd> v(m, cd(0.0, 0.0));
  v[0] = cd(1.0, 0.0);
  if (!solve_ge(a, v, m)) return 4;

  // 6. out = Re(v^T R)/n, threaded over d
  {
    std::vector<std::thread> ts;
    long long chunk = (d + num_threads - 1) / num_threads;
    for (int t = 0; t < num_threads; ++t) {
      ts.emplace_back([&, t] {
        long long lo = t * chunk, hi = std::min<long long>(d, lo + chunk);
        for (long long j = lo; j < hi; ++j) out[j] = 0.0f;
        for (int j = 0; j < m; ++j) {
          int row = idx[j];
          double vr = v[j].real(), vi = v[j].imag();
          const float* rr = r_re + (long long)row * d;
          const float* ri = r_im + (long long)row * d;
          for (long long k = lo; k < hi; ++k)
            out[k] += (float)((vr * rr[k] - vi * ri[k]) / n);
        }
      });
    }
    for (auto& th : ts) th.join();
  }
  return 0;
}

// Back-compat entry without erasure support.
int draco_cyclic_decode(int n, int s, long long d,
                        const float* r_re, const float* r_im,
                        const double* rand_factor,
                        float* out, int32_t* honest_out, int num_threads) {
  return draco_cyclic_decode_present(n, s, d, r_re, r_im, rand_factor, nullptr,
                                     out, honest_out, num_threads);
}

}  // extern "C"
