// Native gradient wire compression: byte-shuffle filter + zlib deflate.
//
// Replaces the reference's blosc('snappy') gradient packer (reference:
// src/compress_gradient.py:7-15). blosc is not in this image; the shuffle
// filter it applies before the codec is what makes float gradients
// compressible, so we implement shuffle + deflate directly. The byte format
// is owned by draco_tpu/utils/compress.py (which prepends dtype/shape
// headers); this file only transforms raw byte payloads.

#include <cstdint>
#include <cstring>
#include <vector>

#include <zlib.h>

namespace {

// Byte transposition across elements: output groups byte 0 of every element,
// then byte 1, ... (same filter as blosc's SHUFFLE).
void shuffle_bytes(const uint8_t* src, uint8_t* dst, long long nbytes, int elem) {
  long long nelem = nbytes / elem;
  for (int b = 0; b < elem; ++b) {
    const uint8_t* s = src + b;
    uint8_t* o = dst + b * nelem;
    for (long long i = 0; i < nelem; ++i) o[i] = s[i * elem];
  }
  // trailing bytes (nbytes not divisible by elem) are passed through
  std::memcpy(dst + nelem * elem, src + nelem * elem, nbytes - nelem * elem);
}

void unshuffle_bytes(const uint8_t* src, uint8_t* dst, long long nbytes, int elem) {
  long long nelem = nbytes / elem;
  for (int b = 0; b < elem; ++b) {
    const uint8_t* s = src + b * nelem;
    uint8_t* o = dst + b;
    for (long long i = 0; i < nelem; ++i) o[i * elem] = s[i];
  }
  std::memcpy(dst + nelem * elem, src + nelem * elem, nbytes - nelem * elem);
}

}  // namespace

extern "C" {

long long draco_compress_bound(long long nbytes) {
  return (long long)compressBound((uLong)nbytes);
}

// Shuffle (if elem_size > 1) then deflate. Returns compressed size, or -1 on
// error. dst must have capacity draco_compress_bound(nbytes).
long long draco_compress(const uint8_t* src, long long nbytes, int elem_size,
                         uint8_t* dst, long long dst_cap, int level) {
  if (nbytes < 0 || elem_size < 1) return -1;
  const uint8_t* payload = src;
  std::vector<uint8_t> shuffled;
  if (elem_size > 1 && nbytes >= elem_size) {
    shuffled.resize(nbytes);
    shuffle_bytes(src, shuffled.data(), nbytes, elem_size);
    payload = shuffled.data();
  }
  uLongf out_len = (uLongf)dst_cap;
  if (compress2(dst, &out_len, payload, (uLong)nbytes, level) != Z_OK) return -1;
  return (long long)out_len;
}

// Inflate then unshuffle. dst_bytes must be the exact original size.
// Returns dst_bytes, or -1 on error.
long long draco_decompress(const uint8_t* src, long long src_bytes,
                           uint8_t* dst, long long dst_bytes, int elem_size) {
  if (src_bytes < 0 || dst_bytes < 0 || elem_size < 1) return -1;
  if (elem_size > 1 && dst_bytes >= elem_size) {
    std::vector<uint8_t> shuffled(dst_bytes);
    uLongf out_len = (uLongf)dst_bytes;
    if (uncompress(shuffled.data(), &out_len, src, (uLong)src_bytes) != Z_OK) return -1;
    if ((long long)out_len != dst_bytes) return -1;
    unshuffle_bytes(shuffled.data(), dst, dst_bytes, elem_size);
    return dst_bytes;
  }
  uLongf out_len = (uLongf)dst_bytes;
  if (uncompress(dst, &out_len, src, (uLong)src_bytes) != Z_OK) return -1;
  return (long long)out_len;
}

}  // extern "C"
