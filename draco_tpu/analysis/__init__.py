"""Static analysis of the chip-bound jitted programs (the program linter).

``registry``  — catalog of every hot-loop program + its Manifest
``rules``     — the nine rules (constant_bloat, donation, dtype,
                collectives, host_traffic, memory_budget,
                sharding_contract, collective_axes, replication_leaks)
                over jaxpr + exported StableHLO + compiled memory/cost
                analysis and I/O shardings
``sharding``  — the static sharding auditor (rules 7-9): partition-table
                coverage, per-axis collective classification and the
                replication-leak check against parallel/partition.py
``controls``  — seeded-defect programs proving each rule is live

Driver: ``tools/program_lint.py`` (artifact
``baselines_out/program_lint.json``); CI: ``tests/test_program_lint.py``.
"""

from draco_tpu.analysis.registry import (  # noqa: F401
    BF16_DTYPES,
    COLLECTIVE_KINDS,
    DEFAULT_DTYPES,
    BuiltProgram,
    LintProgram,
    Manifest,
    collect,
    get,
)
from draco_tpu.analysis.rules import (  # noqa: F401
    RULE_NAMES,
    lint_built,
    lint_program,
    trace_and_export,
)
from draco_tpu.analysis.sharding import (  # noqa: F401
    classify_collective,
    parse_module_collectives,
    rule_collective_axes,
    rule_replication_leaks,
    rule_sharding_contract,
)
