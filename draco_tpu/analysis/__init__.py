"""Static analysis of the chip-bound jitted programs (the program linter).

``registry``  — catalog of every hot-loop program + its Manifest
``rules``     — the six rules (constant_bloat, donation, dtype,
                collectives, host_traffic, memory_budget) over jaxpr +
                exported StableHLO + compiled memory/cost analysis
``controls``  — seeded-defect programs proving each rule is live

Driver: ``tools/program_lint.py`` (artifact
``baselines_out/program_lint.json``); CI: ``tests/test_program_lint.py``.
"""

from draco_tpu.analysis.registry import (  # noqa: F401
    BF16_DTYPES,
    COLLECTIVE_KINDS,
    DEFAULT_DTYPES,
    BuiltProgram,
    LintProgram,
    Manifest,
    collect,
    get,
)
from draco_tpu.analysis.rules import (  # noqa: F401
    RULE_NAMES,
    lint_built,
    lint_program,
    trace_and_export,
)
