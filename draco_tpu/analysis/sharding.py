"""The static sharding auditor: lint rules 7-9 (sharding_contract,
collective_axes, replication_leaks).

Both GSPMD defects the chaos harness has caught were statically decidable
and were caught at runtime anyway: PR 6's retrace-on-reshard (an
unnormalized ``P('tp', None)`` carry spec compared unequal to XLA's
normalized report, silently retracing every second dispatch) and PR 7's
sharded bitmask pack (a ``P('w')`` buffer that silently went replicated
and shifted every bit). These rules make that class fail
``program_lint.json`` instead of a chaos cell three PRs later:

  sharding_contract  (a) every array arg leaf matches EXACTLY ONE rule of
                     the program's declared partition table
                     (parallel/partition.py) and that rule's spec is
                     normalized (``norm_spec`` fixed-point — the PR 6
                     class); (b) every donated state input leaf's compiled
                     sharding equals its corresponding output leaf's (the
                     static form of retrace-on-reshard: in != out means
                     the second dispatch reshards the carry)
  collective_axes    every explicit collective in the exported module is
                     classified by the mesh axis it reduces over (via
                     ``replica_groups`` / ``source_target_pairs`` against
                     the mesh's device grid) and the per-axis {kind:
                     count} map must equal ``Manifest.collective_axes``
                     — tree combine programs pin one psum per level ON
                     that level's axis; the row also carries a per-axis
                     byte ledger so cross-host vs intra-host traffic is
                     priced before multi-host lands (ROADMAP item 1)
  replication_leaks  arrays the partition table declares sharded over a
                     real (size>1) mesh axis must not compile
                     fully-replicated — the silent O(n*d) memory /
                     bandwidth regression class (the PR 7 neighborhood)

The compiled I/O shardings come from the same host compile that already
records the memory ledger (``rules.trace_and_export``); the collective
classification reads the exported StableHLO text, where explicit
(shard_map) collectives carry their device groups and GSPMD-deferred ones
do not yet exist — the same boundary the count rule (rule 4) pins.
"""

from __future__ import annotations

import json
import re
from typing import Optional

from draco_tpu.parallel.partition import (
    arg_leaf_paths,
    match_report,
    norm_spec,
    spec_axes,
)

_ITEMSIZE = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "i32": 4, "i16": 2, "i8": 1, "i1": 1,
    "ui64": 8, "ui32": 4, "ui16": 2, "ui8": 1,
    "complex<f32>": 8, "complex<f64>": 16,
}

_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(all_reduce|all_gather|all_to_all|collective_permute|"
    r"reduce_scatter)\b")

# the function-type separator that ends an op's operand segment: generic
# non-region ops print `}> : (tensor<...`, region ops `}) : (tensor<...`;
# region BODIES pretty-print (`stablehlo.add ... : tensor<f32>`, no
# parenthesized function type), so the first match is the op's own type
_OPERAND_TYPE_RE = re.compile(
    r"\)\s*:\s*\(\s*tensor<((?:\d+x)*)([a-z0-9]+(?:<[a-z0-9]+>)?)>")

_GROUPS_RE = re.compile(
    r"replica_groups\s*=\s*dense<(.*?)>\s*:\s*tensor<((?:\d+x?)*)xi64>",
    re.S)
_PAIRS_RE = re.compile(
    r"source_target_pairs\s*=\s*dense<(.*?)>\s*:\s*tensor<((?:\d+x?)*)xi64>",
    re.S)


def _skip(reason):
    return {"ok": True, "skipped": True, "reason": reason}


def _parse_id_matrix(body: str, dims_txt: str) -> "list[list[int]]":
    """A dense<...> i64 matrix attr: JSON-shaped nested lists, or a splat
    scalar broadcast to the attr's tensor shape."""
    body = body.strip()
    dims = [int(d) for d in dims_txt.split("x") if d]
    if not body:
        return []
    if body.startswith("["):
        mat = json.loads(body)
        if mat and not isinstance(mat[0], list):
            mat = [mat]
        return [[int(v) for v in row] for row in mat]
    rows, cols = (dims + [1, 1])[:2]
    return [[int(body)] * cols for _ in range(rows)]


def parse_module_collectives(mlir_text: str) -> "list[dict]":
    """Every explicit collective op in the exported module text, with its
    device groups (or permute pairs) and per-shard operand bytes."""
    ops = []
    for m in _COLLECTIVE_RE.finditer(mlir_text):
        window = mlir_text[m.start():m.start() + 20000]
        tm = _OPERAND_TYPE_RE.search(window)
        nbytes = None
        if tm is not None:
            dims = [int(d) for d in tm.group(1).split("x") if d]
            elems = 1
            for d in dims:
                elems *= d
            nbytes = elems * _ITEMSIZE.get(tm.group(2), 4)
        attrs = window[:tm.start()] if tm is not None else window
        op = {"kind": m.group(1), "bytes": nbytes,
              "groups": None, "pairs": None}
        gm = _GROUPS_RE.search(attrs)
        if gm is not None:
            op["groups"] = _parse_id_matrix(gm.group(1), gm.group(2))
        pm = _PAIRS_RE.search(attrs)
        if pm is not None:
            op["pairs"] = _parse_id_matrix(pm.group(1), pm.group(2))
        ops.append(op)
    return ops


def _device_grids(mesh):
    """The two id models a module's device groups may use, as mesh-shaped
    integer grids: flat position in the mesh's device assignment
    (partition ids) and the actual jax device ids (use_global_device_ids).
    On the reshaped-``jax.devices()`` CI meshes they coincide."""
    import numpy as np

    shape = tuple(mesh.devices.shape)
    flat = np.arange(int(np.prod(shape))).reshape(shape)
    ids = np.vectorize(lambda d: d.id)(mesh.devices).reshape(shape)
    return [flat, ids]


def _axis_partitions(mesh):
    """axis name -> candidate partitions of device ids into groups that a
    collective over exactly that axis would carry (size-1 axes excluded:
    a collective over a trivial axis is a no-op and classifies nowhere)."""
    import numpy as np

    names = list(mesh.axis_names)
    parts = {}
    for grid in _device_grids(mesh):
        for i, name in enumerate(names):
            size = grid.shape[i]
            if size <= 1:
                continue
            rows = np.moveaxis(grid, i, -1).reshape(-1, size)
            part = frozenset(frozenset(int(v) for v in row) for row in rows)
            parts.setdefault(name, set()).add(part)
    return parts


def classify_collective(mesh, op: dict) -> Optional[str]:
    """The mesh axis a collective reduces/permutes over, or None."""
    import numpy as np

    parts = _axis_partitions(mesh)
    if op.get("groups"):
        observed = frozenset(frozenset(g) for g in op["groups"])
        for axis, candidates in parts.items():
            if observed in candidates:
                return axis
        return None
    if op.get("pairs"):
        names = list(mesh.axis_names)
        for grid in _device_grids(mesh):
            coords = {int(grid[idx]): idx
                      for idx in np.ndindex(*grid.shape)}
            axes = set()
            ok = True
            for s, t in op["pairs"]:
                if s not in coords or t not in coords:
                    ok = False
                    break
                diff = [i for i in range(len(names))
                        if coords[s][i] != coords[t][i]]
                if len(diff) != 1:
                    ok = False
                    break
                axes.add(names[diff[0]])
            if ok and len(axes) == 1:
                return axes.pop()
        return None
    return None


def _spec_of(sharding):
    return getattr(sharding, "spec", None)


def rule_sharding_contract(art) -> dict:
    """Rule 7: partition-table coverage (exactly-one match, normalized
    spec) + donated-carry sharding equality (compiled input leaf sharding
    == corresponding output leaf sharding)."""
    import jax

    built = art.built
    res: dict = {}
    errors = []

    if built.partition_rules is None:
        res["table"] = {"skipped": True,
                        "reason": "no partition table registered"}
    else:
        paths = arg_leaf_paths(built.args, built.arg_names)
        report = match_report(built.partition_rules, paths)
        bad = [r for r in report
               if r["n_matches"] != 1 or not r["normalized"]]
        res["table"] = {"leaves_checked": len(report),
                        "violations": bad[:6]}
        for r in bad[:3]:
            if r["n_matches"] == 0:
                errors.append(f"{r['path']}: matched by NO partition rule "
                              f"— extend the route table "
                              f"(parallel/partition.py)")
            elif r["n_matches"] > 1:
                errors.append(f"{r['path']}: matched by {r['n_matches']} "
                              f"partition rules — tables must be disjoint")
            else:
                errors.append(f"{r['path']}: rule spec {r['spec']} is not "
                              f"normalized (trailing None) — the PR 6 "
                              f"retrace-on-reshard class; declare "
                              f"norm_spec fixed-points only")

    if art.manifest.require_donated is None:
        res["carry"] = {"skipped": True,
                        "reason": "no donated state carry to hold the "
                                  "in==out contract to"}
    elif art.input_shardings is None or art.output_shardings is None:
        res["carry"] = {"skipped": True,
                        "reason": f"compiled shardings unavailable: "
                                  f"{art.compile_error or art.export_error}"}
    else:
        paths = arg_leaf_paths(built.args, built.arg_names)
        n_state = len(jax.tree.leaves(built.args[0]))
        if (len(art.input_shardings) != len(paths)
                or len(art.output_shardings) < n_state):
            res["carry"] = {
                "skipped": True,
                "reason": f"cannot align leaves to compiled shardings "
                          f"({len(art.input_shardings)} input shardings "
                          f"for {len(paths)} arg leaves — jit pruned "
                          f"unused args)"}
        else:
            mismatched = []
            for i in range(n_state):
                s_in = _spec_of(art.input_shardings[i])
                s_out = _spec_of(art.output_shardings[i])
                if s_in is not None and s_out is not None:
                    same = norm_spec(s_in) == norm_spec(s_out)
                else:  # non-Named shardings: compare HLO sharding text
                    same = str(art.input_shardings[i]) == str(
                        art.output_shardings[i])
                if not same:
                    mismatched.append({"path": paths[i][0],
                                       "in": str(s_in),
                                       "out": str(s_out)})
            res["carry"] = {"state_leaves": n_state,
                            "mismatched": mismatched[:6]}
            for mm in mismatched[:3]:
                errors.append(
                    f"{mm['path']}: donated carry enters {mm['in']} but "
                    f"returns {mm['out']} — the second dispatch reshards "
                    f"(static retrace-on-reshard, the PR 6 bug shape); "
                    f"commit the state sharding and pin out_shardings")

    if res["table"].get("skipped") and res["carry"].get("skipped"):
        return {**_skip(f"{res['table']['reason']}; "
                        f"{res['carry']['reason']}"), **res}
    if errors:
        return {"ok": False, **res, "error": "; ".join(errors)}
    return {"ok": True, **res}


def rule_collective_axes(art) -> dict:
    """Rule 8: per-axis collective budget + the per-axis byte ledger."""
    from draco_tpu.analysis.registry import COLLECTIVE_KINDS

    m = art.manifest
    if m.collective_axes is None:
        return _skip("manifest.collective_axes is None (kernel-only or "
                     "meshless program)")
    if art.mlir_text is None:
        return _skip(f"export unavailable: {art.export_error}")
    mesh = art.built.mesh
    if mesh is None:
        return {"ok": False,
                "error": "manifest.collective_axes declared but the "
                         "program registered no mesh to classify against"}
    unknown_axes = set(m.collective_axes) - set(mesh.axis_names)
    unknown_kinds = {k for per in m.collective_axes.values()
                     for k in per} - set(COLLECTIVE_KINDS)
    if unknown_axes or unknown_kinds:
        return {"ok": False,
                "error": f"manifest.collective_axes names unknown "
                         f"axes {sorted(unknown_axes)} / kinds "
                         f"{sorted(unknown_kinds)}"}

    observed: dict = {}
    ledger: dict = {}
    for op in parse_module_collectives(art.mlir_text):
        axis = classify_collective(mesh, op) or "?"
        observed.setdefault(axis, {}).setdefault(op["kind"], 0)
        observed[axis][op["kind"]] += 1
        led = ledger.setdefault(axis, {"ops": 0, "bytes": 0})
        led["ops"] += 1
        led["bytes"] += op["bytes"] or 0

    expected = {axis: {k: int(n) for k, n in per.items() if n}
                for axis, per in m.collective_axes.items()}
    expected = {axis: per for axis, per in expected.items() if per}
    res = {"observed": observed, "expected": expected,
           "axis_ledger": ledger}
    if observed != expected:
        return {"ok": False, **res,
                "error": f"per-axis collective structure drifted from the "
                         f"manifest (expected {expected}, observed "
                         f"{observed}; axis '?' = device groups matching "
                         f"no single mesh axis) — a wrong-axis psum "
                         f"reduces over the wrong devices even when the "
                         f"op COUNT is unchanged; a deliberate topology "
                         f"change updates Manifest.collective_axes"}
    return {"ok": True, **res}


def rule_replication_leaks(art) -> dict:
    """Rule 9: declared-sharded arrays must not compile fully-replicated
    (checked on real, size>1 mesh axes; the folded w x 1 meshes make
    trivial-axis sharding vacuous by construction)."""
    built = art.built
    if built.partition_rules is None:
        return _skip("no partition table registered")
    if art.input_shardings is None:
        return _skip(f"compiled shardings unavailable: "
                     f"{art.compile_error or art.export_error}")
    mesh_sizes = dict(built.mesh.shape) if built.mesh is not None else {}
    paths = arg_leaf_paths(built.args, built.arg_names)
    report = match_report(built.partition_rules, paths)
    declared = {r["path"]: r for r in report}

    if len(art.input_shardings) != len(paths):
        # jit pruned unused args -> positional alignment is impossible;
        # fall back to the aggregate form of the check
        any_decl = any(
            r["n_matches"] == 1 and r["spec"] not in (None, "PartitionSpec()")
            for r in report)
        all_repl = all(getattr(s, "is_fully_replicated", False)
                       for s in art.input_shardings)
        res = {"aggregate_only": True,
               "reason": f"{len(art.input_shardings)} compiled input "
                         f"shardings for {len(paths)} arg leaves (jit "
                         f"pruned unused args)",
               "inputs_checked": len(art.input_shardings)}
        if any_decl and art.input_shardings and all_repl:
            return {"ok": False, **res,
                    "error": "the table declares sharded buffers but "
                             "EVERY compiled input is fully replicated — "
                             "the O(n*d) replication-leak class"}
        return {"ok": True, **res}

    leaks = []
    checked = 0
    for (path, leaf), sh in zip(paths, art.input_shardings):
        r = declared.get(path)
        if r is None or r["n_matches"] != 1:
            continue  # scalars / coverage problems: rule 7's business
        rule_spec = next(spec for pat, spec in built.partition_rules
                         if re.search(pat, path))
        need = {a for a in spec_axes(rule_spec)
                if mesh_sizes.get(a, 1) > 1}
        if not need:
            continue
        checked += 1
        compiled_spec = _spec_of(sh)
        if compiled_spec is None:
            if getattr(sh, "is_fully_replicated", False):
                leaks.append({"path": path, "declared": str(rule_spec),
                              "compiled": "replicated"})
            continue
        if not need <= set(spec_axes(compiled_spec)):
            leaks.append({"path": path, "declared": str(rule_spec),
                          "compiled": str(compiled_spec)})
    res = {"declared_sharded_leaves": checked, "leaks": leaks[:6]}
    if leaks:
        return {"ok": False, **res,
                "error": f"{len(leaks)} table-declared-sharded arrays "
                         f"compile without their declared axes (first: "
                         f"{leaks[0]['path']} declared "
                         f"{leaks[0]['declared']}, compiled "
                         f"{leaks[0]['compiled']}) — a fully-replicated "
                         f"'sharded' buffer is the silent O(n*d) "
                         f"memory/bandwidth regression (the PR 7 "
                         f"sharded-pack neighborhood)"}
    return {"ok": True, **res}
