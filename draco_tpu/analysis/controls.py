"""Negative controls: one deliberately-defective program per lint rule.

A linter that silently stops seeing defects is worse than no linter — the
round-5 d-sized-constant bug shipped precisely because nothing was looking.
Each control here seeds exactly ONE defect of the kind its rule exists to
catch, into an otherwise-clean miniature of the training-step shape
(donated state carry, sharded batch, scalar metrics). The test suite
(tests/test_program_lint.py) and the artifact
(``baselines_out/program_lint.json`` ``negative_controls`` section) assert
that each control trips exactly its rule and every other rule stays green —
the same proving-the-harness-is-live discipline as the mis-tiled
pallas_call in tools/tpu_attn_lowering_check.py.

The controls are self-contained (no model/route imports) so a route
refactor cannot accidentally blunt them.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from draco_tpu.analysis.registry import (
    BuiltProgram,
    LintProgram,
    Manifest,
)


@dataclasses.dataclass(frozen=True)
class Control:
    program: LintProgram
    expected_fail: str  # the one rule this defect must trip


def _mini_mesh():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices())
    return Mesh(devs.reshape(len(devs)), ("w",))


def _mini_state(mesh, d=64):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    return (
        jax.device_put(jnp.zeros((d,), jnp.float32), repl),
        jax.device_put(jnp.asarray(1, jnp.int32), repl),
    )


def _mini_batch(mesh, d=64):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.devices.size
    return jax.device_put(jnp.ones((n, d), jnp.float32),
                          NamedSharding(mesh, P("w")))


def _psum_grads(mesh):
    """The honest miniature's gradient fold: an explicit per-device psum
    (ONE all_reduce), the smallest stand-in for a route's collective
    structure."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from draco_tpu.runtime import shard_map

    return shard_map(lambda x: lax.psum(x, "w"), mesh=mesh,
                     in_specs=P("w", None), out_specs=P(),
                     check_vma=False)


_MINI_COLLECTIVES = {"all_reduce": 1}


def _build_baked_constant() -> BuiltProgram:
    """Defect: a ~2 MB array closed over as a program constant (the round-5
    bug shape, at CI scale)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    mesh = _mini_mesh()
    big = jnp.asarray(np.ones(512 * 1024 + 1, np.float32))  # > 1 MB limit

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)
        w = w - 0.01 * (g + big[: w.shape[0]])
        return (w, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_baked_constant", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES))


def _build_undonated_carry() -> BuiltProgram:
    """Defect: the state carry is NOT donated (donate_argnums dropped)."""
    import jax
    import jax.numpy as jnp

    mesh = _mini_mesh()

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)
        return (w - 0.01 * g, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f)  # <- no donate_argnums
    return BuiltProgram("control_undonated_carry", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES))


def _build_f64_upcast() -> BuiltProgram:
    """Defect: an f64 accumulation inside the step (traced under
    jax.experimental.enable_x64, the only way f64 can sneak in)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    mesh = _mini_mesh()

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)
        g = g.astype(jnp.float64).cumsum().astype(jnp.float32)  # the upcast
        return (w - 0.01 * g, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_f64_upcast", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES),
                        trace_ctx=enable_x64)


def _build_extra_all_gather() -> BuiltProgram:
    """Defect: a gratuitous all_gather next to the budgeted psum (the
    accidental-reshard shape the collective budget exists for)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from draco_tpu.runtime import shard_map

    mesh = _mini_mesh()

    def fold(x):
        g = lax.psum(x, "w")
        extra = lax.all_gather(jnp.sum(x, axis=-1), "w")  # <- unbudgeted
        return g + jnp.sum(extra)

    folded = shard_map(fold, mesh=mesh, in_specs=P("w", None), out_specs=P(),
                       check_vma=False)

    def f(state, x):
        w, step = state
        g = folded(x).sum(0)
        return (w - 0.01 * g, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_extra_all_gather", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES))


def _build_host_outfeed_in_scan() -> BuiltProgram:
    """Defect: an outfeed inside the scanned body — the host round-trip
    that re-serializes every chunk on the dispatch link."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    mesh = _mini_mesh()

    def f(state, xs):
        def body(st, x):
            w, step = st
            g = _psum_grads(mesh)(x).sum(0)
            token = lax.create_token()
            lax.outfeed(token, jnp.sum(g))  # <- host hop per scanned step
            return (w - 0.01 * g, step + 1), jnp.sum(w)

        return lax.scan(body, state, xs)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    xs = jnp.stack([_mini_batch(mesh)] * 2)
    return BuiltProgram("control_host_outfeed_in_scan", fn,
                        (_mini_state(mesh), xs), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES))


def _build_wide_narrow_wire() -> BuiltProgram:
    """Defect (ISSUE 15): the manifest DECLARES a bf16 narrow wire
    (``required_dtypes={"bf16"}``) but the program never materializes a
    bf16 tensor — the silently-f32 "narrow" program shape: a dropped or
    dead-code-eliminated quantize ships the wide wire under a narrow
    name, which only the required-dtypes half of the dtype rule can
    see (all element types are individually allowed)."""
    import jax
    import jax.numpy as jnp

    mesh = _mini_mesh()

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)  # all-f32: the quantize is "gone"
        return (w - 0.01 * g, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    from draco_tpu.analysis.registry import BF16_DTYPES

    return BuiltProgram("control_wide_narrow_wire", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES,
                                 allowed_dtypes=BF16_DTYPES,
                                 required_dtypes=frozenset({"bf16"})))


def _build_memory_hog() -> BuiltProgram:
    """Defect: a working set far beyond the manifest's declared peak-memory
    budget — a runtime (1024, 1024) matrix product whose operands and
    result must materialize (~8 MB of temps against a 4 MB budget). The
    matrix derives from the batch, so neither constant folding nor the
    serialized module absorbs it: the bytes exist only as run-time buffers,
    exactly the class of regression (dropped donation, lost remat, stray
    materialized temp) the memory_budget rule exists to see."""
    import jax
    import jax.numpy as jnp

    mesh = _mini_mesh()

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)
        t = jnp.sin(x.sum()
                    + jnp.arange(1024 * 1024, dtype=jnp.float32)
                    ).reshape(1024, 1024)
        waste = (t @ t.T).sum()  # forces the big temps to materialize
        w = w - 0.01 * (g + waste * 1e-20)
        return (w, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_memory_hog", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES,
                                 max_peak_bytes=4 << 20))


# arg naming + per-axis budget shared by the sharding-auditor controls
# (rules 7-9); the partition table is built lazily (module stays jax-free)
_MINI_ARGS = ("state", "batch")
_MINI_AXES = {"w": {"all_reduce": 1}}


def _mini_rules():
    """The honest miniature's partition table: replicated carry, batch
    rows over w."""
    from jax.sharding import PartitionSpec as P

    return (("^state/", P()), ("^batch$", P("w")))


def _build_resharded_carry() -> BuiltProgram:
    """Defect (the PR 6 bug shape, statically): the donated carry enters
    replicated but the step's output pins it to ``P('w')`` — compiled
    input sharding != output sharding, so the SECOND dispatch of the real
    training loop reshards (and retraces) the carry every step. Only the
    carry half of sharding_contract can see it: counts, dtypes, donation
    and memory are all unchanged."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mini_mesh()
    shard_w = NamedSharding(mesh, P("w"))

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)
        w = jax.lax.with_sharding_constraint(w - 0.01 * g, shard_w)
        return (w, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_resharded_carry", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES,
                                 collective_axes=_MINI_AXES),
                        partition_rules=_mini_rules(), arg_names=_MINI_ARGS)


def _build_unnormalized_spec() -> BuiltProgram:
    """Defect (PR 6's other half): the partition table declares the batch
    as ``P('w', None)`` — NOT a ``norm_spec`` fixed-point. XLA reports
    shardings normalized, so any spec comparison or jit-boundary pin made
    with the trailing-None form compares unequal and silently reshards/
    retraces. The program itself is clean; only the table is wrong."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = _mini_mesh()

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)
        return (w - 0.01 * g, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_unnormalized_spec", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES,
                                 collective_axes=_MINI_AXES),
                        partition_rules=(("^state/", P()),
                                         ("^batch$", P("w", None))),
                        arg_names=_MINI_ARGS)


def _build_unmatched_param() -> BuiltProgram:
    """Defect: the partition table has no rule for the batch operand — an
    array leaf whose sharding nobody declared. Coverage holes are how new
    buffers (a fresh optimizer slot, a new wire tensor) silently pick up
    compiler-chosen layouts; the table subcheck makes the hole itself the
    failure."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = _mini_mesh()

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)
        return (w - 0.01 * g, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_unmatched_param", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES,
                                 collective_axes=_MINI_AXES),
                        partition_rules=(("^state/", P()),),
                        arg_names=_MINI_ARGS)


def _build_wrong_axis_psum() -> BuiltProgram:
    """Defect: on a 2-D (w, tp) mesh the gradient psum reduces over ``tp``
    instead of ``w`` — the COUNT budget (rule 4) still sees exactly one
    all_reduce, but the reduction spans the wrong device groups (summing a
    worker's tensor-parallel replicas instead of folding workers). Only
    the per-axis classification (rule 8) can see it."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from draco_tpu.runtime import shard_map

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs.reshape(len(devs) // 2, 2), ("w", "tp"))

    fold = shard_map(lambda x: lax.psum(x, "tp"),  # <- should be "w"
                     mesh=mesh, in_specs=P("w", None),
                     out_specs=P("w", None), check_vma=False)

    def f(state, x):
        w, step = state
        g = fold(x).sum(0)
        return (w - 0.01 * g, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_wrong_axis_psum", fn,
                        (_mini_state(mesh), _mini_batch(mesh)), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES,
                                 collective_axes=_MINI_AXES),
                        partition_rules=_mini_rules(), arg_names=_MINI_ARGS)


def _build_replicated_wire() -> BuiltProgram:
    """Defect (the PR 7 neighborhood): the table declares the batch wire
    sharded over ``w`` but the program commits it fully replicated — every
    device holds all n workers' rows, the silent O(n*d) memory/bandwidth
    regression. The shard_map boundary reshards internally so the psum
    (and every count/dtype/donation invariant) is unchanged; only
    replication_leaks compares the compiled input against the table."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mini_mesh()
    n = mesh.devices.size
    batch = jax.device_put(jnp.ones((n, 64), jnp.float32),
                           NamedSharding(mesh, P()))  # <- replicated wire

    def f(state, x):
        w, step = state
        g = _psum_grads(mesh)(x).sum(0)
        return (w - 0.01 * g, step + 1), jnp.sum(w)

    with mesh:
        fn = jax.jit(f, donate_argnums=(0,))
    return BuiltProgram("control_replicated_wire", fn,
                        (_mini_state(mesh), batch), mesh,
                        Manifest(collectives=_MINI_COLLECTIVES,
                                 collective_axes=_MINI_AXES),
                        partition_rules=_mini_rules(), arg_names=_MINI_ARGS)


def control_programs() -> Tuple[Control, ...]:
    mk = lambda name, build: LintProgram(  # noqa: E731
        name=name, build=build, route="controls")
    return (
        Control(mk("control_baked_constant", _build_baked_constant),
                "constant_bloat"),
        Control(mk("control_undonated_carry", _build_undonated_carry),
                "donation"),
        Control(mk("control_f64_upcast", _build_f64_upcast), "dtype"),
        Control(mk("control_wide_narrow_wire", _build_wide_narrow_wire),
                "dtype"),
        Control(mk("control_extra_all_gather", _build_extra_all_gather),
                "collectives"),
        Control(mk("control_host_outfeed_in_scan",
                   _build_host_outfeed_in_scan), "host_traffic"),
        Control(mk("control_memory_hog", _build_memory_hog),
                "memory_budget"),
        # the static sharding auditor's live defects (rules 7-9)
        Control(mk("control_resharded_carry", _build_resharded_carry),
                "sharding_contract"),
        Control(mk("control_unnormalized_spec", _build_unnormalized_spec),
                "sharding_contract"),
        Control(mk("control_unmatched_param", _build_unmatched_param),
                "sharding_contract"),
        Control(mk("control_wrong_axis_psum", _build_wrong_axis_psum),
                "collective_axes"),
        Control(mk("control_replicated_wire", _build_replicated_wire),
                "replication_leaks"),
    )
