"""The nine static rules run against every registered chip-bound program.

Each rule inspects the static artifacts of a :class:`~draco_tpu.analysis.
registry.BuiltProgram` — the closed jaxpr (``jit_fn.trace``), the
``jax.export``-ed StableHLO module, and the compiled executable's
memory/cost analysis — against the program's
:class:`~draco_tpu.analysis.registry.Manifest`:

  constant_bloat   no closed-over constant ≥ manifest.max_constant_bytes and
                   the serialized module ≤ max_module_bytes (generalizes the
                   round-5 d-sized-constant guard, tests/test_program_size.py
                   lineage: a (d,) f32 closure serialized 638 MB at the
                   d≈159M flagship and wedged a 27-min chip window, PERF.md
                   §4 / rng.random_projection_factors_in_graph)
  donation         the state carry is actually marked for buffer reuse in
                   the exported module (``jax.buffer_donor`` /
                   ``tf.aliasing_output`` attrs on exactly the expected
                   number of inputs), and each donated input has a distinct
                   same-shape/dtype output to alias into — requesting
                   donation in jit is not the same as XLA being able to
                   honour it (a carry-structure change silently doubles
                   peak HBM)
  dtype            no f64/complex<f64> anywhere; module element types ⊆ the
                   manifest's allowed set; on bf16 routes every bf16→f32
                   promotion site is a whitelisted primitive (accumulation
                   converts), so accidental f32 upcasts of whole activations
                   fail statically
  collectives      explicit collective-op counts by kind equal the manifest
                   (the communication structure IS the algorithm — an
                   accidental extra all-gather is a correctness/perf bug
                   even when outputs match)
  host_traffic     zero infeed/outfeed/send/recv ops and zero host-callback
                   custom calls or callback primitives — one host hop inside
                   a scanned body re-serializes the chunk on the ~70 ms
                   dispatch link the scan exists to hide (PERF.md §0)
  memory_budget    the compiled executable's peak-memory estimate
                   (``compiled.memory_analysis()``: argument + output +
                   temp + generated-code bytes, minus donated-alias bytes)
                   stays under manifest.max_peak_bytes; the rule row is
                   also the per-program memory/cost LEDGER — every row
                   carries the raw byte columns and the program's analytic
                   flops (``cost_analysis``), so the committed artifact is
                   the round-over-round record tools/perf_watch.py diffs
                   (PERF.md §8). Measured on the CPU-host compile of the
                   same program the CI mesh executes — an estimate of
                   shape, not a chip HBM number.

Rules 7-9 are the static sharding auditor (analysis/sharding.py):
``sharding_contract`` (partition-table coverage + donated-carry sharding
equality, the static form of PR 6's retrace-on-reshard),
``collective_axes`` (each collective classified by the mesh axis it
reduces over, checked against Manifest.collective_axes, with a per-axis
byte ledger), and ``replication_leaks`` (table-declared-sharded arrays
must not compile fully-replicated — the PR 7 neighborhood).

Rules degrade gracefully: host callbacks make a program un-exportable on
this jax (NotImplementedError), so the jaxpr-level half of host_traffic
still trips while module-level rules report ``skipped`` with the export
error; any OTHER export failure is itself a violation (synthetic rule
``export``). Likewise a program the host backend cannot compile reports
``memory_budget`` as ``skipped`` with the compile error rather than
blocking the jaxpr/module-level rules. A rule whose manifest field is
``None`` reports ``skipped``.
"""

from __future__ import annotations

import re
from typing import Any, Optional

from draco_tpu.analysis.registry import (
    COLLECTIVE_KINDS,
    BuiltProgram,
    LintProgram,
)

RULE_NAMES = ("constant_bloat", "donation", "dtype", "collectives",
              "host_traffic", "memory_budget", "sharding_contract",
              "collective_axes", "replication_leaks")

# jaxpr primitives that move data to/from the host at run time
_HOST_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback", "infeed", "outfeed",
})

# custom_call targets that are device-side compiler intrinsics, not host
# traffic: sharding markers, Mosaic kernels, and the XLA linalg lowerings
# (spelled Qr/Eigh/... when exported for tpu, lapack_*/blas_* for cpu)
_SAFE_CUSTOM_CALLS = re.compile(
    r"^(Sharding|SPMDFullToShardShape|SPMDShardToFullShape|mhlo\.\w+|"
    r"Qr|Eigh|LuDecomposition|ProductOfElementaryHouseholderReflectors|"
    r"Cholesky|tpu_custom_call|annotate_device_placement|"
    r"lapack_\w+|blas_\w+)$"
)

_TENSOR_ELEM_RE = re.compile(
    r"tensor<(?:\d+x)*"
    r"(f64|f32|f16|bf16|i64|i32|i16|i8|i1|ui64|ui32|ui16|ui8|"
    r"complex<f32>|complex<f64>)"
)


class Artifacts:
    """What one trace+export+compile pass yields; rules only read this."""

    def __init__(self, built: BuiltProgram, closed_jaxpr, mlir_text,
                 serialized_bytes, export_error, memory=None,
                 cost_flops=None, compile_error=None,
                 input_shardings=None, output_shardings=None):
        self.built = built
        self.manifest = built.manifest
        self.jaxpr = closed_jaxpr  # ClosedJaxpr | None
        self.mlir_text: Optional[str] = mlir_text
        self.serialized_bytes: Optional[int] = serialized_bytes
        self.export_error: Optional[str] = export_error
        self.memory: Optional[dict] = memory  # _memory_columns() | None
        self.cost_flops: Optional[float] = cost_flops
        self.compile_error: Optional[str] = compile_error
        # flattened compiled I/O shardings (the sharding auditor's
        # ground truth, rules 7/9) — None when the host compile is
        # skipped or failed
        self.input_shardings: Optional[list] = input_shardings
        self.output_shardings: Optional[list] = output_shardings


def _memory_columns(compiled) -> Optional[dict]:
    """The per-program memory ledger: XLA's static memory analysis of the
    compiled executable, as integer byte columns + the peak estimate the
    memory_budget rule caps. ``peak_bytes`` = argument + output + temp +
    generated-code − aliased (donated buffers alias into outputs, so they
    are counted once) — XLA's own working-set accounting of the program."""
    ma = compiled.memory_analysis()
    if ma is None:
        return None
    cols = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
    }
    cols["peak_bytes"] = (cols["argument_bytes"] + cols["output_bytes"]
                          + cols["temp_bytes"]
                          + cols["generated_code_bytes"]
                          - cols["alias_bytes"])
    return cols


def _cost_flops(compiled) -> Optional[float]:
    """Analytic FLOPs of the optimized program (same source bench.py's MFU
    uses; a scan body is counted once regardless of trip count)."""
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0)) if cost else 0.0
    return flops if flops > 0 else None


def trace_and_export(built: BuiltProgram,
                     platforms=("tpu",)) -> Artifacts:
    """Trace the closed jaxpr, cross-platform-export the module on the CPU
    host (the lowering-check methodology: the whole StableHLO (+Pallas)
    lowering stack runs without a chip, tools/tpu_attn_lowering_check.py),
    and compile for the host backend to capture the executable's
    memory/cost analysis (the memory_budget ledger)."""
    import contextlib

    import jax.export

    mesh_ctx = (built.mesh if built.mesh is not None
                else contextlib.nullcontext())
    with mesh_ctx, built.trace_ctx():
        closed = built.fn.trace(*built.args).jaxpr
        mlir_text = serialized = export_error = None
        try:
            exp = jax.export.export(built.fn, platforms=list(platforms))(
                *built.args)
            mlir_text = exp.mlir_module()
            serialized = len(exp.mlir_module_serialized)
        except Exception as e:
            export_error = f"{type(e).__name__}: {str(e)[:300]}"
        memory = cost_flops = compile_error = None
        in_sh = out_sh = None
        if not built.capture_memory:
            compile_error = ("capture_memory disabled for this program "
                             "(chip-tier row: host compile prohibitive or "
                             "impossible)")
        else:
            try:
                import jax

                compiled = built.fn.lower(*built.args).compile()
                memory = _memory_columns(compiled)
                cost_flops = _cost_flops(compiled)
                # the sharding auditor's ground truth (rules 7/9): the
                # executable's resolved I/O shardings, flattened in arg /
                # output pytree order
                in_sh = jax.tree.leaves(compiled.input_shardings[0])
                out_sh = jax.tree.leaves(compiled.output_shardings)
            except Exception as e:  # un-compilable on the host backend:
                # memory_budget skips with the reason, other rules still run
                compile_error = f"{type(e).__name__}: {str(e)[:300]}"
    return Artifacts(built, closed, mlir_text, serialized, export_error,
                     memory=memory, cost_flops=cost_flops,
                     compile_error=compile_error,
                     input_shardings=in_sh, output_shardings=out_sh)


def _walk_eqns(jaxpr):
    """Yield every eqn in a (Closed)Jaxpr including sub-jaxprs (scan/pjit/
    cond/remat bodies)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            vals = p if isinstance(p, (list, tuple)) else (p,)
            for v in vals:
                if hasattr(v, "jaxpr") or hasattr(v, "eqns"):
                    yield from _walk_eqns(v)


def _skip(reason):
    return {"ok": True, "skipped": True, "reason": reason}


def _need_mlir(art: Artifacts):
    if art.mlir_text is None:
        return _skip(f"export unavailable: {art.export_error}")
    return None


def rule_constant_bloat(art: Artifacts) -> dict:
    import numpy as np

    m = art.manifest
    consts = getattr(art.jaxpr, "consts", [])
    sizes = sorted(
        int(np.prod(np.shape(c))) * np.dtype(getattr(c, "dtype", np.float32)
                                             ).itemsize
        for c in consts
    )
    biggest = sizes[-1] if sizes else 0
    res = {"max_constant_bytes": biggest, "num_constants": len(sizes),
           "module_bytes": art.serialized_bytes}
    if biggest > m.max_constant_bytes:
        return {"ok": False, **res,
                "error": f"closed-over constant of {biggest} bytes embedded "
                         f"in the program (limit {m.max_constant_bytes}) — "
                         f"generate it in-graph instead "
                         f"(rng.random_projection_factors_in_graph)"}
    if art.serialized_bytes is None:
        return {**_skip(f"export unavailable: {art.export_error}"), **res}
    if art.serialized_bytes > m.max_module_bytes:
        return {"ok": False, **res,
                "error": f"serialized module is {art.serialized_bytes} bytes "
                         f"(limit {m.max_module_bytes}) — a large array is "
                         f"being baked into the program (PERF.md §4)"}
    return {"ok": True, **res}


def _expected_donated(built: BuiltProgram):
    import jax

    m = built.manifest
    if m.require_donated is None:
        return None
    if m.require_donated == "state":
        return len(jax.tree.leaves(built.args[0]))
    return int(m.require_donated)


def rule_donation(art: Artifacts) -> dict:
    import collections

    import jax

    expected = _expected_donated(art.built)
    if expected is None:
        return _skip("manifest.require_donated is None (timing-harness "
                     "loops re-call with the same state and cannot donate)")
    missing = _need_mlir(art)
    if missing:
        return missing
    txt = art.mlir_text
    observed = (len(re.findall(r"jax\.buffer_donor\s*=\s*true", txt))
                + len(re.findall(r"tf\.aliasing_output", txt)))
    res = {"expected_donated": expected, "observed_donated": observed}
    if observed != expected:
        return {"ok": False, **res,
                "error": f"{observed} inputs carry a donation attr in the "
                         f"exported module but the state carry has "
                         f"{expected} leaves — donation is requested in jit "
                         f"but not reaching the module (dropped "
                         f"donate_argnums?); the carry will be copied, "
                         f"doubling its HBM footprint"}
    # feasibility: XLA aliases a donated input only into an output of
    # identical shape/dtype; every carry leaf must find a distinct one
    # or the donation silently degrades to a copy at compile time
    outs = collections.Counter(
        (tuple(a.shape), str(a.dtype)) for a in art.jaxpr.out_avals
    )
    unmatched = []
    for leaf in jax.tree.leaves(art.built.args[0]):
        key = (tuple(leaf.shape), str(leaf.dtype))
        if outs[key] > 0:
            outs[key] -= 1
        else:
            unmatched.append(key)
    if unmatched:
        return {"ok": False, **res,
                "error": f"{len(unmatched)} donated inputs have no "
                         f"same-shape/dtype output to alias into (first: "
                         f"{unmatched[0]}) — XLA will keep the input buffer "
                         f"live and the donation is a no-op"}
    return {"ok": True, **res}


def rule_dtype(art: Artifacts) -> dict:
    m = art.manifest
    # jaxpr side runs even when export is blocked: f64 avals anywhere?
    wide = set()
    for eqn in _walk_eqns(art.jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None and str(dt) in ("float64", "complex128"):
                wide.add(str(dt))
    if wide:
        return {"ok": False, "found": sorted(wide),
                "error": f"{sorted(wide)} values in the jaxpr — double "
                         f"precision never belongs in a chip-bound program "
                         f"(silent 2x HBM + emulated math on TPU)"}
    promos = set()
    if "bf16" in m.allowed_dtypes:
        for eqn in _walk_eqns(art.jaxpr):
            if any(hasattr(v, "jaxpr") or hasattr(v, "eqns")
                   for p in eqn.params.values()
                   for v in (p if isinstance(p, (list, tuple)) else (p,))):
                continue  # container (scan/pjit/remat/...): its body is
                # walked separately; mixed carry dtypes are not a site
            ins = {str(getattr(getattr(v, "aval", None), "dtype", ""))
                   for v in eqn.invars}
            outs = {str(getattr(getattr(v, "aval", None), "dtype", ""))
                    for v in eqn.outvars}
            if "bfloat16" in ins and "float32" in outs:
                promos.add(str(eqn.primitive))
        rogue = promos - set(m.bf16_promotion_whitelist)
        if rogue:
            return {"ok": False, "promotion_sites": sorted(promos),
                    "error": f"bf16->f32 promotion at non-whitelisted "
                             f"primitives {sorted(rogue)} — only explicit "
                             f"accumulation converts "
                             f"({m.bf16_promotion_whitelist}) may promote"}
    missing = _need_mlir(art)
    res = {"promotion_sites": sorted(promos)} if promos else {}
    if missing:
        return {**missing, **res}
    types = set(_TENSOR_ELEM_RE.findall(art.mlir_text))
    res["element_types"] = sorted(types)
    hard_bad = types & {"f64", "complex<f64>"}
    if hard_bad:
        return {"ok": False, **res,
                "error": f"{sorted(hard_bad)} tensors in the exported module"}
    extra = types - m.allowed_dtypes
    if extra:
        return {"ok": False, **res,
                "error": f"element types {sorted(extra)} not in the "
                         f"manifest's allowed set {sorted(m.allowed_dtypes)}"}
    missing_req = m.required_dtypes - types
    if missing_req:
        # the narrow-wire contract (ISSUE 15): a manifest that declares a
        # narrow wire dtype REQUIRES it in the module — a silently-f32
        # "narrow" program means the quantize was dropped or DCE'd and
        # the wire is wide again under a narrow name
        return {"ok": False, **res,
                "error": f"manifest requires element types "
                         f"{sorted(m.required_dtypes)} in the module but "
                         f"{sorted(missing_req)} never appear — a "
                         f"narrow-wire program whose wire is silently f32 "
                         f"(dropped/dead-code-eliminated quantize?)"}
    return {"ok": True, **res}


def count_collectives(mlir_text: str) -> dict:
    return {k: len(re.findall(rf"stablehlo\.{k}\b", mlir_text))
            for k in COLLECTIVE_KINDS}


def rule_collectives(art: Artifacts) -> dict:
    m = art.manifest
    if m.collectives is None:
        return _skip("manifest.collectives is None (GSPMD-deferred or "
                     "kernel-only program)")
    missing = _need_mlir(art)
    if missing:
        return missing
    observed = count_collectives(art.mlir_text)
    expected = {k: int(m.collectives.get(k, 0)) for k in COLLECTIVE_KINDS}
    unknown = set(m.collectives) - set(COLLECTIVE_KINDS)
    if unknown:
        return {"ok": False, "observed": observed,
                "error": f"manifest names unknown collective kinds "
                         f"{sorted(unknown)}"}
    if observed != expected:
        diff = {k: (expected[k], observed[k]) for k in COLLECTIVE_KINDS
                if expected[k] != observed[k]}
        return {"ok": False, "observed": observed, "expected": expected,
                "error": f"explicit collective counts drifted from the "
                         f"manifest (kind: expected, observed) {diff} — if "
                         f"the change is a deliberate algorithm change, "
                         f"update the manifest (PERF.md §6)"}
    return {"ok": True, "observed": observed}


def rule_host_traffic(art: Artifacts) -> dict:
    m = art.manifest
    hits = []
    for eqn in _walk_eqns(art.jaxpr):
        if str(eqn.primitive) in _HOST_PRIMS:
            hits.append(f"jaxpr:{eqn.primitive}")
    if art.mlir_text is not None:
        txt = art.mlir_text
        for op in re.findall(r"stablehlo\.(infeed|outfeed|send|recv)\b", txt):
            hits.append(f"mlir:{op}")
        for target in re.findall(r'custom_call\s*@([\w.$]+)', txt):
            if not _SAFE_CUSTOM_CALLS.match(target):
                hits.append(f"custom_call:{target}")
    res = {"transfers": len(hits), "sites": hits[:8]}
    if len(hits) > m.host_transfer_budget:
        return {"ok": False, **res,
                "error": f"{len(hits)} host-transfer sites (budget "
                         f"{m.host_transfer_budget}) — a host hop inside "
                         f"the program serializes every scanned chunk on "
                         f"the dispatch link (PERF.md §0): {hits[:4]}"}
    return {"ok": True, **res}


def rule_memory_budget(art: Artifacts) -> dict:
    m = art.manifest
    if m.max_peak_bytes is None:
        return _skip("manifest.max_peak_bytes is None")
    if art.memory is None:
        return _skip(f"memory analysis unavailable: "
                     f"{art.compile_error or 'backend reported none'}")
    res = {"memory": art.memory, "flops": art.cost_flops}
    peak = art.memory["peak_bytes"]
    if peak > m.max_peak_bytes:
        return {"ok": False, **res,
                "error": f"peak-memory estimate {peak} bytes exceeds the "
                         f"manifest budget {m.max_peak_bytes} — the "
                         f"program's working set outgrew its declared "
                         f"budget (dropped donation? lost remat? an "
                         f"accidental materialized temp?); raise the "
                         f"manifest only for a deliberate change "
                         f"(PERF.md §8)"}
    return {"ok": True, **res}


from draco_tpu.analysis.sharding import (  # noqa: E402 (rule wiring)
    rule_collective_axes,
    rule_replication_leaks,
    rule_sharding_contract,
)

_RULES = {
    "constant_bloat": rule_constant_bloat,
    "donation": rule_donation,
    "dtype": rule_dtype,
    "collectives": rule_collectives,
    "host_traffic": rule_host_traffic,
    "memory_budget": rule_memory_budget,
    "sharding_contract": rule_sharding_contract,
    "collective_axes": rule_collective_axes,
    "replication_leaks": rule_replication_leaks,
}


def lint_built(built: BuiltProgram, platforms=("tpu",), only=None) -> dict:
    """Run the rules; returns the report row for this program.

    ``lint_ok`` is True iff no rule failed AND the export either succeeded
    or was blocked by host traffic that the host rule already flagged (any
    other export failure is reported as the synthetic rule ``export``).
    ``only`` restricts to a subset of rule names (the
    ``tools/program_lint.py --only`` fast-iteration path); the row then
    carries just those rules.
    """
    names = RULE_NAMES if only is None else tuple(
        n for n in RULE_NAMES if n in set(only))
    art = trace_and_export(built, platforms=platforms)
    rules = {name: _RULES[name](art) for name in names}
    failed = [n for n in names if not rules[n]["ok"]]
    if art.export_error is not None and "host_traffic" not in failed:
        rules["export"] = {"ok": False, "error": art.export_error}
        failed.append("export")
    return {
        "lint_ok": not failed,
        "failed_rules": failed,
        "rules": rules,
        "export_platforms": list(platforms),
        **built.extra,
    }


def lint_program(program: LintProgram, only=None) -> dict:
    """Build + lint one registered program (the tools' row thunk)."""
    row = lint_built(program.build(), platforms=program.export_platforms,
                     only=only)
    return {"ok": row["lint_ok"], "route": program.route, **row}
