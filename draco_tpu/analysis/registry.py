"""The catalog of chip-bound programs and their manifests.

Every jitted hot-loop program that can ever reach a chip window — the
coded-DP ``train_step``/``train_many`` (training/step.py) and the five LM
token-route drivers including the K-fused ``make_token_train_many`` scans
(parallel/{sp,tp,pp,ep}_step.py) — registers here with CI-sized example
arguments and a :class:`Manifest` of the compiled-program invariants no
output-level unit test can see: constant bytes, donation, dtype discipline,
explicit collective counts, host traffic. ``analysis/rules.py`` checks the
manifests; ``tools/program_lint.py`` drives the whole catalog and writes
``baselines_out/program_lint.json``.

Why a registry instead of per-route bespoke tests: round 5 shipped a
d-sized closed-over constant that wedged a 27-minute chip window, and PR
1/2 re-found donation and placement defects by hand. Each of those
invariants was guarded for exactly ONE program (tests/test_program_size.py
and the three copy-adjacent lowering tools); every other program trusted
review. The registry makes the guard a property of *registration*: a new
route ships with a manifest or it does not lint, and the manifest IS the
reviewable statement of the program's communication structure — which the
CodedReduce / CC-efficient gradient-coding lines (PAPERS.md) treat as the
algorithm itself.

Registration is lazy: each route module exposes ``lint_programs()``
returning :class:`LintProgram` entries whose ``build`` callables construct
the mesh/setup/args only when the linter runs them (imports stay cheap,
and the CPU-host device count is whatever the caller's process set up —
tools/_lowering_common.setup_cpu_host or tests/conftest.py, 8 virtual
devices either way). Chip-scale audit tools register their own
chip-tier entries through the same dataclasses (tools/tpu_lm_lowering_check,
tools/tpu_parallel_lowering_check).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable, Optional, Tuple

# Element types an honest draco_tpu program may contain (MLIR spelling).
# f64/complex<f64> are NEVER allowed — rules.rule_dtype hard-fails on them
# regardless of the manifest. i64 shows up as index arithmetic on the
# shard_map/GSPMD routes (iota/gather bookkeeping), not as compute.
DEFAULT_DTYPES = frozenset(
    {"f32", "i1", "i8", "i16", "i32", "i64", "ui8", "ui16", "ui32"}
)
BF16_DTYPES = DEFAULT_DTYPES | {"bf16"}

# The explicit collective kinds the budget rule counts (StableHLO op
# names; reduce_scatter is what lax.psum_scatter lowers to). GSPMD-inserted
# collectives (from shardings/with_sharding_constraint) materialize only
# inside the XLA SPMD partitioner, AFTER export — a manifest pins the
# *explicit* ICI structure (shard_map psum/ppermute/a2a rings); routes that
# rely purely on sharding propagation legitimately pin all-zero counts.
COLLECTIVE_KINDS = ("all_reduce", "all_gather", "all_to_all",
                    "collective_permute", "reduce_scatter")


@dataclasses.dataclass(frozen=True)
class Manifest:
    """Per-program invariants the five lint rules enforce.

    ``require_donated``: exact number of input leaves that must carry a
    donation attr in the exported module (``jax.buffer_donor`` /
    ``tf.aliasing_output``). The sentinel ``"state"`` resolves to
    ``len(jax.tree.leaves(args[0]))`` at lint time — the whole state carry.
    ``None`` skips the rule (timing-harness loops that deliberately re-call
    with the same state cannot donate).

    ``collectives``: expected explicit-collective op counts by kind
    (missing kinds default to 0). ``None`` skips the rule.

    ``collective_axes``: the per-axis extension of that budget (rule 8,
    analysis/sharding.py): ``{axis: {kind: count}}`` — every explicit
    collective must classify onto a declared mesh axis with exactly the
    declared count (a tree combine pins one psum per level ON that
    level's axis; wrong-axis psums fail even at an unchanged op count).
    ``{}`` asserts zero explicit collectives on every axis (the
    GSPMD-deferred routes); ``None`` skips the rule.

    ``host_transfer_budget`` is 0 for every registered program: a single
    infeed/outfeed/host-callback inside a scanned body serializes the chunk
    on the host link and defeats the whole scan-chunk design (PERF.md §0).

    ``max_peak_bytes``: cap on the program's peak-memory estimate from
    XLA's ``compiled.memory_analysis()`` (argument + output + temp +
    generated-code bytes, minus donated-alias bytes) — the
    ``memory_budget`` rule. The CI-sized registrations sit far under the
    default 2 GiB cap; the cap exists so the manifest is a reviewable
    memory budget a program cannot silently outgrow (a dropped donation or
    a remat regression shows up here as bytes, not as an OOM three rungs up
    the chip ladder). ``None`` skips the rule. The measured columns
    (memory/cost) are recorded on every row regardless of the cap.
    """

    max_constant_bytes: int = 1 << 20  # per closed-over constant
    max_module_bytes: int = 1 << 20  # whole serialized StableHLO module
    require_donated: Any = "state"  # int | "state" | None
    allowed_dtypes: frozenset = DEFAULT_DTYPES
    bf16_promotion_whitelist: Tuple[str, ...] = ("convert_element_type",)
    # Element types that MUST appear in the exported module (ISSUE 15):
    # a narrow-wire production program declares its wire dtype here
    # ({"bf16"} / {"i8"}), so a "narrow" registration whose module is
    # silently all-f32 (the quantize got dropped, dead-code-eliminated,
    # or the config stopped reaching the step body) trips the dtype rule
    # instead of shipping a wide wire under a narrow name. Empty = no
    # requirement (every pre-ISSUE-15 manifest).
    required_dtypes: frozenset = frozenset()
    collectives: Optional[dict] = None
    collective_axes: Optional[dict] = None  # {axis: {kind: count}}
    host_transfer_budget: int = 0
    max_peak_bytes: Optional[int] = 2 << 30  # memory_budget rule cap


@dataclasses.dataclass
class BuiltProgram:
    """A traceable chip-bound program: the jitted callable, CI-sized example
    args, the mesh to trace under, and the manifest to lint against.

    ``trace_ctx`` wraps trace+export (negative controls use
    ``jax.experimental.enable_x64``); ``donate_argnums`` names which args
    the ``"state"`` donation sentinel resolves over (arg 0 by convention).

    ``capture_memory``: compile for the host backend to record the
    memory/cost ledger (rules.rule_memory_budget). Chip-tier audit rows
    opt out where a host compile is pointless or prohibitive — the
    d≈159M lm_big rungs (a CPU backend-compile of the flagship costs
    real minutes; the lowering audit needs only trace+export) and the
    Pallas kernel rows (tpu_custom_call cannot compile for CPU at all);
    the rule then reports ``skipped`` with the reason.

    ``partition_rules``: the program's declared partition table — a tuple
    of ``(path_regex, PartitionSpec)`` rows (parallel/partition.py is the
    single source; routes pass their table). The sharding auditor (rules
    7/9) holds every array arg leaf to it. ``arg_names`` names the
    positional args for the leaf-path vocabulary the regexes match
    (``state/params/...``, ``tokens``); unnamed args fall back to
    ``arg<i>``. ``None`` partition_rules = the table halves of rules 7/9
    report skipped (kernel rows with no mesh).
    """

    name: str
    fn: Any  # jitted callable
    args: tuple
    mesh: Any = None
    manifest: Manifest = dataclasses.field(default_factory=Manifest)
    trace_ctx: Callable = contextlib.nullcontext
    extra: dict = dataclasses.field(default_factory=dict)  # report fields
    capture_memory: bool = True
    partition_rules: Optional[Tuple] = None  # ((regex, PartitionSpec), ...)
    arg_names: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class LintProgram:
    """A registered program: ``build()`` constructs the BuiltProgram lazily.

    ``fast``: part of the ``--fast`` / CI-core subset (small models, a few
    seconds each). The big-d constant-bloat guard program is the deliberate
    exception — meaningful only when d is CI-large, so it builds ~3.3M
    params and stays out of ``--fast``.

    ``export_platforms``: lowering target for jax.export. ``("tpu",)``
    exercises the TPU lowering stack on the CPU host (the lowering-check
    methodology, tools/tpu_attn_lowering_check.py); the big-d program uses
    ``("cpu",)`` — its rule is about serialized bytes, and a cpu lowering
    of a 3.3M-param scan is substantially cheaper.
    """

    name: str
    build: Callable[[], BuiltProgram]
    route: str  # which module registered it (report/filtering)
    fast: bool = True
    export_platforms: Tuple[str, ...] = ("tpu",)


def collect() -> "list[LintProgram]":
    """All registered programs, by importing each route module and asking it
    for ``lint_programs()``. Import order is the route order; names must be
    unique across routes."""
    from draco_tpu.coding import topology
    from draco_tpu.ops import decode_kernels
    from draco_tpu.parallel import ep_step, pp_step, sp_step, tp_step
    from draco_tpu.training import step as cnn_step

    programs: list[LintProgram] = []
    for mod in (cnn_step, sp_step, tp_step, pp_step, ep_step,
                decode_kernels, topology):
        programs.extend(mod.lint_programs())
    names = [p.name for p in programs]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate lint program names: {sorted(dupes)}")
    return programs


def get(name: str) -> LintProgram:
    for p in collect():
        if p.name == name:
            return p
    raise KeyError(
        f"no lint program named {name!r}; registered: "
        f"{[p.name for p in collect()]}"
    )


def ci_lm_config(**overrides):
    """The CI-sized TransformerLM config the LM route registrations share
    (one source so the routes cannot drift apart on the baseline shape).
    n=8 logical coded workers (folds onto a 4-wide mesh w axis in equal
    lane blocks on the 8-device CI host), cyclic s=1 shared redundancy."""
    from draco_tpu.config import TrainConfig

    kw = dict(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=8, approach="cyclic", redundancy="shared", mode="normal",
        worker_fail=1, err_mode="rev_grad", seq_len=64, vocab=64,
        model_dim=64, model_heads=2, model_layers=1, max_steps=2,
        eval_freq=0, train_dir="", log_every=10 ** 9,
    )
    kw.update(overrides)
    return TrainConfig(**kw)


def lm_example_tokens(cfg, k: Optional[int] = None):
    """Example (tokens, adv_mask[s]) for an LM route program — the same
    synthetic stream the production loop feeds (sp_step.synthetic_text),
    stacked to (K, n, B, T) when ``k`` is given."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu import rng as drng
    from draco_tpu.parallel.sp_step import synthetic_text

    adv = drng.adversary_schedule(cfg.seed, (k or 1) + 1, cfg.num_workers,
                                  cfg.num_adversaries)
    if k is None:
        toks = jnp.asarray(synthetic_text(cfg.seed, 1, cfg.num_workers,
                                          cfg.batch_size, cfg.seq_len,
                                          cfg.vocab))
        return toks, jnp.asarray(np.asarray(adv[1]))
    toks = jnp.asarray(np.stack([
        synthetic_text(cfg.seed, s, cfg.num_workers, cfg.batch_size,
                       cfg.seq_len, cfg.vocab)
        for s in range(1, k + 1)
    ]))
    return toks, jnp.asarray(np.asarray(adv[1:k + 1]))


def built_token_program(name, cfg, mesh, setup, manifest, many=False,
                        k=2, partition_rules=None) -> BuiltProgram:
    """Wrap an LM route setup's chip-bound callable as a BuiltProgram:
    either the single ``train_step`` or the K-fused ``train_token_many``
    scan (K = leading dim of the example operands; ``cfg.token_gen ==
    'device'`` feeds the (K,) step-index vector the production chunked loop
    uploads, parallel/token_loop.py). ``partition_rules`` is the route's
    declared partition table (parallel/partition.py); the arg-path
    vocabulary is fixed here: ``state``, ``tokens``, ``adv_mask``,
    ``present``."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu import rng as drng

    arg_names = ("state", "tokens", "adv_mask", "present")
    extra = {"dim": setup.dim, "devices_in_mesh": int(mesh.devices.size)}
    if many:
        if cfg.token_gen == "device":
            # the program regenerates tokens in-graph; its whole token
            # input is the (K,) step vector — don't build host batches
            adv = drng.adversary_schedule(cfg.seed, k + 1, cfg.num_workers,
                                          cfg.num_adversaries)
            toks = jnp.arange(1, k + 1, dtype=jnp.int32)
            masks = jnp.asarray(np.asarray(adv[1:k + 1]))
        else:
            toks, masks = lm_example_tokens(cfg, k)
        return BuiltProgram(name, setup.train_token_many,
                            (setup.state, toks, masks, None), mesh,
                            manifest, extra=extra,
                            partition_rules=partition_rules,
                            arg_names=arg_names)
    toks, mask = lm_example_tokens(cfg)
    return BuiltProgram(name, setup.train_step, (setup.state, toks, mask),
                        mesh, manifest, extra=extra,
                        partition_rules=partition_rules,
                        arg_names=arg_names)
