"""Byzantine attack simulation as pure, branch-free functions.

Reference semantics (src/model_ops/utils.py:6-23, constants ADVERSARY_=-100,
CONST_=-100):

  * plain paths (baseline / repetition):
      rev_grad : g -> -100 * g
      constant : g -> -100 * ones
      random   : g -> -100 * N(0, 1) noise, seeded per (seed, step) —
                 implemented here (the reference left it a TODO and passed
                 the gradient through untouched); the draw folds the same
                 deterministic (seed, step) discipline as every schedule,
                 so all devices and both regimes agree bit-for-bit, and
                 per-ROW noise keeps repetition-group collusion impossible
  * cyclic path (``cyclic=True``) the attack is *additive* on top of the
    honest encoded value:
      rev_grad : g -> g + (-100 * g)      (i.e. -99 * g)
      constant : g -> g + (-100 * ones)   (adds to the real part only, since
                  the reference adds a float array to a complex one)
      random   : g -> g + (-100 * noise)  (independent re/im draws)

Attacks are applied inside the jitted step with jnp.where over a per-step
per-worker boolean mask (the schedule from draco_tpu.rng.adversary_schedule),
so the computation is identical on every device and bit-reproducible —
the reference achieves the same with agreed seeds (util.py:100-103).
"""

from __future__ import annotations

import jax.numpy as jnp

ADVERSARY = -100.0
CONST = -100.0
# the random attack's key salt (seed + _RANDOM_SALT), alongside the
# augment/dropout/vote-fingerprint salts in training/step.py (+2/+3/+4)
_RANDOM_SALT = 7
_ALIE_INERT_WARNED = set()  # one warning per inert (n, n_mal) pair


def random_key(seed, step):
    """The random attack's per-step key — folded from (seed, step) exactly
    like every other schedule draw, so all devices and both execution
    regimes (eager / K-fused scan with a traced step) agree bit-for-bit."""
    import jax

    return jax.random.fold_in(jax.random.key(seed + _RANDOM_SALT),
                              jnp.asarray(step, jnp.int32))


def _require_key(key):
    if key is None:
        raise ValueError(
            "err_mode='random' needs the per-step key (attacks.random_key"
            "(seed, step)) — the seeded random-gradient attack rides the "
            "same deterministic (seed, step) schedule discipline as "
            "everything else; a keyless call has no stream to draw from"
        )
    return key


def attack_plain(grads: jnp.ndarray, err_mode: str,
                 magnitude: float = ADVERSARY, key=None) -> jnp.ndarray:
    """Adversarial transform of raw per-worker gradients, shape (n, d).

    ``magnitude`` is the reference's --adversarial knob (distributed_nn.py:66;
    there parsed but hardcoded to -100 at the call sites — here it is real)."""
    if err_mode == "rev_grad":
        return magnitude * grads
    if err_mode == "constant":
        return jnp.full_like(grads, magnitude)
    if err_mode == "random":
        import jax

        return magnitude * jax.random.normal(_require_key(key), grads.shape,
                                             grads.dtype)
    raise ValueError(f"unknown err_mode: {err_mode}")


def attack_cyclic(enc_re: jnp.ndarray, enc_im: jnp.ndarray, err_mode: str,
                  magnitude: float = ADVERSARY, key=None):
    """Adversarial transform of encoded rows, real/imag parts, shape (n, d)."""
    if err_mode == "rev_grad":
        return enc_re + magnitude * enc_re, enc_im + magnitude * enc_im
    if err_mode == "constant":
        # complex + real array: only the real part shifts
        return enc_re + magnitude, enc_im
    if err_mode == "random":
        import jax

        kr, ki = jax.random.split(_require_key(key))
        return (enc_re + magnitude * jax.random.normal(kr, enc_re.shape,
                                                       enc_re.dtype),
                enc_im + magnitude * jax.random.normal(ki, enc_im.shape,
                                                       enc_im.dtype))
    raise ValueError(f"unknown err_mode: {err_mode}")


def _honest_stats(grads: jnp.ndarray, mask: jnp.ndarray):
    """Mean and std over honest rows only — what a colluding adversary that
    can observe its peers (the standard omniscient threat model of ALIE/IPM)
    would estimate."""
    w = (~mask).astype(grads.dtype)[:, None]
    cnt = jnp.maximum(jnp.sum(w), 1.0)
    mu = jnp.sum(grads * w, axis=0) / cnt
    var = jnp.sum((grads - mu) ** 2 * w, axis=0) / cnt
    return mu, jnp.sqrt(var)


def _alie_z(n: int, n_mal: int) -> float:
    """ALIE's evasion quantile (Baruch et al. 2019, 'A Little Is Enough'):
    the largest z such that the perturbed value still looks like a
    non-outlier to a trimmed/median rule over n workers with n_mal
    colluders: z = Phi^-1((n - n_mal - s)/(n - n_mal)), s = floor(n/2+1) - n_mal."""
    import math
    import statistics

    s = math.floor(n / 2 + 1) - n_mal
    p = max(min((n - n_mal - s) / max(n - n_mal, 1), 1.0 - 1e-6), 1e-6)
    return statistics.NormalDist().inv_cdf(p)


def inject_plain(
    grads: jnp.ndarray, mask: jnp.ndarray, err_mode: str,
    magnitude: float = ADVERSARY, n_mal: int = 1, step=None, seed=None,
) -> jnp.ndarray:
    """grads: (n, d); mask: (n,) bool — True rows are Byzantine.

    Beyond the reference's three modes, two modern colluding attacks that
    stress APPROXIMATE robust rules (cyclic decode is exact and rejects any
    of them identically; reference parity owes neither):

      alie : mu - z*sigma of the honest rows, z the evasion quantile of
             Baruch et al. 2019 — hides inside the empirical variance
      ipm  : -0.5 * mu of the honest rows (inner-product manipulation,
             Xie et al. 2020) — flips the aggregate's direction while
             staying small

    ``n_mal`` is the STATIC colluder count (config worker_fail — the mask is
    traced under jit, so the quantile cannot read it). Both attacks scale
    linearly with |magnitude| relative to the reference's default (-100):
    canonical at the default CLI knob, proportionally stronger/weaker when
    --adversarial is set. The SIGN of the knob is deliberately ignored here —
    it encodes direction for rev_grad's multiplicative payload, but alie/ipm
    fix their own direction (evade below the mean / oppose the mean); letting
    a positive --adversarial flip them would silently turn ipm into +0.5*mu,
    a benign nudge toward the honest aggregate."""
    if err_mode in ("alie", "ipm"):
        n = grads.shape[0]
        scale = abs(magnitude) / abs(ADVERSARY)  # 1.0 at the reference default
        mu, sigma = _honest_stats(grads, mask)
        if err_mode == "alie":
            z = _alie_z(n, max(n_mal, 1))
            if z <= 0 and (n, n_mal) not in _ALIE_INERT_WARNED:
                _ALIE_INERT_WARNED.add((n, n_mal))
                import warnings

                warnings.warn(
                    f"alie is inert at n={n}, n_mal={n_mal}: the evasion "
                    f"quantile z={z:.3f} <= 0, so the payload is (at most) "
                    f"the honest mean — the attack needs more workers or "
                    f"more colluders to have any z to hide behind",
                    stacklevel=2,
                )
            bad = mu - scale * z * sigma
        else:
            bad = -0.5 * scale * mu
        return jnp.where(mask[:, None], bad[None, :], grads)
    key = (random_key(seed, step) if err_mode == "random"
           and step is not None and seed is not None else None)
    return jnp.where(mask[:, None],
                     attack_plain(grads, err_mode, magnitude, key=key),
                     grads)


def inject_cyclic(
    enc_re: jnp.ndarray, enc_im: jnp.ndarray, mask: jnp.ndarray, err_mode: str,
    magnitude: float = ADVERSARY, step=None, seed=None,
):
    key = (random_key(seed, step) if err_mode == "random"
           and step is not None and seed is not None else None)
    bad_re, bad_im = attack_cyclic(enc_re, enc_im, err_mode, magnitude,
                                   key=key)
    m = mask[:, None]
    return jnp.where(m, bad_re, enc_re), jnp.where(m, bad_im, enc_im)
