"""Byzantine attack simulation as pure, branch-free functions.

Reference semantics (src/model_ops/utils.py:6-23, constants ADVERSARY_=-100,
CONST_=-100):

  * plain paths (baseline / repetition):
      rev_grad : g -> -100 * g
      constant : g -> -100 * ones
      random   : passthrough (a TODO in the reference, kept for parity)
  * cyclic path (``cyclic=True``) the attack is *additive* on top of the
    honest encoded value:
      rev_grad : g -> g + (-100 * g)      (i.e. -99 * g)
      constant : g -> g + (-100 * ones)   (adds to the real part only, since
                  the reference adds a float array to a complex one)

Attacks are applied inside the jitted step with jnp.where over a per-step
per-worker boolean mask (the schedule from draco_tpu.rng.adversary_schedule),
so the computation is identical on every device and bit-reproducible —
the reference achieves the same with agreed seeds (util.py:100-103).
"""

from __future__ import annotations

import jax.numpy as jnp

ADVERSARY = -100.0
CONST = -100.0


def attack_plain(grads: jnp.ndarray, err_mode: str, magnitude: float = ADVERSARY) -> jnp.ndarray:
    """Adversarial transform of raw per-worker gradients, shape (n, d).

    ``magnitude`` is the reference's --adversarial knob (distributed_nn.py:66;
    there parsed but hardcoded to -100 at the call sites — here it is real)."""
    if err_mode == "rev_grad":
        return magnitude * grads
    if err_mode == "constant":
        return jnp.full_like(grads, magnitude)
    if err_mode == "random":
        return grads
    raise ValueError(f"unknown err_mode: {err_mode}")


def attack_cyclic(enc_re: jnp.ndarray, enc_im: jnp.ndarray, err_mode: str, magnitude: float = ADVERSARY):
    """Adversarial transform of encoded rows, real/imag parts, shape (n, d)."""
    if err_mode == "rev_grad":
        return enc_re + magnitude * enc_re, enc_im + magnitude * enc_im
    if err_mode == "constant":
        # complex + real array: only the real part shifts
        return enc_re + magnitude, enc_im
    if err_mode == "random":
        return enc_re, enc_im
    raise ValueError(f"unknown err_mode: {err_mode}")


def inject_plain(
    grads: jnp.ndarray, mask: jnp.ndarray, err_mode: str, magnitude: float = ADVERSARY
) -> jnp.ndarray:
    """grads: (n, d); mask: (n,) bool — True rows are Byzantine."""
    return jnp.where(mask[:, None], attack_plain(grads, err_mode, magnitude), grads)


def inject_cyclic(
    enc_re: jnp.ndarray, enc_im: jnp.ndarray, mask: jnp.ndarray, err_mode: str,
    magnitude: float = ADVERSARY,
):
    bad_re, bad_im = attack_cyclic(enc_re, enc_im, err_mode, magnitude)
    m = mask[:, None]
    return jnp.where(m, bad_re, enc_re), jnp.where(m, bad_im, enc_im)
