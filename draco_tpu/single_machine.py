"""Single-process sanity/benchmark path (reference: src/single_machine.py +
src/nn_ops/__init__.py NN_Trainer). Equivalent to the distributed trainer
with num_workers=1, approach=baseline, no adversaries — one device, plain SGD.

  python -m draco_tpu.single_machine --network LeNet --dataset MNIST --max-steps 500
"""

from __future__ import annotations

import argparse

from draco_tpu.cli import add_fit_args, config_from_args, maybe_force_cpu_mesh


def main(argv=None):
    parser = add_fit_args(argparse.ArgumentParser(description="draco_tpu single machine"))
    args = parser.parse_args(argv)
    args.approach = "baseline"
    args.mode = "normal"
    args.num_workers = 1
    args.worker_fail = 0

    maybe_force_cpu_mesh(args)

    cfg = config_from_args(args)
    if cfg.network == "TransformerLM":
        # LM single-machine path: the (w=1, sp=1) token loop — same
        # dispatch the distributed CLI uses, minus the coded axes. The
        # model-parallel knobs span devices this entry point doesn't have:
        # reject them loudly rather than silently running unsharded.
        if (cfg.seq_shards > 1 or cfg.tensor_shards > 1
                or cfg.expert_shards > 1 or cfg.pipeline_shards > 1
                or cfg.pp_microbatches > 0):
            raise SystemExit(
                "single_machine is the one-device path; use "
                "python -m draco_tpu.cli for seq/tensor/expert/pipeline "
                "shards"
            )
        from draco_tpu.parallel import make_mesh_2d
        from draco_tpu.parallel.sp_step import train_sp

        _, last = train_sp(cfg, make_mesh_2d(1, 1))
        return last

    from draco_tpu.training.trainer import Trainer

    trainer = Trainer(cfg)
    return trainer.run()


if __name__ == "__main__":
    main()
