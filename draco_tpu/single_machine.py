"""Single-process sanity/benchmark path (reference: src/single_machine.py +
src/nn_ops/__init__.py NN_Trainer). Equivalent to the distributed trainer
with num_workers=1, approach=baseline, no adversaries — one device, plain SGD.

  python -m draco_tpu.single_machine --network LeNet --dataset MNIST --max-steps 500
"""

from __future__ import annotations

import argparse

from draco_tpu.cli import add_fit_args, config_from_args, maybe_force_cpu_mesh


def main(argv=None):
    parser = add_fit_args(argparse.ArgumentParser(description="draco_tpu single machine"))
    args = parser.parse_args(argv)
    args.approach = "baseline"
    args.mode = "normal"
    args.num_workers = 1
    args.worker_fail = 0

    maybe_force_cpu_mesh(args)

    from draco_tpu.training.trainer import Trainer

    cfg = config_from_args(args)
    trainer = Trainer(cfg)
    return trainer.run()


if __name__ == "__main__":
    main()
