"""draco_tpu — a TPU-native framework for Byzantine-resilient coded distributed training.

A ground-up JAX/XLA/Pallas re-design of the capabilities of DRACO
(hwang595/Draco; "DRACO: Byzantine-resilient Distributed Training via
Redundant Gradients", ICML 2018): synchronous data-parallel training where
workers evaluate redundant gradients, send linear combinations, and an
algebraic decode removes the influence of up to s Byzantine workers.

Architecture (TPU-first, not a port):
  * The reference's parameter-server *process* (rank 0 over MPI) becomes a
    *program phase*: one pjit-compiled SPMD step over a device mesh axis
    ``w`` of n logical workers. Per-worker gradients are a vmap axis;
    encode/decode/aggregation are linear algebra on the stacked (n, d)
    gradient matrix; XLA inserts the ICI collectives the reference did by
    hand with MPI Isend/Irecv (reference: src/master/baseline_master.py,
    src/worker/baseline_worker.py).
  * The reference's native C++ decoder (src/c_coding.cpp) becomes
    fixed-shape jittable linear algebra (draco_tpu.coding.cyclic), with an
    optional C++ host reference used for testing.
  * The hand-rolled per-layer gradient streaming models
    (src/model_ops/*_split.py) are unnecessary under XLA async collectives;
    models are plain Flax modules.
"""

__version__ = "0.1.0"

from draco_tpu.config import TrainConfig  # noqa: F401
