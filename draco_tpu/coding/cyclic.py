"""Cyclic (DFT) gradient code — construction, encode, decode.

Re-derivation of the reference's cyclic code (src/coding.py, decode in
src/master/cyclic_master.py:146-197 with the native error-locator solve in
src/c_coding.cpp:15-84), designed for XLA: fixed shapes, no data-dependent
control flow, complex arithmetic carried as (real, imag) pairs because the
heavy products run on the MXU as real matmuls.

The math (n workers, s Byzantine, ŝ = 2s+1):

  * C = DFT(n)/√n, symmetric unitary. C1 = first n−2s columns, C2 = last 2s.
  * Encoding matrix W (n×n): column k lies in span(C1) and row i is supported
    on the cyclic window {i, …, i+ŝ−1 (mod n)}; W = C1·Q with Q[0,:] = 1.
    Worker i evaluates the ŝ batch-gradients in its window and ships the
    complex combination Σ_k W[i,k]·g_k.
  * Received matrix R (n×d) = W·G + ε where ε has ≤ s nonzero rows.
  * Decode: project R to a vector with a random factor (catch corruption in
    any coordinate), form the syndrome E2 = C2ᴴ·(R·f) — zero iff ε = 0,
    since C2ᴴC1 = 0 — solve the s×s Hankel system for the error-locator
    polynomial, evaluate it on the DFT grid to locate honest rows, then find
    v supported on honest rows with vᵀC1 = e1ᵀ, which gives
    vᵀW = 1ᵀ  ⇒  vᵀR = Σ_k g_k exactly.

Everything below the construction is jit-compatible and shape-static: the
data-dependent "err_indices" selection of the reference
(cyclic_master.py:162-169) becomes `jnp.nonzero(..., size=n-2s)`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from draco_tpu.coding import linalg as linalg_mod
from draco_tpu.ops import coded as ops_coded

PREC = jax.lax.Precision.HIGHEST

# Ridge for the error-locator Hankel solve, shared by the jit decode below and
# the native oracle (native/coding.cpp locator_alpha) so borderline
# rank-deficient cases (< s actually-corrupt rows) rank rows identically on
# both paths. Must sit well above float32 epsilon — see the normalisation
# comment in decode().
# Relative singular-value cutoff for the locator least-squares (σ below
# rcond·σmax truncated). Shared with the native decoder (native/coding.cpp
# locator_alpha, which applies the equivalent rcond² eigenvalue cutoff on its
# float64 gram) so jit and host decodes rank borderline rank-deficient rows
# identically. Sits well above f32 σ noise (~1e-7·σmax) and well below the
# locator system's genuine σmin (cond(A) is O(1e3) for corrupt-row spreads
# seen at n≤32).
LOCATOR_RCOND = 1e-5

# Decode-health row-flagging threshold (relative amplitude): a received row
# whose deviation from the fitted codeword exceeds HEALTH_REL_TOL × the
# RMS row magnitude counts as a located error. Honest-row deviations are
# pure f32 solve noise (~1e-6 relative, even through the m×m fit); the
# in-scope attack payloads sit at O(100×) the honest magnitude (attacks.py
# ADVERSARY=-100) — five orders of margin either side.
HEALTH_REL_TOL = 1e-3

# Golden-ratio Weyl constant for the λ-regularized locator's honest-subset
# bias (ISSUE 15): rows ranked by frac(r·φ) form a maximally-spread subset
# (three-distance theorem), whose DFT extrapolation amplification is O(1)
# (measured 2–9× across study shapes) where the index-contiguous first
# n−2s rows amplify ~4e4× at n=32 — the mechanism behind the PR 10
# quant-noise blowup: with no live adversary the locator magnitudes are
# noise, the chosen subset is noise-driven (or contiguous under the index
# bias), and the exact codeword fit extrapolates the excluded rows with
# that amplification. The spread bias only engages on the λ path; the
# exact λ=0 decode keeps the historical index bias bit-for-bit.
SPREAD_PHI = 0.6180339887498949


def _spread_rank(n: int) -> np.ndarray:
    """Host-side (n,) f32 spread ranks: rank of frac(r·φ) — the λ-path
    tie-break ordering (SPREAD_PHI docstring)."""
    key = (np.arange(n) * SPREAD_PHI) % 1.0
    return np.argsort(np.argsort(key)).astype(np.float32)


# Loud-row forensics threshold (relative ENERGY vs the median present row):
# a present row whose projected energy exceeds LOUD_REL_TOL × the median is
# "loud". A forensic-only accusation signal (obs/forensics.py) — it feeds
# the per-worker accusation columns, never the decode, the located_errors
# count, or the step guard. Rationale: beyond the locator budget (> s
# corrupt rows) exact location is information-theoretically impossible and
# the fitted-codeword deviations above say nothing (any n−2s rows define an
# exact codeword), but the in-scope attack payloads are magnitude outliers
# (O(100×) amplitude ⇒ O(1e4×) energy) while honest encoded rows sit within
# ~6× of their median energy (measured, PERF.md §10) — 30× energy splits the
# two with more than an order of margin either side. The median (not the
# mean) keeps the baseline honest with up to s+1 corrupt rows present, and
# absent rows are excluded from both sides (a zero-filled erasure is
# known-missing, not quiet).
LOUD_REL_TOL = 30.0


# --------------------------------------------------------------------------
# Construction (host-side numpy, run identically by every participant at
# setup — reference: search_w called on all ranks, util.py:185)
# --------------------------------------------------------------------------

def _dft_c(n: int) -> np.ndarray:
    """Symmetric scaled DFT matrix C[p,q] = exp(-2πi·pq/n)/√n."""
    p, q = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    return np.exp(-2j * np.pi * p * q / n) / np.sqrt(n)


def _cyclic_support(n: int, hat_s: int) -> np.ndarray:
    """0/1 mask, row i supported on the cyclic window [i, i+hat_s)."""
    mask = np.zeros((n, n))
    for i in range(n):
        mask[i, (np.arange(i, i + hat_s) % n)] = 1.0
    return mask


def _solve_w(c1: np.ndarray, support: np.ndarray) -> np.ndarray:
    """W with columns in span(C1), support matching ``support``, Q[0,:]=1.

    For column k: W[:,k] = C1 @ q with q[0] = 1 and W[j,k] = 0 for all j
    outside the column's support — a small complex least-squares per column.
    """
    n, m = c1.shape
    w = np.zeros((n, n), dtype=complex)
    for k in range(n):
        zero_rows = np.where(support[:, k] == 0)[0]
        a = c1[zero_rows, 1:]
        b = -c1[zero_rows, 0]
        q_tail, *_ = np.linalg.lstsq(a, b, rcond=None)
        q = np.concatenate([[1.0 + 0j], q_tail])
        w[:, k] = c1 @ q
    return w


@dataclasses.dataclass(frozen=True)
class CyclicCode:
    """All constants the encode/decode kernels need, as device-ready arrays."""

    n: int
    s: int
    # encoding matrix entries gathered at each worker's support:
    # w_sel[i, k] = W[i, batch_ids[i, k]], shape (n, hat_s), as re/im pairs
    w_sel_re: np.ndarray
    w_sel_im: np.ndarray
    batch_ids: np.ndarray  # (n, hat_s) int32 — which batches worker i computes
    # syndrome operator C2^H, shape (2s, n)
    c2h_re: np.ndarray
    c2h_im: np.ndarray
    # C1, shape (n, n-2s) — decode's recombination basis
    c1_re: np.ndarray
    c1_im: np.ndarray
    # locator evaluation grid: est[t, j] = exp(+2πi t/n)^j, shape (n, s+1)
    est_re: np.ndarray
    est_im: np.ndarray
    # support-masked full W for the shared-compute encode path, (n, n)
    w_masked_re: np.ndarray
    w_masked_im: np.ndarray
    # full matrices kept for tests / host tooling
    w_full: np.ndarray  # complex (n, n)
    support: np.ndarray  # (n, n) 0/1

    @property
    def hat_s(self) -> int:
        return 2 * self.s + 1


def build_cyclic_code(n: int, s: int) -> CyclicCode:
    if n <= 4 * s:
        raise ValueError(f"cyclic code needs n > 4s, got n={n}, s={s}")
    hat_s = 2 * s + 1
    c = _dft_c(n)
    c1 = c[:, : n - hat_s + 1]  # n-2s columns
    support = _cyclic_support(n, hat_s)
    w = _solve_w(c1, support)
    c2 = c[:, n - hat_s + 1 :]
    c2h = c2.conj().T  # (2s, n)
    batch_ids = np.stack([np.where(support[i] != 0)[0] for i in range(n)]).astype(np.int32)
    w_sel = np.take_along_axis(w, batch_ids, axis=1)  # (n, hat_s)
    t = np.arange(n)
    z = np.exp(2j * np.pi * t / n)
    est = np.stack([z**j for j in range(s + 1)], axis=1)  # (n, s+1)
    f32 = lambda x: np.ascontiguousarray(x, dtype=np.float32)
    return CyclicCode(
        n=n,
        s=s,
        w_sel_re=f32(w_sel.real),
        w_sel_im=f32(w_sel.imag),
        batch_ids=batch_ids,
        c2h_re=f32(c2h.real),
        c2h_im=f32(c2h.imag),
        c1_re=f32(c1.real),
        c1_im=f32(c1.imag),
        est_re=f32(est.real),
        est_im=f32(est.imag),
        w_masked_re=f32(w.real * support),
        w_masked_im=f32(w.imag * support),
        w_full=w,
        support=support,
    )


# --------------------------------------------------------------------------
# Encode (on-device, per worker-shard; reference: cyclic_worker.py:165-194)
# --------------------------------------------------------------------------

def encode(code: CyclicCode, grads: jnp.ndarray):
    """Encode per-batch gradients into per-worker complex rows.

    grads: (n, hat_s, d) — grads[i, k] is the gradient of the batch_ids[i, k]-th
    batch, computed by worker i. Returns (enc_re, enc_im), each (n, d):
    row i = Σ_k W[i, batch_ids[i,k]] · grads[i, k].
    """
    enc_re = jnp.einsum("nk,nkd->nd", jnp.asarray(code.w_sel_re), grads, precision=PREC)
    enc_im = jnp.einsum("nk,nkd->nd", jnp.asarray(code.w_sel_im), grads, precision=PREC)
    return enc_re, enc_im


def encode_shared(code: CyclicCode, batch_grads: jnp.ndarray):
    """Encode from one-copy batch gradients (TPU-native fast path).

    batch_grads: (n, d) — gradient of batch k at row k, each computed once.
    Equivalent to :func:`encode` when redundant computations of the same batch
    agree bitwise (they do: per-batch gradients are deterministic functions of
    (params, batch) under XLA). One fused complex matmul (Pallas on TPU —
    draco_tpu.ops.coded — streaming the (n, d) gradient matrix once).
    """
    return ops_coded.complex_matmul(
        jnp.asarray(code.w_masked_re), jnp.asarray(code.w_masked_im), batch_grads
    )


def encode_segment(code: CyclicCode, batch_grads: jnp.ndarray, a: int,
                   b: int):
    """Per-segment encode for the streaming segmented wire (ISSUE 16):
    the encode is a d-column-separable matmul, so the [a, b) slice of the
    full encode equals encoding the [a, b) gradient columns —
    ``encode_shared(code, g)[..][:, a:b] == encode_segment(code, g, a, b)``
    bitwise (identical contractions over the same operand columns). This
    is what lets workers emit per-segment codeword messages without any
    new encode weights: the segment-sliced weights ARE the full weights.
    """
    return ops_coded.complex_matmul(
        jnp.asarray(code.w_masked_re), jnp.asarray(code.w_masked_im),
        batch_grads[..., a:b]
    )


# --------------------------------------------------------------------------
# Decode (replicated phase; reference: cyclic_master.py:152-173 +
# c_coding.cpp:15-84)
# --------------------------------------------------------------------------

# The stacked-real-embedding complex solve moved to coding/linalg.py
# (ISSUE 12 satellite: one shared home for the hand-rolled solvers, used
# by both code families and the fused decode kernels' reference path).
# Bit-identical ops — the XLA decode path stays bitwise.
_complex_solve = linalg_mod.complex_solve


def _locate_v(code: CyclicCode, e_re: jnp.ndarray, e_im: jnp.ndarray,
              present: Optional[jnp.ndarray] = None,
              rel_tol: float = HEALTH_REL_TOL, lam: float = 0.0):
    """Locator + recombination vector from one projected column e (n,).

    ``lam`` (ISSUE 15): Tikhonov λ for the LOCATOR solve only — the Hankel
    system is the one that goes rank-deficient with fewer than s corrupt
    rows and amplifies a narrow wire's quantization noise
    (obs/numerics.WIRE_LOCATOR_LAMBDA scales λ to the dtype's noise floor
    on the scale-normalized system). The recombination and health-fit
    solves stay exact: their honest-row DFT submatrices are full-rank by
    construction. λ=0 (every f32-wire caller) is bitwise the historical
    path.

    Steps 2–5 of the decode: syndrome → error-locator solve → honest-row
    top-k → recombination vector v with vᵀC1 = e1ᵀ supported on those rows.
    Shape-static and vmap-able (layer-granularity decode maps this over the
    per-layer projected columns). Returns (v_re, v_im, honest, health) —
    the first three (n,), ``health`` the decode-health dict (below).

    Decode health (in-graph, no host traffic): the paper's exactness
    guarantee — the decoder *exactly* removes ≤ s corruptions — made
    observable. After choosing the honest set, fit the codeword those rows
    imply (the m×m solve ``C1[idx] q̂ = e[idx]``) and measure every row's
    deviation ``|e − C1 q̂|``:

      * honest rows deviate by f32 solve noise only (≈1e-6 relative);
      * a corrupt row deviates by its injected error magnitude;
      * rows above ``rel_tol`` × RMS(e) are ``flagged`` (present rows
        only — a zero-filled straggler erasure is known-missing, not a
        detected adversary). ``rel_tol`` defaults to HEALTH_REL_TOL (the
        f32 wire's solve-noise margin); the shadow-quantized decode
        (obs/numerics.py, ISSUE 10) passes a wider quantization-aware
        threshold because honest rows on a bf16/int8 wire deviate by
        rounding noise, not f32 noise;
      * ``residual`` is the *unflagged* present rows' deviation energy as
        a fraction of total received energy — ≈ 0 whenever the decode is
        self-consistent (the located-honest codeword explains every row it
        claims is honest), and the fault signal when it is not: with more
        corruption than the locator budget the honest set is mislocated,
        the fitted codeword is poisoned, and genuinely honest rows deviate
        loudly (they then also over-flag, so ``located > s`` is the
        companion budget-exceeded signal).
    """
    n, s = code.n, code.s
    c2h_re = jnp.asarray(code.c2h_re)
    c2h_im = jnp.asarray(code.c2h_im)

    # presence + received-energy statistics (the λ path's signal scale and
    # the health normalisation both read these)
    pres_f = (jnp.ones((n,), jnp.float32) if present is None
              else present.astype(jnp.float32))
    energy = e_re**2 + e_im**2
    msq = jnp.sum(energy * pres_f) / jnp.maximum(jnp.sum(pres_f), 1.0)

    # 2. syndrome E2 = C2^H e, shape (2s,)
    e2_re = jnp.matmul(c2h_re, e_re, precision=PREC) - jnp.matmul(c2h_im, e_im, precision=PREC)
    e2_im = jnp.matmul(c2h_re, e_im, precision=PREC) + jnp.matmul(c2h_im, e_re, precision=PREC)

    if s > 0:
        # 3. Hankel system A α = b from syndrome entries
        #    (c_coding.cpp:74-79: A[i,:] = E2[s-i-1 : 2s-i-1], b[i] = E2[2s-i-1])
        rows = jnp.arange(s)
        cols = jnp.arange(s)
        idx = (s - rows[:, None] - 1) + cols[None, :]
        a_re, a_im = e2_re[idx], e2_im[idx]
        b_idx = 2 * s - rows - 1
        b_re, b_im = e2_re[b_idx], e2_im[b_idx]
        # α is invariant to a common scaling of (A, b); normalising by the
        # syndrome magnitude makes the truncation threshold scale-free. With
        # fewer than s corrupt rows the Hankel system is genuinely
        # rank-deficient (geometric syndromes); the truncated pseudoinverse
        # keeps the solve NaN-free there while staying exact (f32 exact) on
        # full-rank systems, so corrupt-row locator magnitudes sit ~1e-5 vs
        # honest ~1.
        syn = jnp.maximum(jnp.max(e2_re**2 + e2_im**2) ** 0.5, 1e-30)
        if lam == 0.0:
            scale = syn
        else:
            # λ path (ISSUE 15): normalise by the SIGNAL scale (present-row
            # RMS of e) instead of the syndrome's own magnitude. A pure-
            # quantization syndrome is then ~the dtype noise floor λ is
            # calibrated to — self-normalisation would blow it up to O(1)
            # and hand the solve pure noise, the PR 10 amplification.
            scale = jnp.maximum(jnp.sqrt(msq), 1e-30)
        alpha_re, alpha_im = _complex_solve(
            a_re / scale, a_im / scale, b_re / scale, b_im / scale,
            rcond=LOCATOR_RCOND, lam=lam,
        )

        # 4. locator polynomial p(z) = z^s - Σ α_j z^j, roots at corrupt rows
        #    (cyclic_master.py:159-162)
        poly_re = jnp.concatenate([-alpha_re, jnp.ones((1,), a_re.dtype)])
        poly_im = jnp.concatenate([-alpha_im, jnp.zeros((1,), a_re.dtype)])
        est_re = jnp.asarray(code.est_re)
        est_im = jnp.asarray(code.est_im)
        val_re = jnp.matmul(est_re, poly_re, precision=PREC) - jnp.matmul(est_im, poly_im, precision=PREC)
        val_im = jnp.matmul(est_re, poly_im, precision=PREC) + jnp.matmul(est_im, poly_re, precision=PREC)
        mag = val_re**2 + val_im**2
        if lam > 0.0:
            # syndrome significance gate (branchless): a syndrome at the
            # quantization noise floor certifies NO corruption — the
            # locator output is pure amplified noise there, so the row
            # magnitudes collapse to uniform and the spread bias below
            # picks the deterministic well-conditioned subset. A real
            # corruption (O(100×) payloads) puts the relative syndrome
            # orders of magnitude above λ and the gate is transparent.
            # gate at 2λ: the gate must clear the dtype's measured
            # noise-floor maximum with margin, while the SOLVE cutoff
            # (σ ≤ λ dropped, coding/linalg) must not eat the genuine
            # locator directions — one λ cannot serve both (measured:
            # int8 at n=32 s=3 mislocates live adversaries when the
            # cutoff rides at the gate's 2^-5, locates exactly at 2^-6)
            live = (syn / scale) > 2.0 * lam
            mag = jnp.where(live, mag, jnp.ones_like(mag))
    else:
        mag = jnp.ones((n,), jnp.float32)

    # Deterministic tie-break: honest rows equidistant from a locator root
    # tie exactly (DFT-grid symmetry), and float noise would break the tie
    # differently per projection — per-layer decodes would then pick
    # different (all equally valid) honest sets. An index-monotone bias far
    # above float noise (~1e-7·mean) and far below any honest magnitude
    # (≳5e-2·mean) pins the choice, identically in the jit and native
    # decoders (native/coding.cpp draco_cyclic_decode). The λ path biases
    # by SPREAD rank instead (SPREAD_PHI docstring): the subset it pins in
    # the gated no-corruption state extrapolates at O(1) amplification.
    order = (jnp.arange(n, dtype=mag.dtype) if lam == 0.0
             else jnp.asarray(_spread_rank(n)))
    mag = mag + order * ((1e-3 / n) * jnp.mean(mag))

    # 5. recombination vector v supported on n-2s located-honest rows,
    #    v^T C1[idx] = e1^T  (fixed-shape stand-in for the reference's
    #    dynamic err_indices + scipy lsq_linear, cyclic_master.py:164-171).
    #    Rows are chosen as the top n-2s by locator magnitude — corrupt rows
    #    are locator roots, so they sit in the bottom s — which stays
    #    full-rank (any n-2s distinct rows of the DFT Vandermonde C1 are
    #    independent) even when fewer than s rows are actually corrupt and a
    #    thresholded mask would under- or over-fill. The returned mask marks
    #    exactly the rows the recombination used.
    if present is not None:
        # absent rows are never eligible, whatever the locator thinks; in the
        # erasure-only regime the locator may be overwhelmed (e > s), but any
        # n-2s present rows are honest and exactness holds regardless of mag
        mag = jnp.where(present, mag, -1.0)
    m = n - 2 * s
    idx = jnp.sort(jax.lax.top_k(mag, m)[1])
    honest = jnp.zeros((n,), dtype=bool).at[idx].set(True)
    rec_re = jnp.asarray(code.c1_re)[idx]  # (m, m)
    rec_im = jnp.asarray(code.c1_im)[idx]
    e1 = jnp.zeros((m,), rec_re.dtype).at[0].set(1.0)
    v_re, v_im = _complex_solve(rec_re.T, rec_im.T, e1, jnp.zeros_like(e1))

    v_full_re = jnp.zeros((n,), rec_re.dtype).at[idx].set(v_re)
    v_full_im = jnp.zeros((n,), rec_re.dtype).at[idx].set(v_im)

    # ---- decode health (docstring above): codeword fit + per-row deviation
    # (pres_f / energy / msq computed at the top alongside the λ path's
    # signal scale)
    q_re, q_im = _complex_solve(rec_re, rec_im, e_re[idx], e_im[idx])
    c1_re = jnp.asarray(code.c1_re)
    c1_im = jnp.asarray(code.c1_im)
    fit_re = jnp.matmul(c1_re, q_re, precision=PREC) - jnp.matmul(
        c1_im, q_im, precision=PREC)
    fit_im = jnp.matmul(c1_re, q_im, precision=PREC) + jnp.matmul(
        c1_im, q_re, precision=PREC)
    dev = (e_re - fit_re) ** 2 + (e_im - fit_im) ** 2  # (n,) |e - C1 q̂|²
    flagged = (dev > (rel_tol**2) * msq) & (pres_f > 0)
    resid_sq = jnp.sum(jnp.where(flagged, 0.0, dev) * pres_f) / jnp.maximum(
        jnp.sum(energy * pres_f), 1e-30)
    # loud-row outlier mask (LOUD_REL_TOL docstring): forensic-only — the
    # accusation signal that survives the beyond-budget regime, where the
    # fitted-codeword deviations above are blind (the chosen-row fit is a
    # square solve, exact on whatever rows it picked). NaN energies (a
    # non-finite wire) compare False on both sides, so a NaN-poisoned
    # column accuses nobody here — the ingest-row check
    # (obs/forensics.nonfinite_rows) owns that attribution.
    med = jnp.nanmedian(jnp.where(pres_f > 0, energy, jnp.nan))
    loud = (energy > LOUD_REL_TOL * med) & (pres_f > 0)
    health = {"residual": jnp.sqrt(resid_sq), "flagged": flagged,
              "loud": loud,
              # per-row relative deviation sqrt(dev/msq) — the quantity
              # rel_tol thresholds. Not a metric column: tools/wire_study
              # reads it to DERIVE the per-(n, s, dtype) narrow-wire
              # threshold table (honest-max vs adversary-min margins)
              "dev_rel": jnp.sqrt(dev / jnp.maximum(msq, 1e-30))}
    return v_full_re, v_full_im, honest, health


def locator_core(e_re, e_im, c2h_re, c2h_im, c1_re, c1_im, est_re, est_im,
                 pres_f, s: int, rel_tol: float = HEALTH_REL_TOL,
                 lam: float = 0.0):
    """Steps 2–5 of the decode + health, batched over projected columns —
    the fused counterpart of :func:`_locate_v` (ISSUE 12 tentpole).

    Identical math and identical health semantics, restructured for the
    fused decode kernels: a leading batch axis (the per-layer projected
    columns ``decode_layers`` vmaps over) and only the op set Mosaic
    lowers inside a Pallas kernel body — the three separate
    ``_complex_solve`` calls become one-sided Jacobi (the truncated
    locator least squares, ``linalg.jacobi_lstsq``) plus ONE Gauss–Jordan
    inverse of the honest-row submatrix that serves both the
    recombination vector (row 0 of ``rec⁻¹``) and the health fit
    (``rec⁻¹ e_sel``); ``top_k``/gather/median become pairwise-rank masks
    and matmul compaction (coding/linalg.py). The Pallas kernel
    (``ops/decode_kernels.cyclic_locator``) calls THIS function on its
    VMEM blocks and the ``decode_impl="pallas"`` CPU fallback jits it on
    the full (L, n) stack, so the two lowerings cannot drift
    algorithmically. Against the XLA path the results are bounded-err
    with identical flag/honest sets (the selection and flag margins are
    orders of magnitude above the solver differences; the equivalence
    suite pins both).

    e_re, e_im: (bb, n) projected columns. pres_f: (1 or bb, n) f32
    presence (all-ones when every row arrived). Returns
    ``(v_re, v_im, honest, flagged, loud, residual)`` — the first five
    (bb, n) with the v pair already carrying the 1/1 scale of
    ``_locate_v`` (callers fold /n into it), ``residual`` (bb,).
    """
    bb, n = e_re.shape
    m = n - 2 * s
    pres_f = jnp.broadcast_to(pres_f, (bb, n))
    # presence-weighted received energy (the λ path's signal scale and the
    # health normalisation below)
    energy = e_re ** 2 + e_im ** 2
    msq = (jnp.sum(energy * pres_f, axis=1)
           / jnp.maximum(jnp.sum(pres_f, axis=1), 1.0))[:, None]

    if s > 0:
        # 2. syndrome (bb, 2s): one complex matmul pair
        e2_re = (jnp.matmul(e_re, c2h_re.T, precision=PREC)
                 - jnp.matmul(e_im, c2h_im.T, precision=PREC))
        e2_im = (jnp.matmul(e_re, c2h_im.T, precision=PREC)
                 + jnp.matmul(e_im, c2h_re.T, precision=PREC))
        # 3. Hankel system rows via STATIC slices (A[i, j] = E2[s-i-1+j],
        #    b[i] = E2[2s-i-1]) — no gather, Mosaic constraint
        a_re = jnp.stack(
            [e2_re[:, s - 1 - i:2 * s - 1 - i] for i in range(s)], axis=1)
        a_im = jnp.stack(
            [e2_im[:, s - 1 - i:2 * s - 1 - i] for i in range(s)], axis=1)
        b_re = jnp.concatenate(
            [e2_re[:, 2 * s - 1 - i:2 * s - i] for i in range(s)], axis=1)
        b_im = jnp.concatenate(
            [e2_im[:, 2 * s - 1 - i:2 * s - i] for i in range(s)], axis=1)
        # same scale-free normalisation as _locate_v; the λ path divides
        # by the SIGNAL scale instead and gates on syndrome significance
        # (_locate_v's λ-branch comments — identical semantics here)
        syn = jnp.sqrt(jnp.maximum(
            jnp.max(e2_re ** 2 + e2_im ** 2, axis=1), 1e-60))[:, None]
        if lam == 0.0:
            scale = syn
        else:
            scale = jnp.maximum(jnp.sqrt(msq), 1e-30)
        big = jnp.concatenate([
            jnp.concatenate([a_re, -a_im], axis=2),
            jnp.concatenate([a_im, a_re], axis=2),
        ], axis=1) / scale[:, :, None]
        rhs = jnp.concatenate([b_re, b_im], axis=1) / scale
        al = linalg_mod.jacobi_lstsq(big, rhs, LOCATOR_RCOND,
                                     lam=lam)  # (bb, 2s)
        alpha_re, alpha_im = al[:, :s], al[:, s:]
        # 4. locator polynomial evaluated on the DFT grid
        poly_re = jnp.concatenate(
            [-alpha_re, jnp.ones((bb, 1), e_re.dtype)], axis=1)
        poly_im = jnp.concatenate(
            [-alpha_im, jnp.zeros((bb, 1), e_re.dtype)], axis=1)
        val_re = (jnp.matmul(poly_re, est_re.T, precision=PREC)
                  - jnp.matmul(poly_im, est_im.T, precision=PREC))
        val_im = (jnp.matmul(poly_re, est_im.T, precision=PREC)
                  + jnp.matmul(poly_im, est_re.T, precision=PREC))
        mag = val_re ** 2 + val_im ** 2
        if lam > 0.0:
            # syndrome significance gate at 2λ (_locate_v λ-branch comment)
            live = (syn / scale) > 2.0 * lam  # (bb, 1)
            mag = jnp.where(live, mag, jnp.ones_like(mag))
    else:
        mag = jnp.ones((bb, n), jnp.float32)

    # deterministic tie-break (see _locate_v) + absent rows never eligible;
    # the λ path biases by SPREAD rank (SPREAD_PHI) — computed from iota
    # pairwise comparisons, no host constant (Mosaic kernel body)
    if lam == 0.0:
        bias = jax.lax.broadcasted_iota(jnp.float32, (bb, n), 1)
    else:
        ki = jax.lax.broadcasted_iota(jnp.float32, (n, n), 0) * SPREAD_PHI
        kj = jax.lax.broadcasted_iota(jnp.float32, (n, n), 1) * SPREAD_PHI
        ki = ki - jnp.floor(ki)
        kj = kj - jnp.floor(kj)
        rank = jnp.sum((kj < ki).astype(jnp.float32), axis=1)  # (n,)
        bias = jnp.broadcast_to(rank[None, :], (bb, n))
    mag = mag + bias * ((1e-3 / n) * jnp.mean(mag, axis=1, keepdims=True))
    mag = jnp.where(pres_f > 0, mag, -1.0)

    # 5. honest set + recombination vector + health fit, through ONE
    #    Gauss–Jordan inverse of the (m, m) honest-row submatrix
    honest = linalg_mod.topk_mask(mag, m)  # (bb, n) bool
    sel = linalg_mod.select_matrix(honest, m)  # (bb, m, n) f32
    rec_re = jnp.matmul(sel.reshape(bb * m, n), c1_re,
                        precision=PREC).reshape(bb, m, m)
    rec_im = jnp.matmul(sel.reshape(bb * m, n), c1_im,
                        precision=PREC).reshape(bb, m, m)
    e_sel_re = jnp.sum(sel * e_re[:, None, :], axis=2)  # (bb, m)
    e_sel_im = jnp.sum(sel * e_im[:, None, :], axis=2)
    inv_re, inv_im = linalg_mod.gauss_inv_c(rec_re, rec_im)
    # vᵀ rec = e1ᵀ  ⇒  v = row 0 of rec⁻¹, scattered back through sel
    # (sliced, not integer-indexed: integer indexing lowers to a gather,
    # which Mosaic cannot lower in the kernel body)
    row0_re = inv_re[:, 0:1, :].reshape(bb, m, 1)
    row0_im = inv_im[:, 0:1, :].reshape(bb, m, 1)
    v_re = jnp.sum(row0_re * sel, axis=1)  # (bb, n)
    v_im = jnp.sum(row0_im * sel, axis=1)
    # health fit: q̂ = rec⁻¹ e_sel (the same inverse), codeword = C1 q̂
    q_re = (jnp.sum(inv_re * e_sel_re[:, None, :], axis=2)
            - jnp.sum(inv_im * e_sel_im[:, None, :], axis=2))
    q_im = (jnp.sum(inv_re * e_sel_im[:, None, :], axis=2)
            + jnp.sum(inv_im * e_sel_re[:, None, :], axis=2))
    fit_re = (jnp.matmul(q_re, c1_re.T, precision=PREC)
              - jnp.matmul(q_im, c1_im.T, precision=PREC))
    fit_im = (jnp.matmul(q_re, c1_im.T, precision=PREC)
              + jnp.matmul(q_im, c1_re.T, precision=PREC))
    dev = (e_re - fit_re) ** 2 + (e_im - fit_im) ** 2
    # energy / msq computed at the top (the λ path's signal scale)
    flagged = (dev > (rel_tol ** 2) * msq) & (pres_f > 0)
    resid_sq = (jnp.sum(jnp.where(flagged, 0.0, dev) * pres_f, axis=1)
                / jnp.maximum(jnp.sum(energy * pres_f, axis=1), 1e-30))
    # loud-row forensics (LOUD_REL_TOL docstring): rank-selection median
    # over present∧non-NaN rows matches _locate_v's nanmedian exactly
    med = linalg_mod.masked_median(
        energy, (pres_f > 0) & ~jnp.isnan(energy))[:, None]
    loud = (energy > LOUD_REL_TOL * med) & (pres_f > 0)
    return v_re, v_im, honest, flagged, loud, jnp.sqrt(resid_sq)


def _run_locator(code: CyclicCode, e_re_l, e_im_l, present, rel_tol,
                 impl: str, lam: float = 0.0):
    """Dispatch the batched locator: ``fused`` = :func:`locator_core`
    lowered through XLA (the decode_impl="pallas" CPU fallback),
    ``pallas``/``pallas_interpret`` = the hand-tiled kernel
    (ops/decode_kernels.cyclic_locator) running the same function on VMEM
    blocks."""
    n = code.n
    pres_f = (jnp.ones((1, n), jnp.float32) if present is None
              else jnp.asarray(present).astype(jnp.float32)[None, :])
    if impl in ("pallas", "pallas_interpret"):
        from draco_tpu.ops import decode_kernels

        return decode_kernels.cyclic_locator(
            code, e_re_l, e_im_l, pres_f, rel_tol,
            interpret=(impl == "pallas_interpret"), lam=lam)
    return locator_core(
        e_re_l, e_im_l,
        jnp.asarray(code.c2h_re), jnp.asarray(code.c2h_im),
        jnp.asarray(code.c1_re), jnp.asarray(code.c1_im),
        jnp.asarray(code.est_re), jnp.asarray(code.est_im),
        pres_f, code.s, rel_tol, lam=lam)


def decode(code: CyclicCode, r_re: jnp.ndarray, r_im: jnp.ndarray, rand_factor: jnp.ndarray,
           present: Optional[jnp.ndarray] = None, with_health: bool = False,
           rel_tol: float = HEALTH_REL_TOL, impl: str = "xla",
           lam: float = 0.0, wire=None):
    """Recover the exact sum of the n batch gradients from corrupt rows.

    r_re, r_im: (n, d) received encoded rows (≤ s rows arbitrarily corrupt).
    rand_factor: (d,) random projection (reference: cyclic_master.py:58-61).
    present: optional (n,) bool — False rows never arrived (stragglers /
    crashed workers; they must be zero-filled by the caller). Known-missing
    rows are *erasures*: they cost one redundancy unit instead of two, so the
    decode is exact when either (a) no adversary is live and ≤ 2s rows are
    missing, or (b) adversaries + missing ≤ s (the locator treats each
    zero-filled row as one located error). No reference counterpart — the
    reference PS simply blocks forever on a missing worker
    (baseline_master.py:112-116).

    Returns (n·mean-gradient, honest_mask): the (d,) real decoded sum / n and
    the (n,) mask of rows the recombination actually used (True = treated as
    honest; exactly n-2s rows are True, every located adversary and every
    absent row is False). ``with_health=True`` appends the decode-health
    dict (``_locate_v`` docstring: scalar ``residual`` ≈ 0 iff the decode is
    self-consistent, (n,) bool ``flagged`` marking present rows whose
    received value deviates from the fitted codeword, (n,) bool ``loud``
    marking magnitude-outlier present rows — the forensic-only accusation
    signal, LOUD_REL_TOL) — in-graph values for the telemetry metric
    columns, backward-compatible 2-tuple otherwise.

    ``impl`` selects the locator implementation (ISSUE 12): ``"xla"`` is
    the historical lowering, bit-for-bit unchanged (the K∈{1,4} bitwise
    suites run it); ``"fused"`` runs the batched :func:`locator_core`
    through XLA (the decode_impl="pallas" CPU fallback — bounded-err vs
    xla, identical honest/flag sets); ``"pallas"`` runs the hand-tiled
    kernel (ops/decode_kernels, TPU backends). Both non-xla paths fold
    the 1/n into the recombination vector.
    """
    n = code.n
    # 1. project to one column: e = R @ f  (the only O(n·d) work besides the
    #    final recombination — one fused pass over (R_re, R_im))
    e_re, e_im = ops_coded.complex_project(r_re, r_im, rand_factor)
    if impl == "xla":
        v_full_re, v_full_im, honest, health = _locate_v(code, e_re, e_im,
                                                         present, rel_tol,
                                                         lam=lam)
        # 6. recombine: Re(v^T R) / n — the second O(n·d) pass, fused
        decoded = ops_coded.complex_recombine(v_full_re, v_full_im,
                                              r_re, r_im) / n
    else:
        v_re, v_im, honest_l, flagged_l, loud_l, resid_l = _run_locator(
            code, e_re[None, :], e_im[None, :], present, rel_tol, impl,
            lam=lam)
        honest = honest_l[0]
        health = {"residual": resid_l[0], "flagged": flagged_l[0],
                  "loud": loud_l[0]}
        from draco_tpu.ops import decode_kernels

        if (impl in ("pallas", "pallas_interpret")
                and decode_kernels.narrow_kernel_ok(wire)):
            # narrow-ingest recombination (ISSUE 15): the kernel streams
            # the REAL narrow wire buffers and dequantizes in-tile — the
            # widened f32 (n, d) matrix never round-trips HBM
            decoded = decode_kernels.cyclic_narrow_recombine(
                v_re[0] / n, v_im[0] / n, wire,
                interpret=(impl == "pallas_interpret"))
        else:
            decoded = ops_coded.complex_recombine(v_re[0] / n, v_im[0] / n,
                                                  r_re, r_im)
    if with_health:
        return decoded, honest, health
    return decoded, honest


def _recombine_layers_fused(n: int, v_re_l, v_im_l, bounds, r_re, r_im):
    """Per-layer recombination of the fused decode path (PERF.md §14):
    same per-segment complex matvecs as the XLA path, but assembled by
    dynamic_update_slice writes into one preallocated (d,) output instead
    of a concatenate, and with the 1/n already folded into the v pair —
    measured fastest of the in-jit assembly variants on XLA:CPU (the
    gather- and broadcast-materialized (n, d) weight-matrix forms win as
    standalone microbenches but fuse pathologically inside the full step
    program). On TPU the same structure lets consecutive segment writes
    land in place."""
    del n  # shape-independent assembly (n rides in the operands)
    segs = list(zip(bounds[:-1], bounds[1:]))
    out = jnp.zeros((r_re.shape[1],), jnp.float32)
    for i, (a, b) in enumerate(segs):
        seg = ops_coded.complex_recombine(v_re_l[i], v_im_l[i],
                                          r_re[:, a:b], r_im[:, a:b])
        out = jax.lax.dynamic_update_slice(out, seg, (a,))
    return out


def decode_layers(code: CyclicCode, r_re: jnp.ndarray, r_im: jnp.ndarray,
                  rand_factor: jnp.ndarray, offsets,
                  present: Optional[jnp.ndarray] = None,
                  with_health: bool = False,
                  rel_tol: float = HEALTH_REL_TOL, impl: str = "xla",
                  lam: float = 0.0, wire=None):
    """Layer-granularity decode — one locator per parameter tensor.

    The reference decodes each layer independently with its own random
    projection factor (cyclic_master.py:125-129 loops layers, :58-61 draws a
    factor per layer); this is that semantics on the flattened (n, d) matrix:
    ``offsets`` are the static leaf boundaries (len L+1), segment ℓ =
    [offsets[ℓ], offsets[ℓ+1]). Each segment gets its own projection (a slice
    of the same (d,) factor vector), its own locator solve and its own
    recombination vector; the tiny per-layer solves run batched under one
    vmap. When corruption is per-worker (a whole row is attacked — the only
    kind the wire protocol admits) every layer locates the same set, and this
    agrees with the global decode; the per-layer locators additionally catch
    corruption confined to a single layer's coordinates, which a single
    global projection could only see through that layer's contribution.

    Returns (decoded (d,), honest (L, n)); ``with_health=True`` appends the
    combined decode-health dict — residual is the worst layer's (a single
    inconsistent layer is a fault), flagged is the union over layers (a row
    corrupted in any layer's coordinates is a located error).

    ``impl`` as in :func:`decode`. This is the fused kernel's home regime
    (ISSUE 12): the per-layer locators run as ONE batched
    :func:`locator_core` call over the (L, n) projected-column stack —
    a hand-tiled Pallas grid on TPU, one XLA program on CPU — instead of
    L vmapped solver chains, and the per-layer recombination is re-tiled
    per worker count (:func:`_recombine_layers_fused`).

    ``wire`` (ISSUE 15) is accepted for signature parity with
    :func:`decode` but the layer-granularity recombination keeps the
    widened f32 rows: the per-layer segment boundaries do not align with
    the narrow wire's per-block scale tiling, so the in-tile dequant
    kernel applies to the GLOBAL decode only (PERF.md §17).
    """
    del wire
    n = code.n
    bounds = [int(o) for o in offsets]
    e_res, e_ims = [], []
    for a, b in zip(bounds[:-1], bounds[1:]):
        e_re, e_im = ops_coded.complex_project(
            r_re[:, a:b], r_im[:, a:b], rand_factor[a:b]
        )
        e_res.append(e_re)
        e_ims.append(e_im)
    e_re_l = jnp.stack(e_res)  # (L, n)
    e_im_l = jnp.stack(e_ims)
    if impl == "xla":
        v_re_l, v_im_l, honest_l, health_l = jax.vmap(
            lambda er, ei: _locate_v(code, er, ei, present, rel_tol, lam)
        )(e_re_l, e_im_l)
        parts = [
            ops_coded.complex_recombine(v_re_l[i], v_im_l[i], r_re[:, a:b], r_im[:, a:b])
            for i, (a, b) in enumerate(zip(bounds[:-1], bounds[1:]))
        ]
        decoded = jnp.concatenate(parts) / n
        if with_health:
            health = {"residual": jnp.max(health_l["residual"]),
                      "flagged": jnp.any(health_l["flagged"], axis=0),
                      "loud": jnp.any(health_l["loud"], axis=0),
                      "dev_rel": jnp.max(health_l["dev_rel"], axis=0)}
            return decoded, honest_l, health
        return decoded, honest_l
    v_re_l, v_im_l, honest_l, flagged_l, loud_l, resid_l = _run_locator(
        code, e_re_l, e_im_l, present, rel_tol, impl, lam=lam)
    decoded = _recombine_layers_fused(n, v_re_l / n, v_im_l / n, bounds,
                                      r_re, r_im)
    if with_health:
        health = {"residual": jnp.max(resid_l),
                  "flagged": jnp.any(flagged_l, axis=0),
                  "loud": jnp.any(loud_l, axis=0)}
        return decoded, honest_l, health
    return decoded, honest_l


def decode_segments(code: CyclicCode, r_re: jnp.ndarray, r_im: jnp.ndarray,
                    rand_factor: jnp.ndarray, bounds,
                    present: Optional[jnp.ndarray] = None,
                    with_health: bool = False,
                    rel_tol: float = HEALTH_REL_TOL, impl: str = "xla",
                    lam: float = 0.0, wire=None):
    """Streaming segmented decode (ISSUE 16; arXiv:1903.01974's
    multi-message communication): one locator per WIRE SEGMENT instead of
    one per layer — ``bounds`` are the quantum-aligned segment cuts
    (obs/numerics.wire_segment_bounds; len S+1), segment j =
    [bounds[j], bounds[j+1]).

    Segment algebra: each segment gets its own projection column (a slice
    of the same (d,) factor), its own syndrome + Hankel locator solve and
    its own recombination vector — exactly the layer-granularity decode's
    structure (:func:`decode_layers`), so the same correctness argument
    applies: the wire protocol corrupts whole ROWS, so every segment of a
    corrupt row carries that row's error and every segment's locator sees
    it; a straggler erasure zero-fills all its segments under the same
    present mask. The per-step accusation/health verdict is the FOLD
    across segments — residual = worst segment (a single inconsistent
    segment is a fault), flagged/loud = union (a row corrupt in any
    segment's coordinates is a located error) — so detection P/R, guards,
    incidents and the autopilot keep seeing one verdict per step.

    Unlike :func:`decode_layers`, segment cuts ARE aligned to the narrow
    wire's per-block scale tiling (the bounds contract), so the
    narrow-ingest recombination applies per segment: on the kernel path
    each segment streams its own slice of the REAL narrow buffers and
    dequantizes in-tile (ops/decode_kernels.wire_slice_pair — the
    segment-offset entry point, no new kernels).

    Returns ``(decoded (d,), honest (S', n)[, health])`` — callers fold
    honest with ``jnp.all(axis=0)`` like the layer path. S'=len(bounds)-1.
    """
    n = code.n
    bounds = [int(o) for o in bounds]
    segs = list(zip(bounds[:-1], bounds[1:]))
    e_res, e_ims = [], []
    for a, b in segs:
        e_re, e_im = ops_coded.complex_project(
            r_re[:, a:b], r_im[:, a:b], rand_factor[a:b]
        )
        e_res.append(e_re)
        e_ims.append(e_im)
    e_re_l = jnp.stack(e_res)  # (S', n)
    e_im_l = jnp.stack(e_ims)
    if impl == "xla":
        v_re_l, v_im_l, honest_l, health_l = jax.vmap(
            lambda er, ei: _locate_v(code, er, ei, present, rel_tol, lam)
        )(e_re_l, e_im_l)
        decoded = _recombine_layers_fused(n, v_re_l / n, v_im_l / n,
                                          bounds, r_re, r_im)
        if with_health:
            health = {"residual": jnp.max(health_l["residual"]),
                      "flagged": jnp.any(health_l["flagged"], axis=0),
                      "loud": jnp.any(health_l["loud"], axis=0),
                      "dev_rel": jnp.max(health_l["dev_rel"], axis=0)}
            return decoded, honest_l, health
        return decoded, honest_l
    v_re_l, v_im_l, honest_l, flagged_l, loud_l, resid_l = _run_locator(
        code, e_re_l, e_im_l, present, rel_tol, impl, lam=lam)
    from draco_tpu.ops import decode_kernels

    if (impl in ("pallas", "pallas_interpret")
            and decode_kernels.narrow_kernel_ok(wire)):
        # per-segment narrow ingest: each segment's recombination streams
        # its own slice of the narrow buffers (decode-on-arrival unit)
        out = jnp.zeros((r_re.shape[1],), jnp.float32)
        for i, (a, b) in enumerate(segs):
            seg = decode_kernels.cyclic_narrow_recombine_segment(
                v_re_l[i] / n, v_im_l[i] / n, wire, a, b,
                interpret=(impl == "pallas_interpret"))
            out = jax.lax.dynamic_update_slice(out, seg, (a,))
        decoded = out
    else:
        decoded = _recombine_layers_fused(n, v_re_l / n, v_im_l / n,
                                          bounds, r_re, r_im)
    if with_health:
        health = {"residual": jnp.max(resid_l),
                  "flagged": jnp.any(flagged_l, axis=0),
                  "loud": jnp.any(loud_l, axis=0)}
        return decoded, honest_l, health
    return decoded, honest_l
