"""Hierarchical CodedReduce aggregation — the tree topology (ISSUE 17).

Every coded route used to decode flat PS-style: all n codewords land at one
logical aggregation point, so decode time and ingest bandwidth at that point
grow with n (the PR 9 device ledger prices decode at 17-25% of LM device
time, and the PR 15 threshold table shows the locator degrading as n grows).
CodedReduce (PAPERS.md, arXiv:1902.01981) replaces the star with a tree whose
per-node fan-in is CONSTANT: the (n,) worker axis is partitioned into
``G = n / g`` leaf groups of fan-in ``g`` (the same consecutive-window
algebra as ``coding/assignment.clustered_assignment`` — worker ``i`` sits in
group ``i // g``), each group runs its OWN small-n code over its g batches,
decodes locally, and parents combine the decoded (d,) partials level by
level until one aggregate remains. Per-node decode cost and ingest bytes are
then O(g·d) at the leaves and O(f·d) at each combine node — independent of
n — while the flat aggregation point pays O(n·d).

Group algebra (mirrors the flat Σ/n convention bitwise at the seams):

  * leaf group j covers workers [j·g, (j+1)·g) and THEIR batch rows — under
    ``redundancy="shared"`` batch k's gradient sits at row k, so group j's
    code mixes exactly its own g rows (a block-diagonal encode; the [lo, hi)
    slice of the tree encode equals the small code's flat encode of those
    rows bit-for-bit);
  * each group decode returns Σ_{k∈group} grads_k / g (the family's own
    Σ/n convention at n=g);
  * the combine is the level-structured mean of group partials —
    mean_j(Σ_group/g) = Σ_all/n — exactly the flat decode's output
    convention.

Per-group code strength: the per-(n, s, dtype) threshold table (PR 15) and
the cyclic existence bound pick the per-group ``s_g``:
``s_g = min(worker_fail, (g-1)//4)`` (the small code needs g > 4·s_g), and
under a narrow wire additionally ``wire_rel_tol(g, s_g, dtype) < 1`` —
config.validate walks s_g down and rejects configs whose declared adversary
load exceeds the worst-case per-group budget (all adversaries in one group).

Health fold (the PR 16 segment fold, applied across worker GROUPS instead
of wire segments): residual = max over groups (a single inconsistent group
is a fault), flagged/loud/dev_rel = the disjoint-group union (per-group
(g,) masks concatenate back to (n,)), honest = concatenation — so the
detection/forensics columns are (n,)-shaped and IDENTICAL to the flat
decode's under the same faults (pinned by tests/test_tree.py and the
committed tree_study cells, live adversaries and straggler drops included).

The mesh-sub-axis form (``lint_programs``): the combine levels map onto
named mesh axes ("tl1" innermost) and parents combine via ``lax.psum`` over
the level's axis name — one all_reduce per level, pinned EXACTLY by the
collectives manifest (the communication structure IS the algorithm). The
production jit routes keep the structured sum (GSPMD schedules it;
collectives={} stays pinned there like every data-parallel route).

Jax-free header: the plan/byte math (``tree_plan``, ``tree_ledger_block``)
imports no jax, so obs/numerics.wire_ledger and config.validate can price
and validate tree configs host-side; everything below build_tree_code
imports jax lazily.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

TOPOLOGIES = ("flat", "tree")

# partial-combine wire width: parents ingest decoded f32 (d,) partials
PARTIAL_BYTES = 4


# --------------------------------------------------------------------------
# jax-free plan algebra (config.validate + obs/numerics consume this)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TreePlan:
    """The static tree shape: who groups with whom, and how groups fold."""

    n: int
    fanout: int
    levels: int  # total levels including the leaf level (>= 2)
    num_groups: int
    # combine fan-ins, innermost (level 1, adjacent groups) first; their
    # product is num_groups and each is <= fanout
    level_fanouts: Tuple[int, ...]
    # leaf group j = workers [group_slices[j][0], group_slices[j][1])
    group_slices: Tuple[Tuple[int, int], ...]

    @property
    def level_widths(self) -> Tuple[int, ...]:
        """Node count per level, leaves first: (G, G/f1, ..., 1)."""
        widths = [self.num_groups]
        for f in self.level_fanouts:
            widths.append(widths[-1] // f)
        return tuple(widths)


def auto_levels(n: int, fanout: int) -> int:
    """Leaf level + enough combine levels of fan-in <= ``fanout`` to fold
    G = n/fanout groups to one root: ``1 + ceil(log_g(G))`` (min 2)."""
    groups = n // fanout
    return 1 + max(1, math.ceil(math.log(groups, fanout))) if groups > 1 \
        else 2


def level_fanouts(num_groups: int, fanout: int,
                  levels: int) -> Tuple[int, ...]:
    """Split the group-folding into ``levels - 1`` per-level fan-ins, each
    <= ``fanout``, innermost first, product exactly ``num_groups``."""
    fans = []
    remaining = num_groups
    for _ in range(levels - 1):
        f = min(fanout, remaining)
        fans.append(max(f, 1))
        remaining = -(-remaining // max(f, 1))
    if math.prod(fans) != num_groups:
        raise ValueError(
            f"tree_levels={levels} cannot fold {num_groups} groups with "
            f"fan-in <= {fanout} (per-level fan-ins {fans} multiply to "
            f"{math.prod(fans)})")
    return tuple(fans)


def tree_plan(n: int, fanout: int, levels: int = 0) -> TreePlan:
    """Validated tree shape for ``n`` workers at fan-in ``fanout``.
    ``levels=0`` auto-derives ``auto_levels``."""
    n, fanout = int(n), int(fanout)
    if fanout < 2:
        raise ValueError(f"tree_fanout must be >= 2, got {fanout}")
    if n % fanout != 0:
        raise ValueError(
            f"topology='tree' needs num_workers % tree_fanout == 0, got "
            f"n={n}, g={fanout}")
    groups = n // fanout
    if groups < 2:
        raise ValueError(
            f"topology='tree' needs at least 2 leaf groups (n > fanout), "
            f"got n={n}, g={fanout} — use topology='flat'")
    lv = int(levels) or auto_levels(n, fanout)
    if lv < 2:
        raise ValueError(f"tree_levels must be >= 2 (or 0 = auto), got {lv}")
    fans = level_fanouts(groups, fanout, lv)
    slices = tuple((j * fanout, (j + 1) * fanout) for j in range(groups))
    return TreePlan(n=n, fanout=fanout, levels=lv, num_groups=groups,
                    level_fanouts=fans, group_slices=slices)


def group_worker_fail(fanout: int, worker_fail: int) -> int:
    """The per-group cyclic error budget: the flat ``s`` capped by the small
    code's existence bound g > 4·s_g. The threshold-table narrowing cap is
    applied on top by config.validate (wire_rel_tol at the GROUP shape)."""
    return min(int(worker_fail), max((int(fanout) - 1) // 4, 0))


def tree_ledger_block(n: int, fanout: int, levels: int, dim: int,
                      physical_bytes_per_worker: int) -> dict:
    """The wire ledger's ``tree`` sub-block (jax-free): per-level ingest
    bytes per step. Level 0 is the leaf ingest — each leaf node receives its
    g workers' codewords, and the per-group bytes SUM EXACTLY to the flat
    ``physical_bytes_per_step`` (the same n codeword rows, partitioned, no
    padding at the seams — perf_watch pins the sum both directions). Combine
    level l >= 1 ingests its children's decoded f32 (d,) partials:
    ``level_widths[l-1] · 4 · dim`` bytes per step — the tree's internal
    traffic, CONSTANT per node (fan-in · 4 · dim) as n grows."""
    plan = tree_plan(n, fanout, levels)
    leaf_group = fanout * int(physical_bytes_per_worker)
    widths = plan.level_widths
    level_bytes = [leaf_group * plan.num_groups]
    level_bytes += [widths[l - 1] * PARTIAL_BYTES * int(dim)
                    for l in range(1, plan.levels)]
    return {
        "fanout": plan.fanout,
        "levels": plan.levels,
        "num_groups": plan.num_groups,
        "level_fanouts": list(plan.level_fanouts),
        "level_widths": list(widths),
        "ingest_bytes_per_group": leaf_group,
        # per-node ingest at each level: what ONE aggregation point pays
        "node_ingest_bytes": [leaf_group] + [
            f * PARTIAL_BYTES * int(dim) for f in plan.level_fanouts],
        "level_bytes_per_step": level_bytes,
    }


# --------------------------------------------------------------------------
# tree codes (jax from here down, imported lazily)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TreeCode:
    """One small per-group code + the plan that tiles it over the fleet.
    Groups are homogeneous (equal size, same scheme), so ONE small code is
    shared by every group — the same constants, the same compiled decode."""

    plan: TreePlan
    group_code: object  # CyclicCode(g, s_g) or ApproxCode(g, r, scheme)
    family: str  # "cyclic" | "approx"

    @property
    def n(self) -> int:
        return self.plan.n

    @property
    def s(self) -> int:
        """Per-group error budget (cyclic); 0 for approx."""
        return getattr(self.group_code, "s", 0)


def build_tree_code(cfg) -> TreeCode:
    """The tree code a config names: cyclic groups at
    ``s_g = group_worker_fail`` or approx groups at the configured
    fractional redundancy. config.validate has already checked the shape."""
    from draco_tpu.coding import approx as approx_mod
    from draco_tpu.coding import cyclic as cyclic_mod

    plan = tree_plan(cfg.num_workers, cfg.tree_fanout, cfg.tree_levels)
    if cfg.approach == "cyclic":
        s_g = group_worker_fail(cfg.tree_fanout, cfg.worker_fail)
        return TreeCode(plan, cyclic_mod.build_cyclic_code(plan.fanout, s_g),
                        "cyclic")
    if cfg.approach == "approx":
        return TreeCode(
            plan,
            approx_mod.build_approx_code(plan.fanout, cfg.code_redundancy,
                                         cfg.assignment_scheme),
            "approx")
    raise ValueError(
        f"topology='tree' supports cyclic/approx, got {cfg.approach!r} "
        "(maj_vote's repetition groups are already a one-level tree)")


def _slice_wire(wire, lo: int, hi: int):
    """The [lo, hi) worker-row slice of a narrow wire tuple — the per-group
    (g, d) block the narrow-ingest kernels take instead of (n, d). Buffers
    are row-major over workers and int8 scales are per-row, so slicing rows
    never splits a scale block."""
    if wire is None:
        return None
    if len(wire) == 4:  # cyclic pair: (mode, buf_re, buf_im, block)
        mode, buf_re, buf_im, block = wire
        return (mode, {k: v[lo:hi] for k, v in buf_re.items()},
                {k: v[lo:hi] for k, v in buf_im.items()}, block)
    mode, buf, block = wire  # approx/maj_vote single: (mode, buf, block)
    return (mode, {k: v[lo:hi] for k, v in buf.items()}, block)


def combine_partials(plan: TreePlan, parts):
    """Level-structured combine of the (G, d) group partials: each combine
    level sums its fan-in children (C-order reshape — level 1 folds adjacent
    groups), the root divides by G. Structurally the tree (the shard_map
    form runs the same sums as per-level psum), numerically the flat
    mean-of-groups = Σ_all/n."""
    import jax.numpy as jnp

    x = jnp.asarray(parts)
    for f in plan.level_fanouts:
        x = x.reshape(-1, f, x.shape[-1]).sum(axis=1)
    return x[0] / plan.num_groups


def encode_tree(tcode: TreeCode, batch_grads):
    """Block-diagonal tree encode from one-copy batch gradients (n, d):
    group j's [lo, hi) rows are the small code's flat encode of that group's
    batch rows — bit-for-bit (same kernel, same operands). Returns the
    cyclic (enc_re, enc_im) pair or the approx (n, d) partial-sum rows."""
    import jax.numpy as jnp

    from draco_tpu.coding import approx as approx_mod
    from draco_tpu.coding import cyclic as cyclic_mod

    code = tcode.group_code
    if tcode.family == "cyclic":
        pairs = [cyclic_mod.encode_shared(code, batch_grads[lo:hi])
                 for lo, hi in tcode.plan.group_slices]
        return (jnp.concatenate([p[0] for p in pairs]),
                jnp.concatenate([p[1] for p in pairs]))
    rows = [approx_mod.encode_shared(code, batch_grads[lo:hi])
            for lo, hi in tcode.plan.group_slices]
    return jnp.concatenate(rows)


def decode_tree_cyclic(tcode: TreeCode, r_re, r_im, rand_factor,
                       present=None, rel_tol: Optional[float] = None,
                       impl: str = "xla", lam: float = 0.0, wire=None,
                       bounds=None):
    """Tree cyclic decode: each leaf group runs the small code's own decode
    (segmented when ``bounds`` has interior cuts — the wire_segments
    composition; the narrow-ingest kernels take the group's (g, d) wire
    block via :func:`_slice_wire`), parents combine the (d,) partials
    level-structured, and the per-group health verdicts fold like the PR 16
    segment fold: residual = max, flagged/loud/dev_rel = disjoint-group
    union back to (n,), honest = concatenation.

    Returns ``(decoded (d,), honest (n,), health)`` — the flat decode's
    contract with honest already folded over segments."""
    import jax.numpy as jnp

    from draco_tpu.coding import cyclic as cyclic_mod

    code = tcode.group_code
    if rel_tol is None:
        rel_tol = cyclic_mod.HEALTH_REL_TOL
    segmented = bounds is not None and len(bounds) > 2
    parts, honests, healths = [], [], []
    for lo, hi in tcode.plan.group_slices:
        pres_g = None if present is None else present[lo:hi]
        wire_g = _slice_wire(wire, lo, hi)
        if segmented:
            dec, hon, hl = cyclic_mod.decode_segments(
                code, r_re[lo:hi], r_im[lo:hi], rand_factor, bounds,
                present=pres_g, with_health=True, rel_tol=rel_tol,
                impl=impl, lam=lam, wire=wire_g)
            hon = jnp.all(hon, axis=0)  # (S', g) -> (g,): the segment fold
        else:
            dec, hon, hl = cyclic_mod.decode(
                code, r_re[lo:hi], r_im[lo:hi], rand_factor,
                present=pres_g, with_health=True, rel_tol=rel_tol,
                impl=impl, lam=lam, wire=wire_g)
        parts.append(dec)
        honests.append(hon)
        healths.append(hl)
    decoded = combine_partials(tcode.plan, jnp.stack(parts))
    honest = jnp.concatenate(honests)
    health = {"residual": jnp.max(jnp.stack([h["residual"]
                                             for h in healths])),
              "flagged": jnp.concatenate([h["flagged"] for h in healths]),
              "loud": jnp.concatenate([h["loud"] for h in healths])}
    if all("dev_rel" in h for h in healths):
        health["dev_rel"] = jnp.concatenate([h["dev_rel"] for h in healths])
    return decoded, honest, health


def decode_tree_approx(tcode: TreeCode, rows, present=None,
                       batch_grads=None, impl: str = "xla", wire=None,
                       bounds=None):
    """Tree approx decode: per-group optimal-decoding (segmented under the
    wire_segments composition), level-structured combine, and the health
    fold that keeps the family's certificate comparable to flat:

      * ``residual`` is measured at the ROOT against the full true mean —
        the flat formula on the tree aggregate, so guard/incident
        thresholds keep their meaning;
      * ``bound`` = sqrt(Σ_j bound_j²) — the exact ‖u − 1‖₂ of the
        block-diagonal system, and err ≤ bound·‖G‖_F/n still holds
        (Cauchy-Schwarz across groups);
      * ``recovered_fraction`` = mean over equal-size groups (the same
        batch-coverage fraction as flat).

    Returns ``(decoded (d,), v (n,), health)``."""
    import jax.numpy as jnp

    from draco_tpu.coding import approx as approx_mod

    code = tcode.group_code
    segmented = bounds is not None and len(bounds) > 2
    parts, vs, bounds_sq, rec = [], [], [], []
    for lo, hi in tcode.plan.group_slices:
        pres_g = None if present is None else present[lo:hi]
        wire_g = _slice_wire(wire, lo, hi)
        bg = None if batch_grads is None else batch_grads[lo:hi]
        if segmented:
            dec, v, hl = approx_mod.decode_segments(
                code, rows[lo:hi], bounds, present=pres_g,
                with_health=True, batch_grads=bg, impl=impl, wire=wire_g)
        else:
            dec, v, hl = approx_mod.decode(
                code, rows[lo:hi], present=pres_g, with_health=True,
                batch_grads=bg, impl=impl, wire=wire_g)
        parts.append(dec)
        vs.append(v)
        bounds_sq.append(hl["bound"] ** 2)
        rec.append(hl["recovered_fraction"])
    decoded = combine_partials(tcode.plan, jnp.stack(parts))
    v_all = jnp.concatenate(vs)
    n = tcode.plan.n
    true_mean = jnp.sum(batch_grads, axis=0) / n
    gfro = jnp.sqrt(jnp.sum(jnp.asarray(batch_grads,
                                        jnp.float32) ** 2))
    scale = jnp.maximum(gfro / n, 1e-30)
    health = {
        "residual": jnp.sqrt(jnp.sum((decoded - true_mean) ** 2)) / scale,
        "bound": jnp.sqrt(jnp.sum(jnp.stack(bounds_sq))),
        "recovered_fraction": jnp.mean(jnp.stack(rec)),
    }
    return decoded, v_all, health


# --------------------------------------------------------------------------
# mesh-sub-axis form: per-level psum combine (the registered programs)
# --------------------------------------------------------------------------


def tree_axis_names(plan: TreePlan) -> Tuple[str, ...]:
    """Combine-level mesh axis names, innermost (level 1) first."""
    return tuple(f"tl{l + 1}" for l in range(len(plan.level_fanouts)))


def tree_mesh(plan: TreePlan, devices=None):
    """Mesh whose axes ARE the combine levels: the device grid is shaped
    (f_top, ..., f_1[, wi]) so C-order places group j at grid multi-index
    unravel(j) — adjacent groups share the innermost ("tl1") axis, exactly
    the groups level 1 folds. A trailing replication axis "wi" soaks up
    devices beyond one per group (each group's block is replicated across
    it). Needs num_groups | device count or device count | num_groups·wi;
    raises when the grid cannot be filled exactly."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    g_axes = tree_axis_names(plan)
    grid_shape = tuple(reversed(plan.level_fanouts))
    need = plan.num_groups
    if len(devices) % need != 0:
        raise ValueError(
            f"tree_mesh: {len(devices)} devices cannot tile {need} groups "
            "evenly")
    wi = len(devices) // need
    names = tuple(reversed(g_axes))
    if wi > 1:
        grid_shape = grid_shape + (wi,)
        names = names + ("wi",)
    grid = np.asarray(devices[: need * wi]).reshape(grid_shape)
    return Mesh(grid, names)


def make_tree_decode_shmap(tcode: TreeCode, mesh, impl: str = "xla",
                           rel_tol: Optional[float] = None,
                           lam: float = 0.0):
    """The mesh-sub-axis tree decode: each device holds its leaf group's
    whole (g, d) codeword block (replicated across "wi" when present),
    decodes it LOCALLY with the small code, then parents combine the (d,)
    partials with one ``lax.psum`` PER LEVEL over that level's axis name —
    the collectives manifest pins exactly ``levels - 1`` all_reduce ops
    (the communication structure is the algorithm; sp_step's ppermute ring
    budget is the precedent for nonzero pins). Returns a jitted
    ``fn(r_re, r_im, rand_factor, present) -> (d,)`` aggregate, replicated.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from draco_tpu.coding import cyclic as cyclic_mod
    from draco_tpu.runtime import shard_map

    from draco_tpu.parallel.partition import tree_rows

    code = tcode.group_code
    plan = tcode.plan
    tol = cyclic_mod.HEALTH_REL_TOL if rel_tol is None else rel_tol
    level_axes = tree_axis_names(plan)
    # rows partition over the level axes only: each device (and every "wi"
    # replica) holds its group's full (g, d) block
    row_spec = tree_rows(level_axes)

    def device_decode(r_re, r_im, rand_factor, present):
        dec, _ = cyclic_mod.decode(code, r_re, r_im, rand_factor,
                                   present=present, with_health=False,
                                   rel_tol=tol, impl=impl, lam=lam)
        out = dec
        for ax in level_axes:  # one all_reduce per combine level
            out = jax.lax.psum(out, ax)
        return out / plan.num_groups

    fn = shard_map(
        device_decode,
        mesh=mesh,
        in_specs=(row_spec, row_spec, P(), row_spec),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def lint_programs():
    """Registered mesh-sub-axis tree programs (analysis/registry.collect):
    the per-level psum counts are pinned EXACTLY by the collectives
    manifest. Shapes are small (the leaf decode is the point — fan-in g,
    not n) and CPU-exportable like every other lint row."""
    import jax
    import numpy as np

    from draco_tpu.analysis.registry import (BuiltProgram, LintProgram,
                                             Manifest)

    def _build(n, g, name):
        import dataclasses as _dc

        from draco_tpu.config import TrainConfig

        cfg = TrainConfig(approach="cyclic", num_workers=n, worker_fail=1,
                          adversary_count=0, redundancy="shared",
                          topology="tree", tree_fanout=g,
                          dataset="synthetic-mnist", network="LeNet",
                          batch_size=2)
        from draco_tpu.parallel.partition import tree_combine_rules

        tcode = build_tree_code(cfg)
        mesh = tree_mesh(tcode.plan)
        fn = make_tree_decode_shmap(tcode, mesh)
        level_axes = tree_axis_names(tcode.plan)
        d = 8192
        args = (np.zeros((n, d), np.float32), np.zeros((n, d), np.float32),
                np.ones((d,), np.float32), np.ones((n,), np.float32))
        manifest = Manifest(
            max_constant_bytes=1 << 20,
            max_module_bytes=1 << 20,
            require_donated=None,
            collectives={"all_reduce": tcode.plan.levels - 1},
            # the combine IS the communication structure: exactly one psum
            # per level, each on that level's own mesh sub-axis
            collective_axes={ax: {"all_reduce": 1} for ax in level_axes},
            host_transfer_budget=0,
            max_peak_bytes=1 << 30,
        )
        return BuiltProgram(name=name, fn=fn, args=args, mesh=mesh,
                            manifest=manifest,
                            partition_rules=tree_combine_rules(level_axes),
                            arg_names=("r_re", "r_im", "rand_factor",
                                       "present"))

    return [
        LintProgram(name="tree_combine_g2_l3",
                    build=lambda: _build(8, 2, "tree_combine_g2_l3"),
                    route="cnn", fast=True),
        LintProgram(name="tree_combine_g4_l2",
                    build=lambda: _build(8, 4, "tree_combine_g4_l2"),
                    route="cnn", fast=True),
    ]
