from draco_tpu.coding.cyclic import CyclicCode, build_cyclic_code, encode, decode  # noqa: F401
from draco_tpu.coding.repetition import RepetitionCode, build_repetition_code, majority_vote  # noqa: F401
from draco_tpu.coding.approx import ApproxCode, build_approx_code  # noqa: F401
from draco_tpu.coding.assignment import build_assignment  # noqa: F401
