"""Batch-to-worker assignment algebra for the approximate code family.

DRACO's exact codes fix the assignment implicitly: the cyclic (DFT) code's
support is the length-(2s+1) cyclic window and the repetition code's is the
group block — both at redundancy r = 2s+1, the price of exact recovery. The
approximate family (coding/approx.py; Stochastic Gradient Coding
arXiv:1905.05383, Approximate Gradient Coding with Optimal Decoding
arXiv:2006.09638) makes the assignment a free, *fractional* parameter
r ∈ [1, n]: this module builds the (n, n) assignment supports and the
replication-normalised encode weights both schemes share.

Two deterministic constructions (every participant rebuilds the identical
matrices from (n, r) alone — the agreed-schedule discipline of rng.py):

  * ``pairwise`` — pair-wise balanced cyclic windows: worker i covers the
    cyclic window of d_i consecutive batches starting at batch i, with
    d_i = ⌊r⌋ + 1 for the first ``⌊(r-⌊r⌋)·n + ½⌋`` workers and ⌊r⌋ for
    the rest, so total compute is ⌊r·n + ½⌋ batch-gradients and every
    batch is replicated ⌊r⌋ or ⌊r⌋+1 times. Consecutive windows give every
    worker pair an overlap that differs by at most one from the cyclic
    optimum — the balanced-overlap property the optimal-decoding analysis
    of arXiv:2006.09638 wants, without that paper's randomised expanders
    (which would break the every-participant-agrees determinism).

  * ``clustered`` — fractional repetition (FRC, the clustering of
    arXiv:1903.01974): integer r = c dividing n; workers are partitioned
    into n/c clusters of c and every member of cluster j computes exactly
    the c batches of batch-group j. Any single survivor per cluster makes
    the decode exact — the strongest per-straggler robustness an
    assignment of redundancy c can buy, at the price that a fully-absent
    cluster loses its whole batch group.

Encode weights: W[i, k] = A[i, k] / m_k where m_k = Σ_i A[i, k] is batch
k's replication count. Column sums are then exactly 1, so the uniform
decode vector v = 1 recovers the exact batch-gradient sum whenever every
worker arrives — full-participation exactness by construction, for any r,
including the mixed ⌊r⌋/⌊r⌋+1 case where a 0/1 assignment alone would not
put the all-ones vector in range(Aᵀ).
"""

from __future__ import annotations

import numpy as np

SCHEMES = ("pairwise", "clustered")


def loads_for(n: int, redundancy: float) -> np.ndarray:
    """(n,) int per-worker batch counts for the pairwise scheme: ⌊r⌋ or
    ⌊r⌋+1, summing to ⌊r·n + ½⌋ (half-up, NOT Python's banker's rounding —
    half-integer products like n=9, r=1.5 must round toward the advertised
    redundancy, never below it)."""
    base = int(np.floor(redundancy))
    extra = int(np.floor((redundancy - base) * n + 0.5))
    return np.asarray([base + (1 if i < extra else 0) for i in range(n)],
                      np.int64)


def pairwise_assignment(n: int, redundancy: float) -> np.ndarray:
    """(n, n) 0/1 pair-wise balanced cyclic-window assignment (module
    docstring). A[i, k] = 1 iff worker i computes batch k."""
    _validate(n, redundancy)
    loads = loads_for(n, redundancy)
    a = np.zeros((n, n), np.float64)
    for i in range(n):
        a[i, (i + np.arange(loads[i])) % n] = 1.0
    return a


def clustered_assignment(n: int, redundancy: float) -> np.ndarray:
    """(n, n) 0/1 fractional-repetition assignment: integer c = r dividing
    n; worker i computes the batches of group i // c (module docstring)."""
    _validate(n, redundancy)
    c = int(round(redundancy))
    if abs(redundancy - c) > 1e-9:
        raise ValueError(
            f"clustered (fractional-repetition) assignment needs integer "
            f"redundancy, got r={redundancy} (use scheme='pairwise' for "
            f"fractional r)"
        )
    if n % c != 0:
        raise ValueError(
            f"clustered assignment needs redundancy {c} to divide "
            f"num_workers {n}"
        )
    a = np.zeros((n, n), np.float64)
    for i in range(n):
        j = i // c
        a[i, j * c : (j + 1) * c] = 1.0
    return a


def build_assignment(n: int, redundancy: float, scheme: str) -> np.ndarray:
    """The (n, n) 0/1 assignment for ``scheme`` ∈ SCHEMES."""
    if scheme == "pairwise":
        return pairwise_assignment(n, redundancy)
    if scheme == "clustered":
        return clustered_assignment(n, redundancy)
    raise ValueError(
        f"unknown assignment scheme {scheme!r}; known: {'|'.join(SCHEMES)}"
    )


def encode_weights(assign: np.ndarray) -> np.ndarray:
    """Replication-normalised encode weights W = A / column-sums(A):
    Σ_i W[i, k] = 1 for every covered batch k, so v = 1 decodes the exact
    sum at full participation (module docstring). A batch nobody computes
    (possible only for degenerate hand-built assignments) keeps weight 0."""
    counts = assign.sum(axis=0)
    if (counts < 1).any():
        raise ValueError(
            f"assignment leaves batches {np.where(counts < 1)[0].tolist()} "
            f"uncovered — every batch needs at least one worker"
        )
    return assign / counts[None, :]


def _validate(n: int, redundancy: float) -> None:
    if n < 1:
        raise ValueError(f"num_workers must be >= 1, got {n}")
    if not (1.0 <= redundancy <= n):
        raise ValueError(
            f"code redundancy must lie in [1, num_workers], got "
            f"r={redundancy} at n={n}"
        )
