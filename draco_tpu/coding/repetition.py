"""Repetition code ("maj_vote") — grouping + on-device majority vote.

Reference semantics: workers are partitioned into groups of size r; members of
a group share a shuffle seed and therefore compute *identical* batches
(rep_worker.py:89); the PS takes, per group, the value held by a strict
majority of members — implemented there as a Boyer–Moore pass with bitwise
np.array_equal (rep_master.py:154-168) — then averages the group winners.

TPU-native formulation: per-worker gradients form (n, d); reshape to
(G, r, d); the vote is an argmax over per-member "agreement counts" computed
from the exact pairwise-equality matrix. Exact equality is sound here for the
same reason it is in the reference: group members run the identical
deterministic computation on identical inputs (a vmap lane under XLA), so
honest replicas agree bitwise while an attacked row differs.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class RepetitionCode:
    n: int
    r: int  # group size

    @property
    def num_groups(self) -> int:
        return self.n // self.r

    def group_of(self, worker: int) -> int:
        return worker // self.r


def build_repetition_code(n: int, r: int) -> RepetitionCode:
    """Byzantine tolerance is (r-1)//2 per group: with r < 3 a single
    adversary ties the vote and the tie-break is arbitrary — config.validate
    enforces r >= 2s+1 whenever worker_fail > 0."""
    if n % r != 0:
        raise ValueError(f"num_workers {n} must be divisible by group_size {r}")
    return RepetitionCode(n=n, r=r)


def majority_vote(code: RepetitionCode, grads: jnp.ndarray,
                  present=None) -> jnp.ndarray:
    """grads: (n, d) -> (d,) mean over groups of each group's majority row.

    ``present``: optional (n,) bool — absent members (stragglers) neither
    vote nor can win; a group with no present member contributes nothing and
    the group mean renormalises. (The reference PS blocks forever on a
    missing member, rep_master.py:104-116.)
    """
    g, r = code.num_groups, code.r
    rows = grads.reshape(g, r, -1)
    # pairwise exact-equality counts, (G, r): agree[g, i] = #{j : row_i == row_j}
    eq = jnp.all(rows[:, :, None, :] == rows[:, None, :, :], axis=-1)
    if present is None:
        agree = jnp.sum(eq, axis=-1)
        winner = jnp.argmax(agree, axis=-1)  # (G,)
        picked = jnp.take_along_axis(rows, winner[:, None, None], axis=1)[:, 0, :]
        return jnp.mean(picked, axis=0)
    pres = present.reshape(g, r)
    agree = jnp.sum(eq & pres[:, None, :], axis=-1)  # only present members vote
    agree = jnp.where(pres, agree, -1)  # absent members cannot win
    winner = jnp.argmax(agree, axis=-1)
    picked = jnp.take_along_axis(rows, winner[:, None, None], axis=1)[:, 0, :]
    group_alive = jnp.any(pres, axis=1).astype(grads.dtype)  # (G,)
    return (group_alive @ picked) / jnp.maximum(jnp.sum(group_alive), 1.0)
