"""Repetition code ("maj_vote") — grouping + on-device majority vote.

Reference semantics: workers are partitioned into groups of size r; members of
a group share a shuffle seed and therefore compute *identical* batches
(rep_worker.py:89); the PS takes, per group, the value held by a strict
majority of members — implemented there as a Boyer–Moore pass with bitwise
np.array_equal (rep_master.py:154-168) — then averages the group winners.

TPU-native formulation: per-worker gradients form (n, d); reshape to
(G, r, d); the vote is an argmax over per-member "agreement counts". Equality
testing is sound here for the same reason it is in the reference: group
members run the identical deterministic computation on identical inputs (a
vmap lane under XLA), so honest replicas agree bitwise while an attacked row
differs.

Cost: the vote is O(r·d) per group, not O(r²·d) — each row is folded to two
position-sensitive 32-bit hashes of its raw bits (one O(d) pass per row) and
the (r, r) agreement matrix is built from those 64-bit fingerprints instead
of materialising the (G, r, r, d) elementwise-equality tensor. Honest
replicas are bit-identical, so hash-equality <=> bit-equality up to a ~2^-64
accidental collision; none of the in-scope error modes (rev_grad / constant /
random / alie / ipm, attacks.py) can steer a hash preimage. Note the
fingerprint compares raw BITS where the old elementwise `==` compared values:
-0.0 vs +0.0 now count as a disagreement (stricter) and a NaN row now agrees
with its own bit-identical replicas (the reference's np.array_equal treats
NaN as always-unequal, rep_master.py:154-168 — either way a lone NaN row
loses the vote to an honest majority).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


def _row_fingerprints(rows: jnp.ndarray):
    """(G, r, d) -> two (G, r) uint32 weighted-sum hashes of each row's bits.

    Weights vary with position so permuted or shifted payloads don't collide
    the way a plain wrapping sum would; arithmetic wraps mod 2^32 by summing
    in uint32. The two weight sequences must be INDEPENDENT functions of the
    position: w1 is affine in j (a Weyl sequence), but a second affine
    sequence would make (h1, h2) jointly depend only on the two moments
    (Σ bits, Σ j·bits) — one ~2^-63 check dressed up as two. w2 is therefore
    splitmix32-finalised (xor-shift/multiply avalanche of j), which is not
    affine in j, so the pair carries genuinely independent ~2^-64 collision
    odds. All elementwise uint32 ops: still one O(d) pass per row.
    """
    if rows.dtype.itemsize not in (2, 4):
        raise ValueError(
            f"majority_vote fingerprints support 2/4-byte element dtypes "
            f"(bf16/f16/f32/i32 — what the gradient stack ever holds), got "
            f"{rows.dtype}"
        )
    uint = {2: jnp.uint16, 4: jnp.uint32}[rows.dtype.itemsize]
    bits = jax.lax.bitcast_convert_type(rows, uint).astype(jnp.uint32)
    j = jax.lax.iota(jnp.uint32, bits.shape[-1])
    w1 = j * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B1)
    z = (j + jnp.uint32(0x9E3779B9))  # splitmix32 finaliser
    z = (z ^ (z >> 16)) * jnp.uint32(0x85EBCA6B)
    z = (z ^ (z >> 13)) * jnp.uint32(0xC2B2AE35)
    w2 = (z ^ (z >> 16)) | jnp.uint32(1)  # odd => bijective per-position weight
    h1 = jnp.sum(bits * w1, axis=-1, dtype=jnp.uint32)
    h2 = jnp.sum(bits * w2, axis=-1, dtype=jnp.uint32)
    return h1, h2


@dataclasses.dataclass(frozen=True)
class RepetitionCode:
    n: int
    r: int  # group size

    @property
    def num_groups(self) -> int:
        return self.n // self.r

    def group_of(self, worker: int) -> int:
        return worker // self.r


def build_repetition_code(n: int, r: int) -> RepetitionCode:
    """Byzantine tolerance is (r-1)//2 per group: with r < 3 a single
    adversary ties the vote and the tie-break is arbitrary — config.validate
    enforces r >= 2s+1 whenever worker_fail > 0."""
    if n % r != 0:
        raise ValueError(f"num_workers {n} must be divisible by group_size {r}")
    return RepetitionCode(n=n, r=r)


def majority_vote(code: RepetitionCode, grads: jnp.ndarray,
                  present=None) -> jnp.ndarray:
    """grads: (n, d) -> (d,) mean over groups of each group's majority row.

    ``present``: optional (n,) bool — absent members (stragglers) neither
    vote nor can win; a group with no present member contributes nothing and
    the group mean renormalises. (The reference PS blocks forever on a
    missing member, rep_master.py:104-116.)
    """
    g, r = code.num_groups, code.r
    rows = grads.reshape(g, r, -1)
    # pairwise-equality counts, (G, r): agree[g, i] = #{j : row_i == row_j},
    # via 64-bit row fingerprints (O(r·d)) — see module docstring
    h1, h2 = _row_fingerprints(rows)
    eq = (h1[:, :, None] == h1[:, None, :]) & (h2[:, :, None] == h2[:, None, :])
    if present is None:
        agree = jnp.sum(eq, axis=-1)
        winner = jnp.argmax(agree, axis=-1)  # (G,)
        picked = jnp.take_along_axis(rows, winner[:, None, None], axis=1)[:, 0, :]
        return jnp.mean(picked, axis=0)
    pres = present.reshape(g, r)
    agree = jnp.sum(eq & pres[:, None, :], axis=-1)  # only present members vote
    agree = jnp.where(pres, agree, -1)  # absent members cannot win
    winner = jnp.argmax(agree, axis=-1)
    picked = jnp.take_along_axis(rows, winner[:, None, None], axis=1)[:, 0, :]
    group_alive = jnp.any(pres, axis=1).astype(grads.dtype)  # (G,)
    return (group_alive @ picked) / jnp.maximum(jnp.sum(group_alive), 1.0)
