"""Repetition code ("maj_vote") — grouping + on-device majority vote.

Reference semantics: workers are partitioned into groups of size r; members of
a group share a shuffle seed and therefore compute *identical* batches
(rep_worker.py:89); the PS takes, per group, the value held by a strict
majority of members — implemented there as a Boyer–Moore pass with bitwise
np.array_equal (rep_master.py:154-168) — then averages the group winners.

TPU-native formulation: per-worker gradients form (n, d); reshape to
(G, r, d); the vote is an argmax over per-member "agreement counts". Equality
testing is sound here for the same reason it is in the reference: group
members run the identical deterministic computation on identical inputs (a
vmap lane under XLA), so honest replicas agree bitwise while an attacked row
differs.

Cost: the vote is O(r·d) per group, not O(r²·d) — each row is folded to two
position-sensitive 32-bit hashes of its raw bits (one O(d) pass per row) and
the (r, r) agreement matrix is built from those 64-bit fingerprints instead
of materialising the (G, r, r, d) elementwise-equality tensor. Honest
replicas are bit-identical, so hash-equality <=> bit-equality up to a ~2^-64
accidental collision. Note the fingerprint compares raw BITS where the old
elementwise `==` compared values: -0.0 vs +0.0 now count as a disagreement
(stricter) and a NaN row now agrees with its own bit-identical replicas (the
reference's np.array_equal treats NaN as always-unequal,
rep_master.py:154-168 — either way a lone NaN row loses the vote to an
honest majority).

Adversarial collision resistance — the honest threat-model ladder:

1. *Oblivious corruption* (the in-scope simulated error modes: rev_grad /
   constant / random / alie / ipm, attacks.py): any ~2^-64 pair of hashes
   suffices; collisions are accidental only.
2. *Adaptive adversary who does NOT know the salt*: the per-position mixing
   must be nonlinear and position-asymmetric. A linear hash
   h = Σ bits_j·w_j mod 2^32 — even with secret odd weights — is
   constructibly collidable (flip the top bit of any two positions: the
   difference 2^31·(w_i + w_j) vanishes because w_i + w_j is even). An
   XOR-symmetric salted avalanche sum Σ mix(bits_j ^ pos_j ^ s) is ALSO
   collidable salt-independently (swap the ``bits ^ pos`` values between
   two positions: the salt XORs out and the term multiset is unchanged).
   Here position therefore enters by *wrapping addition between two
   avalanche rounds* — Σ mix(mix(bits_j ^ s) + posmix_j) — so a
   salt-oblivious forgery would need a differential pair of the avalanche
   with constant output difference across all salts, which splitmix32 does
   not admit; only swapping bit-identical elements "collides", and that is
   the identity. (Regression-tested against both constructions' attacks.)
3. *Adversary who knows the salt*: each term is an invertible function of
   the element, so a colliding row is constructible by inverting the
   avalanche — NO seed-derived fingerprint can beat this. The training
   step derives its per-step key from ``cfg.seed`` (step.py), and the
   reference's whole discipline is that every participant shares that seed
   (rng.py, reference src/util.py:17), so an in-protocol white-box
   adversary is in this tier. For real mutually-untrusting deployments
   either source the key from PS-private entropy (pass your own ``key``)
   or set ``vote_check="exact"`` — bitwise np.array_equal semantics, the
   reference's exact-recovery guarantee (rep_master.py:162) at O(r²·d)
   memory traffic.

With ``key=None`` the salts are fixed public constants: bit-exact
deterministic, fine for tiers 1 and (heuristically) 2, direct-call/test use.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


def _splitmix32(z: jnp.ndarray) -> jnp.ndarray:
    """splitmix32 finaliser: a bijective xor-shift/multiply avalanche. Every
    output bit depends nonlinearly on every input bit — the property the
    collision argument in the module docstring rests on."""
    z = (z ^ (z >> 16)) * jnp.uint32(0x85EBCA6B)
    z = (z ^ (z >> 13)) * jnp.uint32(0xC2B2AE35)
    return z ^ (z >> 16)


def _row_bits(rows: jnp.ndarray) -> jnp.ndarray:
    """Validate the element dtype and bitcast to the matching uint — the one
    place the vote's bit-compare domain (2/4-byte elements) is defined."""
    if rows.dtype.itemsize not in (2, 4):
        raise ValueError(
            f"majority_vote supports 2/4-byte element dtypes "
            f"(bf16/f16/f32/i32 — what the gradient stack ever holds), got "
            f"{rows.dtype}"
        )
    uint = {2: jnp.uint16, 4: jnp.uint32}[rows.dtype.itemsize]
    return jax.lax.bitcast_convert_type(rows, uint)


def _row_fingerprints(rows: jnp.ndarray, key=None):
    """(G, r, d) -> two (G, r) uint32 mix-then-sum hashes of each row's bits.

    Per position j: keyed avalanche of the element's bits, wrapping-ADD the
    avalanched position, avalanche again, then wrapping-sum over j. The
    shape of the construction is load-bearing (module docstring tier 2): the
    outer avalanche over (inner ^-keyed mix + position) is what kills both
    the linear top-bit-pair attack and the salt-independent position-swap
    attack — position must NOT enter by XOR next to the salt, or the salt
    commutes out of a swap. Two salts give two hashes whose joint accidental
    collision odds are ~2^-64; with ``key`` they are drawn from the PRNG,
    with ``key=None`` they are fixed public constants (deterministic
    direct-call/test path). All elementwise uint32 ops: one O(d) pass per
    row either way.
    """
    bits = _row_bits(rows).astype(jnp.uint32)
    j = jax.lax.iota(jnp.uint32, bits.shape[-1])
    if key is None:
        s1 = jnp.uint32(0x9E3779B1)
        s2 = jnp.uint32(0xC2B2AE35)
    else:
        salts = jax.random.bits(key, (2,), jnp.uint32)
        s1, s2 = salts[0], salts[1]
    posmix = _splitmix32(j * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9))
    h1 = jnp.sum(_splitmix32(_splitmix32(bits ^ s1) + posmix),
                 axis=-1, dtype=jnp.uint32)
    h2 = jnp.sum(_splitmix32(_splitmix32(bits ^ s2 ^ jnp.uint32(0x7F4A7C15))
                             + posmix),
                 axis=-1, dtype=jnp.uint32)
    return h1, h2


@dataclasses.dataclass(frozen=True)
class RepetitionCode:
    n: int
    r: int  # group size

    @property
    def num_groups(self) -> int:
        return self.n // self.r

    def group_of(self, worker: int) -> int:
        return worker // self.r


def build_repetition_code(n: int, r: int) -> RepetitionCode:
    """Byzantine tolerance is (r-1)//2 per group: with r < 3 a single
    adversary ties the vote and the tie-break is arbitrary — config.validate
    enforces r >= 2s+1 whenever worker_fail > 0."""
    if n % r != 0:
        raise ValueError(f"num_workers {n} must be divisible by group_size {r}")
    return RepetitionCode(n=n, r=r)


def majority_vote(code: RepetitionCode, grads: jnp.ndarray,
                  present=None, key=None,
                  method: str = "fingerprint",
                  with_health: bool = False):
    """grads: (n, d) -> (d,) mean over groups of each group's majority row.

    ``present``: optional (n,) bool — absent members (stragglers) neither
    vote nor can win; a group with no present member contributes nothing and
    the group mean renormalises. (The reference PS blocks forever on a
    missing member, rep_master.py:104-116.)

    ``key``: optional PRNG key salting the row fingerprints; pass a per-step
    key (the training step does) so a salt-oblivious adaptive adversary
    cannot construct a fingerprint collision — see module docstring.

    ``method``: ``"fingerprint"`` (default, O(r·d) memory traffic) or
    ``"exact"`` — full pairwise bit-equality at O(r²·d), no collision
    surface at all; the right choice when adversaries may know the
    experiment seed (module docstring tier 3; reference exact-recovery
    semantics, rep_master.py:162).

    ``with_health=True`` returns ``(voted, health)`` — the vote's own
    detection record, computed from the agreement matrix the vote already
    built (telemetry metric columns; no extra O(d) pass):

      * ``vote_agree``: fraction of present members whose row bitwise
        matches their group's winner — 1.0 is the all-honest state, each
        live corrupted row subtracts 1/|present|;
      * ``flagged_groups``: number of groups containing ≥ 1 dissenting
        present member (the reference PS would have rejected exactly these
        groups' minority rows, rep_master.py:154-168);
      * ``flagged``: (n,) bool — present members out-voted by their group
        (the per-row located-adversary set; absent stragglers are
        known-missing, never "detected").
    """
    g, r = code.num_groups, code.r
    rows = grads.reshape(g, r, -1)
    # pairwise-equality counts, (G, r): agree[g, i] = #{j : row_i == row_j}
    if method == "exact":
        bits = _row_bits(rows)
        eq = jnp.all(bits[:, :, None, :] == bits[:, None, :, :], axis=-1)
    elif method == "fingerprint":
        # 64-bit row fingerprints (O(r·d)) — see module docstring
        h1, h2 = _row_fingerprints(rows, key=key)
        eq = ((h1[:, :, None] == h1[:, None, :])
              & (h2[:, :, None] == h2[:, None, :]))
    else:
        raise ValueError(
            f"method must be 'fingerprint' or 'exact', got {method!r}"
        )
    if present is None:
        pres = jnp.ones((g, r), bool)
        agree = jnp.sum(eq, axis=-1)
        winner = jnp.argmax(agree, axis=-1)  # (G,)
        picked = jnp.take_along_axis(rows, winner[:, None, None], axis=1)[:, 0, :]
        voted = jnp.mean(picked, axis=0)
    else:
        pres = present.reshape(g, r)
        agree = jnp.sum(eq & pres[:, None, :], axis=-1)  # only present members vote
        agree = jnp.where(pres, agree, -1)  # absent members cannot win
        winner = jnp.argmax(agree, axis=-1)
        picked = jnp.take_along_axis(rows, winner[:, None, None], axis=1)[:, 0, :]
        group_alive = jnp.any(pres, axis=1).astype(grads.dtype)  # (G,)
        voted = (group_alive @ picked) / jnp.maximum(jnp.sum(group_alive), 1.0)
    if not with_health:
        return voted
    # member i agrees with its group's winner iff eq[g, i, winner_g]
    winner_agree = jnp.take_along_axis(
        eq, winner[:, None, None], axis=2)[:, :, 0]  # (G, r) bool
    flagged = pres & ~winner_agree
    n_pres = jnp.maximum(jnp.sum(pres.astype(jnp.float32)), 1.0)
    health = {
        "vote_agree": jnp.sum((winner_agree & pres).astype(jnp.float32))
        / n_pres,
        "flagged_groups": jnp.sum(jnp.any(flagged, axis=1)
                                  .astype(jnp.int32)),
        "flagged": flagged.reshape(code.n),
    }
    return voted, health
