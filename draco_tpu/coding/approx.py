"""Approximate gradient code — partial-recovery decode with a measured
residual-vs-bound certificate.

Third code family alongside ``cyclic`` (exact, r = 2s+1) and ``maj_vote``
(repetition): following the approximate/stochastic gradient-coding line
(PAPERS.md — Stochastic Gradient Coding arXiv:1905.05383, Approximate
Gradient Coding with Optimal Decoding arXiv:2006.09638, clustering
arXiv:1903.01974), it buys straggler tolerance at redundancy close to 1 by
accepting a *bounded, measurable* decode error instead of spending 2s+1×
compute on exactness. This opens the straggler-dominated scenario family
(heterogeneous fleets, spot/preemptible workers) where a single slow worker
either stalls the exact decode or burns a whole unit of its Byzantine
budget (ROADMAP item 3).

The protocol (n workers, n batches, assignment A from coding/assignment.py
at redundancy r, encode weights W = A normalised to unit column sums):

  * Worker i ships the weighted partial sum row_i = Σ_k W[i,k] · g_k —
    real arithmetic, no complex algebra, one (n, n) × (n, d) matmul in the
    shared-redundancy mode.
  * Decode with arrival set S (``present``): solve the optimal-decoding
    least squares of arXiv:2006.09638 — v* = argmin_v ‖W_Sᵀ v − 1‖₂
    against the arrived support only — and output ĝ = Σ_{i∈S} v*_i row_i.
    With u = W_Sᵀ v* the decode equals uᵀG, so the error is (u − 1)ᵀG and

        ‖ĝ/n − ḡ‖₂  ≤  ‖u − 1‖₂ · ‖G‖_F / n        (Cauchy–Schwarz)

    — the *analytic bound*, computable in-graph from the arrived support
    alone. Full participation ⇒ v = 1 is feasible ⇒ u = 1 ⇒ exact recovery
    (f32 solve noise only), for every r and both assignment schemes.

Everything is shape-static and branchless: the least squares is one SVD
on the fixed (n, n) system with the straggler mask folded in as zeroed
rows, so a live per-step ``present`` mask rides the same seeded-schedule
discipline as the adversary plans — no retraces, one compiled program.

Health (the residual-vs-bound harness, ISSUE 8): because this repo
*simulates* the fleet in one SPMD program, the true batch-gradient matrix
G is available in-graph, so the decode's health dict carries the *measured*
relative residual next to the paper's bound at zero extra fetches:

  residual            ‖ĝ/n − ḡ‖₂ / (‖G‖_F / n)  — dimensionless
  bound               ‖u − 1‖₂ — the analytic optimal-decoding error of
                      the arrived support; residual ≤ bound is algebra
                      (violations can only be f32 noise, ~1e-6)
  recovered_fraction  fraction of batches whose support intersects S —
                      1.0 means every batch still contributes (the
                      redundancy payoff); < 1.0 means whole batch
                      gradients were lost to the drop pattern

No Byzantine certificate: the decode weights average whatever arrives, so
config.validate rejects live adversaries under this family — stragglers
are its fault model, and the only per-worker accusation signal it emits is
the non-finite ingest check (obs/forensics.nonfinite_rows). An absent
worker is an erasure, never an accusation.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from draco_tpu.coding import assignment as assign_mod
from draco_tpu.coding import linalg as linalg_mod

PREC = None  # the (n, n) solves are tiny; matmul default precision is fine

# Relative singular-value cutoff for the optimal-decoding least squares:
# whole-cluster absences (clustered scheme) and heavy drop patterns make
# W_Sᵀ genuinely rank-deficient; SVD truncation keeps the solve NaN-free
# there while staying f32-exact on full-rank systems (same role as
# cyclic.LOCATOR_RCOND).
DECODE_RCOND = 1e-5


@dataclasses.dataclass(frozen=True)
class ApproxCode:
    """Device-ready constants of one (n, r, scheme) approximate code."""

    n: int
    redundancy: float
    scheme: str
    assign: np.ndarray  # (n, n) 0/1 support
    weights: np.ndarray  # (n, n) f32 encode weights, unit column sums
    batch_ids: np.ndarray  # (n, max_load) int32, row i's batches (padded)
    lane_weights: np.ndarray  # (n, max_load) f32 weights at batch_ids (0 = pad)
    max_load: int  # widest per-worker batch list (ragged rows padded)


def build_approx_code(n: int, redundancy: float,
                      scheme: str = "pairwise") -> ApproxCode:
    a = assign_mod.build_assignment(n, redundancy, scheme)
    w = assign_mod.encode_weights(a)
    loads = a.sum(axis=1).astype(np.int64)
    max_load = int(loads.max())
    batch_ids = np.zeros((n, max_load), np.int32)
    lane_w = np.zeros((n, max_load), np.float32)
    for i in range(n):
        ks = np.where(a[i] != 0)[0]
        batch_ids[i, : len(ks)] = ks
        lane_w[i, : len(ks)] = w[i, ks]
        # padding replicates the first batch id with weight 0, so a padded
        # lane is a cheap but inert recompute, never an out-of-range gather
        batch_ids[i, len(ks):] = ks[0] if len(ks) else 0
    return ApproxCode(
        n=n, redundancy=float(redundancy), scheme=scheme,
        assign=np.ascontiguousarray(a, np.float32),
        weights=np.ascontiguousarray(w, np.float32),
        batch_ids=batch_ids, lane_weights=lane_w, max_load=max_load,
    )


# --------------------------------------------------------------------------
# encode
# --------------------------------------------------------------------------


def encode_shared(code: ApproxCode, batch_grads: jnp.ndarray) -> jnp.ndarray:
    """(n, d) one-copy batch gradients -> (n, d) per-worker weighted partial
    sums: row i = Σ_k W[i,k] · g_k, one real matmul (the TPU-native
    shared-redundancy path — per-batch gradients are deterministic under
    XLA, so computing each once and combining algebraically is identical to
    every worker recomputing its window)."""
    return jnp.matmul(jnp.asarray(code.weights), batch_grads)


def encode(code: ApproxCode, grads: jnp.ndarray) -> jnp.ndarray:
    """(n, max_load, d) per-worker redundant lanes -> (n, d) weighted
    partial sums. grads[i, k] is the gradient of batch ``batch_ids[i, k]``;
    padded lanes carry weight 0 and contribute nothing."""
    return jnp.einsum("nk,nkd->nd", jnp.asarray(code.lane_weights), grads)


# --------------------------------------------------------------------------
# decode
# --------------------------------------------------------------------------


def decode_weights(code: ApproxCode, present: Optional[jnp.ndarray] = None):
    """Optimal-decoding weights for an arrival set: ``(v, u, bound)``.

    ``v`` (n,): argmin ‖W_Sᵀ v − 1‖₂ with absent workers' rows zeroed —
    the SVD least squares returns the minimal-norm solution, which is 0 on
    the zeroed columns, so an absent worker never carries weight (re-masked
    anyway; note a zero weight alone cannot neutralize a NaN payload —
    0·NaN = NaN — which is why ``decode`` where-selects absent rows to
    true zeros before the combining matmul).
    ``u`` (n,): the effective per-batch coverage W_Sᵀ v. ``bound``: the
    scalar ‖u − 1‖₂ — the analytic decode-error coefficient of
    arXiv:2006.09638 for this arrival set.

    Shared bit-for-bit by every ``decode_impl`` (ISSUE 12): the solve is
    O(n³) on an (n, n) system — nothing to fuse — so the kernel path keeps
    it as a prologue op (the kernel fuses the O(n·d) tail only) and the
    equivalence suites compare decodes built from the identical v."""
    w = jnp.asarray(code.weights)
    n = code.n
    pres = (jnp.ones((n,), jnp.float32) if present is None
            else jnp.asarray(present).astype(jnp.float32))
    wp = w * pres[:, None]
    ones = jnp.ones((n,), jnp.float32)
    v = linalg_mod.truncated_lstsq(wp.T, ones, DECODE_RCOND)
    v = v * pres
    u = jnp.matmul(wp.T, v)
    bound = jnp.sqrt(jnp.sum((u - ones) ** 2))
    return v, u, bound


def recovered_fraction(code: ApproxCode,
                       present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Fraction of batches whose support intersects the arrival set —
    in-graph scalar, 1.0 iff no batch gradient was wholly lost."""
    a = jnp.asarray(code.assign)
    n = code.n
    pres = (jnp.ones((n,), jnp.float32) if present is None
            else jnp.asarray(present).astype(jnp.float32))
    covered = (jnp.matmul(a.T, pres) > 0).astype(jnp.float32)
    return jnp.mean(covered)


def decode(code: ApproxCode, rows: jnp.ndarray,
           present: Optional[jnp.ndarray] = None,
           with_health: bool = False, batch_grads: Optional[jnp.ndarray] = None,
           impl: str = "xla", wire=None):
    """Partial-recovery decode: (n, d) received rows -> (d,) mean gradient.

    ``rows``: per-worker weighted partial sums; absent rows (``present``
    False) are where-masked to true zeros here before combining (callers
    may pre-mask too — harmless, but multiplicative masking alone would
    pass a NaN payload through).
    Returns ``(decoded, v)`` — the (d,) decoded **mean** gradient (the Σg/n
    convention every family shares) and the (n,) decode weights actually
    used. Exact when all workers are present (module docstring); under
    drops the error is ≤ bound · ‖G‖_F / n.

    ``with_health=True`` appends the health dict (module docstring:
    ``residual`` / ``bound`` / ``recovered_fraction``); the *measured*
    residual needs the true batch-gradient matrix, so ``batch_grads``
    ((n, d), pre-mask) is required then — available in-graph because this
    repo simulates the fleet in one SPMD program. That is the
    residual-vs-bound harness: the paper's guarantee refereed per step at
    zero extra fetches.

    ``impl`` (ISSUE 12): ``"xla"`` is the historical lowering, bit-for-bit
    unchanged. ``"fused"`` restructures the O(n·d) health passes (the
    decode_impl="pallas" CPU fallback: the true-mean reduction becomes a
    matvec and the residual algebra fuses into the same sweep — bounded-err
    vs xla from accumulation order only) on the identical weight solve.
    ``"pallas"`` runs the hand-tiled kernel
    (ops/decode_kernels.approx_decode): mask, combine, true-mean and both
    health norms in ONE pass over the (n, d) wire and gradient blocks.
    """
    if impl != "xla":
        return _decode_fused(code, rows, present, with_health, batch_grads,
                             impl, wire=wire)
    v, u, bound = decode_weights(code, present)
    if present is not None:
        # true zero-fill, not multiplicative masking: a NaN payload in an
        # absent row survives both `rows * present` and the zero decode
        # weight (0·NaN = NaN through the matmul); where-select drops it
        rows = jnp.where(jnp.asarray(present).astype(bool)[:, None], rows,
                         jnp.zeros_like(rows))
    decoded = jnp.matmul(v, rows) / code.n
    if not with_health:
        return decoded, v
    if batch_grads is None:
        raise ValueError("with_health=True needs batch_grads (the (n, d) "
                         "pre-mask batch-gradient matrix) to measure the "
                         "residual against the true sum")
    true_mean = jnp.sum(batch_grads, axis=0) / code.n
    gfro = jnp.sqrt(jnp.sum(batch_grads.astype(jnp.float32) ** 2))
    scale = jnp.maximum(gfro / code.n, 1e-30)
    residual = jnp.sqrt(jnp.sum((decoded - true_mean) ** 2)) / scale
    health = {
        "residual": residual,
        "bound": bound,
        "recovered_fraction": recovered_fraction(code, present),
    }
    return decoded, v, health


def _decode_fused(code: ApproxCode, rows, present, with_health, batch_grads,
                  impl: str, wire=None):
    """The fused decode (``decode`` docstring, impl != "xla"): the SAME
    weight solve as the xla path (decode_weights — a bitwise-shared
    prologue op), then the O(n·d) work either as the restructured XLA
    sweep ("fused" — the CPU fallback) or the Pallas kernel
    ("pallas"/"pallas_interpret"). Health semantics identical to the xla
    path; only accumulation order differs. ``wire`` (ISSUE 15): the REAL
    narrow wire buffers ``(mode, buf, block)`` — on the kernel path they
    are ingested narrow and dequantized in-tile
    (ops/decode_kernels.approx_decode), so the widened f32 wire matrix
    never exists in HBM; the XLA paths consume the pre-widened ``rows``."""
    n = code.n
    v, u, bound = decode_weights(code, present)
    pres_b = (jnp.ones((n,), bool) if present is None
              else jnp.asarray(present).astype(bool))
    if not with_health:
        rows_m = jnp.where(pres_b[:, None], rows, jnp.zeros_like(rows))
        return jnp.matmul(v / n, rows_m), v
    if batch_grads is None:
        raise ValueError("with_health=True needs batch_grads (the (n, d) "
                         "pre-mask batch-gradient matrix) to measure the "
                         "residual against the true sum")
    if impl in ("pallas", "pallas_interpret"):
        from draco_tpu.ops import decode_kernels

        if not decode_kernels.narrow_kernel_ok(wire):
            wire = None
        decoded, sq_diff, sq_g = decode_kernels.approx_decode(
            rows, batch_grads, v, pres_b,
            interpret=(impl == "pallas_interpret"), wire=wire)
    else:
        rows_m = jnp.where(pres_b[:, None], rows, jnp.zeros_like(rows))
        decoded = jnp.matmul(v / n, rows_m)
        # true mean as a matvec (one BLAS pass instead of a strided
        # axis-0 reduction) — same value, different accumulation order
        true_mean = jnp.matmul(jnp.full((n,), 1.0 / n, jnp.float32),
                               batch_grads)
        sq_diff = jnp.sum((decoded - true_mean) ** 2)
        sq_g = jnp.sum(batch_grads.astype(jnp.float32) ** 2)
    scale = jnp.maximum(jnp.sqrt(sq_g) / n, 1e-30)
    health = {
        "residual": jnp.sqrt(sq_diff) / scale,
        "bound": bound,
        "recovered_fraction": recovered_fraction(code, present),
    }
    return decoded, v, health


def decode_segments(code: ApproxCode, rows: jnp.ndarray, bounds,
                    present: Optional[jnp.ndarray] = None,
                    with_health: bool = False,
                    batch_grads: Optional[jnp.ndarray] = None,
                    impl: str = "xla", wire=None):
    """Streaming segmented partial-recovery decode (ISSUE 16): ``bounds``
    are the quantum-aligned segment cuts (obs/numerics.wire_segment_bounds,
    len S+1) and each [a, b) wire segment is decoded independently as it
    would arrive.

    Segment algebra: the optimal-decoding weight solve is PRESENCE-only —
    it never touches d — so it runs ONCE and every segment combines with
    the identical ``v`` (``bound`` and ``recovered_fraction`` are likewise
    d-independent, hence per-step by construction). The decode matvec is
    column-separable over d, so per-segment combination assembled by
    dynamic_update_slice equals the unsegmented decode up to accumulation
    order (bounded-err); the residual's two squared-norm accumulators fold
    ACROSS segments before the final sqrt, so the health verdict stays one
    per step. On the kernel path each segment streams its own slice of the
    narrow buffers (ops/decode_kernels.approx_decode_segment — the
    segment-offset entry point, no new kernels).

    Returns ``(decoded, v[, health])`` — the same contract as
    :func:`decode`."""
    import jax

    n = code.n
    bounds = [int(o) for o in bounds]
    segs = list(zip(bounds[:-1], bounds[1:]))
    d = rows.shape[-1]
    v, u, bound = decode_weights(code, present)
    pres_b = (jnp.ones((n,), bool) if present is None
              else jnp.asarray(present).astype(bool))
    if with_health and batch_grads is None:
        raise ValueError("with_health=True needs batch_grads (the (n, d) "
                         "pre-mask batch-gradient matrix) to measure the "
                         "residual against the true sum")
    use_kernel = impl in ("pallas", "pallas_interpret") and with_health
    if use_kernel:
        from draco_tpu.ops import decode_kernels

        if not decode_kernels.narrow_kernel_ok(wire):
            wire = None
    rows_m = jnp.where(pres_b[:, None], rows, jnp.zeros_like(rows))
    out = jnp.zeros((d,), jnp.float32)
    sq_diff = jnp.zeros((), jnp.float32)
    sq_g = jnp.zeros((), jnp.float32)
    for a, b in segs:
        if use_kernel:
            seg, sd, sg = decode_kernels.approx_decode_segment(
                rows, batch_grads, v, pres_b, a, b,
                interpret=(impl == "pallas_interpret"), wire=wire)
            sq_diff = sq_diff + sd
            sq_g = sq_g + sg
        else:
            seg = jnp.matmul(v / n, rows_m[:, a:b])
            if with_health:
                bg = batch_grads[:, a:b]
                true_mean = jnp.matmul(
                    jnp.full((n,), 1.0 / n, jnp.float32), bg)
                sq_diff = sq_diff + jnp.sum((seg - true_mean) ** 2)
                sq_g = sq_g + jnp.sum(bg.astype(jnp.float32) ** 2)
        out = jax.lax.dynamic_update_slice(out, seg, (a,))
    if not with_health:
        return out, v
    scale = jnp.maximum(jnp.sqrt(sq_g) / n, 1e-30)
    health = {
        "residual": jnp.sqrt(sq_diff) / scale,
        "bound": bound,
        "recovered_fraction": recovered_fraction(code, present),
    }
    return out, v, health
