"""Shared small-dense linear algebra for the coded decode paths.

Two tiers, one home (ISSUE 12):

**XLA tier** — the exact solvers the production ``decode_impl="xla"`` paths
have always used, deduplicated here from their former copies:
:func:`complex_solve` (cyclic's stacked-real-embedding solve, previously
``coding/cyclic._complex_solve``) and :func:`truncated_lstsq` (the
rcond-truncated SVD least squares both that embedding and the approx
family's where-masked optimal-decoding solve call). Bit-for-bit the ops the
callers inlined before; the K∈{1,4} bitwise equivalence suites pin that.

**Fused tier** — the same math re-derived for the fused decode kernels
(``ops/decode_kernels.py``): batched over a leading axis and restricted to
the op set Mosaic (the Pallas TPU compiler) lowers inside a kernel body —
no ``lax.linalg`` custom calls, no ``sort``/``top_k``/``gather``/``scatter``,
no traced-index slicing (Mosaic has no ``dynamic_slice``); everything is
matmuls, elementwise algebra, ``broadcasted_iota`` masks and
``fori_loop``-carried tensors. Each fused primitive is used twice: the
Pallas kernels call it on VMEM blocks, and the kernels' REFERENCE path
(the ``decode_impl="pallas"`` CPU fallback, coding/cyclic.py §fused) jits
the identical function on full arrays — so the interpret-mode kernel tests
and the reference path cannot drift algorithmically.

  truncated least squares   :func:`jacobi_lstsq` — one-sided Jacobi SVD,
                            fixed sweep count (quadratic convergence; the
                            systems are ≤ 2s×2s ≤ 10×10). Works on A
                            directly, NOT its gram: the gram squares the
                            condition number and f32 gram eigenvalues below
                            ~1e-7·λmax are noise, which would put the
                            rcond=1e-5 locator cutoff (λ cutoff 1e-10)
                            under the noise floor — the exact failure the
                            XLA tier's docstring warns about.
  square complex solve      :func:`gauss_inv_c` — Gauss–Jordan inverse
                            with partial pivoting on the complex modulus,
                            carried as (re, im) pairs. One inversion serves
                            both decode solves: the recombination vector is
                            ROW 0 of ``rec⁻¹`` (vᵀrec = e1ᵀ ⇒ v = first row)
                            and the health fit is ``rec⁻¹ e_sel`` — the XLA
                            tier pays two separate LU solves for these.
  honest-row top-k          :func:`topk_mask` — pairwise-comparison ranks
                            (n ≤ 64, the (n, n) bool block is nothing);
                            ties break toward the lower index, matching
                            ``lax.top_k``.
  masked compaction         :func:`select_matrix` — the top-k rows as an
                            (m, n) 0/1 selection matrix (cumsum via a
                            triangular matmul), so "gather the honest rows
                            of C1" becomes an MXU matmul instead of a
                            gather.
  masked median             :func:`masked_median` — rank-selection median
                            over a masked axis, matching ``jnp.nanmedian``
                            over present∧finite entries (the cyclic loud-row
                            threshold's statistic).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# XLA tier — the exact production solvers, deduplicated (ISSUE 12 satellite)
# ---------------------------------------------------------------------------


def truncated_lstsq(a: jnp.ndarray, b: jnp.ndarray, rcond: float,
                    lam: float = 0.0):
    """rcond-truncated SVD least squares (singular values below
    ``rcond·σmax`` zeroed), the shared primitive of the cyclic locator
    solve (via :func:`complex_solve`) and the approx family's where-masked
    optimal-decoding solve (coding/approx.decode_weights). Unlike a fixed
    ridge, truncation leaves full-rank systems f32-exact while keeping
    genuinely rank-deficient ones NaN-free — both call sites depend on
    exactly that (cyclic's < s-corrupt locator, approx's whole-cluster
    absences).

    ``lam`` > 0 (ISSUE 15) switches to the noise-floor-regularized solve:
    singular directions with σ ≤ λ are dropped OUTRIGHT on top of the
    relative rcond cutoff (keep σ > max(rcond·σmax, λ)). On the
    signal-scale-normalized locator system a direction at or below λ
    carries only quantization noise — the relative rcond alone keeps it
    whenever σmax is large (a live adversary), which is the PR 10
    finding: the cyclic locator amplifies bf16/int8 rounding past any
    usable flag threshold at n=32 s=3. λ is the hard-truncation limit of
    the Tikhonov family — ridge-DAMPING the kept directions
    (σ/(σ²+λ²)) was measured to distort the locator polynomial enough
    to mislocate live adversaries at int8's noise floor (the σ ≈ λ
    boundary pays up to 50% coefficient shrinkage; PERF.md §17), so
    kept directions solve exactly. ``lam == 0.0`` takes the historical
    path bit-for-bit (a static python branch — the compiled program is
    unchanged)."""
    if lam == 0.0:
        x, _, _, _ = jnp.linalg.lstsq(a, b, rcond=rcond)
        return x
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    smax = jnp.max(s)
    keep = s > jnp.maximum(rcond * smax, lam)
    utb = jnp.matmul(u.T, b)
    coef = jnp.where(keep, 1.0 / jnp.maximum(s, lam * 1e-6), 0.0)
    return jnp.matmul(vt.T, coef * utb)


def complex_solve(a_re, a_im, b_re, b_im, rcond: float = 0.0,
                  lam: float = 0.0):
    """Solve complex A x = b via the real 2m×2m block embedding.

    [[Ar, -Ai], [Ai, Ar]] [xr; xi] = [br; bi]. LU-based jnp.linalg.solve is
    supported on TPU; the systems here are at most (n-2s) × (n-2s).

    rcond > 0 switches to the SVD-truncated least squares
    (:func:`truncated_lstsq`), for systems that can be genuinely
    rank-deficient — the error-locator Hankel system loses rank when fewer
    than s rows are actually corrupt; the reference used an SVD
    least-squares there for the same reason (c_coding.cpp:81). SVD on the
    embedded system (not its gram) keeps the threshold meaningful in f32:
    the gram squares the condition number. ``lam`` > 0 additionally drops
    singular directions at or below the noise floor λ OUTRIGHT — kept
    directions still solve exactly, deliberately NOT ridge-damped
    (narrow-wire locator solves, truncated_lstsq docstring); λ=0 is the
    historical path bit-for-bit.

    (Moved verbatim from ``coding/cyclic._complex_solve`` — the XLA decode
    path must stay bitwise.)
    """
    m = a_re.shape[0]
    top = jnp.concatenate([a_re, -a_im], axis=1)
    bot = jnp.concatenate([a_im, a_re], axis=1)
    big = jnp.concatenate([top, bot], axis=0)
    rhs = jnp.concatenate([b_re, b_im], axis=0)
    if rcond > 0.0:
        x = truncated_lstsq(big, rhs, rcond, lam=lam)
    else:
        x = jnp.linalg.solve(big, rhs)
    return x[:m], x[m:]


# ---------------------------------------------------------------------------
# Fused tier — Mosaic-lowerable batched primitives (leading axis = batch)
# ---------------------------------------------------------------------------

# One-sided Jacobi sweep count. Convergence is quadratic in sweeps; the
# largest system any caller builds is the 2s×2s embedded locator (2s ≤ 10
# at the n=32 s=5 construction ceiling), where 12 cyclic sweeps leave
# off-diagonal mass below f32 noise with a wide margin. Fixed (never
# data-dependent) so the op graph is shape-static.
JACOBI_SWEEPS = 12

# Guard against 0/0 in rotation/normalization algebra on exactly-zero
# columns (an all-absent syndrome block is legitimately the zero matrix).
_TINY = 1e-30


def _i2(shape, dim):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dim)


def _col(a, j):
    """Static column j of a (b, m, k) batch as (b, m) — static-strided
    slice, no dynamic_slice (Mosaic constraint)."""
    return a[:, :, j]


def _set_col(a, j, new):
    """Mask-based static column write: a[:, :, j] = new, Mosaic-safe."""
    return jnp.where(_i2(a.shape, 2) == j, new[:, :, None], a)


def jacobi_lstsq(a: jnp.ndarray, b: jnp.ndarray, rcond: float,
                 sweeps: int = JACOBI_SWEEPS, lam: float = 0.0):
    """Truncated least squares ``min ‖A x − b‖`` via one-sided Jacobi SVD.

    a: (bb, m, m) real, b: (bb, m) — returns x (bb, m) with singular
    directions below ``rcond·σmax`` dropped, the fused-tier counterpart of
    :func:`truncated_lstsq` (same cutoff semantics; σ come out of the
    rotations at high relative accuracy because the gram is never formed).
    ``lam`` > 0 drops directions with σ ≤ λ outright, exactly like the
    XLA tier (truncated_lstsq's noise-floor cutoff — keep
    σ² > max((rcond·σmax)², λ²)); kept directions solve exactly. λ=0
    keeps the historical expression bit-for-bit via a static python
    branch.

    One-sided Jacobi: rotate column pairs of A (accumulating the rotations
    in V) until columns are mutually orthogonal — then A·V = W with
    ``WᵀW = diag(σ²)``, and x = V Σ⁻² Wᵀ b restricted to kept σ. The pair
    loop is a static python loop (m ≤ 10), every update a masked
    elementwise op — no traced indexing anywhere.
    """
    bb, m, _ = a.shape
    v0 = jnp.broadcast_to(
        (_i2((bb, m, m), 1) == _i2((bb, m, m), 2)).astype(a.dtype),
        (bb, m, m))

    def sweep(_, carry):
        w, v = carry
        for p in range(m - 1):
            for q in range(p + 1, m):
                wp, wq = _col(w, p), _col(w, q)
                alpha = jnp.sum(wp * wp, axis=1)
                beta = jnp.sum(wq * wq, axis=1)
                gamma = jnp.sum(wp * wq, axis=1)
                # rotation annihilating the (p, q) off-diagonal of WᵀW:
                # branchless — |γ| ≈ 0 degrades to the identity rotation
                live = jnp.abs(gamma) > _TINY
                g_safe = jnp.where(live, gamma, 1.0)
                zeta = (beta - alpha) / (2.0 * g_safe)
                # NB not jnp.sign: equal column norms give ζ = 0 where the
                # optimal rotation is 45° (t = 1) — sign(0) = 0 would skip it
                sgn = jnp.where(zeta >= 0.0, 1.0, -1.0)
                t = sgn / (jnp.abs(zeta) + jnp.sqrt(1.0 + zeta * zeta))
                t = jnp.where(live, t, 0.0)
                c = 1.0 / jnp.sqrt(1.0 + t * t)
                s = c * t
                c_ = c[:, None]
                s_ = s[:, None]
                new_wp = c_ * wp - s_ * wq
                new_wq = s_ * wp + c_ * wq
                w = _set_col(_set_col(w, p, new_wp), q, new_wq)
                vp, vq = _col(v, p), _col(v, q)
                new_vp = c_ * vp - s_ * vq
                new_vq = s_ * vp + c_ * vq
                v = _set_col(_set_col(v, p, new_vp), q, new_vq)
        return w, v

    # sweeps under ONE fori_loop: the pair loop must stay unrolled (static
    # column slicing) but the sweep body is identical each pass — carrying
    # it keeps the op graph sweeps× smaller, which is the difference
    # between a seconds and a minutes XLA:CPU compile at n=32
    w, v = jax.lax.fori_loop(0, sweeps, sweep, (a, v0))
    sig2 = jnp.sum(w * w, axis=1)  # (bb, m) = σ²
    sig2max = jnp.max(sig2, axis=1, keepdims=True)
    keep = sig2 > (rcond * rcond) * sig2max
    wtb = jnp.sum(w * b[:, :, None], axis=1)  # (bb, m) = Wᵀ b
    if lam > 0.0:
        keep = keep & (sig2 > lam * lam)
    coef = jnp.where(keep, wtb / jnp.maximum(sig2, _TINY), 0.0)
    return jnp.sum(v * coef[:, None, :], axis=2)  # V @ coef


def gauss_inv_c(a_re: jnp.ndarray, a_im: jnp.ndarray):
    """Batched complex matrix inverse via Gauss–Jordan with partial
    pivoting on the complex modulus, carried as (re, im) pairs.

    a_re, a_im: (bb, m, m). Returns (inv_re, inv_im). Every step is
    mask-based (iota one-hots select/ swap/ update rows), the pivot row is
    the max-|a|² row at or below the diagonal with lowest-index tie-break,
    and the m-step elimination runs under one ``fori_loop`` — the whole
    inverse is elementwise algebra Mosaic lowers in-kernel. The decode
    callers invert the honest-row DFT submatrix, full-rank by construction
    (any n−2s distinct rows of the C1 Vandermonde are independent).
    """
    bb, m, _ = a_re.shape
    shape = (bb, m, m)
    eye = (_i2(shape, 1) == _i2(shape, 2)).astype(a_re.dtype)
    eye = jnp.broadcast_to(eye, shape)
    inv_re = eye
    inv_im = jnp.zeros(shape, a_re.dtype)

    def rows_get(t, rowsel):
        return jnp.sum(t * rowsel, axis=1, keepdims=True)  # (bb, 1, m)

    def body(k, carry):
        a_re, a_im, inv_re, inv_im = carry
        csel = (_i2(shape, 2) == k).astype(a_re.dtype)
        col_re = jnp.sum(a_re * csel, axis=2)  # (bb, m)
        col_im = jnp.sum(a_im * csel, axis=2)
        mod = col_re * col_re + col_im * col_im
        # f32 row indices (exact: m ≤ 64) — Mosaic has no integer reductions
        rowix = _i2((bb, m), 1).astype(a_re.dtype)
        kf = jnp.float32(1.0) * k
        mod = jnp.where(rowix >= kf, mod, -1.0)  # eliminated rows ineligible
        mx = jnp.max(mod, axis=1, keepdims=True)
        is_max = mod == mx
        # lowest-index argmax, branchless
        r = jnp.min(jnp.where(is_max, rowix, float(m)), axis=1)  # (bb,)
        rsel_k = (_i2(shape, 1) == k).astype(a_re.dtype)
        rsel_r = (_i2(shape, 1).astype(a_re.dtype)
                  == r[:, None, None]).astype(a_re.dtype)

        def swap(t):
            row_k = rows_get(t, rsel_k)
            row_r = rows_get(t, rsel_r)
            return t + rsel_k * (row_r - row_k) + rsel_r * (row_k - row_r)

        a_re, a_im = swap(a_re), swap(a_im)
        inv_re, inv_im = swap(inv_re), swap(inv_im)

        # pivot = a[k, k]; scale row k by 1/pivot (complex reciprocal)
        p_re = jnp.sum(a_re * rsel_k * csel[:, :m, :], axis=(1, 2))
        p_im = jnp.sum(a_im * rsel_k * csel[:, :m, :], axis=(1, 2))
        pm = jnp.maximum(p_re * p_re + p_im * p_im, _TINY)
        ip_re = (p_re / pm)[:, None, None]
        ip_im = (-p_im / pm)[:, None, None]
        rk_re = rows_get(a_re, rsel_k)
        rk_im = rows_get(a_im, rsel_k)
        ik_re = rows_get(inv_re, rsel_k)
        ik_im = rows_get(inv_im, rsel_k)
        srk_re = rk_re * ip_re - rk_im * ip_im
        srk_im = rk_re * ip_im + rk_im * ip_re
        sik_re = ik_re * ip_re - ik_im * ip_im
        sik_im = ik_re * ip_im + ik_im * ip_re

        # eliminate column k from every other row
        f_re = jnp.where(rowix == k, 0.0, jnp.sum(a_re * csel, axis=2))
        f_im = jnp.where(rowix == k, 0.0, jnp.sum(a_im * csel, axis=2))
        f_re = f_re[:, :, None]
        f_im = f_im[:, :, None]
        a_re2 = a_re - (f_re * srk_re - f_im * srk_im)
        a_im2 = a_im - (f_re * srk_im + f_im * srk_re)
        inv_re2 = inv_re - (f_re * sik_re - f_im * sik_im)
        inv_im2 = inv_im - (f_re * sik_im + f_im * sik_re)
        isrow = _i2(shape, 1) == k
        a_re2 = jnp.where(isrow, srk_re, a_re2)
        a_im2 = jnp.where(isrow, srk_im, a_im2)
        inv_re2 = jnp.where(isrow, sik_re, inv_re2)
        inv_im2 = jnp.where(isrow, sik_im, inv_im2)
        return a_re2, a_im2, inv_re2, inv_im2

    _, _, inv_re, inv_im = jax.lax.fori_loop(
        0, m, body, (a_re, a_im, inv_re, inv_im))
    return inv_re, inv_im


def topk_mask(mag: jnp.ndarray, m: int):
    """Bool mask of the top-m entries per batch row of mag (bb, n), by
    pairwise-comparison rank — no sort, no top_k (Mosaic constraint). Ties
    break toward the lower index (``lax.top_k``'s preference), though the
    cyclic locator's index-monotone bias makes exact ties unreachable."""
    gt = (mag[:, None, :] > mag[:, :, None]) | (
        (mag[:, None, :] == mag[:, :, None])
        & (_i2((mag.shape[0],) + mag.shape[1:] * 2, 2)
           < _i2((mag.shape[0],) + mag.shape[1:] * 2, 1)))
    # f32 count (exact: n ≤ 64) — Mosaic has no integer reductions
    rank = jnp.sum(gt.astype(jnp.float32), axis=2)  # entries ahead of i
    return rank < float(m)


def select_matrix(mask: jnp.ndarray, m: int):
    """The (bb, m, n) 0/1 compaction matrix of a (bb, n) bool mask with
    exactly m set lanes per row: S[r, i] = 1 iff i is the r-th set lane.
    ``S @ X`` then gathers the selected rows of X as a matmul — the MXU
    replacement for a gather Mosaic cannot lower. Cumsum comes from a
    triangular-matrix matmul (built from iota, so no host constant)."""
    bb, n = mask.shape
    mf = mask.astype(jnp.float32)
    tri = (_i2((n, n), 0) <= _i2((n, n), 1)).astype(jnp.float32)
    pos = jnp.dot(mf, tri,
                  preferred_element_type=jnp.float32) - 1.0  # (bb, n)
    shape = (bb, m, n)
    sel = (jnp.broadcast_to(pos[:, None, :], shape)
           == _i2(shape, 1).astype(jnp.float32))
    return jnp.where(jnp.broadcast_to(mask[:, None, :], shape), sel,
                     False).astype(jnp.float32)


def masked_median(x: jnp.ndarray, mask: jnp.ndarray):
    """Median of x (bb, n) over the lanes where mask (bb, n) is True —
    rank-selection (average of the two middle order statistics for even
    counts), matching ``jnp.nanmedian`` over the masked entries. All-False
    rows return NaN, like nanmedian of an all-NaN slice. Non-finite x
    lanes must be excluded by the caller's mask; masked-out lanes are
    value-sanitized so a NaN there cannot leak through the 0·NaN trap."""
    bb, n = x.shape
    mf = mask.astype(x.dtype)
    xs = jnp.where(mask, x, 0.0)
    shape = (bb, n, n)
    lt = (xs[:, None, :] < xs[:, :, None]) | (
        (xs[:, None, :] == xs[:, :, None]) & (_i2(shape, 2) < _i2(shape, 1)))
    lt = lt & jnp.broadcast_to(mask[:, None, :], shape)
    # f32 counts (exact: n ≤ 64) — Mosaic has no integer reductions
    rank = jnp.sum(lt.astype(jnp.float32), axis=2)  # (bb, n) masked rank
    p = jnp.sum(mf, axis=1, keepdims=True)  # (bb, 1)
    k1 = jnp.floor((p - 1.0) * 0.5)
    k2 = jnp.floor(p * 0.5)

    def at_rank(k):
        hit = (rank == k) & mask
        return jnp.sum(jnp.where(hit, xs, 0.0), axis=1)

    med = 0.5 * (at_rank(k1) + at_rank(k2))
    return jnp.where(p[:, 0] > 0, med, jnp.nan)
