"""Mesh / distributed runtime bootstrap.

Replaces the reference's MPI world wiring (reference: src/distributed_nn.py:79-133,
rank 0 = parameter server process, ranks 1..P = workers). Here there are no
roles: one SPMD program over a ``Mesh`` with a worker axis ``w``. A logical
worker is a shard of the worker axis; the "PS" is the replicated post-gather
phase of the same jitted step.

Multi-host: call :func:`init_distributed` once per host before any jax call;
the mesh then spans all hosts' devices and the gradient gather rides ICI
within a slice and DCN across slices — the same program, no code changes
(replaces the reference's NCCL/MPI-over-TCP transport, README.md:16).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "w"

try:  # jax >= 0.6: top-level export, replication check spelled check_vma
    from jax import shard_map
except ImportError:  # jax < 0.6: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_legacy(f, mesh, in_specs, out_specs,
                                 check_rep=check_vma)


def axis_size(axis_name) -> int:
    """``lax.axis_size``, with the jax < 0.6 fallback spelling: psum of a
    unit constant, which constant-folds to a static Python int at trace
    time (so loop bounds / permutation lists built from it stay static)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                          ".jax_cache")


def _machine_tag() -> str:
    """Short fingerprint of the host microarchitecture. XLA:CPU AOT results
    are feature-pinned to the compiling machine (reloading foreign ones can
    SIGILL per XLA's own warning); scoping the cache dir by this tag makes a
    shared/NFS checkout safe across heterogeneous hosts. Accelerator
    binaries don't need it but lose nothing from the extra path level."""
    import hashlib
    import platform as _platform

    feats = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    h = hashlib.sha256(feats.encode()).hexdigest()[:8] if feats else "nofeat"
    return f"{_platform.machine()}-{h}"


def enable_compile_cache(path: Optional[str] = None) -> str:
    """Point XLA's persistent compilation cache at a repo-local directory.

    The flagship coded ResNet step compiles in minutes on the tunnel backend
    (measured r3: the cyclic leg alone consumed bench.py's whole 280 s
    budget, BENCH_r02 rc=124 was the same cost hitting the driver window);
    with the persistent cache warmed by any earlier run of the same shapes
    the recompile is seconds, so every leg fits any driver window. Safe to
    call repeatedly; a cold cache just means one slow first run.
    """
    import sys

    # Explicit CPU environment: skip without touching jax. CPU compiles are
    # cheap, and — measured on this container (PERF.md §9) — XLA:CPU
    # executables built with the persistent cache enabled exhibit
    # donated-carry buffer aliasing corruption: a jit output state that
    # MUTATES under subsequent dispatches (two consecutive device_get of
    # the same array differ, NaNs bleed into later checkpoints). The chaos
    # harness's bitwise classifications caught it; until the upstream
    # runtime is fixed, CPU runs stay uncached.
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return ""
    # If a backend is ALREADY initialized and it's plain CPU, skip: CPU
    # compiles are cheap and the AOT reload warning is noise (nested tools —
    # e.g. convergence_grid driving time_to_acc rows — land here). Only
    # queried when initialized, so this can never trigger the in-process
    # tunnel init the bootstrap must avoid.
    try:
        import jax._src.xla_bridge as _xb

        if _xb.backends_are_initialized() and jax.default_backend() == "cpu":
            return ""
    except Exception:
        pass
    base = path or os.environ.get("JAX_COMPILATION_CACHE_DIR") or _CACHE_DIR
    cache = os.path.join(base, _machine_tag())
    try:
        os.makedirs(cache, exist_ok=True)
    except OSError as e:  # read-only install prefix: run uncached, don't die
        print(f"enable_compile_cache: {cache} unwritable ({e}); compiling "
              f"uncached", file=sys.stderr, flush=True)
        return ""
    jax.config.update("jax_compilation_cache_dir", cache)
    # the default 1 s floor would skip mid-size kernels; cache everything
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return cache


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialise multi-host JAX if requested via args or env.

    No-op on a single host. Mirrors the role of the reference's mpirun
    bootstrap (src/README.md:10) without assigning roles to ranks.
    """
    addr = coordinator_address or os.environ.get("DRACO_COORDINATOR")
    if addr is None:
        return
    if num_processes is None:
        num_processes = int(os.environ["DRACO_NUM_PROCESSES"])
    if process_id is None:
        process_id = int(os.environ["DRACO_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(num_workers: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 1-D mesh with axis ``w``.

    ``num_workers`` logical workers are laid out over the available devices;
    each device holds an equal contiguous block of the worker axis. When
    num_workers does not divide the device count, the mesh shrinks to the
    largest divisor-count of devices and the rest idle — loudly.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not devices:
        raise ValueError("make_mesh: no devices available")
    n_dev = min(len(devices), num_workers)
    while num_workers % n_dev != 0:
        n_dev -= 1
    if n_dev < len(devices):
        print(
            f"make_mesh: using {n_dev}/{len(devices)} devices for "
            f"{num_workers} workers (pick num_workers as a multiple of the "
            f"device count to use the whole slice)",
            flush=True,
        )
    return Mesh(np.asarray(devices[:n_dev]), (WORKER_AXIS,))


def put_global(arr: np.ndarray, sharding: NamedSharding):
    """Host array -> (possibly multi-host) global device array.

    Single-process: a plain sharded device_put. Multi-process: every process
    holds the full host array (batch indices are deterministic, so all hosts
    agree) and contributes only the shards its addressable devices own —
    the multi-host feeding discipline that replaces the reference's per-rank
    MPI sends (baseline_worker.py:258-273); the cross-host gradient gather
    then rides DCN inside the jitted step.
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays with a leading logical-worker axis."""
    return NamedSharding(mesh, P(WORKER_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
