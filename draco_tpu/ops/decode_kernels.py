"""Fused coded-decode kernels — the second and third Pallas TPU kernels
(ISSUE 12, ROADMAP item 4).

PR 9's committed device ledger puts the coded decode at 17–25% of LM
device time (4.5–13.5% CNN) at CI shapes — the largest non-matmul phase,
and the one that grows with n as the flat aggregation point ingests more
codewords. Two kernels attack it:

``cyclic_locator``
    Steps 2–5 of the cyclic decode — syndrome matmuls → Hankel locator
    solve → honest-row top-k → recombination-vector solve → fitted-codeword
    health residual — fused into one kernel, vmapped over per-layer
    projected columns via the grid: each grid step loads an (8, n) block
    of the (L, n) projected-column stack into VMEM and runs the whole
    locator chain on it (``coding/cyclic.locator_core`` — the SAME
    function the CPU reference path jits, so the two lowerings cannot
    drift), instead of round-tripping ~6 solver ops per layer through HBM.
    The in-graph health/forensics columns (residual, flagged, loud,
    honest) are KERNEL OUTPUTS — observability is part of the contract,
    not a casualty of fusion.

``approx_decode``
    The approx family's partial-recovery decode tail: where-mask →
    combine-matvec → true-mean → residual-vs-bound norms, fused into ONE
    pass over the (n, d) wire and batch-gradient blocks (the XLA path
    pays ≥ 4 separate HBM sweeps for the same numbers). The d axis is the
    grid; per-row presence masking (true zeros — a NaN payload survives
    multiplicative masking), the decode matvec, the true-mean matvec and
    both squared-norm accumulators live in VMEM, with 128-lane partial
    sums accumulated across sequential grid steps (the
    ``ops/coded._project_kernel`` accumulator pattern).

Dispatch (``resolve_decode_impl``): ``cfg.decode_impl = "auto"`` keeps
today's XLA lowering off-TPU and selects the kernels on TPU backends;
``"pallas"`` selects the kernels where they can run and otherwise falls
back to their reference lowering (the same fused algorithm through XLA —
coding/cyclic.locator_core / coding/approx._decode_fused), which is what
the committed CPU-container artifacts measure (PERF.md §14); ``"xla"``
pins the historical path bit-for-bit. Interpret mode covers the kernel
bodies in CI without a TPU, and the registered lint rows export the
pallas_call programs for the TPU platform, so the Python-side Mosaic
lowering is exercised on every CI run (the tpu_attn_lowering_check
methodology).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (parity w/ ops)

from draco_tpu.ops.coded import TILE_D, _pad_d, use_pallas

# Layers per cyclic-locator grid step: the f32 sublane tile. The (L, n)
# projected-column stack is padded up to a multiple of this; padded layers
# run the locator on zero columns (harmlessly — the truncated solves are
# zero-safe) and the wrapper slices them away.
LAYER_BLOCK = 8


def resolve_decode_impl(value: str, backend_pallas=None) -> str:
    """cfg.decode_impl -> the coding-layer ``impl`` tag (static per
    process: dispatch depends only on the attached backend, so the jitted
    step programs close over the result — no retraces).

      auto    pallas on TPU backends, xla elsewhere (the default: CI and
              CPU fallbacks keep today's bitwise path)
      xla     the historical lowering, everywhere
      pallas  the kernels on TPU; their fused reference lowering (same
              algorithm through XLA) elsewhere — the CPU-container cells
              of the committed artifacts run this fallback
    """
    if backend_pallas is None:
        backend_pallas = use_pallas()
    if value == "xla":
        return "xla"
    if value == "auto":
        return "pallas" if backend_pallas else "xla"
    if value == "pallas":
        return "pallas" if backend_pallas else "fused"
    raise ValueError(f"decode_impl must be auto|xla|pallas, got {value!r}")


# ---------------------------------------------------------------------------
# cyclic: fused locator (steps 2-5), grid over per-layer projected columns
# ---------------------------------------------------------------------------


def _cyclic_locator_kernel(s, rel_tol, lam, e_re_ref, e_im_ref, c2h_re_ref,
                           c2h_im_ref, c1_re_ref, c1_im_ref, est_re_ref,
                           est_im_ref, pres_ref, v_re_ref, v_im_ref,
                           honest_ref, flagged_ref, loud_ref, resid_ref):
    from draco_tpu.coding import cyclic as cyclic_mod

    v_re, v_im, honest, flagged, loud, resid = cyclic_mod.locator_core(
        e_re_ref[...], e_im_ref[...], c2h_re_ref[...], c2h_im_ref[...],
        c1_re_ref[...], c1_im_ref[...], est_re_ref[...], est_im_ref[...],
        pres_ref[...], s, rel_tol, lam=lam)
    v_re_ref[...] = v_re
    v_im_ref[...] = v_im
    honest_ref[...] = honest.astype(jnp.float32)
    flagged_ref[...] = flagged.astype(jnp.float32)
    loud_ref[...] = loud.astype(jnp.float32)
    # per-layer scalar, lane-broadcast to satisfy the block tiling (the
    # wrapper keeps lane 0) — the flash kernel's lse layout
    resid_ref[...] = jnp.broadcast_to(resid[:, None], resid_ref.shape)


@functools.partial(jax.jit,
                   static_argnames=("s", "rel_tol", "lam", "interpret"))
def _cyclic_locator_pallas(e_re_l, e_im_l, c2h_re, c2h_im, c1_re, c1_im,
                           est_re, est_im, pres_f, s, rel_tol, lam,
                           interpret):
    L, n = e_re_l.shape
    lp = -(-L // LAYER_BLOCK) * LAYER_BLOCK
    if lp != L:
        pad = [(0, lp - L), (0, 0)]
        e_re_l = jnp.pad(e_re_l, pad)
        e_im_l = jnp.pad(e_im_l, pad)
    grid = (lp // LAYER_BLOCK,)
    row = lambda i: (i, 0)  # noqa: E731
    whole = lambda i: (0, 0)  # noqa: E731
    blk = (LAYER_BLOCK, n)
    out = pl.pallas_call(
        functools.partial(_cyclic_locator_kernel, s, rel_tol, lam),
        grid=grid,
        in_specs=[
            pl.BlockSpec(blk, row),
            pl.BlockSpec(blk, row),
            pl.BlockSpec(c2h_re.shape, whole),
            pl.BlockSpec(c2h_im.shape, whole),
            pl.BlockSpec(c1_re.shape, whole),
            pl.BlockSpec(c1_im.shape, whole),
            pl.BlockSpec(est_re.shape, whole),
            pl.BlockSpec(est_im.shape, whole),
            pl.BlockSpec((1, n), whole),
        ],
        out_specs=[pl.BlockSpec(blk, row)] * 6,
        out_shape=[jax.ShapeDtypeStruct((lp, n), jnp.float32)] * 6,
        interpret=interpret,
    )(e_re_l, e_im_l, c2h_re, c2h_im, c1_re, c1_im, est_re, est_im, pres_f)
    v_re, v_im, honest, flagged, loud, resid = out
    return (v_re[:L], v_im[:L], honest[:L] > 0.5, flagged[:L] > 0.5,
            loud[:L] > 0.5, resid[:L, 0])


def cyclic_locator(code, e_re_l, e_im_l, pres_f, rel_tol,
                   interpret: bool = False, lam: float = 0.0):
    """Kernel entry used by ``coding/cyclic._run_locator``: (L, n)
    projected-column stack -> the locator outputs of
    ``coding/cyclic.locator_core`` (v pair, honest/flagged/loud masks,
    per-layer residual). ``pres_f``: (1, n) f32 presence row shared by
    every layer. ``lam``: static Tikhonov λ of the locator solve
    (narrow-wire regularization, ISSUE 15; 0.0 = exact path)."""
    return _cyclic_locator_pallas(
        e_re_l, e_im_l,
        jnp.asarray(code.c2h_re), jnp.asarray(code.c2h_im),
        jnp.asarray(code.c1_re), jnp.asarray(code.c1_im),
        jnp.asarray(code.est_re), jnp.asarray(code.est_im),
        jnp.asarray(pres_f), code.s, float(rel_tol), float(lam), interpret)


# ---------------------------------------------------------------------------
# narrow-ingest dequantization (ISSUE 15): widen bf16/int8 wire tiles to
# f32 INSIDE the kernel body, so the widened (n, d) f32 matrix never
# round-trips HBM — the dequant the XLA fallback pays as a separate
# convert/multiply pass happens on the VMEM-resident tile instead
# ---------------------------------------------------------------------------


def _dequant_tile(q, scale, block):
    """(n, T) narrow tile -> f32. ``q`` bf16 (scale None) or int8 with
    ``scale`` the (n, T/block) per-block f32 scales. The block broadcast
    is a matmul against an iota-built 0/1 expansion matrix — Mosaic has
    no gather/repeat, but (nb, T) one-hot times (n, nb) is MXU work."""
    if scale is None:
        return q.astype(jnp.float32)
    n, t = q.shape
    nb = scale.shape[-1]
    row = jax.lax.broadcasted_iota(jnp.int32, (nb, t), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (nb, t), 1)
    expand = ((col >= row * block)
              & (col < (row + 1) * block)).astype(jnp.float32)
    wide = jnp.dot(scale, expand, preferred_element_type=jnp.float32)
    return q.astype(jnp.float32) * wide


# ---------------------------------------------------------------------------
# approx: fused partial-recovery decode tail, grid over d tiles
# ---------------------------------------------------------------------------


def _approx_decode_body(d, n, block, rows_ref, scale_ref, bg_ref, vn_ref,
                        pres_ref, dec_ref, sqd_ref, sqg_ref):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        sqd_ref[...] = jnp.zeros_like(sqd_ref)
        sqg_ref[...] = jnp.zeros_like(sqg_ref)

    base = j * TILE_D
    cols = base + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_D), 1)
    live = (cols < d).astype(jnp.float32)  # ragged edge tile mask
    pres = pres_ref[...][:, :1]  # (n, 1) — lane 0 of the broadcast block
    raw = _dequant_tile(
        rows_ref[...], None if scale_ref is None else scale_ref[...], block)
    # true zero-fill of absent rows (0·NaN = NaN through the matvec —
    # multiplicative masking alone would pass a NaN payload)
    rows = jnp.where(pres > 0, raw, 0.0) * live
    bg = bg_ref[...] * live
    decoded = jnp.dot(vn_ref[...], rows,
                      preferred_element_type=jnp.float32)  # (1, T), Σv/n·row
    true_mean = jnp.dot(jnp.full((1, n), 1.0 / n, jnp.float32), bg,
                        preferred_element_type=jnp.float32)
    dec_ref[...] = decoded
    diff2 = (decoded - true_mean) ** 2
    sqd_ref[...] += diff2.reshape(TILE_D // 128, 128).sum(
        axis=0, keepdims=True)
    sqg_ref[...] += (bg * bg).reshape(n, TILE_D // 128, 128).sum(
        axis=(0, 1))[None, :]


def _approx_decode_kernel(d, n, rows_ref, bg_ref, vn_ref, pres_ref,
                          dec_ref, sqd_ref, sqg_ref):
    _approx_decode_body(d, n, 0, rows_ref, None, bg_ref, vn_ref, pres_ref,
                        dec_ref, sqd_ref, sqg_ref)


def _approx_decode_kernel_narrow(d, n, block, rows_ref, scale_ref, bg_ref,
                                 vn_ref, pres_ref, dec_ref, sqd_ref,
                                 sqg_ref):
    _approx_decode_body(d, n, block, rows_ref, scale_ref, bg_ref, vn_ref,
                        pres_ref, dec_ref, sqd_ref, sqg_ref)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def _approx_decode_pallas(rows, bg, v_over_n, pres_wide, scale=None,
                          block=0, interpret=False):
    n, d = rows.shape
    rows_p = _pad_d(rows, TILE_D)
    bg_p = _pad_d(bg, TILE_D)
    dp = rows_p.shape[-1]
    grid = (dp // TILE_D,)
    whole = lambda j: (0, 0)  # noqa: E731
    in_specs = [
        pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
        pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
        pl.BlockSpec((1, n), whole),
        pl.BlockSpec((n, 128), whole),
    ]
    operands = [rows_p, bg_p, v_over_n, pres_wide]
    if scale is None:
        kernel = functools.partial(_approx_decode_kernel, d, n)
    else:
        # per-block int8 scales ride their own (n, TILE_D/block) tiles,
        # padded with 1.0 (padded q lanes are 0, so 0·1 stays 0)
        sb = TILE_D // block
        nb = scale.shape[-1]
        nb_p = (dp // TILE_D) * sb
        if nb_p != nb:
            scale = jnp.pad(scale, [(0, 0), (0, nb_p - nb)],
                            constant_values=1.0)
        kernel = functools.partial(_approx_decode_kernel_narrow, d, n,
                                   block)
        in_specs.insert(1, pl.BlockSpec((n, sb), lambda j: (0, j)))
        operands.insert(1, scale)
    decoded, sqd, sqg = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, TILE_D), lambda j: (0, j)),
            pl.BlockSpec((1, 128), whole),
            pl.BlockSpec((1, 128), whole),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
            jax.ShapeDtypeStruct((1, 128), jnp.float32),
            jax.ShapeDtypeStruct((1, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return decoded[0, :d], jnp.sum(sqd), jnp.sum(sqg)


def approx_decode(rows, batch_grads, v, pres_b, interpret: bool = False,
                  wire=None):
    """Kernel entry used by ``coding/approx._decode_fused``: one fused
    pass over the (n, d) wire + gradient blocks. Returns
    ``(decoded (d,), Σ(decoded − true_mean)², Σ batch_grads²)`` — the
    caller folds the two scalars into the residual-vs-bound health.

    ``wire`` (ISSUE 15): the narrow-ingest variant. ``(mode, buf)`` with
    ``buf`` the real narrow buffers (obs/numerics.narrow_wire_rows —
    bf16 ``{"q"}`` or int8 ``{"q", "scale"}`` at ``block`` granularity,
    passed as ``(mode, buf, block)`` for int8): the kernel loads the
    NARROW tiles and dequantizes in VMEM (_dequant_tile), so the widened
    f32 wire matrix never exists in HBM. ``rows`` is ignored then (the
    narrow buffers ARE the wire). int8 requires ``TILE_D % block == 0``
    (the per-tile scale columns must align; callers fall back to the
    pre-widened path otherwise)."""
    n = batch_grads.shape[0]
    pres_wide = jnp.broadcast_to(
        jnp.asarray(pres_b).astype(jnp.float32)[:, None], (n, 128))
    if wire is not None:
        mode, buf = wire[0], wire[1]
        if mode == "bf16":
            return _approx_decode_pallas(
                jnp.asarray(buf["q"]), batch_grads, (v / n)[None, :],
                pres_wide, interpret=interpret)
        block = int(wire[2])
        return _approx_decode_pallas(
            jnp.asarray(buf["q"]), batch_grads, (v / n)[None, :],
            pres_wide, scale=jnp.asarray(buf["scale"]), block=block,
            interpret=interpret)
    return _approx_decode_pallas(rows, batch_grads, (v / n)[None, :],
                                 pres_wide, interpret=interpret)


# ---------------------------------------------------------------------------
# cyclic: narrow-ingest recombination (ISSUE 15) — Re[vᵀ(R_re + i·R_im)]
# with R supplied as the REAL narrow wire buffers, dequantized in-tile
# ---------------------------------------------------------------------------


def _cyclic_recombine_body(block, vr_ref, vi_ref, qr_ref, qi_ref, sr_ref,
                           si_ref, out_ref):
    rr = _dequant_tile(qr_ref[...],
                       None if sr_ref is None else sr_ref[...], block)
    ri = _dequant_tile(qi_ref[...],
                       None if si_ref is None else si_ref[...], block)
    out_ref[...] = (jnp.dot(vr_ref[...], rr,
                            preferred_element_type=jnp.float32)
                    - jnp.dot(vi_ref[...], ri,
                              preferred_element_type=jnp.float32))


def _cyclic_recombine_kernel_bf16(vr_ref, vi_ref, qr_ref, qi_ref, out_ref):
    _cyclic_recombine_body(0, vr_ref, vi_ref, qr_ref, qi_ref, None, None,
                           out_ref)


def _cyclic_recombine_kernel_int8(block, vr_ref, vi_ref, qr_ref, qi_ref,
                                  sr_ref, si_ref, out_ref):
    _cyclic_recombine_body(block, vr_ref, vi_ref, qr_ref, qi_ref, sr_ref,
                           si_ref, out_ref)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def _cyclic_recombine_pallas(v_re, v_im, q_re, q_im, s_re=None, s_im=None,
                             block=0, interpret=False):
    n, d = q_re.shape
    qr_p = _pad_d(q_re, TILE_D)
    qi_p = _pad_d(q_im, TILE_D)
    dp = qr_p.shape[-1]
    grid = (dp // TILE_D,)
    whole = lambda j: (0, 0)  # noqa: E731
    in_specs = [pl.BlockSpec((1, n), whole), pl.BlockSpec((1, n), whole),
                pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
                pl.BlockSpec((n, TILE_D), lambda j: (0, j))]
    operands = [v_re[None, :], v_im[None, :], qr_p, qi_p]
    if s_re is None:
        kernel = _cyclic_recombine_kernel_bf16
    else:
        sb = TILE_D // block
        nb_p = (dp // TILE_D) * sb
        pad = [(0, 0), (0, nb_p - s_re.shape[-1])]
        if nb_p != s_re.shape[-1]:
            s_re = jnp.pad(s_re, pad, constant_values=1.0)
            s_im = jnp.pad(s_im, pad, constant_values=1.0)
        kernel = functools.partial(_cyclic_recombine_kernel_int8, block)
        in_specs += [pl.BlockSpec((n, sb), lambda j: (0, j))] * 2
        operands += [s_re, s_im]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, TILE_D), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, dp), jnp.float32),
        interpret=interpret,
    )(*operands)
    return out[0, :d]


def cyclic_narrow_recombine(v_re, v_im, wire, interpret: bool = False):
    """Narrow-ingest cyclic recombination: ``wire`` is the
    ``(mode, buf_re, buf_im, block)`` tuple of
    obs/numerics.narrow_wire_pair — the REAL bf16/int8 buffers that
    crossed the sharding boundary. The kernel streams the narrow tiles
    once and dequantizes in VMEM (_dequant_tile), so the widened f32
    (n, d) matrix never round-trips HBM — the narrow wire's HBM half of
    the ISSUE 15 win. int8 requires ``TILE_D % block == 0``."""
    mode, buf_re, buf_im, block = wire
    if mode == "bf16":
        return _cyclic_recombine_pallas(
            v_re, v_im, jnp.asarray(buf_re["q"]), jnp.asarray(buf_im["q"]),
            interpret=interpret)
    return _cyclic_recombine_pallas(
        v_re, v_im, jnp.asarray(buf_re["q"]), jnp.asarray(buf_im["q"]),
        s_re=jnp.asarray(buf_re["scale"]), s_im=jnp.asarray(buf_im["scale"]),
        block=int(block), interpret=interpret)


# ---------------------------------------------------------------------------
# streaming segmented wire (ISSUE 16): segment-offset entry points — the
# existing kernels already tile over d, so a segment is just a [a, b) slice
# of the operands (and, for the narrow wire, of the q/scale buffers); no new
# kernels, only sliced dispatch
# ---------------------------------------------------------------------------


def _slice_narrow_buf(buf, a, b, block):
    """[a, b) d-slice of one narrow buffer dict ({"q"[, "scale"]}) — the
    int8 per-block scale columns slice at block granularity, which is why
    interior segment cuts MUST be block-aligned
    (obs/numerics.wire_segment_bounds guarantees it)."""
    out = {"q": buf["q"][:, a:b]}
    if "scale" in buf:
        blk = max(int(block), 1)
        if a % blk:
            raise ValueError(
                f"segment cut {a} not aligned to int8 scale block {blk}")
        out["scale"] = buf["scale"][:, a // blk:-(-b // blk)]
    return out


def wire_slice_pair(wire, a: int, b: int):
    """Segment [a, b) view of a narrow_wire_pair tuple
    ``(mode, buf_re, buf_im, block)`` — same tuple shape, sliced buffers,
    so the unsegmented narrow-ingest kernels consume it unchanged."""
    if wire is None:
        return None
    mode, buf_re, buf_im, block = wire
    return (mode, _slice_narrow_buf(buf_re, a, b, block),
            _slice_narrow_buf(buf_im, a, b, block), block)


def wire_slice_single(wire, a: int, b: int):
    """Segment [a, b) view of a narrow_wire_single tuple
    ``(mode, buf, block)`` (the approx family's real wire)."""
    if wire is None:
        return None
    mode, buf, block = wire
    return (mode, _slice_narrow_buf(buf, a, b, block), block)


def cyclic_narrow_recombine_segment(v_re, v_im, wire, a: int, b: int,
                                    interpret: bool = False):
    """Per-segment narrow-ingest recombination: the [a, b) slice of
    ``cyclic_narrow_recombine`` with this segment's own recombination
    vector — the decode-on-arrival unit of the cyclic streaming wire."""
    return cyclic_narrow_recombine(v_re, v_im, wire_slice_pair(wire, a, b),
                                   interpret=interpret)


def approx_decode_segment(rows, batch_grads, v, pres_b, a: int, b: int,
                          interpret: bool = False, wire=None):
    """Per-segment approx decode tail: the [a, b) slice of
    ``approx_decode`` — returns this segment's ``(decoded (b-a,),
    Σ(decoded − true_mean)², Σ batch_grads²)``; the caller folds the
    scalar accumulators across segments BEFORE the final residual sqrt so
    the health verdict stays per-step."""
    w_seg = None if wire is None else wire_slice_single(wire, a, b)
    return approx_decode(rows[:, a:b], batch_grads[:, a:b], v, pres_b,
                         interpret=interpret, wire=w_seg)


def narrow_kernel_ok(wire) -> bool:
    """Static feasibility of the narrow-ingest kernels for this wire:
    int8 per-block scales must tile evenly into the TILE_D grid."""
    if wire is None:
        return False
    if wire[0] == "bf16":
        return True
    block = int(wire[-1])
    return block >= 1 and TILE_D % block == 0


# ---------------------------------------------------------------------------
# program-lint registration (draco_tpu/analysis) — the kernel-bearing rows
# ---------------------------------------------------------------------------


def lint_programs():
    """The pallas_call-bearing decode programs, linted like the flash
    kernel's rows (tools/tpu_attn_lowering_check.py): exported for the TPU
    platform on the CPU host — so the Python-side Mosaic lowering of both
    kernels runs on every CI lint sweep — with the memory-capture opt-out
    (tpu_custom_call cannot compile for the CPU backend). No state carry
    to donate, no collectives; constant-bloat, dtype and host-traffic
    still apply (a kernel baking a d-sized table or upcasting to f64 must
    fail here, not on chip)."""
    from draco_tpu.analysis.registry import (
        BuiltProgram, LintProgram, Manifest,
    )

    from draco_tpu.analysis.registry import BF16_DTYPES

    kernel_manifest = Manifest(require_donated=None, collectives=None)
    bf16_kernel_manifest = Manifest(require_donated=None, collectives=None,
                                    allowed_dtypes=BF16_DTYPES,
                                    required_dtypes=frozenset({"bf16"}))

    def build_cyclic():
        from draco_tpu.coding import cyclic as cyclic_mod

        code = cyclic_mod.build_cyclic_code(8, 1)
        L, n = 16, 8

        def fn(e_re_l, e_im_l, pres_f):
            return cyclic_locator(code, e_re_l, e_im_l, pres_f,
                                  cyclic_mod.HEALTH_REL_TOL)

        args = (jnp.zeros((L, n), jnp.float32),
                jnp.zeros((L, n), jnp.float32),
                jnp.ones((1, n), jnp.float32))
        return BuiltProgram("kernel_cyclic_locator", jax.jit(fn), args,
                            None, kernel_manifest,
                            extra={"layers": L, "n": n, "s": code.s},
                            capture_memory=False)

    def build_approx():
        n, d = 8, 4096

        def fn(rows, bg, v, pres):
            return approx_decode(rows, bg, v, pres)

        args = (jnp.zeros((n, d), jnp.float32),
                jnp.zeros((n, d), jnp.float32),
                jnp.ones((n,), jnp.float32) / n,
                jnp.ones((n,), bool))
        return BuiltProgram("kernel_approx_decode", jax.jit(fn), args,
                            None, kernel_manifest,
                            extra={"n": n, "d": d},
                            capture_memory=False)

    def build_cyclic_narrow():
        n, d, block = 8, 4096, 256

        def fn(v_re, v_im, q_re, q_im, s_re, s_im):
            wire = ("int8", {"q": q_re, "scale": s_re},
                    {"q": q_im, "scale": s_im}, block)
            return cyclic_narrow_recombine(v_re, v_im, wire)

        nb = d // block
        args = (jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32),
                jnp.zeros((n, d), jnp.int8), jnp.zeros((n, d), jnp.int8),
                jnp.ones((n, nb), jnp.float32),
                jnp.ones((n, nb), jnp.float32))
        return BuiltProgram("kernel_cyclic_narrow_recombine", jax.jit(fn),
                            args, None, kernel_manifest,
                            extra={"n": n, "d": d, "block": block},
                            capture_memory=False)

    def build_approx_narrow():
        n, d, block = 8, 4096, 256

        def fn(q, s, bg, v, pres):
            return approx_decode(q, bg, v, pres,
                                 wire=("int8", {"q": q, "scale": s}, block))

        args = (jnp.zeros((n, d), jnp.int8),
                jnp.ones((n, d // block), jnp.float32),
                jnp.zeros((n, d), jnp.float32),
                jnp.ones((n,), jnp.float32) / n,
                jnp.ones((n,), bool))
        return BuiltProgram("kernel_approx_decode_narrow", jax.jit(fn),
                            args, None, kernel_manifest,
                            extra={"n": n, "d": d, "block": block},
                            capture_memory=False)

    def build_cyclic_narrow_bf16():
        n, d = 8, 4096

        def fn(v_re, v_im, q_re, q_im):
            wire = ("bf16", {"q": q_re}, {"q": q_im}, 256)
            return cyclic_narrow_recombine(v_re, v_im, wire)

        args = (jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32),
                jnp.zeros((n, d), jnp.bfloat16),
                jnp.zeros((n, d), jnp.bfloat16))
        return BuiltProgram("kernel_cyclic_narrow_recombine_bf16",
                            jax.jit(fn), args, None, bf16_kernel_manifest,
                            extra={"n": n, "d": d},
                            capture_memory=False)

    def build_approx_narrow_bf16():
        n, d = 8, 4096

        def fn(q, bg, v, pres):
            return approx_decode(q, bg, v, pres,
                                 wire=("bf16", {"q": q}, 256))

        args = (jnp.zeros((n, d), jnp.bfloat16),
                jnp.zeros((n, d), jnp.float32),
                jnp.ones((n,), jnp.float32) / n,
                jnp.ones((n,), bool))
        return BuiltProgram("kernel_approx_decode_narrow_bf16",
                            jax.jit(fn), args, None, bf16_kernel_manifest,
                            extra={"n": n, "d": d},
                            capture_memory=False)

    return [
        LintProgram(name="kernel_cyclic_locator", build=build_cyclic,
                    route="decode_kernel"),
        LintProgram(name="kernel_approx_decode", build=build_approx,
                    route="decode_kernel"),
        # narrow-ingest variants (ISSUE 15), BOTH wire dtypes: the int8
        # tiles + per-block scales and the bf16 tiles (which hit bf16's
        # stricter sublane tiling) are dequantized in VMEM (_dequant_tile)
        # — the TPU-platform export below runs their Python-side Mosaic
        # lowering on every CI lint sweep, like the other kernel rows
        LintProgram(name="kernel_cyclic_narrow_recombine",
                    build=build_cyclic_narrow, route="decode_kernel"),
        LintProgram(name="kernel_approx_decode_narrow",
                    build=build_approx_narrow, route="decode_kernel"),
        LintProgram(name="kernel_cyclic_narrow_recombine_bf16",
                    build=build_cyclic_narrow_bf16, route="decode_kernel"),
        LintProgram(name="kernel_approx_decode_narrow_bf16",
                    build=build_approx_narrow_bf16, route="decode_kernel"),
    ]
