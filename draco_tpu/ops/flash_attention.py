"""Blockwise causal flash attention as a Pallas TPU kernel.

The LM paths' single-shard attention (parallel/ring_attention.dense_attention)
materialises the full (T, T) score matrix per head — O(T²) HBM traffic and
memory that caps sequence length on one chip. This kernel streams K/V blocks
through VMEM with the online-softmax accumulators (the same m/l/o algebra the
ring uses *across chips*, here applied *within* a chip's sequence), so peak
memory is O(T·Dh + block²) and the (T, T) matrix never exists.

Forward saves only the per-row log-sum-exp; backward recomputes the
probability blocks in two passes (dq sweeping K blocks, dk/dv sweeping Q
blocks) — the standard flash-attention custom VJP, each pass again never
materialising (T, T).

Block-causal skipping: grid steps with j > i (keys entirely in the future)
compute nothing (`pl.when`), so causal attention does ~half the block work.

No reference counterpart (the reference is CNN-only, SURVEY.md §5.7); this
is a hot-op kernel of the TPU build's long-context axis, complementing ring
attention (which shards T across chips; this kernel serves each shard or the
single-chip case). Dispatch mirrors ops/coded.py: Pallas on TPU backends,
dense jnp fallback elsewhere; interpret mode in CI.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.6 names the Mosaic params class TPUCompilerParams; same kwargs
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

from draco_tpu.ops.coded import use_pallas

NEG_INF = -1e30
_LANE = 128
_FALLBACK_WARNED = set()  # one warning per distinct non-tiling shape


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _fit_block(limit: int, t: int, lane_rule: bool) -> int:
    """Largest legal block size <= limit for a length-t axis: must divide t,
    be a multiple of the 8-row sublane tile, and (key blocks only,
    lane_rule=True) be a whole number of 128-wide lane tiles when wider
    than one. A plain min(limit, t) would demote every t not divisible by
    the default (e.g. t=1536 with bk=1024) to the dense fallback — the
    shrink keeps every t%8==0 length kernel-eligible at the biggest block
    the shape allows (t=768 -> 256 under a 1024 limit). Returns 0 when no
    legal block exists (t%8 != 0); _kernel_eligible then rejects."""
    b = min(limit, t)
    b -= b % 8
    while b >= 8:
        if t % b == 0 and (not lane_rule or b <= _LANE or b % _LANE == 0):
            return b
        b -= 8
    return 0


def _kv_residency_map(bq: int, bk: int, causal: bool):
    """Index map for K/V-row input blocks on a (g, <q-block>, <k-block>)
    grid. Causal: clamp at the diagonal — the kernels' pl.when already
    skips compute for j > (i*bq + bq - 1)//bk (the largest k-block with any
    q_pos >= k_pos entry), but without the clamp Mosaic still DMAs those
    future blocks from HBM every step (~2x the causal pass's traffic).
    Repeating the boundary index instead makes consecutive skipped steps
    fetch nothing (Mosaic elides copies when the block index is unchanged).
    The clamp is the identity on every computed block, so outputs are
    untouched; keep this formula in lockstep with the kernels' guards."""
    if not causal:
        return lambda g, i, j: (g, j, 0)
    return lambda g, i, j: (g, jnp.minimum(j, (i * bq + bq - 1) // bk), 0)


def _q_residency_map(bq: int, bk: int, causal: bool):
    """Index map for Q-row input blocks (q, do, per-row stats) on the dk/dv
    grid (g, <k-block>, <q-block>). Causal: the sweep only computes from the
    first diagonal-touching q block, i_min = (j*bk)//bq — which equals
    ceil((j*bk - bq + 1)/bq), the smallest i with i*bq + bq - 1 >= j*bk —
    so clamp residency there (same elision mechanics as _kv_residency_map)."""
    if not causal:
        return lambda g, j, i: (g, i, 0)
    return lambda g, j, i: (g, jnp.maximum(i, (j * bk) // bq), 0)


def _cols(stat, ncols):
    """Widen a lane-broadcast (bq, _LANE) row statistic to ncols columns.

    Mosaic requires the last dim of every block to be _LANE-aligned, so the
    per-row softmax stats live broadcast across all 128 lanes (every lane of a
    row holds the same value — the layout jax's own TPU flash kernel uses);
    to combine a stat with a (bq, ncols) score block, slice when ncols fits
    inside one lane tile, tile when it spans several.
    """
    if ncols <= _LANE:
        return stat[:, :ncols]
    return jnp.tile(stat, (1, ncols // _LANE))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(scale, nk, bq, bk, causal, q_ref, k_ref, v_ref, o_ref,
                lse_ref, acc_ref, m_ref, l_ref):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)

    # a (i, j) block pair holds >= 1 causal (q_pos >= k_pos) entry iff the
    # block's earliest key is no later than its latest query — comparing raw
    # block indices (j <= i) is only correct when bq == bk. Non-causal
    # (the ring's fully-visible past-owner hops) computes every pair.
    @pl.when((j * bk <= i * bq + bq - 1) if causal else (j >= 0))
    def _compute():
        # matmuls take the input dtype (bf16 inputs ride the fast MXU pass)
        # and accumulate f32 via preferred_element_type — the flash standard;
        # all softmax/accumulator algebra stays f32
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (bq, bk) f32
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[...]  # (bq, _LANE), lane-broadcast
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
        p = jnp.exp(s - _cols(m_cur, bk))
        corr = jnp.exp(m_prev - m_cur)  # (bq, _LANE)
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1)[:, None]
        acc_ref[...] = acc_ref[...] * _cols(corr, acc_ref.shape[1]) + \
            jax.lax.dot(p.astype(v.dtype), v,
                        preferred_element_type=jnp.float32)
        m_ref[...] = m_cur

    @pl.when(j == nk - 1)
    def _flush():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / _cols(l, o_ref.shape[2])).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)


@functools.partial(jax.jit,
                   static_argnames=("scale", "bq", "bk", "causal",
                                    "interpret"))
def _flash_fwd(q, k, v, scale, bq, bk, causal, interpret):
    """q, k, v: (G, T, Dh_padded) f32 (G = B·H folded). ``scale`` comes from
    the TRUE head dim (the lane padding must not change the softmax
    temperature). Returns (o, lse); lse is (G, T) — the kernel emits it
    lane-broadcast (G, T, _LANE) to satisfy Mosaic block tiling and the
    wrapper keeps lane 0."""
    g, t, dh = q.shape
    nq, nk = t // bq, t // bk
    grid = (g, nq, nk)
    kern = functools.partial(_fwd_kernel, scale, nk, bq, bk, causal)
    kv_row = _kv_residency_map(bq, bk, causal)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, dh), kv_row),
            pl.BlockSpec((1, bk, dh), kv_row),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dh), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda g, i, j: (g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, t, dh), q.dtype),
            jax.ShapeDtypeStruct((g, t, _LANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, dh), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _p_block(q_ref, k_ref, lse_ref, scale, causal, i, j):
    """Recompute the masked probability block P = exp(S - lse). lse_ref
    holds the (bq, _LANE) lane-broadcast log-sum-exp."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    bq, bk = s.shape
    if causal:
        q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return jnp.exp(s - _cols(lse_ref[0], bk))


def _dq_kernel(scale, nk, bq, bk, causal, has_dlse, *refs):
    if has_dlse:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, dlse_ref,
         dq_ref, dq_acc) = refs
    else:  # hot path (lse output unused): no dlse stream, no dead add
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref,
         dq_ref, dq_acc) = refs
        dlse_ref = None
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when((j * bk <= i * bq + bq - 1) if causal else (j >= 0))
    def _compute():
        p = _p_block(q_ref, k_ref, lse_ref, scale, causal, i, j)  # (bq,bk) f32
        do = do_ref[0]
        v = v_ref[0]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk) f32
        # d lse_i / d s_ij = p_ij, so an lse cotangent adds p * dlse_i
        dsum = dp - _cols(dcap_ref[0], dp.shape[1])
        if dlse_ref is not None:
            dsum = dsum + _cols(dlse_ref[0], dp.shape[1])
        ds = p * dsum
        dq_acc[...] += jax.lax.dot(
            ds.astype(k_ref.dtype), k_ref[0],
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == nk - 1)
    def _flush():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(scale, nq, bq, bk, causal, has_dlse, *refs):
    if has_dlse:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, dlse_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        dlse_ref = None
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when((i * bq + bq - 1 >= j * bk) if causal else (i >= 0))
    def _compute():
        p = _p_block(q_ref, k_ref, lse_ref, scale, causal, i, j)  # (bq,bk)
        do = do_ref[0]
        v = v_ref[0]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )  # pᵀ · do -> (bk, dh)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        dsum = dp - _cols(dcap_ref[0], dp.shape[1])
        if dlse_ref is not None:
            dsum = dsum + _cols(dlse_ref[0], dp.shape[1])
        ds = p * dsum
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0],
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        ) * scale  # dsᵀ · q -> (bk, dh)

    @pl.when(i == nq - 1)
    def _flush():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "bq", "bk", "causal",
                                    "interpret"))
def _flash_bwd(q, k, v, o, lse, do, dlse, scale, bq, bk, causal, interpret):
    """dlse=None is the hot path (lse output unused): the kernels take one
    fewer input stream and skip the dead add."""
    g, t, dh = q.shape
    nq, nk = t // bq, t // bk
    dcap = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # lane-broadcast the per-row stats so their blocks tile (bq, _LANE)
    lse = jnp.broadcast_to(lse[..., None], (g, t, _LANE))
    dcap = jnp.broadcast_to(dcap[..., None], (g, t, _LANE))
    has_dlse = dlse is not None
    stats = [lse, dcap]
    if has_dlse:
        stats.append(jnp.broadcast_to(dlse.astype(jnp.float32)[..., None],
                                      (g, t, _LANE)))

    def q_row(g, i, j):
        return (g, i, 0)

    k_row = _kv_residency_map(bq, bk, causal)

    stat_specs = [pl.BlockSpec((1, bq, _LANE), q_row)] * len(stats)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale, nk, bq, bk, causal, has_dlse),
        grid=(g, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, dh), q_row),
            pl.BlockSpec((1, bk, dh), k_row),
            pl.BlockSpec((1, bk, dh), k_row),
            pl.BlockSpec((1, bq, dh), q_row),
            *stat_specs,
        ],
        out_specs=pl.BlockSpec((1, bq, dh), q_row),
        out_shape=jax.ShapeDtypeStruct((g, t, dh), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, *stats)

    q_row2 = _q_residency_map(bq, bk, causal)

    def k_row2(g, j, i):
        return (g, j, 0)

    stat_specs2 = [pl.BlockSpec((1, bq, _LANE), q_row2)] * len(stats)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale, nq, bq, bk, causal, has_dlse),
        grid=(g, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, dh), q_row2),
            pl.BlockSpec((1, bk, dh), k_row2),
            pl.BlockSpec((1, bk, dh), k_row2),
            pl.BlockSpec((1, bq, dh), q_row2),
            *stat_specs2,
        ],
        out_specs=[
            pl.BlockSpec((1, bk, dh), k_row2),
            pl.BlockSpec((1, bk, dh), k_row2),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, t, dh), k.dtype),
            jax.ShapeDtypeStruct((g, t, dh), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, dh), jnp.float32),
            pltpu.VMEM((bk, dh), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, *stats)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp cores on (G, T, Dh). Two variants sharing fwd/bwd kernels:
# _flash_core returns o only (the hot path — its backward has no dlse
# stream); _flash_core_lse returns (o, lse) with lse differentiable
# (d lse/d s = softmax), which is what lets the ring composition weight
# and merge per-hop outputs under grad.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, scale, bq, bk, causal, interpret):
    return _flash_fwd(q, k, v, scale, bq, bk, causal, interpret)[0]


def _flash_core_fwd(q, k, v, scale, bq, bk, causal, interpret):
    o, lse = _flash_fwd(q, k, v, scale, bq, bk, causal, interpret)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(scale, bq, bk, causal, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, None, scale, bq, bk, causal,
                      interpret)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core_lse(q, k, v, scale, bq, bk, causal, interpret):
    return _flash_fwd(q, k, v, scale, bq, bk, causal, interpret)


def _flash_core_lse_fwd(q, k, v, scale, bq, bk, causal, interpret):
    o, lse = _flash_fwd(q, k, v, scale, bq, bk, causal, interpret)
    return (o, lse), (q, k, v, o, lse)


def _flash_core_lse_bwd(scale, bq, bk, causal, interpret, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _flash_bwd(q, k, v, o, lse, do, dlse, scale, bq, bk, causal,
                      interpret)


_flash_core_lse.defvjp(_flash_core_lse_fwd, _flash_core_lse_bwd)


# ---------------------------------------------------------------------------
# public entry — AttnFn contract of models/transformer.Block
# ---------------------------------------------------------------------------

def flash_attention(q, k, v, *, block_q: int = 512, block_k: int = 1024,
                    force=None, interpret: bool = False):
    """Causal self-attention. q, k, v: (B, T, H, Dh) — the Block contract
    (attention math upstream is f32; the kernel accumulates f32 regardless).

    The causal mask is offset-invariant for self-attention (q and k share
    positions), so no offset argument is needed. Falls back to the dense
    streaming-softmax path off-TPU, when T doesn't tile, or when T is too
    small to block.
    """
    from draco_tpu.parallel.ring_attention import dense_attention

    b, t, h, dh = q.shape
    bq = _fit_block(block_q, t, lane_rule=False)
    bk = _fit_block(block_k, t, lane_rule=True)
    if not _kernel_eligible(t, bq, bk, dh, force, interpret):
        return dense_attention(q, k, v, causal=True)
    return _run_folded(q, k, v, bq, bk, True, interpret, want_lse=False)


def _kernel_eligible(t, bq, bk, dh, force, interpret) -> bool:
    """Shared kernel-vs-dense dispatch for both public wrappers. Blocks
    (including T itself when it becomes the single block) must honour the
    8-sublane f32 tile, and key blocks wider than a lane tile must be whole
    lane tiles so the lane-broadcast row stats can tile across them (_cols).
    force=True on a non-tiling shape raises — a caller that explicitly
    demanded the O(T·Dh)-memory kernel must not silently get the O(T²)
    dense path (advisor r2); a TPU caller falling back warns once."""
    use = force if force is not None else (use_pallas() or interpret)
    tiling_fail = bool(
        bq < 8 or bk < 8  # _fit_block found no legal block (t % 8 != 0)
        or t % 8 or bq % 8 or bk % 8 or t % bq or t % bk
        or dh > _LANE or (bk > _LANE and bk % _LANE))
    if use and not tiling_fail:
        return True
    constraints = (
        f"need t%8==0, t%bq==0, t%bk==0, blocks%8==0, dh<={_LANE}, "
        f"and bk a multiple of {_LANE} when bk>{_LANE}"
    )
    if force and tiling_fail:
        raise ValueError(
            f"flash_attention(force=True): shape does not tile "
            f"(t={t}, bq={bq}, bk={bk}, dh={dh}; {constraints})"
        )
    if use and tiling_fail:
        key = (t, bq, bk, dh)
        if key not in _FALLBACK_WARNED:
            _FALLBACK_WARNED.add(key)
            warnings.warn(
                f"flash_attention: falling back to dense O(T²) attention "
                f"for non-tiling shape (t={t}, bq={bq}, bk={bk}, "
                f"dh={dh}; {constraints})",
                stacklevel=2,
            )
    return False


def _run_folded(q, k, v, bq, bk, causal, interpret, want_lse):
    """(B,T,H,Dh) qkv -> folded kernel call -> o (B,T,H,Dh), or
    (o, lse (B,T,H)) with a differentiable lse when want_lse."""
    b, t, h, dh = q.shape
    dh_p = _ceil_to(dh, _LANE)

    def fold(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, t, dh)  # (B,T,H,D)->(BH,T,D)
        if dh_p != dh:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, dh_p - dh)))
        return x

    args = (fold(q), fold(k), fold(v), 1.0 / (dh ** 0.5),
            bq, bk, causal, interpret)

    def unfold(o):
        return jnp.moveaxis(o[..., :dh].reshape(b, h, t, dh), 1, 2)

    if not want_lse:
        return unfold(_flash_core(*args))
    o, lse = _flash_core_lse(*args)
    return unfold(o), jnp.moveaxis(lse.reshape(b, h, t), 1, 2)  # (B, T, H)


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             block_q: int = 512, block_k: int = 1024,
                             force=None, interpret: bool = False):
    """(o, lse) pair for the ring composition (parallel/ring_attention.
    ring_flash_attention): lse is the per-row log-sum-exp in (B, T, H), and
    is differentiable (the kernels' VJP carries d lse/d s = softmax), which
    is what lets normalized per-hop outputs merge under grad. Falls back to
    the dense streaming path (with lse) off-TPU or for non-tiling shapes."""
    from draco_tpu.parallel.ring_attention import dense_attention_lse

    b, t, h, dh = q.shape
    bq = _fit_block(block_q, t, lane_rule=False)
    bk = _fit_block(block_k, t, lane_rule=True)
    if not _kernel_eligible(t, bq, bk, dh, force, interpret):
        return dense_attention_lse(q, k, v, causal=causal)
    return _run_folded(q, k, v, bq, bk, causal, interpret, want_lse=True)


def attn_impl_fn(cfg):
    """cfg.attn_impl -> AttnFn for the single-shard LM paths (None = Block's
    dense default). One dispatch point shared by sp_step / pp_step."""
    return flash_attention if cfg.attn_impl == "flash" else None
