"""Blockwise causal flash attention as a Pallas TPU kernel.

The LM paths' single-shard attention (parallel/ring_attention.dense_attention)
materialises the full (T, T) score matrix per head — O(T²) HBM traffic and
memory that caps sequence length on one chip. This kernel streams K/V blocks
through VMEM with the online-softmax accumulators (the same m/l/o algebra the
ring uses *across chips*, here applied *within* a chip's sequence), so peak
memory is O(T·Dh + block²) and the (T, T) matrix never exists.

Forward saves only the per-row log-sum-exp; backward recomputes the
probability blocks in two passes (dq sweeping K blocks, dk/dv sweeping Q
blocks) — the standard flash-attention custom VJP, each pass again never
materialising (T, T).

Block-causal skipping: grid steps with j > i (keys entirely in the future)
compute nothing (`pl.when`), so causal attention does ~half the block work.

No reference counterpart (the reference is CNN-only, SURVEY.md §5.7); this
is a hot-op kernel of the TPU build's long-context axis, complementing ring
attention (which shards T across chips; this kernel serves each shard or the
single-chip case). Dispatch mirrors ops/coded.py: Pallas on TPU backends,
dense jnp fallback elsewhere; interpret mode in CI.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from draco_tpu.ops.coded import use_pallas

NEG_INF = -1e30
_LANE = 128
_FALLBACK_WARNED = set()  # one warning per distinct non-tiling shape


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _cols(stat, ncols):
    """Widen a lane-broadcast (bq, _LANE) row statistic to ncols columns.

    Mosaic requires the last dim of every block to be _LANE-aligned, so the
    per-row softmax stats live broadcast across all 128 lanes (every lane of a
    row holds the same value — the layout jax's own TPU flash kernel uses);
    to combine a stat with a (bq, ncols) score block, slice when ncols fits
    inside one lane tile, tile when it spans several.
    """
    if ncols <= _LANE:
        return stat[:, :ncols]
    return jnp.tile(stat, (1, ncols // _LANE))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(scale, nk, bq, bk, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)

    # a (i, j) block pair holds >= 1 causal (q_pos >= k_pos) entry iff the
    # block's earliest key is no later than its latest query — comparing raw
    # block indices (j <= i) is only correct when bq == bk
    @pl.when(j * bk <= i * bq + bq - 1)
    def _compute():
        # matmuls take the input dtype (bf16 inputs ride the fast MXU pass)
        # and accumulate f32 via preferred_element_type — the flash standard;
        # all softmax/accumulator algebra stays f32
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (bq, bk) f32
        q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[...]  # (bq, _LANE), lane-broadcast
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
        p = jnp.exp(s - _cols(m_cur, bk))
        corr = jnp.exp(m_prev - m_cur)  # (bq, _LANE)
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1)[:, None]
        acc_ref[...] = acc_ref[...] * _cols(corr, acc_ref.shape[1]) + \
            jax.lax.dot(p.astype(v.dtype), v,
                        preferred_element_type=jnp.float32)
        m_ref[...] = m_cur

    @pl.when(j == nk - 1)
    def _flush():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / _cols(l, o_ref.shape[2])).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)


@functools.partial(jax.jit,
                   static_argnames=("scale", "bq", "bk", "interpret"))
def _flash_fwd(q, k, v, scale, bq, bk, interpret):
    """q, k, v: (G, T, Dh_padded) f32 (G = B·H folded). ``scale`` comes from
    the TRUE head dim (the lane padding must not change the softmax
    temperature). Returns (o, lse); lse is (G, T) — the kernel emits it
    lane-broadcast (G, T, _LANE) to satisfy Mosaic block tiling and the
    wrapper keeps lane 0."""
    g, t, dh = q.shape
    nq, nk = t // bq, t // bk
    grid = (g, nq, nk)
    kern = functools.partial(_fwd_kernel, scale, nk, bq, bk)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, dh), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda g, i, j: (g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dh), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda g, i, j: (g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, t, dh), q.dtype),
            jax.ShapeDtypeStruct((g, t, _LANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, dh), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _p_block(q_ref, k_ref, lse_ref, scale, i, j):
    """Recompute the masked probability block P = exp(S - lse). lse_ref
    holds the (bq, _LANE) lane-broadcast log-sum-exp."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    bq, bk = s.shape
    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return jnp.exp(s - _cols(lse_ref[0], bk))


def _dq_kernel(scale, nk, bq, bk, q_ref, k_ref, v_ref, do_ref, lse_ref,
               dcap_ref, dq_ref, dq_acc):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(j * bk <= i * bq + bq - 1)
    def _compute():
        p = _p_block(q_ref, k_ref, lse_ref, scale, i, j)  # (bq, bk) f32
        do = do_ref[0]
        v = v_ref[0]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk) f32
        ds = p * (dp - _cols(dcap_ref[0], dp.shape[1]))
        dq_acc[...] += jax.lax.dot(
            ds.astype(k_ref.dtype), k_ref[0],
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == nk - 1)
    def _flush():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(scale, nq, bq, bk, q_ref, k_ref, v_ref, do_ref, lse_ref,
                dcap_ref, dk_ref, dv_ref, dk_acc, dv_acc):
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(i * bq + bq - 1 >= j * bk)
    def _compute():
        p = _p_block(q_ref, k_ref, lse_ref, scale, i, j)  # (bq, bk) f32
        do = do_ref[0]
        v = v_ref[0]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )  # pᵀ · do -> (bk, dh)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - _cols(dcap_ref[0], dp.shape[1]))
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0],
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        ) * scale  # dsᵀ · q -> (bk, dh)

    @pl.when(i == nq - 1)
    def _flush():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "bq", "bk", "interpret"))
def _flash_bwd(q, k, v, o, lse, do, scale, bq, bk, interpret):
    g, t, dh = q.shape
    nq, nk = t // bq, t // bk
    dcap = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # lane-broadcast the per-row stats so their blocks tile (bq, _LANE)
    lse = jnp.broadcast_to(lse[..., None], (g, t, _LANE))
    dcap = jnp.broadcast_to(dcap[..., None], (g, t, _LANE))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale, nk, bq, bk),
        grid=(g, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, dh), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bq, dh), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda g, i, j: (g, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, dh), lambda g, i, j: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((g, t, dh), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, dcap)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale, nq, bq, bk),
        grid=(g, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda g, j, i: (g, i, 0)),
            pl.BlockSpec((1, bk, dh), lambda g, j, i: (g, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda g, j, i: (g, j, 0)),
            pl.BlockSpec((1, bq, dh), lambda g, j, i: (g, i, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda g, j, i: (g, i, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda g, j, i: (g, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, dh), lambda g, j, i: (g, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda g, j, i: (g, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, t, dh), k.dtype),
            jax.ShapeDtypeStruct((g, t, dh), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, dh), jnp.float32),
            pltpu.VMEM((bk, dh), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, dcap)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp core on (G, T, Dh)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, scale, bq, bk, interpret):
    o, _ = _flash_fwd(q, k, v, scale, bq, bk, interpret)
    return o


def _flash_core_fwd(q, k, v, scale, bq, bk, interpret):
    o, lse = _flash_fwd(q, k, v, scale, bq, bk, interpret)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(scale, bq, bk, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, scale, bq, bk, interpret)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# ---------------------------------------------------------------------------
# public entry — AttnFn contract of models/transformer.Block
# ---------------------------------------------------------------------------

def flash_attention(q, k, v, *, block_q: int = 128, block_k: int = 128,
                    force=None, interpret: bool = False):
    """Causal self-attention. q, k, v: (B, T, H, Dh) — the Block contract
    (attention math upstream is f32; the kernel accumulates f32 regardless).

    The causal mask is offset-invariant for self-attention (q and k share
    positions), so no offset argument is needed. Falls back to the dense
    streaming-softmax path off-TPU, when T doesn't tile, or when T is too
    small to block.
    """
    from draco_tpu.parallel.ring_attention import dense_attention

    b, t, h, dh = q.shape
    bq = min(block_q, t)
    bk = min(block_k, t)
    use = force if force is not None else (use_pallas() or interpret)
    # blocks (including T itself when it becomes the single block) must
    # honour the 8-sublane f32 tile
    # key blocks wider than a lane tile must be whole lane tiles so the
    # lane-broadcast row stats can be tiled across them (_cols)
    bad_lane = bk > _LANE and bk % _LANE
    if (not use or t % 8 or bq % 8 or bk % 8 or t % bq or t % bk
            or dh > _LANE or bad_lane):
        tiling_fail = bool(t % 8 or bq % 8 or bk % 8 or t % bq or t % bk
                           or dh > _LANE or bad_lane)
        constraints = (
            f"need t%8==0, t%bq==0, t%bk==0, blocks%8==0, dh<={_LANE}, "
            f"and bk a multiple of {_LANE} when bk>{_LANE}"
        )
        if force and tiling_fail:
            # a caller that explicitly demanded the O(T·Dh)-memory kernel
            # must not silently get the O(T²) dense path (advisor r2)
            raise ValueError(
                f"flash_attention(force=True): shape does not tile "
                f"(t={t}, bq={bq}, bk={bk}, dh={dh}; {constraints})"
            )
        if use and tiling_fail:
            key = (t, bq, bk, dh)
            if key not in _FALLBACK_WARNED:
                _FALLBACK_WARNED.add(key)
                warnings.warn(
                    f"flash_attention: falling back to dense O(T²) attention "
                    f"for non-tiling shape (t={t}, bq={bq}, bk={bk}, "
                    f"dh={dh}; {constraints})",
                    stacklevel=2,
                )
        return dense_attention(q, k, v, causal=True)

    dh_p = _ceil_to(dh, _LANE)

    def fold(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, t, dh)  # (B,T,H,D)->(BH,T,D)
        if dh_p != dh:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, dh_p - dh)))
        return x

    o = _flash_core(fold(q), fold(k), fold(v), 1.0 / (dh ** 0.5),
                    bq, bk, interpret)
    o = o[..., :dh].reshape(b, h, t, dh)
    return jnp.moveaxis(o, 1, 2)  # (B, T, H, Dh)


def attn_impl_fn(cfg):
    """cfg.attn_impl -> AttnFn for the single-shard LM paths (None = Block's
    dense default). One dispatch point shared by sp_step / pp_step."""
    return flash_attention if cfg.attn_impl == "flash" else None
