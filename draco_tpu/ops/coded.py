"""Fused complex-arithmetic kernels for the cyclic gradient code.

Three ops cover the O(n·d) work of a cyclic encode/decode step (reference:
the einsum encode in src/worker/cyclic_worker.py:172-175 and the R-matvecs in
src/master/cyclic_master.py:154,171 around the native s×s solve of
src/c_coding.cpp):

  * ``complex_matmul``    — encode:      (Wr + i·Wi) @ G          for real G
  * ``complex_project``   — decode in:   (Rr + i·Ri) @ f          for real f
  * ``complex_recombine`` — decode out:  Re[(vr + i·vi)ᵀ (Rr + i·Ri)]

All three stream the big (n, d) operand exactly once; the complex pairing is
done in VMEM.

Dispatch: **jnp/XLA by default, everywhere** — measured on a real TPU v5e
(tools/tpu_kernel_check.py, baselines_out/tpu_kernels.json): at ResNet-18
gradient size (n=8, d≈11.2M) XLA's own lowering of the unfused matmul pairs
runs at near HBM-bound speed (encode 2.36 ms, project 0.74 ms, recombine
1.40 ms) while the hand-tiled Pallas kernels are 2.8–4.5× slower (encode
6.6 ms, project 3.3 ms, recombine 4.4 ms): with only n=8 sublanes per block
the sequential 1-D grid cannot saturate HBM, and XLA already fuses the
neighbouring elementwise work. The Pallas paths remain available via
``force=True`` (and run in interpret mode in CI) as regression references
and for future re-tuning on other topologies; production code takes the XLA
path, which is the north-star-sanctioned lowering ("XLA/Pallas").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

PREC = jax.lax.Precision.HIGHEST

# d-axis tile: 8 MXU lanes' worth of f32 per row block; (n≤64, 4096)·f32
# blocks keep well under VMEM even with two inputs + two outputs resident.
TILE_D = 4096


def use_pallas() -> bool:
    """True when the attached backend can lower the Pallas kernels natively
    (a TPU, including TPUs behind plugin backends that report a non-"tpu"
    platform name). Recorded by tools/tpu_kernel_check.py in its report —
    it does NOT drive production dispatch, which defaults to the XLA path
    after hardware measurement (see module docstring)."""
    if jax.default_backend() == "tpu":
        return True
    try:
        kind = jax.devices()[0].device_kind or ""
    except Exception:
        return False
    return "tpu" in kind.lower()


def _pad_d(x: jnp.ndarray, tile: int) -> jnp.ndarray:
    d = x.shape[-1]
    pad = (-d) % tile
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    return x


# --------------------------------------------------------------------------
# encode: (Wr + i Wi) @ G, G real (n, d) -> two (n, d) outputs, one read of G
# --------------------------------------------------------------------------

def _matmul_kernel(wr_ref, wi_ref, g_ref, or_ref, oi_ref):
    g = g_ref[:]
    or_ref[:] = jnp.dot(wr_ref[:], g, preferred_element_type=jnp.float32, precision=PREC)
    oi_ref[:] = jnp.dot(wi_ref[:], g, preferred_element_type=jnp.float32, precision=PREC)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _matmul_pallas(w_re, w_im, g, interpret=False):
    n, d = g.shape
    gp = _pad_d(g, TILE_D)
    dp = gp.shape[-1]
    grid = (dp // TILE_D,)
    out_re, out_im = pl.pallas_call(
        _matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((w_re.shape[0], n), lambda j: (0, 0)),
            pl.BlockSpec((w_im.shape[0], n), lambda j: (0, 0)),
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((w_re.shape[0], TILE_D), lambda j: (0, j)),
            pl.BlockSpec((w_re.shape[0], TILE_D), lambda j: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((w_re.shape[0], dp), jnp.float32),
            jax.ShapeDtypeStruct((w_re.shape[0], dp), jnp.float32),
        ],
        interpret=interpret,
    )(w_re, w_im, gp)
    return out_re[:, :d], out_im[:, :d]


def complex_matmul(w_re, w_im, g, *, force=None, interpret=False):
    """(Wr + i·Wi) @ G for real G: returns (re, im).

    force: None = XLA (measured faster on TPU, see module docstring);
    True = Pallas kernel.
    """
    w_re, w_im, g = jnp.asarray(w_re), jnp.asarray(w_im), jnp.asarray(g)
    if force is True or interpret:
        return _matmul_pallas(w_re, w_im, g, interpret=interpret)
    return (
        jnp.matmul(w_re, g, precision=PREC),
        jnp.matmul(w_im, g, precision=PREC),
    )


# --------------------------------------------------------------------------
# project: (Rr + i Ri) @ f, f real (d,) -> two (n,) outputs; reduction over d
# accumulated per 128-wide lane group across sequential grid steps, both R's
# read once. The (n, 128) output block is a native f32 tile — an (n, 1)
# accumulator block (previous design) made Mosaic allocate scoped-vmem stack
# per grid step, which OOMed at ResNet-18 size (d≈11.2M, 2730 steps) on a
# real v5e; lane partials keep scoped vmem flat in d. Final 128-lane sum
# happens in XLA outside the kernel.
# --------------------------------------------------------------------------

def _project_kernel(d, rr_ref, ri_ref, f_ref, er_ref, ei_ref):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        er_ref[:] = jnp.zeros_like(er_ref)
        ei_ref[:] = jnp.zeros_like(ei_ref)

    n = rr_ref.shape[0]
    base = j * TILE_D
    cols = base + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_D), 1)
    f = jnp.where(cols < d, f_ref[:], 0.0)  # mask the ragged edge tile
    er_ref[:] += (rr_ref[:] * f).reshape(n, TILE_D // 128, 128).sum(axis=1)
    ei_ref[:] += (ri_ref[:] * f).reshape(n, TILE_D // 128, 128).sum(axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _project_pallas(r_re, r_im, f, interpret=False):
    n, d = r_re.shape
    rrp = _pad_d(r_re, TILE_D)
    rip = _pad_d(r_im, TILE_D)
    fp = _pad_d(f[None, :], TILE_D)
    dp = rrp.shape[-1]
    grid = (dp // TILE_D,)
    e_re, e_im = pl.pallas_call(
        functools.partial(_project_kernel, d),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
            pl.BlockSpec((1, TILE_D), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((n, 128), lambda j: (0, 0)),
            pl.BlockSpec((n, 128), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 128), jnp.float32),
            jax.ShapeDtypeStruct((n, 128), jnp.float32),
        ],
        interpret=interpret,
    )(rrp, rip, fp)
    return e_re.sum(axis=1), e_im.sum(axis=1)


def complex_project(r_re, r_im, f, *, force=None, interpret=False):
    """(Rr + i·Ri) @ f for real f (d,): returns (re, im) of shape (n,)."""
    r_re, r_im, f = jnp.asarray(r_re), jnp.asarray(r_im), jnp.asarray(f)
    if force is True or interpret:
        return _project_pallas(r_re, r_im, f, interpret=interpret)
    return (
        jnp.matmul(r_re, f, precision=PREC),
        jnp.matmul(r_im, f, precision=PREC),
    )


# --------------------------------------------------------------------------
# recombine: Re[(vr + i vi)^T (Rr + i Ri)] = vr^T Rr - vi^T Ri, one pass
# --------------------------------------------------------------------------

def _recombine_kernel(vr_ref, vi_ref, rr_ref, ri_ref, out_ref):
    out_ref[:] = jnp.dot(vr_ref[:], rr_ref[:], preferred_element_type=jnp.float32, precision=PREC) - jnp.dot(
        vi_ref[:], ri_ref[:], preferred_element_type=jnp.float32, precision=PREC
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _recombine_pallas(v_re, v_im, r_re, r_im, interpret=False):
    n, d = r_re.shape
    rrp = _pad_d(r_re, TILE_D)
    rip = _pad_d(r_im, TILE_D)
    dp = rrp.shape[-1]
    grid = (dp // TILE_D,)
    out = pl.pallas_call(
        _recombine_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n), lambda j: (0, 0)),
            pl.BlockSpec((1, n), lambda j: (0, 0)),
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, TILE_D), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, dp), jnp.float32),
        interpret=interpret,
    )(v_re[None, :], v_im[None, :], rrp, rip)
    return out[0, :d]


def complex_recombine(v_re, v_im, r_re, r_im, *, force=None, interpret=False):
    """Re[(vr + i·vi)ᵀ (Rr + i·Ri)]: returns real (d,)."""
    v_re, v_im = jnp.asarray(v_re), jnp.asarray(v_im)
    r_re, r_im = jnp.asarray(r_re), jnp.asarray(r_im)
    if force is True or interpret:
        return _recombine_pallas(v_re, v_im, r_re, r_im, interpret=interpret)
    return jnp.matmul(v_re, r_re, precision=PREC) - jnp.matmul(v_im, r_im, precision=PREC)
