"""Fused complex-arithmetic kernels for the cyclic gradient code.

Three ops cover the O(n·d) work of a cyclic encode/decode step (reference:
the einsum encode in src/worker/cyclic_worker.py:172-175 and the R-matvecs in
src/master/cyclic_master.py:154,171 around the native s×s solve of
src/c_coding.cpp):

  * ``complex_matmul``    — encode:      (Wr + i·Wi) @ G          for real G
  * ``complex_project``   — decode in:   (Rr + i·Ri) @ f          for real f
  * ``complex_recombine`` — decode out:  Re[(vr + i·vi)ᵀ (Rr + i·Ri)]

All three stream the big (n, d) operand exactly once; the complex pairing is
done in VMEM. Without fusion each complex product lowers to 2–4 independent
XLA matmuls that each re-read the operand from HBM.

Dispatch: Pallas on TPU, jnp elsewhere (tests run both and compare; the
kernels are also exercised in Pallas interpret mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

PREC = jax.lax.Precision.HIGHEST

# d-axis tile: 8 MXU lanes' worth of f32 per row block; (n≤64, 4096)·f32
# blocks keep well under VMEM even with two inputs + two outputs resident.
TILE_D = 4096


def use_pallas() -> bool:
    if jax.default_backend() == "tpu":
        return True
    # TPU chips reached through plugin backends (e.g. the dev tunnel) report
    # a non-"tpu" platform name but a TPU device kind
    try:
        kind = jax.devices()[0].device_kind or ""
    except Exception:
        return False
    return "tpu" in kind.lower()


def _pad_d(x: jnp.ndarray, tile: int) -> jnp.ndarray:
    d = x.shape[-1]
    pad = (-d) % tile
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    return x


# --------------------------------------------------------------------------
# encode: (Wr + i Wi) @ G, G real (n, d) -> two (n, d) outputs, one read of G
# --------------------------------------------------------------------------

def _matmul_kernel(wr_ref, wi_ref, g_ref, or_ref, oi_ref):
    g = g_ref[:]
    or_ref[:] = jnp.dot(wr_ref[:], g, preferred_element_type=jnp.float32, precision=PREC)
    oi_ref[:] = jnp.dot(wi_ref[:], g, preferred_element_type=jnp.float32, precision=PREC)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _matmul_pallas(w_re, w_im, g, interpret=False):
    n, d = g.shape
    gp = _pad_d(g, TILE_D)
    dp = gp.shape[-1]
    grid = (dp // TILE_D,)
    out_re, out_im = pl.pallas_call(
        _matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((w_re.shape[0], n), lambda j: (0, 0)),
            pl.BlockSpec((w_im.shape[0], n), lambda j: (0, 0)),
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((w_re.shape[0], TILE_D), lambda j: (0, j)),
            pl.BlockSpec((w_re.shape[0], TILE_D), lambda j: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((w_re.shape[0], dp), jnp.float32),
            jax.ShapeDtypeStruct((w_re.shape[0], dp), jnp.float32),
        ],
        interpret=interpret,
    )(w_re, w_im, gp)
    return out_re[:, :d], out_im[:, :d]


def complex_matmul(w_re, w_im, g, *, force=None, interpret=False):
    """(Wr + i·Wi) @ G for real G: returns (re, im).

    force: None = auto (Pallas on TPU), True/False to override.
    """
    w_re, w_im, g = jnp.asarray(w_re), jnp.asarray(w_im), jnp.asarray(g)
    if force is True or interpret or (force is None and use_pallas()):
        return _matmul_pallas(w_re, w_im, g, interpret=interpret)
    return (
        jnp.matmul(w_re, g, precision=PREC),
        jnp.matmul(w_im, g, precision=PREC),
    )


# --------------------------------------------------------------------------
# project: (Rr + i Ri) @ f, f real (d,) -> two (n,) outputs; reduction over d
# accumulated across sequential grid steps, both R's read once
# --------------------------------------------------------------------------

def _project_kernel(d, rr_ref, ri_ref, f_ref, er_ref, ei_ref):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        er_ref[:] = jnp.zeros_like(er_ref)
        ei_ref[:] = jnp.zeros_like(ei_ref)

    base = j * TILE_D
    cols = base + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_D), 1)
    f = jnp.where(cols < d, f_ref[:], 0.0)  # mask the ragged edge tile
    er_ref[:] += jnp.dot(rr_ref[:], f.T, preferred_element_type=jnp.float32, precision=PREC)
    ei_ref[:] += jnp.dot(ri_ref[:], f.T, preferred_element_type=jnp.float32, precision=PREC)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _project_pallas(r_re, r_im, f, interpret=False):
    n, d = r_re.shape
    rrp = _pad_d(r_re, TILE_D)
    rip = _pad_d(r_im, TILE_D)
    fp = _pad_d(f[None, :], TILE_D)
    dp = rrp.shape[-1]
    grid = (dp // TILE_D,)
    e_re, e_im = pl.pallas_call(
        functools.partial(_project_kernel, d),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
            pl.BlockSpec((1, TILE_D), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((n, 1), lambda j: (0, 0)),
            pl.BlockSpec((n, 1), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(rrp, rip, fp)
    return e_re[:, 0], e_im[:, 0]


def complex_project(r_re, r_im, f, *, force=None, interpret=False):
    """(Rr + i·Ri) @ f for real f (d,): returns (re, im) of shape (n,)."""
    r_re, r_im, f = jnp.asarray(r_re), jnp.asarray(r_im), jnp.asarray(f)
    if force is True or interpret or (force is None and use_pallas()):
        return _project_pallas(r_re, r_im, f, interpret=interpret)
    return (
        jnp.matmul(r_re, f, precision=PREC),
        jnp.matmul(r_im, f, precision=PREC),
    )


# --------------------------------------------------------------------------
# recombine: Re[(vr + i vi)^T (Rr + i Ri)] = vr^T Rr - vi^T Ri, one pass
# --------------------------------------------------------------------------

def _recombine_kernel(vr_ref, vi_ref, rr_ref, ri_ref, out_ref):
    out_ref[:] = jnp.dot(vr_ref[:], rr_ref[:], preferred_element_type=jnp.float32, precision=PREC) - jnp.dot(
        vi_ref[:], ri_ref[:], preferred_element_type=jnp.float32, precision=PREC
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _recombine_pallas(v_re, v_im, r_re, r_im, interpret=False):
    n, d = r_re.shape
    rrp = _pad_d(r_re, TILE_D)
    rip = _pad_d(r_im, TILE_D)
    dp = rrp.shape[-1]
    grid = (dp // TILE_D,)
    out = pl.pallas_call(
        _recombine_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n), lambda j: (0, 0)),
            pl.BlockSpec((1, n), lambda j: (0, 0)),
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
            pl.BlockSpec((n, TILE_D), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, TILE_D), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, dp), jnp.float32),
        interpret=interpret,
    )(v_re[None, :], v_im[None, :], rrp, rip)
    return out[0, :d]


def complex_recombine(v_re, v_im, r_re, r_im, *, force=None, interpret=False):
    """Re[(vr + i·vi)ᵀ (Rr + i·Ri)]: returns real (d,)."""
    v_re, v_im = jnp.asarray(v_re), jnp.asarray(v_im)
    r_re, r_im = jnp.asarray(r_re), jnp.asarray(r_im)
    if force is True or interpret or (force is None and use_pallas()):
        return _recombine_pallas(v_re, v_im, r_re, r_im, interpret=interpret)
    return jnp.matmul(v_re, r_re, precision=PREC) - jnp.matmul(v_im, r_im, precision=PREC)
