"""Pallas TPU kernels for the framework's hot ops.

The coded-DP data path is bandwidth-bound, not FLOP-bound: n (workers) is
tiny, d (gradient dimension) is millions, so every encode/decode product is a
skinny matmul whose cost is streaming the (n, d) gradient matrix through HBM.
The kernels here fuse the real/imag pairs of each complex product into a
single pass over the data — one HBM read where naive XLA lowering takes two.

Reference parity note: these replace the role of the reference's native
decoder module (src/c_coding.cpp) on the d-dimensional products; the tiny
s×s / m×m solves stay in jnp.linalg (SURVEY.md §2.2).
"""

from draco_tpu.ops.coded import (
    complex_matmul,
    complex_project,
    complex_recombine,
    use_pallas,
)

__all__ = [
    "complex_matmul",
    "complex_project",
    "complex_recombine",
    "use_pallas",
]
