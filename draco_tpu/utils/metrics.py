"""Structured metric emission (replaces the reference's print-to-stdout
observability, SURVEY.md §5.5) while keeping the reference's segment names —
fetch/comp/encode/comm/decode/update wall-clock splits
(cyclic_worker.py:154-156, baseline_master.py:145) — so per-step timing is
comparable against BASELINE.md."""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional


class MetricWriter:
    """JSONL metrics to ``train_dir/metrics.jsonl`` + human lines to stdout."""

    def __init__(self, train_dir: Optional[str], quiet: bool = False):
        self._fh = None
        self._quiet = quiet
        if train_dir:
            os.makedirs(train_dir, exist_ok=True)
            self._fh = open(os.path.join(train_dir, "metrics.jsonl"), "a")

    def write(self, record: dict):
        record = dict(record, time=time.time())
        if self._fh:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        if not self._quiet:
            step = record.get("step", "?")
            body = ", ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in record.items()
                if k not in ("step", "time")
            )
            print(f"Step: {step}, {body}", file=sys.stdout, flush=True)

    def close(self):
        if self._fh:
            self._fh.close()


class Segments:
    """Wall-clock segment timer with the reference's phase names."""

    def __init__(self):
        self.t = {}
        self._start = None
        self._name = None

    def begin(self, name: str):
        self._name, self._start = name, time.time()

    def end(self):
        if self._name is not None:
            self.t[self._name] = self.t.get(self._name, 0.0) + time.time() - self._start
            self._name = None

    def as_dict(self, prefix: str = "t_"):
        return {prefix + k: round(v, 6) for k, v in self.t.items()}
