"""Structured metric emission (replaces the reference's print-to-stdout
observability, SURVEY.md §5.5) while keeping the reference's segment names —
fetch/comp/encode/comm/decode/update wall-clock splits
(cyclic_worker.py:154-156, baseline_master.py:145) — so per-step timing is
comparable against BASELINE.md."""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import numpy as np


class MetricWriter:
    """JSONL metrics to ``train_dir/metrics.jsonl`` + human lines to stdout."""

    def __init__(self, train_dir: Optional[str], quiet: bool = False):
        self._fh = None
        self._quiet = quiet
        if train_dir:
            os.makedirs(train_dir, exist_ok=True)
            self._fh = open(os.path.join(train_dir, "metrics.jsonl"), "a")

    def write(self, record: dict):
        record = dict(record, time=time.time())
        if self._fh:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        if not self._quiet:
            step = record.get("step", "?")
            body = ", ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in record.items()
                if k not in ("step", "time")
            )
            print(f"Step: {step}, {body}", file=sys.stdout, flush=True)

    def close(self):
        if self._fh:
            self._fh.close()


class DeferredMetricWriter:
    """Chunk-boundary materialization for the scan-fused trainer loop.

    The chunked loop (trainer._run_chunked) hands each chunk's (K, m) device
    metrics block over right after dispatch via :meth:`defer` — no device
    fetch, no host sync. Only :meth:`flush` (called at log/eval/checkpoint
    boundaries) converts the pending blocks to host floats and writes the
    per-step records through the wrapped :class:`MetricWriter`. The JSONL
    schema and the reference segment names are unchanged; only WHEN the
    device→host fetch happens moves, which is the whole point: in steady
    state the host never blocks on the device between chunks.
    """

    def __init__(self, writer: MetricWriter):
        self._writer = writer
        # (steps, names, device block, per-chunk extras)
        self._pending: list = []
        self.last: dict = {}  # most recent materialized record (any step)

    @property
    def depth(self) -> int:
        return len(self._pending)

    def defer(self, steps, names, block, extras: Optional[dict] = None):
        """Queue a chunk: ``block[i, j]`` is metric ``names[j]`` at
        ``steps[i]``. ``extras`` maps key -> scalar (broadcast over the
        chunk) or per-step sequence; values must already be host data."""
        self._pending.append((list(steps), tuple(names), block, extras or {}))

    def sync(self) -> None:
        """Execution barrier: device→host fetch of one element of the
        NEWEST pending block. ``jax.block_until_ready`` only awaits dispatch
        on remote-dispatch backends (utils/timing.py, PERF.md §0); an actual
        transfer is the one portable way to await execution, and chunks run
        in program order, so the newest block landing means every pending
        chunk has executed. No-op when nothing is pending."""
        if self._pending:
            np.asarray(self._pending[-1][2][-1, 0])

    def flush(self, should_log=None, common: Optional[dict] = None) -> dict:
        """Materialize every pending chunk (THE device fetch) and write the
        records for steps where ``should_log(step)`` (default: all).
        ``common`` merges into every flushed record (e.g. the amortized
        t_comp known only at the sync point). Returns the last record."""
        for steps, names, block, extras in self._pending:
            vals = np.asarray(block)  # blocks until the chunk has executed
            for i, step in enumerate(steps):
                rec = {"step": step}
                rec.update(
                    {k: float(vals[i, j]) for j, k in enumerate(names)}
                )
                for k, v in extras.items():
                    rec[k] = float(v[i]) if np.ndim(v) else float(v)
                if common:
                    rec.update(common)
                self.last = rec
                if should_log is None or should_log(step):
                    self._writer.write(rec)
        self._pending = []
        return self.last


class Segments:
    """Wall-clock segment timer with the reference's phase names."""

    def __init__(self):
        self.t = {}
        self._start = None
        self._name = None

    def begin(self, name: str):
        self._name, self._start = name, time.time()

    def end(self):
        if self._name is not None:
            self.t[self._name] = self.t.get(self._name, 0.0) + time.time() - self._start
            self._name = None

    def as_dict(self, prefix: str = "t_"):
        return {prefix + k: round(v, 6) for k, v in self.t.items()}
