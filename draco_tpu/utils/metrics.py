"""Structured metric emission (replaces the reference's print-to-stdout
observability, SURVEY.md §5.5) while keeping the reference's segment names —
fetch/comp/encode/comm/decode/update wall-clock splits
(cyclic_worker.py:154-156, baseline_master.py:145) — so per-step timing is
comparable against BASELINE.md."""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import numpy as np

from draco_tpu.obs.forensics import record_value


class MetricWriter:
    """JSONL metrics to ``train_dir/metrics.jsonl`` + human lines to stdout.

    Records are BUFFERED: ``write`` appends to an in-memory list and the
    file is touched only at :meth:`flush` (called by the loops at their
    flush/eval/checkpoint boundaries and by the DeferredMetricWriter), when
    ``buffer_records`` lines have accumulated, or on :meth:`close` — one
    write+fsync-sized syscall burst per boundary instead of one per record,
    matching the chunked loops' host-dark steady state. ``close()`` always
    drains the buffer, so the tail of an interrupted-but-closed run is
    never lost; ``buffer_records=1`` restores per-record flushing for
    callers that tail the file live.
    """

    def __init__(self, train_dir: Optional[str], quiet: bool = False,
                 buffer_records: int = 64):
        self._fh = None
        self._quiet = quiet
        self._buf: list = []
        self._buffer_records = max(int(buffer_records), 1)
        if train_dir:
            os.makedirs(train_dir, exist_ok=True)
            self._fh = open(os.path.join(train_dir, "metrics.jsonl"), "a")

    def write(self, record: dict):
        record = dict(record, time=time.time())
        if self._fh:
            self._buf.append(json.dumps(record))
            if len(self._buf) >= self._buffer_records:
                self.flush()
        if not self._quiet:
            step = record.get("step", "?")
            body = ", ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in record.items()
                if k not in ("step", "time")
            )
            print(f"Step: {step}, {body}", file=sys.stdout, flush=True)

    def flush(self):
        """Drain the buffer to disk (loops call this at flush boundaries)."""
        if self._fh and self._buf:
            self._fh.write("\n".join(self._buf) + "\n")
            self._fh.flush()
            self._buf = []

    def close(self):
        if self._fh:
            self.flush()
            self._fh.close()
            self._fh = None


class DeferredMetricWriter:
    """Chunk-boundary materialization for the scan-fused trainer loop.

    The chunked loop (trainer._run_chunked) hands each chunk's (K, m) device
    metrics block over right after dispatch via :meth:`defer` — no device
    fetch, no host sync. Only :meth:`flush` (called at log/eval/checkpoint
    boundaries) converts the pending blocks to host floats and writes the
    per-step records through the wrapped :class:`MetricWriter`. The JSONL
    schema and the reference segment names are unchanged; only WHEN the
    device→host fetch happens moves, which is the whole point: in steady
    state the host never blocks on the device between chunks.

    ``observer`` (optional callable) sees EVERY materialized record at
    flush time, logged or not — the run heartbeat (obs/heartbeat.py) hooks
    here to accumulate decode-health precision/recall without adding any
    device fetch beyond the flush's own block materialization.
    """

    def __init__(self, writer: MetricWriter, observer=None):
        self._writer = writer
        self._observer = observer
        # (steps, names, device block, per-chunk extras)
        self._pending: list = []
        self.last: dict = {}  # most recent materialized record (any step)

    @property
    def depth(self) -> int:
        return len(self._pending)

    def defer(self, steps, names, block, extras: Optional[dict] = None):
        """Queue a chunk: ``block[i, j]`` is metric ``names[j]`` at
        ``steps[i]``. ``extras`` maps key -> scalar (broadcast over the
        chunk) or per-step sequence; values must already be host data."""
        self._pending.append((list(steps), tuple(names), block, extras or {}))

    def sync(self) -> None:
        """Execution barrier: device→host fetch of one element of the
        NEWEST pending block. ``jax.block_until_ready`` only awaits dispatch
        on remote-dispatch backends (utils/timing.py, PERF.md §0); an actual
        transfer is the one portable way to await execution, and chunks run
        in program order, so the newest block landing means every pending
        chunk has executed. No-op when nothing is pending."""
        if self._pending:
            np.asarray(self._pending[-1][2][-1, 0])

    def flush(self, should_log=None, common: Optional[dict] = None) -> dict:
        """Materialize every pending chunk (THE device fetch) and write the
        records for steps where ``should_log(step)`` (default: all).
        ``common`` merges into every flushed record (e.g. the amortized
        t_comp known only at the sync point). Returns the last record."""
        for steps, names, block, extras in self._pending:
            vals = np.asarray(block)  # blocks until the chunk has executed
            for i, step in enumerate(steps):
                rec = {"step": step}
                # record_value: packed forensics bitmask columns become
                # exact integer words (a float()/JSON round trip would
                # destroy NaN-pattern payloads — obs/forensics docstring)
                rec.update(
                    {k: record_value(k, vals[i, j])
                     for j, k in enumerate(names)}
                )
                for k, v in extras.items():
                    rec[k] = float(v[i]) if np.ndim(v) else float(v)
                if common:
                    rec.update(common)
                self.last = rec
                if self._observer is not None:
                    self._observer(rec)
                if should_log is None or should_log(step):
                    self._writer.write(rec)
        self._pending = []
        # a flush boundary is THE durability point of the chunked regime:
        # drain the wrapped writer's record buffer with it
        self._writer.flush()
        return self.last


class Segments:
    """Wall-clock segment timer with the reference's phase names.

    Durations come from ``time.perf_counter`` — monotonic, so an NTP slew
    or DST step mid-segment cannot produce negative or wildly wrong
    t_fetch/t_comp values the way the old ``time.time()`` deltas could.
    The record-level ``time`` field (MetricWriter.write) deliberately stays
    wall-clock: it timestamps the record for humans; only durations need
    monotonicity."""

    def __init__(self):
        self.t = {}
        self._start = None
        self._name = None

    def begin(self, name: str):
        self._name, self._start = name, time.perf_counter()

    def end(self):
        if self._name is not None:
            self.t[self._name] = (self.t.get(self._name, 0.0)
                                  + time.perf_counter() - self._start)
            self._name = None

    def as_dict(self, prefix: str = "t_"):
        return {prefix + k: round(v, 6) for k, v in self.t.items()}
