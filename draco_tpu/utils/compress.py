"""Gradient/array wire compression — API parity with the reference's
``compress_gradient.compress/decompress`` (reference:
src/compress_gradient.py:7-15, blosc.pack_array with the 'snappy' codec).

On-ICI gradient traffic needs no host compression in the SPMD design
(SURVEY.md §5.8), so this serves where bytes still cross a slow link:
compressed ``.dcg`` checkpoints (utils/checkpoint.py, ``--compress-ckpt``),
which the evaluator's train_dir polling auto-detects.
Format: a fixed header (dtype/shape/elem-size) + byte-shuffled
deflate payload — blosc's SHUFFLE filter re-implemented natively
(native/compress.cpp), with a numpy+zlib fallback that produces byte-identical
streams (same shuffle, same zlib), so archives are portable across backends.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from draco_tpu import native

_MAGIC = b"DCG1"


def _shuffle_np(raw: bytes, elem: int) -> bytes:
    a = np.frombuffer(raw, np.uint8)
    n = (len(a) // elem) * elem
    body = a[:n].reshape(-1, elem).T
    return body.tobytes() + a[n:].tobytes()


def _unshuffle_np(raw: bytes, elem: int) -> bytes:
    a = np.frombuffer(raw, np.uint8)
    n = (len(a) // elem) * elem
    body = np.ascontiguousarray(a[:n].reshape(elem, -1).T)
    return body.tobytes() + a[n:].tobytes()


def compress(grad: np.ndarray, level: int = 1) -> bytes:
    """Pack an ndarray (reference: compress_gradient.py:7-10)."""
    arr = np.asarray(grad)
    # ascontiguousarray promotes 0-d to (1,), losing the scalar shape
    if arr.ndim:
        arr = np.ascontiguousarray(arr)
    elem = arr.dtype.itemsize
    dt = arr.dtype.str.encode()
    header = _MAGIC + struct.pack(
        "<BBH", elem, len(dt), arr.ndim
    ) + dt + struct.pack(f"<{arr.ndim}q", *arr.shape) + struct.pack("<q", arr.nbytes)
    if native.AVAILABLE:
        payload = native.compress_bytes(arr, elem, level)
    else:
        raw = arr.tobytes()
        if elem > 1 and arr.nbytes >= elem:
            raw = _shuffle_np(raw, elem)
        payload = zlib.compress(raw, level)
    return header + payload


def decompress(buf: bytes) -> np.ndarray:
    """Unpack (reference: compress_gradient.py:12-15)."""
    if buf[:4] != _MAGIC:
        raise ValueError("not a draco_tpu compressed array")
    elem, dt_len, ndim = struct.unpack_from("<BBH", buf, 4)
    off = 8
    dtype = np.dtype(buf[off : off + dt_len].decode())
    off += dt_len
    shape = struct.unpack_from(f"<{ndim}q", buf, off)
    off += 8 * ndim
    (nbytes,) = struct.unpack_from("<q", buf, off)
    off += 8
    payload = buf[off:]
    if native.AVAILABLE:
        raw = native.decompress_bytes(payload, nbytes, elem)
    else:
        raw = zlib.decompress(payload)
        if elem > 1 and nbytes >= elem:
            raw = _unshuffle_np(raw, elem)
    return np.frombuffer(raw, dtype).reshape(shape).copy()
