"""Honest device timing through asynchronous / remote PJRT backends.

On a directly-attached TPU, ``jax.block_until_ready`` is a true execution
barrier. Behind remote-dispatch backends (e.g. the dev-tunnel plugin used
for single-chip access here) it only waits for dispatch: timing loops built
on it report launch latency (~0.02 ms regardless of workload — measured
implied throughput of 88,000 TFLOPS on a 197-TFLOP chip). The only barrier
that provably waits for execution everywhere is a device→host fetch of
result bytes.

Protocol (used by bench.py and tools/tpu_kernel_check.py):

  1. measure the host round-trip latency on an already-ready buffer,
  2. enqueue all reps (dependency-free launches back-pressure fine; for
     per-step numbers of a training loop, fold the steps into ONE jitted
     ``lax.scan`` so Python dispatch is off the timed path entirely),
  3. synchronise by fetching one scalar of the final output,
  4. subtract the round-trip latency.

Verified physical on TPU v5e: bf16 4096³ matmul times at 187 TFLOPS (95% of
peak) under this protocol vs 75,000+ "TFLOPS" under block_until_ready.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def fetch_scalar(out) -> float:
    """Device→host fetch of one element of the first array leaf — the
    execution barrier that works on remote backends too."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.ravel(leaf)[0])


def measure_rtt(reps: int = 10) -> float:
    """Seconds of pure host↔device round-trip on an already-ready buffer
    (median of ``reps`` samples — tunnel RTT has multi-ms outliers)."""
    tiny = jnp.zeros((1,), jnp.float32)
    fetch_scalar(tiny)  # materialise + first-fetch path
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch_scalar(tiny)
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


def timeit_chained(step, carry, consts=(), reps: int = 20,
                   target_s: float = 1.5) -> float:
    """Per-iteration seconds of ``step(carry, *consts)`` chained inside ONE
    jitted fori_loop, synchronised by a device→host fetch minus RTT.

    The one honest protocol for sub-ms ops on remote backends; shared by
    tools/tpu_kernel_check.py and tools/tpu_perf.py. Requirements on
    ``step`` (violations produce fantasy numbers):

      * big operands enter via ``consts`` (jit arguments) — a closed-over
        concrete array bakes into the HLO and 413s the remote compiler;
      * the carry must depend on every output of the op under test through
        a NON-LINEAR function (e.g. ``jnp.sum(out**2)``) or by carrying the
        full output. A slice feedback lets XLA dead-code-eliminate the rest
        of the op; a *linear* reduction (plain ``sum``) of a linear op lets
        XLA reassociate (``sum(R@f) == colsum(R)·f``) and hoist the O(n·d)
        work out of the loop — observed as 0.0 ms readings.

    The trip count is a traced argument (fori_loop lowers to while_loop),
    so adaptively scaling reps until the loop body is ~``target_s`` of
    device time costs no recompile.
    """
    @jax.jit
    def loop(c, consts, n_iters):
        return jax.lax.fori_loop(0, n_iters, lambda i, c: step(c, *consts), c)

    n0 = jnp.asarray(reps, jnp.int32)
    out = loop(carry, consts, n0)
    fetch_scalar(out)
    rtt = measure_rtt()
    t0 = time.perf_counter()
    out = loop(carry, consts, n0)
    fetch_scalar(out)
    total = time.perf_counter() - t0 - rtt
    if total < target_s:
        scale = min(int(target_s / max(total, 0.01)) + 1, 200)
        n1 = jnp.asarray(reps * scale, jnp.int32)
        t0 = time.perf_counter()
        out = loop(carry, consts, n1)
        fetch_scalar(out)
        return max(time.perf_counter() - t0 - rtt, 0.0) / (reps * scale)
    return max(total, 0.0) / reps


def time_scanned_steps(compiled_loop, init_state, operands, *, steps: int,
                       warmup: int = 1, reps: int = 2):
    """Per-step seconds of a compiled ``lax.scan``-of-train-steps loop under
    the fetch-sync protocol (items 1-4 above), plus the final per-step loss
    array. ``compiled_loop(state, *operands) -> (state, losses)`` must fold
    ``steps`` steps into one device program; warmup executions settle
    compile/donation, timed reps chain through the state. Shared by bench.py
    and tools/tpu_lm_perf.py so the protocol cannot drift between them."""
    rtt = measure_rtt()
    st = init_state
    losses = None
    for _ in range(max(warmup, 1)):
        st, losses = compiled_loop(st, *operands)
    fetch_scalar(losses)
    t0 = time.perf_counter()
    for _ in range(max(reps, 1)):
        st, losses = compiled_loop(st, *operands)
    fetch_scalar(losses)
    dt = max(time.perf_counter() - t0 - rtt, 0.0) / (max(reps, 1) * steps)
    return dt, losses


def timeit_device(fn, *args, reps: int = 30, rtt: float | None = None) -> float:
    """Average seconds per ``fn(*args)`` call with execution-barrier sync.

    Warms up (compile + first run), enqueues ``reps`` launches, fetches one
    scalar of the last output, subtracts the measured round trip. For
    multi-step training loops prefer folding steps into one jitted scan and
    timing that single call.
    """
    if rtt is None:
        rtt = measure_rtt()
    out = fn(*args)
    fetch_scalar(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    fetch_scalar(out)
    return max((time.perf_counter() - t0 - rtt) / reps, 0.0)
