"""Honest device timing through asynchronous / remote PJRT backends.

On a directly-attached TPU, ``jax.block_until_ready`` is a true execution
barrier. Behind remote-dispatch backends (e.g. the dev-tunnel plugin used
for single-chip access here) it only waits for dispatch: timing loops built
on it report launch latency (~0.02 ms regardless of workload — measured
implied throughput of 88,000 TFLOPS on a 197-TFLOP chip). The only barrier
that provably waits for execution everywhere is a device→host fetch of
result bytes.

Protocol (used by bench.py and tools/tpu_kernel_check.py):

  1. measure the host round-trip latency on an already-ready buffer,
  2. enqueue all reps (dependency-free launches back-pressure fine; for
     per-step numbers of a training loop, fold the steps into ONE jitted
     ``lax.scan`` so Python dispatch is off the timed path entirely),
  3. synchronise by fetching one scalar of the final output,
  4. subtract the round-trip latency.

Verified physical on TPU v5e: bf16 4096³ matmul times at 187 TFLOPS (95% of
peak) under this protocol vs 75,000+ "TFLOPS" under block_until_ready.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def fetch_scalar(out) -> float:
    """Device→host fetch of one element of the first array leaf — the
    execution barrier that works on remote backends too."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.ravel(leaf)[0])


def measure_rtt(reps: int = 10) -> float:
    """Seconds of pure host↔device round-trip on an already-ready buffer
    (median of ``reps`` samples — tunnel RTT has multi-ms outliers)."""
    tiny = jnp.zeros((1,), jnp.float32)
    fetch_scalar(tiny)  # materialise + first-fetch path
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch_scalar(tiny)
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


def timeit_device(fn, *args, reps: int = 30, rtt: float | None = None) -> float:
    """Average seconds per ``fn(*args)`` call with execution-barrier sync.

    Warms up (compile + first run), enqueues ``reps`` launches, fetches one
    scalar of the last output, subtracts the measured round trip. For
    multi-step training loops prefer folding steps into one jitted scan and
    timing that single call.
    """
    if rtt is None:
        rtt = measure_rtt()
    out = fn(*args)
    fetch_scalar(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    fetch_scalar(out)
    return max((time.perf_counter() - t0 - rtt) / reps, 0.0)
