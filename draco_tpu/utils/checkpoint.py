"""Step-indexed checkpointing via Orbax (replaces the reference's
torch.save of whole modules / state_dicts every eval_freq steps,
baseline_master.py:237-248, and the hardcoded ../checkpoints resume path,
baseline_master.py:54-57). Layout: ``{train_dir}/model_step_{k}/`` — the same
naming contract the reference's evaluator polls for
(distributed_evaluator.py:83).

``compress=True`` writes ``model_step_{k}.dcg`` instead: one file of
byte-shuffled deflate payloads (draco_tpu.utils.compress — the wire-format
successor of the reference's ``--compress-grad`` blosc path,
compress_gradient.py:7-15), for train_dirs that cross a slow link (the
reference shipped checkpoints over NFS to the evaluator). ``load`` and the
evaluator auto-detect either format. Compressed saves are single-host only:
gathering non-addressable shards is exactly what Orbax's collective save is
for, so multi-host runs must keep the Orbax path.

Resilience hardening (ISSUE 6):

* every ``.dcg`` save writes a ``.dcg.sha256`` checksum sidecar, and load
  verifies it — torn/bit-flipped/truncated checkpoint bytes raise the named
  :class:`CheckpointCorruptError` (path + expected/actual checksum) instead
  of a raw ``struct.error``/zlib traceback, which is what lets the resume
  path walk back to the last good checkpoint
  (resilience/supervisor.restore_with_walkback);
* ``save(..., keep=N)`` runs retain-last-N GC so long runs stop growing
  ``train_dir`` unboundedly — GC never deletes the newest checkpoint.
"""

from __future__ import annotations

import hashlib
import os
import re
import shutil
import struct
import zlib
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from draco_tpu.utils import compress as compress_mod

_DCG_MAGIC = b"DCKP"


class CheckpointCorruptError(ValueError):
    """Named error for torn checkpoint BYTES (checksum mismatch, truncation,
    decompress failure) — the class resume walk-back retries past. Structural
    mismatches (wrong leaf count/shape/dtype) stay plain ValueError: those
    mean the wrong abstract state, and loading an older checkpoint would not
    fix them."""

    def __init__(self, path: str, reason: str, expected: str = "",
                 actual: str = ""):
        detail = f"corrupt checkpoint {path}: {reason}"
        if expected or actual:
            detail += (f" (expected checksum {expected or '?'}, "
                       f"actual {actual or '?'})")
        super().__init__(detail)
        self.path = path
        self.reason = reason
        self.expected = expected
        self.actual = actual


def _path(train_dir: str, step: int) -> str:
    return os.path.abspath(os.path.join(train_dir, f"model_step_{step}"))


def _sidecar(dcg_path: str) -> str:
    return dcg_path + ".sha256"


def save(train_dir: str, step: int, state: Any, compress: bool = False,
         keep: int = 0) -> str:
    """Write the step's checkpoint; ``keep > 0`` then garbage-collects all
    but the newest ``keep`` checkpoints in ``train_dir`` (retain-last-N;
    the newest one — including the one just written — always survives)."""
    os.makedirs(train_dir, exist_ok=True)
    path = _path(train_dir, step)
    if compress:
        if jax.process_count() > 1:
            raise ValueError(
                "compressed checkpoints are single-host only (multi-host saves "
                "need Orbax's collective gather of non-addressable shards)"
            )
        leaves = jax.tree.leaves(jax.device_get(state))
        tmp = path + ".dcg.tmp"
        digest = hashlib.sha256()
        with open(tmp, "wb") as f:
            def put(chunk: bytes) -> None:
                digest.update(chunk)
                f.write(chunk)

            # streamed write + incremental hash: never the whole serialized
            # payload in one host buffer on top of the device_get copies
            put(_DCG_MAGIC + struct.pack("<I", len(leaves)))
            for leaf in leaves:
                blob = compress_mod.compress(np.asarray(leaf))
                put(struct.pack("<Q", len(blob)))
                put(blob)
        # ordering that keeps every crash window loadable: (1) drop the OLD
        # sidecar, (2) atomically install the new bytes, (3) write the new
        # sidecar. A crash inside the window leaves a COMPLETE payload
        # (old or new — os.replace is atomic) with no sidecar, which loads
        # unverified (the structural walk still catches truncation); any
        # sidecar that exists always matches its payload, so a good
        # checkpoint can never read as corrupt after a torn re-save.
        sidecar = _sidecar(path + ".dcg")
        try:
            os.remove(sidecar)
        except FileNotFoundError:
            pass
        os.replace(tmp, path + ".dcg")
        with open(sidecar + ".tmp", "w") as f:
            f.write(digest.hexdigest() + "\n")
        os.replace(sidecar + ".tmp", sidecar)
        gc_checkpoints(train_dir, keep)
        return path + ".dcg"
    # single-host: plain numpy payload. Multi-host: keep global jax.Arrays —
    # device_get cannot materialise non-addressable shards; Orbax gathers
    # them collectively (all processes must call save).
    payload = jax.device_get(state) if jax.process_count() == 1 else state
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, payload, force=True)
    if jax.process_index() == 0:
        gc_checkpoints(train_dir, keep)
    return path


def gc_checkpoints(train_dir: str, keep: int) -> list:
    """Retain-last-N: delete every checkpoint in ``train_dir`` except the
    newest ``keep``. ``keep <= 0`` keeps everything (the default save
    behavior). Returns the deleted step numbers. The newest checkpoint is
    never deleted (keep is clamped to >= 1 once GC is active)."""
    if keep <= 0:
        return []
    steps = available_steps(train_dir)
    doomed = steps[:-max(keep, 1)]
    for step in doomed:
        path = _path(train_dir, step)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        for f in (path + ".dcg", _sidecar(path + ".dcg")):
            if os.path.isfile(f):
                os.remove(f)
    return doomed


def _verify_sidecar(path: str) -> None:
    """Streamed sidecar-checksum verification (1 MB chunks — never the
    whole payload in one host buffer); no-op when no sidecar exists
    (pre-hardening checkpoints, or the torn-re-save window save() leaves
    deliberately sidecar-less)."""
    sidecar = _sidecar(path)
    if not os.path.isfile(sidecar):
        return
    with open(sidecar) as f:
        expected = f.read().strip()
    if not expected:
        return
    digest = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            digest.update(chunk)
    actual = digest.hexdigest()
    if actual != expected:
        raise CheckpointCorruptError(path, "checksum mismatch",
                                     expected=expected, actual=actual)


def verify(train_dir: str, step: int) -> None:
    """Integrity-check the step's ``.dcg`` checkpoint bytes WITHOUT an
    abstract state: sidecar checksum + structural blob-length walk. Raises
    :class:`CheckpointCorruptError` on torn bytes — what tools (chaos_run)
    and pre-flight checks call to prove a checkpoint is loadable-shaped
    before committing to a resume. Orbax-dir checkpoints are skipped (their
    integrity surfaces at restore)."""
    path = _path(train_dir, step) + ".dcg"
    if not os.path.isfile(path):
        return
    _verify_sidecar(path)
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        head = f.read(8)
        if len(head) < 8:
            raise CheckpointCorruptError(path, "truncated header")
        if head[:4] != _DCG_MAGIC:
            raise CheckpointCorruptError(path, "bad magic (torn header)")
        (count,) = struct.unpack("<I", head[4:])
        pos = 8
        for i in range(count):
            f.seek(pos)
            lenb = f.read(8)
            if len(lenb) < 8:
                raise CheckpointCorruptError(
                    path, f"truncated at blob {i} length")
            (blen,) = struct.unpack("<Q", lenb)
            pos += 8 + blen
            if pos > size:
                raise CheckpointCorruptError(
                    path, f"truncated inside blob {i}")


def _load_dcg(path: str, abstract_state: Any) -> Any:
    leaves_abs, treedef = jax.tree.flatten(abstract_state)
    # single streamed pass: the sidecar digest accumulates over the same
    # chunked reads the blob parse consumes (no whole-file buffer, no
    # second I/O pass over a multi-GB checkpoint on the slow-link
    # train_dirs this format targets) and is compared at EOF
    sidecar = _sidecar(path)
    expected = ""
    if os.path.isfile(sidecar):
        with open(sidecar) as f:
            expected = f.read().strip()
    digest = hashlib.sha256()
    with open(path, "rb") as f:
        def take(n: int, what: str) -> bytes:
            data = f.read(n)
            digest.update(data)
            if len(data) < n:
                raise CheckpointCorruptError(
                    path, f"truncated while reading {what} "
                          f"(needed {n} bytes, had {len(data)})")
            return data

        def check_digest() -> None:
            """Drain the rest of the file into the digest and compare to
            the sidecar — the arbiter of whether an anomaly is torn BYTES
            (checksum mismatch -> CheckpointCorruptError, the class
            walk-back retries past) or a genuinely structural mismatch."""
            for chunk in iter(lambda: f.read(1 << 20), b""):
                digest.update(chunk)
            if expected:
                actual = digest.hexdigest()
                if actual != expected:
                    raise CheckpointCorruptError(
                        path, "checksum mismatch", expected=expected,
                        actual=actual)

        try:
            head = take(8, "header")
            if head[:4] != _DCG_MAGIC:
                # a file under OUR naming contract with the wrong magic is
                # torn bytes, not a format question — classified corrupt
                # so the resume walk-back can retry past it (sidecar-less
                # checkpoints have no other header guard)
                raise CheckpointCorruptError(path,
                                             "bad magic (torn header)")
            (count,) = struct.unpack("<I", head[4:])
            if count != len(leaves_abs):
                raise ValueError(
                    f"checkpoint holds {count} arrays, abstract state has "
                    f"{len(leaves_abs)}"
                )
            out = []
            for leaf in leaves_abs:
                (blen,) = struct.unpack("<Q", take(8, "blob length"))
                blob = take(blen, "blob")
                try:
                    arr = compress_mod.decompress(blob)
                except (zlib.error, struct.error, ValueError) as e:
                    raise CheckpointCorruptError(
                        path, f"blob decompress failed: {e}") from e
                if (tuple(arr.shape) != tuple(leaf.shape)
                        or arr.dtype != leaf.dtype):
                    raise ValueError(
                        f"checkpoint leaf {arr.shape}/{arr.dtype} does "
                        f"not match abstract {leaf.shape}/{leaf.dtype}"
                    )
                sharding = getattr(leaf, "sharding", None)
                out.append(jax.device_put(arr, sharding)
                           if sharding is not None else arr)
        except Exception:
            # prefer the checksum verdict whenever the sidecar disagrees —
            # the operator-facing error then carries path + expected/actual
            # (the satellite contract), and a structural-LOOKING failure on
            # torn bytes (e.g. a corrupt blob decompressing to the wrong
            # shape) still classifies as corruption; with a clean digest
            # (or no sidecar) the original error stands
            check_digest()
            raise
        # success path: trailing-byte drain + verification before trusting
        # the parse (a mismatch also catches payload appended past the
        # declared blobs)
        check_digest()
    return jax.tree.unflatten(treedef, out)


def load(train_dir: str, step: int, abstract_state: Any) -> Any:
    path = _path(train_dir, step)
    if os.path.isfile(path + ".dcg"):
        # no hint wrapping here: the .dcg loader fails on IO/corruption, a
        # class of error the opt-state-unification explanation never fits
        return _load_dcg(path + ".dcg", abstract_state)
    try:
        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(path, abstract_state)
    except Exception as e:  # re-raise with a format-version hint when the
        # failure is a pytree-structure mismatch: the raw Orbax error gives
        # no clue that pre-unification constant-schedule checkpoints (opt
        # state was the bare rule's, optim.py docstring) legitimately
        # cannot restore into the current chain(rule, scale_by_schedule)
        # structure. Gate requires structure-AND-match (or treedef, or
        # Orbax's container-kind complaint "Expected dict, got [...]" —
        # the error this exact break actually raises) in the message so IO
        # errors whose *paths* contain words like 'tree' don't get dressed
        # up as a version problem.
        msg = str(e).lower()
        if (("structure" in msg and "match" in msg) or "treedef" in msg
                or re.search(r"expected (dict|list|tuple|pytree)", msg)):
            raise ValueError(
                f"checkpoint restore of '{path}' failed with a pytree "
                f"structure mismatch: {e}\n"
                f"If this checkpoint was written before the opt-state "
                f"unification (constant lr schedules now carry the same "
                f"chain(rule, scale_by_schedule) state as every other "
                f"schedule — draco_tpu/optim.py), its optimizer state has "
                f"the old structure and cannot be restored; restart with a "
                f"fresh optimizer state (params restore fine via a "
                f"params-only abstract state) or re-save under the current "
                f"version."
            ) from e
        raise


def exists(train_dir: str, step: int) -> bool:
    path = _path(train_dir, step)
    return os.path.isdir(path) or os.path.isfile(path + ".dcg")


def available_steps(train_dir: str):
    if not os.path.isdir(train_dir):
        return []
    steps = []
    for name in os.listdir(train_dir):
        m = re.fullmatch(r"model_step_(\d+)(\.dcg)?", name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(set(steps))
