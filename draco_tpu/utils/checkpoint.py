"""Step-indexed checkpointing via Orbax (replaces the reference's
torch.save of whole modules / state_dicts every eval_freq steps,
baseline_master.py:237-248, and the hardcoded ../checkpoints resume path,
baseline_master.py:54-57). Layout: ``{train_dir}/model_step_{k}/`` — the same
naming contract the reference's evaluator polls for
(distributed_evaluator.py:83).

``compress=True`` writes ``model_step_{k}.dcg`` instead: one file of
byte-shuffled deflate payloads (draco_tpu.utils.compress — the wire-format
successor of the reference's ``--compress-grad`` blosc path,
compress_gradient.py:7-15), for train_dirs that cross a slow link (the
reference shipped checkpoints over NFS to the evaluator). ``load`` and the
evaluator auto-detect either format. Compressed saves are single-host only:
gathering non-addressable shards is exactly what Orbax's collective save is
for, so multi-host runs must keep the Orbax path.
"""

from __future__ import annotations

import os
import re
import struct
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from draco_tpu.utils import compress as compress_mod

_DCG_MAGIC = b"DCKP"


def _path(train_dir: str, step: int) -> str:
    return os.path.abspath(os.path.join(train_dir, f"model_step_{step}"))


def save(train_dir: str, step: int, state: Any, compress: bool = False) -> str:
    os.makedirs(train_dir, exist_ok=True)
    path = _path(train_dir, step)
    if compress:
        if jax.process_count() > 1:
            raise ValueError(
                "compressed checkpoints are single-host only (multi-host saves "
                "need Orbax's collective gather of non-addressable shards)"
            )
        leaves = jax.tree.leaves(jax.device_get(state))
        blobs = [compress_mod.compress(np.asarray(leaf)) for leaf in leaves]
        tmp = path + ".dcg.tmp"
        with open(tmp, "wb") as f:
            f.write(_DCG_MAGIC + struct.pack("<I", len(blobs)))
            for blob in blobs:
                f.write(struct.pack("<Q", len(blob)))
                f.write(blob)
        os.replace(tmp, path + ".dcg")
        return path + ".dcg"
    # single-host: plain numpy payload. Multi-host: keep global jax.Arrays —
    # device_get cannot materialise non-addressable shards; Orbax gathers
    # them collectively (all processes must call save).
    payload = jax.device_get(state) if jax.process_count() == 1 else state
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, payload, force=True)
    return path


def _load_dcg(path: str, abstract_state: Any) -> Any:
    leaves_abs, treedef = jax.tree.flatten(abstract_state)
    with open(path, "rb") as f:
        head = f.read(8)
        if head[:4] != _DCG_MAGIC:
            raise ValueError(f"not a draco_tpu compressed checkpoint: {path}")
        (count,) = struct.unpack("<I", head[4:])
        if count != len(leaves_abs):
            raise ValueError(
                f"checkpoint holds {count} arrays, abstract state has {len(leaves_abs)}"
            )
        out = []
        for leaf in leaves_abs:
            (blen,) = struct.unpack("<Q", f.read(8))
            arr = compress_mod.decompress(f.read(blen))
            if tuple(arr.shape) != tuple(leaf.shape) or arr.dtype != leaf.dtype:
                raise ValueError(
                    f"checkpoint leaf {arr.shape}/{arr.dtype} does not match "
                    f"abstract {leaf.shape}/{leaf.dtype}"
                )
            sharding = getattr(leaf, "sharding", None)
            out.append(jax.device_put(arr, sharding) if sharding is not None else arr)
    return jax.tree.unflatten(treedef, out)


def load(train_dir: str, step: int, abstract_state: Any) -> Any:
    path = _path(train_dir, step)
    if os.path.isfile(path + ".dcg"):
        # no hint wrapping here: the .dcg loader fails on IO/corruption, a
        # class of error the opt-state-unification explanation never fits
        return _load_dcg(path + ".dcg", abstract_state)
    try:
        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(path, abstract_state)
    except Exception as e:  # re-raise with a format-version hint when the
        # failure is a pytree-structure mismatch: the raw Orbax error gives
        # no clue that pre-unification constant-schedule checkpoints (opt
        # state was the bare rule's, optim.py docstring) legitimately
        # cannot restore into the current chain(rule, scale_by_schedule)
        # structure. Gate requires structure-AND-match (or treedef, or
        # Orbax's container-kind complaint "Expected dict, got [...]" —
        # the error this exact break actually raises) in the message so IO
        # errors whose *paths* contain words like 'tree' don't get dressed
        # up as a version problem.
        msg = str(e).lower()
        if (("structure" in msg and "match" in msg) or "treedef" in msg
                or re.search(r"expected (dict|list|tuple|pytree)", msg)):
            raise ValueError(
                f"checkpoint restore of '{path}' failed with a pytree "
                f"structure mismatch: {e}\n"
                f"If this checkpoint was written before the opt-state "
                f"unification (constant lr schedules now carry the same "
                f"chain(rule, scale_by_schedule) state as every other "
                f"schedule — draco_tpu/optim.py), its optimizer state has "
                f"the old structure and cannot be restored; restart with a "
                f"fresh optimizer state (params restore fine via a "
                f"params-only abstract state) or re-save under the current "
                f"version."
            ) from e
        raise


def exists(train_dir: str, step: int) -> bool:
    path = _path(train_dir, step)
    return os.path.isdir(path) or os.path.isfile(path + ".dcg")


def available_steps(train_dir: str):
    if not os.path.isdir(train_dir):
        return []
    steps = []
    for name in os.listdir(train_dir):
        m = re.fullmatch(r"model_step_(\d+)(\.dcg)?", name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(set(steps))
