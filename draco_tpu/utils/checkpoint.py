"""Step-indexed checkpointing via Orbax (replaces the reference's
torch.save of whole modules / state_dicts every eval_freq steps,
baseline_master.py:237-248, and the hardcoded ../checkpoints resume path,
baseline_master.py:54-57). Layout: ``{train_dir}/model_step_{k}/`` — the same
naming contract the reference's evaluator polls for
(distributed_evaluator.py:83)."""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


def _path(train_dir: str, step: int) -> str:
    return os.path.abspath(os.path.join(train_dir, f"model_step_{step}"))


def save(train_dir: str, step: int, state: Any) -> str:
    os.makedirs(train_dir, exist_ok=True)
    path = _path(train_dir, step)
    # single-host: plain numpy payload. Multi-host: keep global jax.Arrays —
    # device_get cannot materialise non-addressable shards; Orbax gathers
    # them collectively (all processes must call save).
    payload = jax.device_get(state) if jax.process_count() == 1 else state
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, payload, force=True)
    return path


def load(train_dir: str, step: int, abstract_state: Any) -> Any:
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(_path(train_dir, step), abstract_state)


def exists(train_dir: str, step: int) -> bool:
    return os.path.isdir(_path(train_dir, step))


def available_steps(train_dir: str):
    if not os.path.isdir(train_dir):
        return []
    steps = []
    for name in os.listdir(train_dir):
        m = re.fullmatch(r"model_step_(\d+)", name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)
