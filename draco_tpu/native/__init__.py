"""ctypes bindings for the native runtime library (native/*.cpp).

The reference shipped its hot decoder as a pybind11/Eigen extension
(reference: src/c_coding.cpp + prebuilt c_coding.so). This image has no
pybind11, so the native layer is a plain C-ABI shared library loaded with
ctypes; it is built on demand from ``native/`` with the system toolchain and
cached next to this file. Everything here degrades gracefully: if the build
fails, ``AVAILABLE`` is False and callers use pure-Python fallbacks that
produce byte-identical results (draco_tpu/utils/compress.py) or numpy math
(tests assert native/jnp decode equivalence when the library is present).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SRC_DIR = os.path.join(_REPO, "native")
_LIB_PATH = os.path.join(_HERE, "libdraco_native.so")
_SOURCES = ("coding.cpp", "compress.cpp", "loader.cpp")

_lib = None
AVAILABLE = False
BUILD_ERROR: str | None = None


def _stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC_DIR, s)) > lib_mtime
        for s in _SOURCES
        if os.path.exists(os.path.join(_SRC_DIR, s))
    )


def build(verbose: bool = False) -> bool:
    """Compile native/*.cpp -> libdraco_native.so. Returns success."""
    global BUILD_ERROR
    if not os.path.isdir(_SRC_DIR):
        BUILD_ERROR = f"native source dir missing: {_SRC_DIR}"
        return False
    cmd = [
        os.environ.get("CXX", "g++"), "-O3", "-std=c++17", "-fPIC", "-Wall",
        "-pthread", *[os.path.join(_SRC_DIR, s) for s in _SOURCES],
        "-shared", "-lz", "-o", _LIB_PATH,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:  # no toolchain
        BUILD_ERROR = str(e)
        return False
    if proc.returncode != 0:
        BUILD_ERROR = proc.stderr[-2000:]
        if verbose:
            print(proc.stderr, file=sys.stderr)
        return False
    return True


def _load():
    global _lib, AVAILABLE, BUILD_ERROR
    if _stale() and not build():
        return
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        BUILD_ERROR = str(e)
        return
    c = ctypes
    f64p, f32p = c.POINTER(c.c_double), c.POINTER(c.c_float)
    u8p, i32p, i64p = c.POINTER(c.c_uint8), c.POINTER(c.c_int32), c.POINTER(c.c_int64)

    lib.draco_solve_poly_a.restype = c.c_int
    lib.draco_solve_poly_a.argtypes = [c.c_int, c.c_int, f64p, f64p, f64p, f64p]

    lib.draco_cyclic_decode.restype = c.c_int
    lib.draco_cyclic_decode.argtypes = [
        c.c_int, c.c_int, c.c_longlong, f32p, f32p, f64p, f32p, i32p, c.c_int,
    ]
    lib.draco_cyclic_decode_present.restype = c.c_int
    lib.draco_cyclic_decode_present.argtypes = [
        c.c_int, c.c_int, c.c_longlong, f32p, f32p, f64p, i32p, f32p, i32p,
        c.c_int,
    ]

    lib.draco_compress_bound.restype = c.c_longlong
    lib.draco_compress_bound.argtypes = [c.c_longlong]
    lib.draco_compress.restype = c.c_longlong
    lib.draco_compress.argtypes = [u8p, c.c_longlong, c.c_int, u8p, c.c_longlong, c.c_int]
    lib.draco_decompress.restype = c.c_longlong
    lib.draco_decompress.argtypes = [u8p, c.c_longlong, u8p, c.c_longlong, c.c_int]

    lib.draco_loader_create.restype = c.c_void_p
    lib.draco_loader_create.argtypes = [c.c_int]
    lib.draco_loader_destroy.restype = None
    lib.draco_loader_destroy.argtypes = [c.c_void_p]
    lib.draco_loader_submit.restype = c.c_longlong
    lib.draco_loader_submit.argtypes = [
        c.c_void_p, u8p, c.c_longlong, i64p, c.c_longlong, u8p,
    ]
    lib.draco_loader_wait.restype = c.c_int
    lib.draco_loader_wait.argtypes = [c.c_void_p, c.c_longlong]

    _lib = lib
    AVAILABLE = True


if os.environ.get("DRACO_TPU_NO_NATIVE", "") != "1":
    _load()


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# --------------------------------------------------------------------------
# Coding
# --------------------------------------------------------------------------

def solve_poly_a(n: int, s: int, e: np.ndarray) -> np.ndarray:
    """Error-locator coefficients for projected column e (complex, len n).

    Reference-parity signature (c_coding.cpp:15 takes (n, s, R) with R the
    projected column). Requires the native library.
    """
    if not AVAILABLE:
        raise RuntimeError(f"native library unavailable: {BUILD_ERROR}")
    e = np.asarray(e, dtype=np.complex128)
    e_re = np.ascontiguousarray(e.real)
    e_im = np.ascontiguousarray(e.imag)
    a_re = np.zeros(s, np.float64)
    a_im = np.zeros(s, np.float64)
    rc = _lib.draco_solve_poly_a(
        n, s, _ptr(e_re, ctypes.c_double), _ptr(e_im, ctypes.c_double),
        _ptr(a_re, ctypes.c_double), _ptr(a_im, ctypes.c_double),
    )
    if rc != 0:
        raise ValueError(f"draco_solve_poly_a failed with code {rc}")
    return a_re + 1j * a_im


def cyclic_decode_host(n: int, s: int, r: np.ndarray,
                       rand_factor: np.ndarray, num_threads: int = 0,
                       present: np.ndarray | None = None):
    """Full native decode of received rows r ((n, d) complex) — returns
    (mean_gradient (d,) float32, honest_mask (n,) bool). Host-side oracle /
    fallback for draco_tpu.coding.cyclic.decode. ``present``: optional (n,)
    bool erasure mask (False rows known-missing, zero-filled), same budget as
    the jit decode."""
    if not AVAILABLE:
        raise RuntimeError(f"native library unavailable: {BUILD_ERROR}")
    r = np.asarray(r)
    d = r.shape[1]
    r_re = np.ascontiguousarray(r.real, dtype=np.float32)
    r_im = np.ascontiguousarray(r.imag, dtype=np.float32)
    f = np.ascontiguousarray(rand_factor, dtype=np.float64)
    out = np.zeros(d, np.float32)
    honest = np.zeros(n, np.int32)
    pres_ptr = None
    if present is not None:
        pres = np.ascontiguousarray(present, dtype=np.int32)
        pres_ptr = _ptr(pres, ctypes.c_int32)
    rc = _lib.draco_cyclic_decode_present(
        n, s, d, _ptr(r_re, ctypes.c_float), _ptr(r_im, ctypes.c_float),
        _ptr(f, ctypes.c_double), pres_ptr, _ptr(out, ctypes.c_float),
        _ptr(honest, ctypes.c_int32), num_threads,
    )
    if rc != 0:
        raise ValueError(f"draco_cyclic_decode failed with code {rc}")
    return out, honest.astype(bool)


# --------------------------------------------------------------------------
# Compression (raw payload transforms; framing lives in utils/compress.py)
# --------------------------------------------------------------------------

def compress_bytes(raw: bytes | np.ndarray, elem_size: int, level: int = 1) -> bytes:
    if not AVAILABLE:
        raise RuntimeError(f"native library unavailable: {BUILD_ERROR}")
    src = np.frombuffer(raw, dtype=np.uint8) if isinstance(raw, (bytes, bytearray)) \
        else np.ascontiguousarray(raw).view(np.uint8).reshape(-1)
    n = src.nbytes
    cap = _lib.draco_compress_bound(n)
    dst = np.zeros(cap, np.uint8)
    size = _lib.draco_compress(
        _ptr(src, ctypes.c_uint8), n, elem_size, _ptr(dst, ctypes.c_uint8), cap, level
    )
    if size < 0:
        raise ValueError("draco_compress failed")
    return dst[:size].tobytes()


def decompress_bytes(buf: bytes, raw_nbytes: int, elem_size: int) -> bytes:
    if not AVAILABLE:
        raise RuntimeError(f"native library unavailable: {BUILD_ERROR}")
    src = np.frombuffer(buf, dtype=np.uint8)
    dst = np.zeros(raw_nbytes, np.uint8)
    size = _lib.draco_decompress(
        _ptr(src, ctypes.c_uint8), src.nbytes, _ptr(dst, ctypes.c_uint8),
        raw_nbytes, elem_size,
    )
    if size != raw_nbytes:
        raise ValueError("draco_decompress failed")
    return dst.tobytes()


# --------------------------------------------------------------------------
# Batch loader
# --------------------------------------------------------------------------

class BatchLoader:
    """Thread-pool gather of dataset rows into batch buffers, off the GIL.

    Replaces the reference's multiprocess DataLoader
    (my_data_loader.py:137-319): ``submit`` starts an async gather of
    ``indices`` rows from a (N, ...) source array into a fresh batch array;
    ``wait`` blocks until it is filled. Buffers are pinned in the pending
    table so the C++ threads never outlive them.
    """

    def __init__(self, num_threads: int = 2):
        if not AVAILABLE:
            raise RuntimeError(f"native library unavailable: {BUILD_ERROR}")
        self._h = _lib.draco_loader_create(num_threads)
        self._pending: dict[int, tuple] = {}

    def submit(self, src: np.ndarray, indices: np.ndarray) -> int:
        assert src.flags["C_CONTIGUOUS"]
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        # the C++ gather computes src + i*row_bytes with no checks; keep
        # numpy's IndexError failure mode rather than a silent OOB read
        if len(idx) and (idx.min() < 0 or idx.max() >= len(src)):
            raise IndexError(
                f"gather index out of range [0, {len(src)}): "
                f"min={idx.min()}, max={idx.max()}"
            )
        row_bytes = src[0].nbytes if len(src) else 0
        out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
        ticket = _lib.draco_loader_submit(
            self._h, _ptr(src.view(np.uint8).reshape(-1), ctypes.c_uint8),
            row_bytes, _ptr(idx, ctypes.c_int64), len(idx),
            _ptr(out.view(np.uint8).reshape(-1), ctypes.c_uint8),
        )
        self._pending[ticket] = (src, idx, out)
        return ticket

    def wait(self, ticket: int) -> np.ndarray:
        _lib.draco_loader_wait(self._h, ticket)
        _, _, out = self._pending.pop(ticket)
        return out

    def close(self):
        if self._h is not None:
            _lib.draco_loader_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
