"""Force-(re)build the native library: ``python -m draco_tpu.native.build``."""

from draco_tpu import native


def main():
    ok = native.build(verbose=True)
    if ok:
        print(f"built {native._LIB_PATH}")
    else:
        raise SystemExit(f"native build failed:\n{native.BUILD_ERROR}")


if __name__ == "__main__":
    main()
