"""The one profiler-window implementation both production loops share.

PR 4 wired ``--profile-dir`` into four loop bodies (Trainer eager/chunked,
token_loop eager/chunked) as four copy-pasted ``jax.profiler.start_trace`` /
``stop_trace`` blocks — and the drain-before-stop fix (stop during async
dispatch truncates the still-executing profiled steps) was re-implemented
per site, incompletely (the CNN eager loop never drained). ISSUE 9
deduplicates them into :func:`profiler_window`, which also stamps the
**wall-clock anchor** (``profile_dir/host_anchor.json``) that the merged
host+device timeline needs: the host tracer's relative timestamp at the
moment ``start_trace`` returned, pairing with the capture's own start-time
origin (obs/device_attr.device_time_origin) to put both event streams on
one clock.

Window semantics (unchanged from the per-site logic):

* ``maybe_start(step_end)`` before a work unit whose last step is
  ``step_end`` — starts the capture at the first unit reaching
  ``profile_steps[0]`` (chunk-snapped under K>1), at most once per run.
* ``maybe_stop(step_end, drain)`` after the unit — stops once
  ``step_end >= profile_steps[1] - 1``, draining ``drain`` (the state
  carry) through ``jax.block_until_ready`` first so the capture contains
  the full device execution, not the dispatch tail.
* ``stop(drain)`` in the loop's exit path — the safety stop when the run
  ends inside the window.

The disabled path is a shared no-op singleton (``NULL_PROFILER_WINDOW``):
loops hold a window unconditionally and never branch on enablement, the
same contract as the tracer (obs/tracer.py). jax is imported lazily inside
start/stop so the obs package stays importable without jax (the jax-free
tools import sibling modules).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from draco_tpu.obs.tracer import NULL_TRACER

ANCHOR_FILE = "host_anchor.json"


def _quiet_start_trace(log_dir: str) -> None:
    """``jax.profiler.start_trace`` with the python tracer DISABLED.

    The default capture interleaves a python-callstack event per host frame
    — ~1M events for a CI-sized 8-step window, flooding the bounded trace
    buffer and truncating the device stream this module exists to capture
    (the host half is already covered by the span tracer, obs/tracer.py).
    jax 0.4.x exposes no public knob, so this builds the ProfilerSession
    with ``ProfileOptions.python_tracer_level = 0`` through the same
    internal state ``start_trace`` uses; if the internals move with a
    toolchain bump, it degrades to the public (noisy) ``start_trace``
    rather than losing the capture."""
    import jax

    already_active = False
    try:
        from jax._src import profiler as _prof
        from jax._src import xla_bridge
        from jax._src.lib import xla_client

        opts = xla_client.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        with _prof._profile_state.lock:
            if _prof._profile_state.profile_session is not None:
                already_active = True
            else:
                xla_bridge.get_backend()  # the session needs a live backend
                _prof._profile_state.profile_session = \
                    xla_client.profiler.ProfilerSession(opts)
                _prof._profile_state.create_perfetto_link = False
                _prof._profile_state.create_perfetto_trace = False
                _prof._profile_state.log_dir = str(log_dir)
    except Exception:
        # internals moved (or a backend/XLA error — note XlaRuntimeError
        # subclasses RuntimeError, so no bare RuntimeError re-raise here):
        # keep capturing via the public path, accept the noise
        jax.profiler.start_trace(log_dir)
        return
    if already_active:
        # only OUR sentinel propagates — a second concurrent window is a
        # caller bug, not a degradation case
        raise RuntimeError(
            "profiler session already active — only one "
            "profiler_window may run at a time")


class NullProfilerWindow:
    """Disabled window: every call is a no-op (no clock read, no branch
    beyond the method call)."""

    __slots__ = ()
    active = False
    profiled = False

    def maybe_start(self, step_end: int, first_step=None) -> None:
        pass

    def maybe_stop(self, step_end: int, drain=None) -> None:
        pass

    def stop(self, drain=None) -> None:
        pass


NULL_PROFILER_WINDOW = NullProfilerWindow()


class ProfilerWindow:
    """One jax.profiler capture window over steps
    [profile_steps[0], profile_steps[1]) — snapped outward to whole work
    units by the caller's ``step_end`` granularity (a chunk profiles whole
    or not at all, exactly the PR 4 per-site behavior)."""

    def __init__(self, profile_dir: str, profile_steps: tuple = (3, 8),
                 tracer=NULL_TRACER, on_stop=None):
        self.dir = profile_dir
        self.steps = tuple(profile_steps)
        self.tracer = tracer
        self.active = False
        self.profiled = False
        self._anchor: dict = {}
        self._first: Optional[int] = None
        self._last_end: Optional[int] = None
        # called with the profile dir after a successful stop — the loops
        # pass heartbeat.observe_device so status.json grows the ``device``
        # block from the capture that just landed
        self._on_stop = on_stop

    def maybe_start(self, step_end: int, first_step=None) -> None:
        """``first_step``: the unit's FIRST step (chunk start) — under K>1
        the capture snaps outward to the whole chunk, so the profiled step
        count is [first_step, last stop step], not [profile_steps)."""
        if self.active or self.profiled or step_end < self.steps[0]:
            return
        os.makedirs(self.dir, exist_ok=True)
        _quiet_start_trace(self.dir)
        self._first = int(first_step if first_step is not None else step_end)
        # stamped AFTER start_trace returns; with the python tracer off the
        # capture has no start event, so the merge anchors on the DRAIN
        # stamp below instead (device_attr.merge_timeline)
        self._anchor = {
            "schema": 1,
            "profile_steps": list(self.steps),
            "first_step": self._first,
            "started_unix": time.time(),
            "started_perf": time.perf_counter(),
            # host-tracer-relative µs of the same instant (None when the
            # run has no tracer — the timeline then keeps separate origins)
            "tracer_ts_us": getattr(self.tracer, "now_us", lambda: None)(),
        }
        self.active = True

    def maybe_stop(self, step_end: int, drain=None) -> None:
        if not self.active:
            return
        self._last_end = int(step_end)  # newest unit fully inside the window
        if step_end >= self.steps[1] - 1:
            self.stop(drain)

    def stop(self, drain=None) -> None:
        """Stop the capture (drain first — the PR 4 fix, now unconditional:
        stopping mid-async-dispatch truncates the profiled steps) and write
        the anchor file."""
        if not self.active:
            return
        import jax

        if drain is not None:
            try:
                jax.block_until_ready(drain)
            except Exception:
                # a poisoned carry (fault injection, device error) raises on
                # await — the loops call stop() from their finally blocks,
                # so propagating here would MASK the original exception and
                # leak the profiler session; a truncated capture is the
                # honest outcome of a run that died mid-window
                pass
        # the DRAIN stamp: the devices just went idle, so the capture's last
        # device-event END corresponds to this host instant — the merge
        # anchor that survives the python tracer being off
        self._anchor.update(
            drained_unix=time.time(),
            drained_perf=time.perf_counter(),
            drained_tracer_ts_us=getattr(self.tracer, "now_us",
                                         lambda: None)(),
        )
        jax.profiler.stop_trace()
        self.active = False
        self.profiled = True
        self._anchor.update(
            stopped_unix=time.time(),
            stopped_perf=time.perf_counter(),
            last_step=self._last_end,
        )
        if self._last_end is not None and self._first is not None:
            self._anchor["steps_profiled"] = self._last_end - self._first + 1
        tmp = os.path.join(self.dir, ANCHOR_FILE + ".tmp")
        try:
            with open(tmp, "w") as fh:
                json.dump(self._anchor, fh)
            os.replace(tmp, os.path.join(self.dir, ANCHOR_FILE))
        except OSError:
            pass  # anchor is best-effort; the capture itself already landed
        if self._on_stop is not None:
            try:
                self._on_stop(self.dir)
            except Exception:
                pass  # observation must never take the run down


def profiler_window(profile_dir: Optional[str], profile_steps: tuple = (3, 8),
                    enabled: bool = True, tracer=NULL_TRACER, on_stop=None):
    """The one construction rule all four loop sites share: a real window
    iff a profile_dir is configured on the metrics-emitting process, else
    the shared no-op singleton (callers never branch)."""
    if profile_dir and enabled:
        return ProfilerWindow(profile_dir, profile_steps, tracer, on_stop)
    return NULL_PROFILER_WINDOW
