"""Run heartbeat: ``train_dir/status.json`` rewritten at flush boundaries.

Long chip jobs run for hours with the host dark between flushes; the only
way to watch one today is to tail stdout or poll metrics.jsonl (which the
buffered MetricWriter now also only touches at flush boundaries). The
heartbeat is the external-monitoring contract instead: a single small JSON
file, atomically replaced (tmp + rename) at every flush boundary, holding
everything a dashboard or a watchdog needs —

  step / total_steps / steps_per_s / eta_s   progress and rate
  loss (+ prec1 when the route emits it)     last materialized train record
  decode_health                              cumulative detection
                                             precision/recall vs the seeded
                                             adversary schedule, last decode
                                             residual / vote agreement
  prefetch_depth                             in-flight prefetch requests
  updated_at                                 wall-clock of the last beat

The decode-health precision/recall is computed HERE, on the host, from the
per-step in-graph columns (det_tp / det_adv / located_errors /
det_flagged) that ride the (K, m) metric block — the device never runs a
callback and the host never does an extra fetch: :meth:`observe` is wired
as the DeferredMetricWriter observer, so it sees exactly the records the
flush materializes anyway.

A stale ``updated_at`` is itself the signal: a watchdog that sees no beat
for a few flush periods knows the run is wedged without attaching to it.

Terminal states (ISSUE 6): every beat carries ``state: "running"``; the
loops end the file's life with :meth:`terminal` — ``"done"`` on normal
completion, ``"preempted"`` (plus ``resumable_step``) when a
SIGTERM/SIGINT graceful stop snapped a boundary checkpoint, ``"crashed"``
(plus a one-line ``cause``) when an unhandled exception escapes — so
``tools/trace_report.py`` and operators can distinguish the three without
parsing a traceback.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from draco_tpu.obs.forensics import AccusationLedger

# status.json payload schema version. The payload grew organically across
# PRs 4-6 with no versioning; consumers (tools/trace_report.py,
# tools/chaos_run.py) tolerate files with no ``schema`` field (pre-version
# runs) and assert it when present. Bump when a field changes meaning or
# moves — additive fields do not need a bump.
#   2: first versioned schema (adds ``schema`` itself, the ``forensics``
#      block, and ``num_workers``). The ``device`` block (ISSUE 9 — last
#      profiled window's phase fractions / decode share) is ADDITIVE under
#      schema 2: consumers tolerate it missing, assert it when present.
#   3: the numerics observatory (ISSUE 10): a static ``wire`` block (the
#      logical worker→aggregator bytes ledger, obs/numerics.wire_ledger,
#      set once per run via :meth:`RunHeartbeat.set_wire` — BOTH
#      production loops stamp it on every run, watch or not, since the
#      ledger is derived from shapes alone) and a folded ``numerics``
#      block (last dynamic-range values + worst-case underflow/overflow
#      fractions + shadow-wire error/agreement extremes +
#      ``shadow_sentinel_steps``, the count of fault-poisoned shadow
#      comparisons), which appears only on watch-enabled runs. Consumers
#      tolerate either block missing, assert shape when present.
#   4: the incident engine (ISSUE 13): an ``incidents`` block (open
#      episodes, per-type totals, last onset — obs/incidents.py) on
#      watch-enabled runs (``cfg.incident_watch="on"``), carried by the
#      terminal crash/preempted write too.
#   5: run identity (ISSUE 19): a ``run_id`` (stable per train_dir —
#      re-read from the dir's existing status.json on construction so a
#      resumed run keeps the id its first attempt minted) and an optional
#      operator-facing ``job_name`` (cfg.job_name). Consumers tolerate
#      both missing (pre-fleet runs); the fleet registry
#      (obs/fleet.RunRegistry) uses run_id to fold a resumed run's
#      attempts as ONE run.
STATUS_SCHEMA = 5

# The ONE schema contract table (ISSUE 13 satellite): optional status.json
# block name -> the schema version that introduced it. Every jax-free
# consumer (tools/trace_report.py, tools/incident_report.py,
# tools/forensics_report.py, tools/chaos_run.py, tools/check_artifacts.py)
# validates against THIS table via :func:`check_status_schema` instead of
# carrying its own accepted-set literal, so a schema bump cannot silently
# strand a tool.
STATUS_BLOCKS = {
    "decode_health": 2, "guard": 2, "forensics": 2, "device": 2,
    "wire": 3, "numerics": 3,
    "incidents": 4,
    # the autopilot's ``control`` block (control/autopilot.py — current
    # regime, swaps, quarantined workers, last remediation) is ADDITIVE
    # under schema 4: consumers tolerate it missing, assert when present
    "control": 4,
    # run identity (ISSUE 19): both optional-on-read — every consumer
    # tolerates their absence (pre-fleet files), asserts placement via
    # this table when present
    "run_id": 5, "job_name": 5,
}
KNOWN_STATUS_SCHEMAS = tuple(range(2, STATUS_SCHEMA + 1))


def check_status_schema(status: dict, path: str = "status.json",
                        tool: str = "this tool") -> dict:
    """Validate a loaded status.json payload against the central contract:
    a ``schema`` field, when present, must be a version this tree knows
    (pre-versioning files carry none and are accepted), and no optional
    block may appear under a schema older than the one that introduced it.
    Raises SystemExit naming the mismatch — silently folding an unknown
    payload shape would misreport the run. Returns ``status`` unchanged."""
    if not isinstance(status, dict):
        return status
    schema = status.get("schema")
    if schema is not None and schema not in KNOWN_STATUS_SCHEMAS:
        raise SystemExit(
            f"{path}: status.json schema {schema!r} not in known "
            f"{KNOWN_STATUS_SCHEMAS} — update {tool} alongside "
            f"obs/heartbeat.STATUS_SCHEMA")
    if schema is not None:
        for block, introduced in STATUS_BLOCKS.items():
            if block in status and schema < introduced:
                raise SystemExit(
                    f"{path}: block {block!r} requires status schema >= "
                    f"{introduced}, payload claims {schema} — a writer and "
                    f"obs/heartbeat.STATUS_BLOCKS disagree")
    return status

# per-step detection-count columns (in-graph, coding/cyclic.py +
# coding/repetition.py): tp = flagged ∧ adversarial ∧ present,
# adv = adversarial ∧ present, flagged = located_errors | det_flagged
_TP_KEY = "det_tp"
_ADV_KEY = "det_adv"
_FLAGGED_KEYS = ("located_errors", "det_flagged")
# last-value health fields copied verbatim from the newest record (the
# approx family's residual-vs-bound certificate rides the last three:
# parallel/common.APPROX_HEALTH_NAMES)
_LAST_KEYS = ("decode_residual", "vote_agree", "flagged_groups",
              "honest_located", "decode_residual_bound",
              "recovered_fraction")

# numerics-observatory fold (obs/numerics.py, ISSUE 10): last-value range
# stats, running maxima of the danger fractions and shadow errors, running
# minimum of the shadow flag agreement — the ``numerics`` status block
_NX_LAST = ("nx_grad_absmax", "nx_grad_rms", "nx_wire_absmax",
            "nx_wire_rms", "nx_agg_absmax", "nx_agg_rms")
_NX_MAX = ("nx_wire_uf_bf16", "nx_wire_uf_int8", "nx_wire_of_bf16",
           "nx_grad_nonfinite", "nx_wire_nonfinite", "shadow_err",
           "shadow_residual")
_NX_MIN = ("shadow_flag_agree",)


class RunHeartbeat:
    """Accumulates per-step records (:meth:`observe`) and rewrites
    ``status.json`` on :meth:`beat`. Disabled (``train_dir`` falsy or not
    the metrics-emitting process) it is a cheap no-op — both methods
    return immediately."""

    def __init__(self, train_dir: Optional[str], enabled: bool = True,
                 num_workers: Optional[int] = None, incidents=None,
                 job_name: Optional[str] = None):
        self.path = (os.path.join(train_dir, "status.json")
                     if (train_dir and enabled) else None)
        if self.path:
            os.makedirs(train_dir, exist_ok=True)
        # run identity (ISSUE 19): stable per train_dir — a resume into
        # the same dir re-reads the id the first attempt minted (torn or
        # pre-fleet status files mint a fresh one); the fleet registry
        # folds attempts sharing an id as ONE run
        self.run_id = self._load_or_mint_run_id() if self.path else None
        self.job_name = str(job_name) if job_name else None
        self._t0 = time.perf_counter()
        self._first_step: Optional[int] = None
        self._tp = 0.0
        self._adv = 0.0
        self._flagged = 0.0
        self._guard_trips = 0.0
        self._skipped_steps = 0.0
        self._guard_seen = False  # any record carried guard columns
        self._last: dict = {}
        # numerics-observatory fold (ISSUE 10): the ``numerics`` status
        # block accumulated from the nx_*/shadow_* columns, plus the
        # static ``wire`` ledger the loops stamp once (set_wire)
        self._nx: dict = {}
        self._wire: Optional[dict] = None
        # last profiled window's device block (obs/device_attr.py, ISSUE 9)
        # — set by observe_device, wired as the profiler window's on_stop
        # hook; rides every subsequent beat
        self._device: Optional[dict] = None
        # autopilot ``control`` block (control/autopilot.py, set_control)
        self._control: Optional[dict] = None
        # newest record that actually carried detection columns — kept
        # separately from _last so a mixed-route train_dir (a trailing
        # record WITHOUT the optional health family, e.g. a baseline run
        # sharing the dir) cannot hide the cumulative health block
        self._last_health_rec: dict = {}
        self._last_payload: dict = {}
        self.beats = 0
        # per-worker accusation ledger (obs/forensics.py), fed by the same
        # observer hook; needs the worker count to unpack the bitmask
        # columns — loops pass cfg.num_workers, bare constructions skip
        # forensics entirely
        self.ledger = (AccusationLedger(num_workers)
                       if (self.path and num_workers) else None)
        # incident engine (obs/incidents.py, ISSUE 13): rides the same
        # observer hook + the beat — zero extra fetches; None = watch off
        self.incidents = incidents if self.path else None

    def _load_or_mint_run_id(self) -> str:
        """Re-read the dir's existing run_id (resume keeps identity), else
        mint a fresh one. Tolerates every partial state a killed run
        leaves behind — identity must never take a run down."""
        try:
            with open(self.path) as fh:
                prior = json.load(fh)
            rid = prior.get("run_id") if isinstance(prior, dict) else None
            if isinstance(rid, str) and rid:
                return rid
        except (OSError, ValueError):
            pass
        import uuid

        return uuid.uuid4().hex[:12]

    # ---- accumulation ----------------------------------------------------
    def observe(self, record: dict) -> None:
        """One materialized train record (every step, logged or not) —
        wired as the DeferredMetricWriter observer in the chunked loops,
        called inline per step by the eager loops. Every column family is
        optional (baseline routes emit no health/guard/forensics columns;
        eval records carry none): a record only advances the accumulators
        for the families it carries."""
        if self.path is None:
            return
        step = record.get("step")
        if step is not None and self._first_step is None:
            self._first_step = int(step)
        if _TP_KEY in record:
            self._tp += float(record[_TP_KEY])
            self._adv += float(record.get(_ADV_KEY, 0.0))
            for k in _FLAGGED_KEYS:
                if k in record:
                    self._flagged += float(record[k])
                    break
            self._last_health_rec = record
        elif "decode_residual_bound" in record:
            # approx family (ISSUE 8): no detection columns — the health
            # block carries the last residual/bound/coverage instead, and
            # the empty detection denominators read as the healthy 1.0
            self._last_health_rec = record
        if "guard_trips" in record:
            self._guard_trips += float(record["guard_trips"])
            self._skipped_steps += float(record.get("skipped_steps", 0.0))
            self._guard_seen = True
        # numerics observatory (ISSUE 10): fold whatever nx_/shadow_
        # columns the record carries — last values for the range stats,
        # running max for the danger fractions / shadow errors, running
        # min for the flag agreement
        for k in _NX_LAST:
            if k in record:
                self._nx[k] = float(record[k])
        # a shadow column at the -1.0 NaN sentinel (numerics.
        # SHADOW_SENTINEL) marks a fault-poisoned comparison: it must stay
        # VISIBLE at the roll-up, not vanish under max() — count the step
        # once and exclude sentinel values from the extreme folds
        if any(k in record and float(record[k]) < 0.0
               for k in _NX_MAX + _NX_MIN if k.startswith("shadow_")):
            self._nx["shadow_sentinel_steps"] = \
                self._nx.get("shadow_sentinel_steps", 0) + 1
        for k in _NX_MAX:
            if k in record:
                v = float(record[k])
                if k.startswith("shadow_") and v < 0.0:
                    continue
                key = f"{k}_max"
                self._nx[key] = max(self._nx.get(key, float("-inf")), v)
        for k in _NX_MIN:
            if k in record:
                v = float(record[k])
                if v < 0.0:
                    continue
                key = f"{k}_min"
                self._nx[key] = min(self._nx.get(key, float("inf")), v)
        # engine first: it unpacks the record's forensics masks once into
        # its cache, which the heartbeat's own ledger fold then reuses —
        # one bit-unpack per record on the watch-enabled observer path
        if self.incidents is not None:
            self.incidents.observe(record)
        if self.ledger is not None:
            # reuse only when the engine unpacked for the SAME worker
            # count (the loops wire both from cfg.num_workers; a bare
            # mismatched construction falls back to its own unpack)
            masks = (self.incidents.current_masks
                     if self.incidents is not None
                     and self.incidents.num_workers == self.ledger.n
                     else None)
            self.ledger.observe(record, masks=masks)
        self._last = record

    def set_wire(self, ledger: Optional[dict]) -> None:
        """Stamp the run's static logical wire-bytes ledger
        (obs/numerics.wire_ledger) — the ``wire`` status block. Called once
        by both production loops right after setup, when the program's
        flat-gradient dimension is known. None (or a disabled heartbeat)
        is a no-op."""
        if self.path is None or ledger is None:
            return
        self._wire = dict(ledger)

    def set_control(self, block: Optional[dict]) -> None:
        """Stamp the autopilot's ``control`` status block (current regime,
        swaps, quarantined workers, last remediation — control/autopilot
        status_block). Refreshed at every autopilot decision pass; rides
        every subsequent beat AND the terminal write, so the run's last
        word records the regime it ended in."""
        if self.path is None or block is None:
            return
        self._control = dict(block)

    def observe_device(self, profile_dir: str) -> None:
        """Fold the just-stopped profiler capture into the ``device`` status
        block (phase fractions, decode share, attribution coverage — ISSUE
        9). Wired as ``obs.profiling.profiler_window``'s ``on_stop`` hook by
        both production loops, so the block lands on the first beat after
        the capture window closes. Best-effort: a torn capture (or a run
        with no capture at all) folds nothing, and observation must never
        take the run down."""
        if self.path is None:
            return
        try:
            from draco_tpu.obs import device_attr

            fold = device_attr.fold_capture(profile_dir)
            block = device_attr.device_status_block(fold) if fold else None
        except Exception:
            return
        if block is not None:
            block["profile_dir"] = profile_dir
            self._device = block

    def decode_health(self) -> Optional[dict]:
        """Cumulative detection precision/recall (1.0 denominators-empty:
        nothing flagged / no live adversary is a healthy state) + the
        newest per-step health values."""
        if not self._last_health_rec:
            return None
        health = {
            "precision": (self._tp / self._flagged) if self._flagged else 1.0,
            "recall": (self._tp / self._adv) if self._adv else 1.0,
            "flagged_total": self._flagged,
            "adv_total": self._adv,
        }
        for k in _LAST_KEYS:
            if k in self._last_health_rec:
                health[k] = float(self._last_health_rec[k])
        return health

    # ---- emission --------------------------------------------------------
    def beat(self, step: int, total_steps: Optional[int] = None,
             extra: Optional[dict] = None) -> Optional[dict]:
        """Rewrite status.json (atomic). ``extra`` merges verbatim (e.g.
        ``{"prefetch_depth": 1}``). Returns the written payload (None when
        disabled) so tests and callers can assert on it."""
        if self.path is None:
            return None
        now = time.perf_counter()
        done = step - (self._first_step or step) + 1
        dt = max(now - self._t0, 1e-9)
        rate = done / dt
        payload = {
            "schema": STATUS_SCHEMA,
            "state": "running",
            "run_id": self.run_id,
            "step": int(step),
            "total_steps": int(total_steps) if total_steps else None,
            "steps_per_s": round(rate, 4),
            "eta_s": (round(max(total_steps - step, 0) / rate, 1)
                      if (total_steps and rate > 0) else None),
            "updated_at": time.time(),
        }
        if self.job_name:
            payload["job_name"] = self.job_name
        for k in ("loss", "prec1"):
            if k in self._last:
                payload[k] = float(self._last[k])
        health = self.decode_health()
        if health is not None:
            payload["decode_health"] = health
        # keyed off "ever seen", not the newest record: a mixed-route
        # train_dir whose trailing record carries no guard columns must not
        # hide the cumulative totals
        if self._guard_seen:
            payload["guard"] = {"trips": self._guard_trips,
                                "skipped_steps": self._skipped_steps}
        if self.ledger is not None and self.ledger.active:
            # per-worker forensics (obs/forensics.AccusationLedger):
            # top suspects, trust vector, episode counts
            payload["forensics"] = self.ledger.summary()
        if self._wire is not None:
            # static logical wire-bytes ledger (ISSUE 10, set_wire)
            payload["wire"] = self._wire
        if self._nx:
            # folded numerics-observatory block (ISSUE 10)
            payload["numerics"] = dict(self._nx)
        if self._device is not None:
            # last profiled window's device-time attribution (ISSUE 9);
            # consumers tolerate the key missing, assert it when present
            payload["device"] = self._device
        if self._control is not None:
            # the autopilot's runtime-control state (control/autopilot.py)
            payload["control"] = self._control
        if self.incidents is not None:
            # the beat IS the engine's beat-source observation (throughput
            # wall-rate, compile counters, prefetch depth/restarts all
            # arrive in ``extra``), then the folded block rides the payload
            self.incidents.observe_beat(step, extra)
            payload["incidents"] = self.incidents.status_block()
        if extra:
            payload.update(extra)
        self._write(payload)
        self.beats += 1
        return payload

    def terminal(self, state: str, cause: Optional[str] = None,
                 resumable_step: Optional[int] = None) -> Optional[dict]:
        """Write the run's FINAL status.json state: ``done`` | ``preempted``
        | ``crashed`` (module docstring). Builds on the last beat's payload
        so a monitor keeps step/rate/health context, then overrides
        ``state`` (+ one-line ``cause``, + ``resumable_step`` when a
        graceful stop snapped a boundary checkpoint to resume from)."""
        if self.path is None:
            return None
        # seed from the last payload for step/rate/health context, but
        # strip terminal-only keys: a terminal seeded from a PREVIOUS
        # terminal (block-wise callers re-run between beats) must not leak
        # a stale cause or resumable_step into a different final state
        payload = {k: v for k, v in self._last_payload.items()
                   if k not in ("state", "cause", "resumable_step")}
        payload["schema"] = STATUS_SCHEMA  # present even with no prior beat
        payload["state"] = state
        payload["run_id"] = self.run_id  # identity even with no prior beat
        if self.job_name:
            payload["job_name"] = self.job_name
        payload["updated_at"] = time.time()
        if self._device is not None:
            # a capture window that stops on the run's LAST work unit has
            # no later beat — the terminal write is the block's only ride
            payload["device"] = self._device
        if self._control is not None:
            # the regime the run ENDED in (a post-last-beat remediation
            # must survive into the run's last word — same rule as the
            # incidents block below)
            payload["control"] = self._control
        if self.incidents is not None:
            # the FINAL incidents state must ride the terminal write: an
            # incident that opened after the last beat (a crash step, a
            # SIGTERM-boundary guard trip) would otherwise vanish from the
            # run's last word — the same bug PR 9 fixed for ``device``
            # (ISSUE 13 satellite, pinned by the SIGTERM-path test)
            payload["incidents"] = self.incidents.status_block()
            self.incidents.finalize()
        if cause is not None:
            payload["cause"] = str(cause)[:500]
        if resumable_step is not None:
            payload["resumable_step"] = int(resumable_step)
        self._write(payload)
        return payload

    def _write(self, payload: dict) -> None:
        self._last_payload = payload
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, self.path)
