"""Telemetry spine: host span tracing + run heartbeats.

The scan-chunked loops (PR 1–2) are fast precisely because the host goes
dark between flushes — which also means nothing shows where a chunk's
wall-clock went, and no artifact of a run shows whether the decode caught
the seeded adversaries. This package is the observability layer ROADMAP's
production north star needs, built under the PR 1–2 invariant: **no new
device fetches in steady state** and zero overhead when disabled.

  tracer.py     SpanTracer — Chrome-trace-event host spans
                (gather/upload/dispatch/sync/flush/eval/ckpt + prefetcher
                worker-thread lanes + queue-depth counters) written to
                ``trace_dir/trace.json``, loadable in Perfetto / chrome://
                tracing; ``NULL_TRACER`` is the allocation-free disabled
                path every loop runs by default.
  heartbeat.py  RunHeartbeat — ``train_dir/status.json`` rewritten
                atomically at every flush boundary (step, steps/s, ETA,
                last loss, decode health, prefetch queue depth, compile
                counters) so external monitors can watch a long chip job
                without touching the process.
  compile_watch.py  CompileWatch — the compiler-facing half (ISSUE 5):
                every XLA executable build becomes a ``compiles.jsonl``
                ledger row + a ``compile`` lane event in trace.json via
                jax.monitoring, and a steady-state guard (warn by default,
                raise in tests) trips on any recompilation of a labelled
                registered program after its warmup build.
  profiling.py  profiler_window — the ONE jax.profiler start/stop window
                both production loops run (drain-before-stop + the
                wall-clock anchor the merged host+device timeline needs);
                previously four copy-pasted blocks (ISSUE 9).
  device_attr.py  The device-side half of the spine (ISSUE 9, jax-free):
                parses a jax.profiler capture into the per-phase chip
                ledger (draco_comp/encode/decode/update + explicit
                residual, rows summing to the profiled window), the
                collective comms ledger cross-checked against the PR 3
                Manifest counts (mismatch = hard error), and the merged
                host+device Perfetto timeline. Driven by
                tools/device_profile.py; folded by tools/trace_report.py.
  incidents.py  IncidentEngine (ISSUE 13): typed, attributed, stateful
                run-health incidents folded from the per-step column
                families at the heartbeat observer hook — declaratively
                registered detectors (throughput / residual drift / trust
                collapse / guard burn / numerics / compile storm /
                prefetch starvation) with onset/offset hysteresis,
                streamed to ``train_dir/incidents.jsonl`` and the
                ``incidents`` status block; replayed jax-free by
                tools/incident_report.py.
  replay.py     The shared torn-tail-tolerant JSONL reader every jax-free
                replay tool folds metrics.jsonl / incidents.jsonl through.
  forensics.py  Per-worker Byzantine forensics (ISSUE 7): the coded steps'
                (n,) accusation/present/seeded-adversary masks packed into
                f32-carried uint32 bitmask columns riding the (K, m) metric
                block, and the host ``AccusationLedger`` folding them (via
                the heartbeat's observer hook) into per-worker counters,
                trust scores, and attack episodes — the ``forensics`` block
                of status.json and the input to tools/forensics_report.py.

The in-graph half of the telemetry (decode-health metric columns) lives
where the math lives: coding/cyclic.py + coding/repetition.py produce the
per-step health values inside the jitted programs, and they ride the
existing (K, m) metric block through DeferredMetricWriter — named scopes
and metric columns, never host callbacks, so every registered program
stays green under the PR 3 linter's host_traffic rule.
"""

from draco_tpu.obs.compile_watch import (
    CompileWatch,
    RetraceError,
    RetraceWarning,
    make_compile_watch,
)
from draco_tpu.obs.forensics import AccusationLedger
from draco_tpu.obs.heartbeat import (
    STATUS_SCHEMA,
    RunHeartbeat,
    check_status_schema,
)
from draco_tpu.obs.incidents import IncidentEngine, make_engine
from draco_tpu.obs.profiling import NULL_PROFILER_WINDOW, profiler_window
from draco_tpu.obs.tracer import NULL_TRACER, SpanTracer, make_tracer

__all__ = ["NULL_PROFILER_WINDOW", "NULL_TRACER", "STATUS_SCHEMA",
           "AccusationLedger", "CompileWatch", "IncidentEngine",
           "RetraceError", "RetraceWarning", "RunHeartbeat", "SpanTracer",
           "check_status_schema", "make_compile_watch", "make_engine",
           "make_tracer", "profiler_window"]
