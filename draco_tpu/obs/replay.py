"""Shared JSONL replay scaffold for the jax-free artifact tools.

``tools/forensics_report.py``, ``tools/trace_report.py``,
``tools/incident_report.py`` and ``tools/chaos_run.py`` all replay a run's
``metrics.jsonl`` (and now ``incidents.jsonl``) on the host, and each used
to hand-roll the same partial-artifact tolerance: a run killed mid-write
leaves a missing file, an empty file, or a torn final line, and none of
those states may take a report down. This module is the ONE reader they
share (ISSUE 13 satellite), so the tolerance rules cannot drift between
tools:

  * missing / unreadable file  -> yields nothing
  * blank lines                -> skipped
  * torn (non-JSON) tail line  -> skipped
  * non-dict JSON line         -> skipped

Stdlib-only and jax-free — the same discipline as the rest of
draco_tpu/obs: every consumer runs on a laptop against artifacts scp'd
from a chip job.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, List, NamedTuple, Optional


def iter_jsonl(path: str) -> Iterator[dict]:
    """Yield every dict record of a JSONL file, tolerating the partial
    states a killed run leaves behind (module docstring)."""
    try:
        fh = open(path)
    except OSError:
        return
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line of an interrupted run
            if isinstance(rec, dict):
                yield rec


def train_records(path: str, require_loss: bool = True) -> List[dict]:
    """The run's TRAIN records from metrics.jsonl: eval records dropped,
    and (by default) records without a ``loss`` — the same stream the
    heartbeat's observer hook sees live, so a host ledger replayed over
    these records reproduces the live fold whenever every step was logged
    (``log_every=1``, the chaos/report discipline)."""
    out = []
    for rec in iter_jsonl(path):
        if rec.get("split") == "eval":
            continue
        if require_loss and "loss" not in rec:
            continue
        out.append(rec)
    return out


def record_at_step(path: str, step: int) -> Optional[dict]:
    """The LAST train record at ``step`` (re-runs in a shared train_dir
    append; the newest wins), or None."""
    rec = None
    for r in train_records(path, require_loss=True):
        if r.get("step") == step:
            rec = r
    return rec


def metrics_path(path: str) -> str:
    """Resolve a train_dir (or a direct file path) to its metrics.jsonl."""
    if os.path.isdir(path):
        return os.path.join(path, "metrics.jsonl")
    return path


class RunFiles(NamedTuple):
    """The one run-dir layout contract (ISSUE 19 satellite): every
    jax-free consumer that folds a run directory resolves its artifact
    paths through :func:`find_run_files` instead of re-deriving the
    joins inline — incident_report, forensics_report and the fleet
    registry all read the same three files by construction. Any path
    may point at a file that does not exist; existence is the READER's
    concern (iter_jsonl tolerates absence)."""

    root: str
    status: str
    metrics: str
    incidents: str


def find_run_files(path: str) -> RunFiles:
    """Resolve a train_dir (or a direct metrics.jsonl path — the
    historical CLI contract of the replay tools) to the run's artifact
    paths. Never touches the filesystem beyond one ``isdir``."""
    metrics = metrics_path(path)
    root = path if os.path.isdir(path) else (os.path.dirname(path) or ".")
    return RunFiles(root=root,
                    status=os.path.join(root, "status.json"),
                    metrics=metrics,
                    incidents=os.path.join(root, "incidents.jsonl"))


def infer_num_workers(records: List[dict], status_path: str,
                      tool: str = "obs/replay.py") -> int:
    """The worker-count fallback chain the per-worker replay tools share
    (forensics_report / incident_report): the run's status.json forensics
    block (schema-validated against the central contract table), else the
    highest worker ever marked present in the packed masks + 1 — the
    inference only under-counts workers that never sent a single row,
    which contribute nothing to any counter."""
    import json

    from draco_tpu.obs.forensics import MASK_PREFIX, unpack_bits
    from draco_tpu.obs.heartbeat import check_status_schema

    try:
        with open(status_path) as fh:
            status = json.load(fh)
        if isinstance(status, dict):
            check_status_schema(status, status_path, tool)
            n = (status.get("forensics") or {}).get("num_workers")
            if n:
                return int(n)
    except (OSError, ValueError):
        pass
    hi = 0
    for rec in records:
        words = []
        w = 0
        while f"{MASK_PREFIX}present{w}" in rec:
            words.append(int(rec[f"{MASK_PREFIX}present{w}"]))
            w += 1
        if words:
            bits = unpack_bits(words, len(words) * 32)
            if any(bits):
                hi = max(hi, max(i for i, b in enumerate(bits) if b) + 1)
    return max(hi, 1)
