"""Fleet observatory: multi-run registry + SLO engine + roll-up (ISSUE 19).

ROADMAP item 4 ("fleet-as-a-service") needs per-job artifacts rolled up
into a fleet-level dashboard + SLO report before the autopilot can be
promoted from run babysitter to fleet scheduler. This module is that
observation half, and it follows the obs/ discipline end to end:

  * importable WITHOUT jax (like incidents.py / replay.py) — every
    consumer runs on a laptop against artifacts scp'd from a chip job;
  * zero device cost — it only folds files the runs already write
    (status.json, metrics.jsonl, incidents.jsonl); no extra fetches,
    no graph changes;
  * torn / empty / missing inputs degrade with a visible note on the
    RunSummary, never a traceback (obs/replay tolerance rules).

Three layers:

**RunRegistry** — discovers run directories and folds each one's
status.json (validated through the central ``check_status_schema``
contract), incidents.jsonl, and metrics.jsonl tail into a
:class:`RunSummary`. A resumed run (same ``run_id`` across attempts, or
an incident-stream seq reset inside one dir) folds as ONE run. A
crashed run (``state: "crashed"``) folds as an SLO violation, not a
parse error.

**SLO engine** — declaratively registered, mirroring the PR 13
``register_detector``/``detector_table()`` pattern: ``@register_slo``
classes land in the enumerable ``SLOS`` registry, thresholds are
overridable via ``parse_slo_thresholds("<slo>.<key>=<float>")``. Each
SLO evaluates one RunSummary into an error budget (``budget`` /
``burned`` / ``burn_frac``), burn-rate windows (max burn inside
trailing fast/slow step windows), and a typed verdict
(``ok | violated | not_evaluated``). Six SLOs ship: step-availability,
detection-quality (the Draco P/R certificate as an SLO — never
evaluated on the baseline approach, which emits no columns),
decode-health, throughput (vs the run's own warm baseline), incident
MTTR/MTTD (onset→remediation latency joined from autopilot
``remediation`` events in the same stream), and the wire-byte budget.

**Fleet roll-up** — cross-run per-worker trust fold (a worker accused
in 3 of 4 runs outranks a one-run spike), fleet compute-to-target, and
per-run SLO compliance; emitted by ``tools/fleet_report.py`` and
proven by ``tools/fleet_study.py`` → ``baselines_out/fleet_slo.json``.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

from draco_tpu.obs import replay
from draco_tpu.obs.forensics import AccusationLedger, record_masks
from draco_tpu.obs.heartbeat import STATUS_SCHEMA, check_status_schema

# fleet.json / fleet_slo.json payload schema (bump on shape changes)
FLEET_SCHEMA = 1

# typed SLO verdicts — the only three states a fleet report may print
VERDICTS = ("ok", "violated", "not_evaluated")

# SLOs whose burn is a pure function of the committed artifacts (no
# wall-clock in the *burn* accounting) — their per-run burn sum is the
# ``budget_burned`` scalar perf_watch pins at 0 on clean cells
DETERMINISTIC_SLOS = ("step_availability", "detection_quality",
                      "decode_health", "wire_bytes")

# metrics.jsonl tail cap per run: the registry folds at most this many
# train records (newest kept). Long-run cumulative truth (detection
# P/R, guard totals) comes from status.json; the tail feeds the
# step-resolved folds (residuals, rates, burn windows).
DEFAULT_TAIL = 4096

# steps each offline throughput sample spans: records materialize in
# per-chunk flush BURSTS (a chunk's K records share one wall-clock
# neighborhood), so record-to-record deltas measure flush cadence, not
# training rate — every rate sample divides >= RATE_SPAN steps by the
# wall clock they actually took, which averages across flush bursts
RATE_SPAN = 8

_FLAGGED_KEYS = ("located_errors", "det_flagged")


# --------------------------------------------------------------------------
# RunSummary + fold
# --------------------------------------------------------------------------


@dataclasses.dataclass
class RunSummary:
    """One run directory folded into plain data. Every field is optional
    in spirit: a torn or partial run leaves Nones/empties plus a note —
    the SLO layer decides what is evaluable, the fold never raises."""

    run_dir: str
    run_id: Optional[str] = None
    job_name: Optional[str] = None
    schema: Optional[int] = None
    state: Optional[str] = None
    status: Dict[str, Any] = dataclasses.field(default_factory=dict)
    step: Optional[int] = None
    total_steps: Optional[int] = None
    steps_per_s: Optional[float] = None
    loss: Optional[float] = None
    updated_at: Optional[float] = None
    # metrics tail fold
    records: int = 0
    first_step: Optional[int] = None
    last_step: Optional[int] = None
    losses: List[Tuple[int, float]] = dataclasses.field(default_factory=list)
    skipped_events: List[Tuple[int, float]] = \
        dataclasses.field(default_factory=list)
    guard_trips: float = 0.0
    skipped_steps: float = 0.0
    guard_seen: bool = False
    detection: Optional[Dict[str, float]] = None
    residuals: List[Tuple[int, float, Optional[float]]] = \
        dataclasses.field(default_factory=list)
    rates: List[Tuple[int, float]] = dataclasses.field(default_factory=list)
    record_times: Dict[int, float] = dataclasses.field(default_factory=dict)
    num_workers: Optional[int] = None
    worker_rows: Optional[List[dict]] = None  # replayed forensics fold
    forensics: Optional[dict] = None          # status.json summary block
    wire: Optional[dict] = None
    control: Optional[dict] = None
    # incidents stream
    events: List[dict] = dataclasses.field(default_factory=list)
    remediations: List[dict] = dataclasses.field(default_factory=list)
    resumed: bool = False
    attempts: int = 1
    notes: List[str] = dataclasses.field(default_factory=list)

    @property
    def steps_observed(self) -> int:
        """Steps the fold has evidence for: the record span when the
        tail carries steps, else the status step counter."""
        if self.first_step is not None and self.last_step is not None:
            return self.last_step - self.first_step + 1
        return int(self.step or 0)

    def label(self) -> str:
        base = os.path.basename(os.path.normpath(self.run_dir)) or \
            self.run_dir
        return self.job_name or base


def _fold_status(out: RunSummary, status_path: str, tool: str) -> None:
    try:
        with open(status_path) as fh:
            status = json.load(fh)
    except OSError:
        out.notes.append("status.json missing")
        return
    except ValueError:
        out.notes.append("status.json torn/unreadable")
        return
    if not isinstance(status, dict):
        out.notes.append("status.json not an object")
        return
    try:
        check_status_schema(status, status_path, tool)
    except SystemExit as e:
        # an unknown (newer) schema must not take the whole fleet
        # report down — the run degrades to metrics-only with a note
        out.notes.append(f"status.json rejected: {e}")
        return
    out.status = status
    out.schema = status.get("schema")
    out.state = status.get("state")
    out.run_id = status.get("run_id")
    out.job_name = status.get("job_name")
    out.step = status.get("step")
    out.total_steps = status.get("total_steps")
    out.steps_per_s = status.get("steps_per_s")
    out.loss = status.get("loss")
    out.updated_at = status.get("updated_at")
    out.forensics = status.get("forensics")
    out.wire = status.get("wire")
    out.control = status.get("control")
    if out.schema is not None and out.schema < 5 and out.run_id is None:
        out.notes.append(f"pre-run_id status (schema {out.schema})")
    guard = status.get("guard")
    if isinstance(guard, dict):
        out.guard_seen = True
        out.guard_trips = float(guard.get("trips", 0.0))
        out.skipped_steps = float(guard.get("skipped_steps", 0.0))
    health = status.get("decode_health")
    if isinstance(health, dict):
        out.detection = {
            "precision": float(health.get("precision", 1.0)),
            "recall": float(health.get("recall", 1.0)),
            "flagged_total": float(health.get("flagged_total", 0.0)),
            "adv_total": float(health.get("adv_total", 0.0)),
        }


def _fold_records(out: RunSummary, files: replay.RunFiles,
                  tail: int) -> None:
    recs: "collections.deque[dict]" = collections.deque(maxlen=tail)
    total = 0
    for rec in replay.train_records(files.metrics):
        recs.append(rec)
        total += 1
    if not total:
        out.notes.append("metrics.jsonl missing or empty")
        return
    if total > len(recs):
        out.notes.append(
            f"metrics tail: folded newest {len(recs)}/{total} records")
    out.records = len(recs)
    det_tp = det_adv = det_flagged = 0.0
    det_seen = False
    prev_step: Optional[int] = None
    prev_time: Optional[float] = None
    any_masks = False
    for rec in recs:
        step = rec.get("step")
        step = int(step) if step is not None else None
        if step is not None:
            if out.first_step is None:
                out.first_step = step
            out.last_step = step
        if "loss" in rec and step is not None:
            out.losses.append((step, float(rec["loss"])))
        if "guard_trips" in rec:
            if not out.guard_seen:
                # recompute only when status carried no cumulative
                # guard block (torn run) — the tail may undercount
                out.guard_trips += float(rec["guard_trips"])
                out.skipped_steps += float(rec.get("skipped_steps", 0.0))
            skipped = float(rec.get("skipped_steps", 0.0))
            if step is not None:
                out.skipped_events.append((step, skipped))
        if "det_tp" in rec:
            det_seen = True
            det_tp += float(rec["det_tp"])
            det_adv += float(rec.get("det_adv", 0.0))
            for k in _FLAGGED_KEYS:
                if k in rec:
                    det_flagged += float(rec[k])
                    break
        if "decode_residual" in rec and step is not None:
            bound = rec.get("decode_residual_bound")
            out.residuals.append(
                (step, float(rec["decode_residual"]),
                 float(bound) if bound is not None else None))
        t = rec.get("time")
        if t is not None and step is not None:
            if prev_step is None or step > prev_step:
                out.record_times[step] = float(t)
                prev_step, prev_time = step, float(t)
        if "wmask_accused0" in rec:
            any_masks = True
    del prev_time
    pts = sorted(out.record_times.items())
    base = 0
    for i, (step, t) in enumerate(pts):
        # newest base point at least RATE_SPAN steps back
        while base + 1 < i and pts[base + 1][0] <= step - RATE_SPAN:
            base += 1
        bstep, bt = pts[base]
        if bstep <= step - RATE_SPAN and t > bt:
            out.rates.append((step, (step - bstep) / (t - bt)))
    if det_seen and out.detection is None:
        out.detection = {
            "precision": (det_tp / det_flagged) if det_flagged else 1.0,
            "recall": (det_tp / det_adv) if det_adv else 1.0,
            "flagged_total": det_flagged,
            "adv_total": det_adv,
        }
    if any_masks:
        n = replay.infer_num_workers(list(recs), files.status,
                                     tool="obs/fleet.py")
        out.num_workers = n
        ledger = AccusationLedger(n)
        for rec in recs:
            ledger.observe(rec, masks=record_masks(rec, n))
        out.worker_rows = ledger.worker_rows()
    elif out.forensics:
        out.num_workers = out.forensics.get("num_workers")


def _fold_incidents(out: RunSummary, incidents_path: str) -> None:
    prev_seq: Optional[int] = None
    resets = 0
    for ev in replay.iter_jsonl(incidents_path):
        if "event" not in ev:
            continue
        seq = ev.get("seq")
        if isinstance(seq, int):
            if prev_seq is not None and seq <= prev_seq:
                resets += 1
            prev_seq = seq
        out.events.append(ev)
        if ev.get("event") == "remediation":
            out.remediations.append(ev)
    if resets:
        out.resumed = True
        out.attempts = resets + 1
        out.notes.append(
            f"incident seq reset x{resets}: folded as one resumed run "
            f"({resets + 1} attempts)")


def fold_run(path: str, tail: int = DEFAULT_TAIL,
             tool: str = "obs/fleet.py") -> RunSummary:
    """Fold one run directory (or metrics.jsonl path) into a RunSummary.
    Never raises on torn/empty/missing inputs — degradations land in
    ``notes``."""
    files = replay.find_run_files(path)
    out = RunSummary(run_dir=files.root)
    _fold_status(out, files.status, tool)
    _fold_records(out, files, tail)
    _fold_incidents(out, files.incidents)
    return out


class RunRegistry:
    """Discovers run directories and folds them into RunSummaries,
    merging attempts that share a ``run_id`` so a resumed run counts as
    ONE run in every roll-up."""

    def __init__(self, run_dirs: List[str], tail: int = DEFAULT_TAIL,
                 tool: str = "obs/fleet.py"):
        self.summaries = _merge_attempts(
            [fold_run(d, tail=tail, tool=tool) for d in run_dirs])

    @staticmethod
    def discover(root: str) -> List[str]:
        """Run directories under ``root``: every directory holding a
        status.json or metrics.jsonl (sorted, stable)."""
        found = []
        for dirpath, _dirnames, filenames in os.walk(root):
            if "status.json" in filenames or "metrics.jsonl" in filenames:
                found.append(dirpath)
        return sorted(found)


def _merge_attempts(summaries: List[RunSummary]) -> List[RunSummary]:
    by_id: Dict[str, List[RunSummary]] = {}
    order: List[Tuple[str, RunSummary]] = []
    for i, s in enumerate(summaries):
        key = s.run_id or f"__anon_{i}__"
        if key not in by_id:
            order.append((key, s))
        by_id.setdefault(key, []).append(s)
    out = []
    for key, _first in order:
        group = by_id[key]
        if len(group) == 1:
            out.append(group[0])
            continue
        primary = max(group, key=lambda s: ((s.updated_at or 0.0),
                                            s.records))
        primary.resumed = True
        primary.attempts += sum(g.attempts for g in group
                                if g is not primary)
        primary.notes.append(
            f"run_id {key} seen in {len(group)} dirs: folded as one "
            f"resumed run (kept {primary.run_dir})")
        out.append(primary)
    return out


# --------------------------------------------------------------------------
# SLO registry (mirrors obs/incidents.register_detector)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One registered SLO: identity + declarative threshold defaults
    (every key overridable via ``parse_slo_thresholds`` strings)."""

    name: str
    thresholds: Dict[str, float]
    doc: str
    factory: Any


SLOS: Dict[str, SLOSpec] = {}


def register_slo(name: str, thresholds: Dict[str, float]):
    """Class decorator declaring an SLO into the enumerable registry.
    The class must expose ``evaluate(run: RunSummary) -> dict`` built on
    :func:`slo_result` so every verdict is typed the same way."""

    def deco(cls):
        SLOS[name] = SLOSpec(
            name=name, thresholds=dict(thresholds),
            doc=(cls.__doc__ or "").strip().splitlines()[0], factory=cls)
        return cls

    return deco


def slo_table() -> List[dict]:
    """The enumerable SLO set (PERF.md §21's table source)."""
    return [{"name": s.name, "thresholds": dict(s.thresholds),
             "doc": s.doc} for s in SLOS.values()]


def parse_slo_thresholds(spec: str) -> Dict[str, float]:
    """``"throughput.floor_frac=0.25,mttr.mttr_max_s=60"`` -> override
    dict. Unknown SLO or threshold keys are config-time errors (the
    registry is the contract), values must parse as floats."""
    out: Dict[str, float] = {}
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        try:
            key, val = item.split("=", 1)
            slo, th = key.strip().split(".", 1)
            fval = float(val)
        except ValueError:
            raise ValueError(
                f"SLO threshold {item!r} is not '<slo>.<key>=<float>'")
        if slo not in SLOS:
            raise ValueError(
                f"unknown SLO {slo!r} (registered: "
                f"{', '.join(sorted(SLOS))})")
        if th not in SLOS[slo].thresholds:
            raise ValueError(
                f"SLO {slo!r} has no threshold {th!r} (declared: "
                f"{', '.join(sorted(SLOS[slo].thresholds))})")
        out[f"{slo}.{th}"] = fval
    return out


def make_slos(overrides: Any = "") -> Dict[str, Any]:
    """Instantiate every registered SLO with defaults + overrides
    (string spec or an already-parsed dict)."""
    if isinstance(overrides, str):
        overrides = parse_slo_thresholds(overrides)
    out = {}
    for name, spec in SLOS.items():
        th = dict(spec.thresholds)
        for key, val in (overrides or {}).items():
            slo, tkey = key.split(".", 1)
            if slo == name:
                th[tkey] = val
        out[name] = spec.factory(th)
    return out


def slo_result(name: str, evaluated: bool, ok: Optional[bool] = None,
               budget: float = 0.0, burned: float = 0.0,
               windows: Optional[dict] = None, detail: str = "",
               **extra) -> dict:
    """The one typed-verdict shape every SLO returns. ``burn_frac`` is
    None when a zero budget burned anyway (an infinite burn rate — kept
    JSON-clean instead of Infinity)."""
    if evaluated:
        verdict = "ok" if ok else "violated"
        if budget > 0:
            burn_frac: Optional[float] = burned / budget
        else:
            burn_frac = 0.0 if burned <= 0 else None
    else:
        verdict, ok, burn_frac = "not_evaluated", None, None
    return dict({
        "slo": name,
        "evaluated": bool(evaluated),
        "ok": ok if ok is None else bool(ok),
        "verdict": verdict,
        "budget": float(budget),
        "burned": float(burned),
        "burn_frac": burn_frac,
        "windows": windows or {},
        "detail": detail,
    }, **extra)


def burn_windows(events: List[Tuple[int, float]],
                 windows: Dict[str, float]) -> dict:
    """Max burn inside any trailing window of W steps, per named window
    — the burn-RATE half of the error budget: a slow leak and a spike
    can burn the same total, only the window fold tells them apart."""
    out = {}
    evs = sorted((int(s), float(b)) for s, b in events)
    for label, w in windows.items():
        w = max(int(w), 1)
        best, best_at, lo, acc = 0.0, None, 0, 0.0
        for hi, (step, b) in enumerate(evs):
            acc += b
            while evs[lo][0] <= step - w:
                acc -= evs[lo][1]
                lo += 1
            if acc > best:
                best, best_at = acc, step
        out[label] = {"steps": w, "max_burn": best, "at_step": best_at}
    return out


class _SLO:
    def __init__(self, thresholds: Dict[str, float]):
        self.th = dict(thresholds)


@register_slo("step_availability",
              thresholds={"budget_frac": 0.02, "window_fast": 8.0,
                          "window_slow": 32.0})
class StepAvailabilitySLO(_SLO):
    """Step availability: guard-skipped steps vs an availability budget
    (budget_frac of observed steps); a crashed terminal state is an
    availability violation by definition, never a parse error."""

    def evaluate(self, run: RunSummary) -> dict:
        crashed = run.state == "crashed"
        if not run.records and run.step is None and not crashed:
            return slo_result("step_availability", False,
                              detail="no step evidence "
                                     "(no records, no status)")
        steps = max(run.steps_observed, 1)
        burned = float(run.skipped_steps)
        budget = self.th["budget_frac"] * steps
        wins = burn_windows(run.skipped_events,
                            {"fast": self.th["window_fast"],
                             "slow": self.th["window_slow"]})
        ok = burned <= budget and not crashed
        if crashed:
            cause = run.status.get("cause")
            detail = "terminal state 'crashed'" + \
                (f": {cause}" if cause else "")
        else:
            detail = (f"{burned:g} skipped of {steps} steps "
                      f"(budget {budget:g})")
        return slo_result("step_availability", True, ok=ok,
                          budget=budget, burned=burned, windows=wins,
                          detail=detail, crashed=crashed,
                          guard_trips=run.guard_trips)


@register_slo("detection_quality",
              thresholds={"precision_floor": 1.0, "recall_floor": 1.0,
                          "window_fast": 8.0, "window_slow": 32.0})
class DetectionQualitySLO(_SLO):
    """Detection quality: the Draco P/R-1.0 certificate as an SLO —
    burned = false accusations + missed adversaries; never evaluated on
    the baseline approach, which emits no detection columns."""

    def evaluate(self, run: RunSummary) -> dict:
        det = run.detection
        if det is None:
            return slo_result("detection_quality", False,
                              detail="no detection columns "
                                     "(baseline route or no records)")
        p, r = det["precision"], det["recall"]
        flagged, adv = det["flagged_total"], det["adv_total"]
        tp = min(p * flagged, r * adv) if (flagged and adv) else \
            (p * flagged if flagged else r * adv)
        burned = max(flagged - tp, 0.0) + max(adv - tp, 0.0)
        budget = ((1.0 - self.th["precision_floor"]) * flagged
                  + (1.0 - self.th["recall_floor"]) * adv)
        ok = (p >= self.th["precision_floor"] - 1e-12
              and r >= self.th["recall_floor"] - 1e-12)
        return slo_result(
            "detection_quality", True, ok=ok, budget=budget,
            burned=burned,
            detail=f"precision {p:g} recall {r:g} "
                   f"(floors {self.th['precision_floor']:g}/"
                   f"{self.th['recall_floor']:g})",
            precision=p, recall=r, flagged_total=flagged, adv_total=adv)


@register_slo("decode_health",
              thresholds={"cyclic_tol": 1e-3, "bound_frac": 0.95,
                          "ew_alpha": 0.25, "crossing_budget": 0.0,
                          "window_fast": 8.0, "window_slow": 32.0})
class DecodeHealthSLO(_SLO):
    """Decode health: cyclic residual tolerance crossings (exact decode
    must sit at numerical noise) and approx EW residual/bound drift
    toward the certificate edge."""

    def evaluate(self, run: RunSummary) -> dict:
        if not run.residuals:
            return slo_result("decode_health", False,
                              detail="no residual columns in tail")
        tol = self.th["cyclic_tol"]
        alpha = self.th["ew_alpha"]
        events = []
        burned = 0.0
        ew: Optional[float] = None
        hard = 0
        for step, res, bound in run.residuals:
            if bound is None:
                bad = (not math.isfinite(res)) or res > tol
            else:
                ratio = (res / bound) if bound > 0 else \
                    (0.0 if res == 0 else float("inf"))
                if math.isfinite(ratio):
                    ew = ratio if ew is None else \
                        (1 - alpha) * ew + alpha * ratio
                bad = (not math.isfinite(res)) or \
                    (math.isfinite(bound) and res > bound)
            if bad:
                hard += 1
                burned += 1.0
                events.append((step, 1.0))
        drift = ew is not None and ew > self.th["bound_frac"]
        budget = self.th["crossing_budget"]
        ok = burned <= budget and not drift
        wins = burn_windows(events, {"fast": self.th["window_fast"],
                                     "slow": self.th["window_slow"]})
        detail = (f"{hard} residual crossings / {len(run.residuals)} "
                  f"rows" + (f"; EW residual/bound {ew:.3g} over "
                             f"{self.th['bound_frac']:g}" if drift
                             else ""))
        return slo_result("decode_health", True, ok=ok, budget=budget,
                          burned=burned, windows=wins, detail=detail,
                          ew_residual_over_bound=ew)


@register_slo("throughput",
              thresholds={"warmup": 3.0, "ew_alpha": 0.3,
                          "floor_frac": 0.3, "budget_frac": 0.1,
                          "window_fast": 8.0, "window_slow": 32.0})
class ThroughputSLO(_SLO):
    """Throughput: EW steps/s from the records' wall-clock stream vs
    the run's own warm baseline — burn = post-warmup samples below
    floor_frac of the warm median."""

    def evaluate(self, run: RunSummary) -> dict:
        warmup = int(self.th["warmup"])
        rates = run.rates
        if len(rates) <= warmup + 1:
            return slo_result("throughput", False,
                              detail=f"{len(rates)} rate samples "
                                     f"(need > {warmup + 1})")
        warm = sorted(r for _s, r in rates[warmup:warmup + 5])
        baseline = warm[len(warm) // 2]
        alpha = self.th["ew_alpha"]
        floor = self.th["floor_frac"] * baseline
        ew = baseline
        events = []
        burned = 0.0
        for step, r in rates[warmup:]:
            ew = (1 - alpha) * ew + alpha * r
            if r < floor:
                burned += 1.0
                events.append((step, 1.0))
        samples = len(rates) - warmup
        budget = self.th["budget_frac"] * samples
        ok = burned <= budget
        wins = burn_windows(events, {"fast": self.th["window_fast"],
                                     "slow": self.th["window_slow"]})
        return slo_result(
            "throughput", True, ok=ok, budget=budget, burned=burned,
            windows=wins,
            detail=f"{burned:g}/{samples} samples under "
                   f"{floor:.3g} steps/s (warm baseline "
                   f"{baseline:.3g})",
            warm_baseline=baseline, ew_steps_per_s=ew)


@register_slo("incident_mttr",
              thresholds={"mttr_max_s": 300.0, "mttd_max_s": 300.0})
class IncidentMttrSLO(_SLO):
    """Incident MTTR/MTTD: onset→remediation wall-clock latency joined
    from autopilot ``remediation`` events in the same incident stream
    (MTTR), and onset-step record time → onset event time (MTTD);
    unattributed remediations burn the (zero) budget."""

    def evaluate(self, run: RunSummary) -> dict:
        onsets = {}
        detect_lags = []
        for ev in run.events:
            if ev.get("event") != "onset":
                continue
            key = (ev.get("type"), ev.get("onset_step"))
            onsets.setdefault(key, ev)
            ts = ev.get("ts")
            step_t = run.record_times.get(ev.get("onset_step"))
            if ts is not None and step_t is not None:
                detect_lags.append(max(float(ts) - step_t, 0.0))
        if not run.remediations:
            return slo_result(
                "incident_mttr", False,
                detail=f"no remediation events "
                       f"({len(onsets)} onsets)",
                mttd_s=(sum(detect_lags) / len(detect_lags)
                        if detect_lags else None))
        latencies = []
        unattributed = 0
        for rem in run.remediations:
            trig = rem.get("trigger") or {}
            key = (trig.get("type"), trig.get("onset_step"))
            onset = onsets.get(key)
            ts, onset_ts = rem.get("ts"), \
                (onset or {}).get("ts")
            if onset is None or ts is None or onset_ts is None:
                unattributed += 1
                continue
            lat = float(ts) - float(onset_ts)
            if not math.isfinite(lat) or lat < 0:
                unattributed += 1
                continue
            latencies.append(lat)
        mttr = (sum(latencies) / len(latencies)) if latencies else None
        mttd = (sum(detect_lags) / len(detect_lags)) if detect_lags \
            else None
        slow = sum(1 for x in latencies if x > self.th["mttr_max_s"])
        slow += sum(1 for x in detect_lags
                    if x > self.th["mttd_max_s"])
        burned = float(unattributed + slow)
        ok = burned == 0 and mttr is not None
        return slo_result(
            "incident_mttr", True, ok=ok, budget=0.0, burned=burned,
            detail=f"{len(latencies)}/{len(run.remediations)} "
                   f"remediations attributed; MTTR "
                   f"{'%.3gs' % mttr if mttr is not None else 'n/a'}",
            mttr_s=mttr, mttd_s=mttd,
            remediations=len(run.remediations),
            attributed=len(latencies), unattributed=unattributed)


@register_slo("wire_bytes", thresholds={"tol_frac": 0.0})
class WireBytesSLO(_SLO):
    """Wire-byte budget: the status ``wire`` block must stay internally
    consistent with its own ledger — the materialized dtype's physical
    bytes equal the logical candidate row, per-step = per-worker × n,
    and the segment bytes sum to the whole."""

    def evaluate(self, run: RunSummary) -> dict:
        wire = run.wire
        if not isinstance(wire, dict):
            return slo_result("wire_bytes", False,
                              detail="no wire block in status.json")
        tol = self.th["tol_frac"]
        problems = []

        def close(a, b):
            a, b = float(a), float(b)
            return abs(a - b) <= tol * max(abs(a), abs(b), 1.0)

        dtype = wire.get("wire_dtype")
        cand = (wire.get("bytes_per_worker") or {}).get(dtype)
        phys_w = wire.get("physical_bytes_per_worker")
        phys_s = wire.get("physical_bytes_per_step")
        n = wire.get("num_workers")
        if cand is None or phys_w is None:
            problems.append(f"ledger missing dtype row {dtype!r}")
        elif not close(cand, phys_w):
            problems.append(
                f"physical_bytes_per_worker {phys_w} != ledger "
                f"{dtype} row {cand}")
        if None not in (phys_w, phys_s, n) and \
                not close(phys_s, float(phys_w) * float(n)):
            problems.append(
                f"physical_bytes_per_step {phys_s} != per_worker x "
                f"{n}")
        segs = wire.get("segments")
        if isinstance(segs, dict) and phys_w is not None:
            seg_sum = sum(segs.get("physical_bytes_per_worker") or [])
            if not close(seg_sum, phys_w):
                problems.append(
                    f"segment bytes sum {seg_sum} != per_worker "
                    f"{phys_w}")
        burned = float(len(problems))
        return slo_result(
            "wire_bytes", True, ok=not problems, budget=0.0,
            burned=burned,
            detail="; ".join(problems) if problems else
                   f"{dtype} wire consistent "
                   f"({phys_w} B/worker/step)",
            wire_dtype=dtype,
            physical_bytes_per_step=phys_s)


def evaluate_run(run: RunSummary,
                 slos: Optional[Dict[str, Any]] = None) -> Dict[str, dict]:
    """Every registered SLO evaluated on one RunSummary (registry
    order)."""
    slos = slos if slos is not None else make_slos()
    return {name: slo.evaluate(run) for name, slo in slos.items()}


def budget_burned(results: Dict[str, dict]) -> float:
    """The run's deterministic error-budget burn — the scalar the
    committed fleet study pins at 0 on clean cells (throughput and
    MTTR burn wall-clock-dependent amounts and are gated separately)."""
    return sum(results[name]["burned"] for name in DETERMINISTIC_SLOS
               if name in results and results[name]["evaluated"])


# --------------------------------------------------------------------------
# fleet roll-up
# --------------------------------------------------------------------------


def worker_rollup(summaries: List[RunSummary], top: int = 8) -> List[dict]:
    """Cross-run per-worker trust fold: rank by the number of RUNS that
    accused the worker first (a worker accused in 3 of 4 runs outranks
    a one-run spike), then by total accusations, then by minimum
    trust."""
    stats: Dict[int, dict] = {}
    for s in summaries:
        rows = s.worker_rows
        if rows is None and s.forensics:
            # degraded path: no records to replay — use the status
            # block's trust vector + top suspects
            trust = s.forensics.get("trust") or []
            suspects = {d.get("worker"): d.get("accused", 0)
                        for d in (s.forensics.get("top_suspects") or [])}
            rows = [{"worker": w, "accused": suspects.get(w, 0),
                     "trust": t} for w, t in enumerate(trust)]
        if not rows:
            continue
        for row in rows:
            w = int(row["worker"])
            st = stats.setdefault(
                w, {"worker": w, "runs_seen": 0, "runs_accusing": 0,
                    "accused_total": 0, "min_trust": 1.0,
                    "trust_sum": 0.0})
            st["runs_seen"] += 1
            st["accused_total"] += int(row.get("accused", 0))
            if row.get("accused", 0):
                st["runs_accusing"] += 1
            t = float(row.get("trust", 1.0))
            st["min_trust"] = min(st["min_trust"], t)
            st["trust_sum"] += t
    out = []
    for st in stats.values():
        st["mean_trust"] = round(st.pop("trust_sum") / st["runs_seen"], 4)
        out.append(st)
    out.sort(key=lambda r: (-r["runs_accusing"], -r["accused_total"],
                            r["min_trust"], r["worker"]))
    return out[:top]


def compute_rollup(summaries: List[RunSummary],
                   target_loss: Optional[float] = None) -> dict:
    """Fleet compute-to-target: per-run worker-steps spent, and (when a
    target loss is given) the worker-steps each run needed to first
    reach it — the autopilot_study objective lifted to the fleet."""
    by_run = []
    total_ws = 0.0
    for s in summaries:
        n = s.num_workers or 0
        steps = s.steps_observed
        ws = float(steps * n)
        total_ws += ws
        to_target = None
        if target_loss is not None:
            first = s.first_step
            for step, loss in s.losses:
                if loss <= target_loss:
                    base = first if first is not None else step
                    to_target = float((step - base + 1) * n)
                    break
        by_run.append({"run": s.label(), "run_id": s.run_id,
                       "steps": steps, "workers": n,
                       "worker_steps": ws, "final_loss": s.loss,
                       "worker_steps_to_target": to_target})
    reached = [r["worker_steps_to_target"] for r in by_run
               if r["worker_steps_to_target"] is not None]
    return {
        "target_loss": target_loss,
        "total_worker_steps": total_ws,
        "runs_reaching_target": len(reached) if target_loss is not None
        else None,
        "worker_steps_to_target_total": (sum(reached) if reached
                                         else None),
        "by_run": by_run,
    }


def fleet_fold(summaries: List[RunSummary], overrides: Any = "",
               target_loss: Optional[float] = None) -> dict:
    """The whole fleet folded: per-run SLO results + compliance counts,
    the cross-run worker table, and compute-to-target — the fleet.json
    / fleet_slo.json body."""
    slos = make_slos(overrides)
    runs = []
    compliance = {name: {"ok": 0, "violated": 0, "not_evaluated": 0}
                  for name in SLOS}
    all_ok = True
    for s in summaries:
        results = evaluate_run(s, slos)
        for name, res in results.items():
            compliance[name][res["verdict"]] += 1
            if res["verdict"] == "violated":
                all_ok = False
        runs.append({
            "run": s.label(), "run_dir": s.run_dir, "run_id": s.run_id,
            "job_name": s.job_name, "state": s.state,
            "schema": s.schema, "steps": s.steps_observed,
            "records": s.records, "loss": s.loss,
            "resumed": s.resumed, "attempts": s.attempts,
            "notes": list(s.notes),
            "budget_burned": budget_burned(results),
            "slo": results,
        })
    return {
        "fleet_schema": FLEET_SCHEMA,
        "status_schema": STATUS_SCHEMA,
        "runs": runs,
        "slo_table": slo_table(),
        "slo_compliance": compliance,
        "workers": worker_rollup(summaries),
        "compute": compute_rollup(summaries, target_loss),
        "all_ok": all_ok,
    }
