"""Incident engine: online run-health SLOs over the telemetry spine.

The repo *measures* everything — per-step decode health and forensics
masks (PR 7), per-phase device time (PR 9), wire numerics and the
shadow-quantized wire (PR 10), compile/retrace and guard events — but
until this module nothing *watched* those streams: a trust collapse or a
compile storm was only visible to a human replaying metrics.jsonl after
the fact. This engine folds the per-step column families into typed,
attributed, stateful **incidents** — onset/offset episodes with severity,
the evidence that fired, and the implicated worker set where forensics can
name one — riding the existing heartbeat observer hook: ZERO extra device
fetches, zero retraces, zero graph changes (the K ∈ {1,4} equivalence
suites run bitwise-identical with the watch on).

Detector classes are **declaratively registered** (:func:`register_detector`)
with their thresholds, so the set is enumerable (``detector_table()``),
overridable per run (``--incident-thresholds "trust.floor=0.4,..."``), and
unit-testable on synthesized column streams. Two sources:

  ``record``  driven by :meth:`IncidentEngine.observe` — one call per
              materialized train record (the DeferredMetricWriter observer
              / eager-loop hook the heartbeat already runs). Replayable
              offline from metrics.jsonl (tools/incident_report.py): the
              detector sees ONLY record columns, so the offline fold is
              bit-identical to the live one whenever every step was logged.
  ``beat``    driven by :meth:`IncidentEngine.observe_beat` — once per
              heartbeat flush boundary, fed the beat extras the loops
              already assemble (prefetch depth/restarts, compile counters)
              plus the wall clock. NOT recomputable offline (host wall
              time and counters are not metric columns); the offline
              report carries these through from incidents.jsonl verbatim.

Hysteresis: a detector must fire ``on_count`` consecutive observations to
OPEN an incident and stay quiet ``off_count`` consecutive observations to
CLOSE it — a single noisy step can neither open nor close an episode (the
no-flapping contract, pinned in tests). Hard signals (a non-finite ingest
row, a guard trip, a steady-state recompile) run with ``on_count=1``:
they are never noise.

Incidents stream to ``train_dir/incidents.jsonl`` — append-only, one JSON
line per onset/offset event, torn-tail tolerated by every consumer
(obs/replay.py) — and fold into the ``incidents`` block of status.json
(STATUS_SCHEMA 4), which the terminal crash/preempted write carries too.
``tools/chaos_run.py`` proves the detectors end to end: every injected
fault class must raise exactly the expected incident type with the right
worker attribution, or the cell FAILS.

This is the sensing layer ROADMAP item 5's adaptive autopilot actuates on:
detectors fire on exactly the regime breaks the coding theory names — a
sustained straggle feasibility breach (arXiv:1905.05383), a residual
drifting toward the optimal-decoding bound (arXiv:2006.09638) — so a
controller can re-select (family, r, dtype) from typed events instead of
raw columns.

Importable WITHOUT jax (host arithmetic only), same discipline as the rest
of draco_tpu/obs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from draco_tpu.obs.forensics import AccusationLedger, record_masks

INCIDENT_SCHEMA = 1

# severity ladder: "warn" = degraded but inside every budget (operator
# attention), "critical" = a budget/certificate breach (autopilot action)
SEVERITIES = ("warn", "critical")
SOURCES = ("record", "beat")


@dataclasses.dataclass(frozen=True)
class DetectorSpec:
    """One registered detector: its identity, severity, source stream, and
    declarative threshold defaults (every key overridable via
    ``parse_thresholds`` strings)."""

    name: str
    severity: str
    source: str  # "record" | "beat"
    thresholds: Dict[str, float]
    doc: str
    factory: Any


DETECTORS: Dict[str, DetectorSpec] = {}


def register_detector(name: str, severity: str, source: str,
                      thresholds: Dict[str, float]):
    """Class decorator declaring a detector into the enumerable registry.
    ``thresholds`` MUST include the hysteresis pair ``on_count`` /
    ``off_count`` — the engine owns the state machine, the detector only
    votes fire/quiet per observation."""
    assert severity in SEVERITIES and source in SOURCES
    assert "on_count" in thresholds and "off_count" in thresholds

    def deco(cls):
        DETECTORS[name] = DetectorSpec(
            name=name, severity=severity, source=source,
            thresholds=dict(thresholds),
            doc=(cls.__doc__ or "").strip().splitlines()[0],
            factory=cls)
        return cls

    return deco


def detector_table() -> List[dict]:
    """The enumerable detector set (PERF.md §15's table source): name,
    severity, source, and the declared threshold defaults."""
    return [{"name": s.name, "severity": s.severity, "source": s.source,
             "thresholds": dict(s.thresholds), "doc": s.doc}
            for s in DETECTORS.values()]


def parse_thresholds(spec: str) -> Dict[str, float]:
    """``"trust.floor=0.4,guard.off_count=2"`` -> override dict. Unknown
    detector or threshold keys are config-time errors (the registry is the
    contract), values must parse as floats."""
    out: Dict[str, float] = {}
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        try:
            key, val = item.split("=", 1)
            det, th = key.strip().split(".", 1)
            fval = float(val)
        except ValueError:
            raise ValueError(
                f"incident threshold {item!r} is not "
                f"'<detector>.<key>=<float>'")
        if det not in DETECTORS:
            raise ValueError(
                f"unknown incident detector {det!r} (registered: "
                f"{', '.join(sorted(DETECTORS))})")
        if th not in DETECTORS[det].thresholds:
            raise ValueError(
                f"detector {det!r} has no threshold {th!r} (declared: "
                f"{', '.join(sorted(DETECTORS[det].thresholds))})")
        out[f"{det}.{th}"] = fval
    return out


# --------------------------------------------------------------------------
# detectors
# --------------------------------------------------------------------------


class _Detector:
    """Base: holds merged thresholds; ``update`` (record source) or
    ``update_beat`` (beat source) returns None when the stream carries no
    signal for it (hysteresis holds), else (firing, evidence, workers)."""

    def __init__(self, th: Dict[str, float], num_workers: Optional[int]):
        self.th = th
        self.n = num_workers

    def update(self, record: dict, ctx: "IncidentEngine"):
        raise NotImplementedError

    def update_beat(self, step: int, extra: dict, ctx: "IncidentEngine"):
        raise NotImplementedError


def _accused_workers(ctx: "IncidentEngine") -> Optional[Tuple[int, ...]]:
    """The current record's accused worker set — the attribution every
    record-source detector reuses where the step can name one (None when
    the record carries no masks). Reads the engine's per-record mask cache
    (``ctx.current_masks``): the bit-twiddling unpack runs ONCE per
    observed record, not once per consuming detector."""
    masks = ctx.current_masks
    if masks is None:
        return None
    return tuple(i for i, b in enumerate(masks["accused"]) if b) or None


@register_detector(
    "nonfinite", severity="critical", source="record",
    thresholds={"frac_max": 0.0, "on_count": 1, "off_count": 2})
class NonfiniteDetector(_Detector):
    """Non-finite ingest: the numerics observatory's nonfinite fractions
    (nx_grad_nonfinite / nx_wire_nonfinite, ISSUE 10) above ``frac_max``.
    A NaN/Inf gradient row is never noise — on_count=1 — and the forensics
    ingest check names the victim worker, so the incident is attributed."""

    def update(self, record, ctx):
        vals = [record.get("nx_grad_nonfinite"),
                record.get("nx_wire_nonfinite")]
        vals = [float(v) for v in vals if isinstance(v, (int, float))]
        if not vals:
            return None
        worst = max(vals)
        firing = worst > self.th["frac_max"]
        return (firing, {"nonfinite_frac": worst},
                _accused_workers(ctx) if firing else None)


@register_detector(
    "guard", severity="critical", source="record",
    thresholds={"on_count": 1, "off_count": 4})
class GuardDetector(_Detector):
    """Guard-trip / skipped-step budget burn: the in-graph step guard
    (resilience/guards.py) skipped an update this record. Every trip means
    a training step was paid for and thrown away — on_count=1, and the
    episode's length IS the burn. Attributed via the step's accused set."""

    def update(self, record, ctx):
        trips = record.get("guard_trips")
        if not isinstance(trips, (int, float)):
            return None
        firing = float(trips) > 0.0
        ev = {"guard_trips": float(trips),
              "skipped_steps": float(record.get("skipped_steps", 0.0))}
        return (firing, ev, _accused_workers(ctx) if firing else None)


@register_detector(
    "trust", severity="critical", source="record",
    thresholds={"floor": 0.5, "on_count": 1, "off_count": 4})
class TrustDetector(_Detector):
    """Trust collapse: a present worker's EW trust (obs/forensics
    AccusationLedger, alpha=0.2) under ``floor``. The EW itself is the
    hysteresis — ~4 consecutive accusations to cross 0.5 from fresh, so a
    single false accusation cannot open an episode — and the collapsed
    workers are the attribution."""

    def update(self, record, ctx):
        ledger = ctx.ledger
        if ledger is None or ctx.current_masks is None:
            return None
        floor = self.th["floor"]
        # a QUARANTINED worker's trust is frozen at its collapse (absent
        # workers earn no evidence either way) — excluding it lets the
        # episode close once the remediation lands, so the autopilot's
        # clean-evidence window can actually accumulate
        low = tuple(w for w in range(ledger.n)
                    if ledger.trust[w] < floor
                    and w not in ctx.quarantined)
        return (bool(low),
                {"min_trust": round(min(ledger.trust), 4)},
                low or None)


@register_detector(
    "decode_residual", severity="critical", source="record",
    thresholds={"cyclic_tol": 1e-3, "bound_frac": 0.95, "alpha": 0.25,
                "slack": 0.0, "on_count": 2, "off_count": 3})
class ResidualDetector(_Detector):
    """Decode-residual drift. Exact families (cyclic): the fitted-codeword
    residual crossing ``cyclic_tol`` (clean decodes sit at f32 solve noise
    ~1e-6; NaN — the beyond-budget signature — counts as a crossing).
    Approx family: the EW of measured-residual / analytic-bound
    (arXiv:2006.09638) exceeding ``bound_frac`` — the decode drifting
    toward its worst case (within-budget drops sit at 0.5–0.85 of the
    bound, straggler_study.json) — or any outright bound violation."""

    def __init__(self, th, num_workers):
        super().__init__(th, num_workers)
        self._ew: Optional[float] = None

    def update(self, record, ctx):
        res = record.get("decode_residual")
        if not isinstance(res, (int, float)):
            return None
        res = float(res)
        bound = record.get("decode_residual_bound")
        if isinstance(bound, (int, float)):  # approx family
            bound = float(bound)
            # narrow-wire slack (ISSUE 15, make_engine): on a bf16/int8
            # wire the measured residual carries the end-to-end
            # quantization error on TOP of the analytic bound (which
            # prices drops only) — the dtype's slack is the family's
            # normal state, same widening guards.assess applies. 0 on f32.
            qres = max(res - self.th["slack"], 0.0) if res == res else res
            # full-participation steps: both sit at f32 noise — ratio is
            # meaningless there, and a healthy 0 must drain the EW
            ratio = qres / bound if bound > 1e-6 else 0.0
            if not (ratio == ratio):  # NaN residual: poisoned decode
                ratio = 2.0
            a = self.th["alpha"]
            self._ew = ratio if self._ew is None else \
                a * ratio + (1.0 - a) * self._ew
            violated = not (qres <= bound + 1e-5)
            firing = violated or self._ew > self.th["bound_frac"]
            return (firing, {"residual": res, "bound": bound,
                             "ew_ratio": round(self._ew, 4)}, None)
        # exact families: a rel-tol crossing, NaN-safe (not <= , so a NaN
        # residual — the mislocated beyond-budget decode — fires)
        firing = not (res <= self.th["cyclic_tol"])
        return (firing, {"residual": res},
                _accused_workers(ctx) if firing else None)


@register_detector(
    "numerics_drift", severity="warn", source="record",
    thresholds={"uf_bf16_max": 0.5, "of_bf16_max": 1e-3,
                "hist_shift_max": 0.6, "warmup": 4,
                "on_count": 3, "off_count": 3})
class NumericsDriftDetector(_Detector):
    """Numerics drift on the coded wire (ISSUE 10 columns): the bf16
    underflow fraction past ``uf_bf16_max``, any overflow fraction past
    ``of_bf16_max``, or the 6-bin exponent histogram shifting more than
    ``hist_shift_max`` total-variation distance from its own warm baseline
    (mean of the first ``warmup`` watched records). Soft signal —
    on_count=3, so a single noisy step never opens an episode."""

    def __init__(self, th, num_workers):
        super().__init__(th, num_workers)
        self._warm: List[List[float]] = []
        self._baseline: Optional[List[float]] = None

    def update(self, record, ctx):
        uf = record.get("nx_wire_uf_bf16")
        if not isinstance(uf, (int, float)):
            return None
        of = float(record.get("nx_wire_of_bf16", 0.0))
        hist = []
        i = 0
        while f"nx_wire_exp{i}" in record:
            hist.append(float(record[f"nx_wire_exp{i}"]))
            i += 1
        shift = 0.0
        if hist:
            if self._baseline is None:
                self._warm.append(hist)
                if len(self._warm) >= int(self.th["warmup"]):
                    m = len(self._warm)
                    self._baseline = [sum(col) / m
                                      for col in zip(*self._warm)]
                return (False, {"warmup": len(self._warm)}, None)
            shift = 0.5 * sum(abs(a - b)
                              for a, b in zip(hist, self._baseline))
        firing = (float(uf) > self.th["uf_bf16_max"]
                  or of > self.th["of_bf16_max"]
                  or shift > self.th["hist_shift_max"])
        return (firing, {"uf_bf16": float(uf), "of_bf16": of,
                         "hist_shift": round(shift, 4)}, None)


@register_detector(
    "straggle", severity="warn", source="record",
    thresholds={"streak": 4, "on_count": 1, "off_count": 2})
class StraggleDetector(_Detector):
    """Sustained per-worker absence: some worker's present bit has been
    off for ``streak`` consecutive observed records — the churn /
    preempted-worker / feasibility-pressure signal (the regime the
    committed straggler study prices, and the evidence the autopilot's
    redundancy dial acts on). Scheduled one-off drops rotate workers and
    never build a streak, so a clean straggle_mode="drop" run stays
    silent; a spot-instance drop or a churn episode fires within
    ``streak`` steps, attributed to the absent worker(s). Workers the
    autopilot QUARANTINED are excluded — their absence is policy, not
    telemetry (``IncidentEngine.quarantined``)."""

    def __init__(self, th, num_workers):
        super().__init__(th, num_workers)
        self._streaks: Optional[list] = None

    def update(self, record, ctx):
        masks = ctx.current_masks
        if masks is None:
            return None
        present = masks["present"]
        n = len(present)
        if self._streaks is None or len(self._streaks) != n:
            self._streaks = [0] * n
        for w in range(n):
            if w in ctx.quarantined or present[w]:
                self._streaks[w] = 0
            else:
                self._streaks[w] += 1
        k = int(self.th["streak"])
        hot = tuple(w for w in range(n) if self._streaks[w] >= k)
        return (bool(hot),
                {"max_absent_streak": max(self._streaks, default=0)},
                hot or None)


@register_detector(
    "throughput", severity="warn", source="beat",
    thresholds={"warmup_beats": 3, "alpha": 0.3, "drop_frac": 0.4,
                "on_count": 2, "off_count": 2})
class ThroughputDetector(_Detector):
    """Throughput regression: the EW steps/s between heartbeat flush
    boundaries falling more than ``drop_frac`` below its own warm baseline
    (the EW frozen after ``warmup_beats`` inter-beat rates). Host
    wall-clock driven — beat source, carried through (not recomputed) by
    the offline replay."""

    def __init__(self, th, num_workers):
        super().__init__(th, num_workers)
        self._prev: Optional[Tuple[int, float]] = None
        self._ew: Optional[float] = None
        self._rates = 0
        self._baseline: Optional[float] = None

    def update_beat(self, step, extra, ctx):
        now = ctx.clock()
        prev, self._prev = self._prev, (step, now)
        if prev is None:
            return None
        dsteps, dt = step - prev[0], now - prev[1]
        if dsteps <= 0 or dt <= 0:
            return None
        rate = dsteps / dt
        a = self.th["alpha"]
        self._ew = rate if self._ew is None else \
            a * rate + (1.0 - a) * self._ew
        self._rates += 1
        ev = {"steps_per_s": round(rate, 4),
              "ew_steps_per_s": round(self._ew, 4)}
        if self._rates <= int(self.th["warmup_beats"]) \
                or self._baseline is None:
            # warm baseline: the EW at end of warmup — and ALWAYS at least
            # the first rate (warmup_beats=0 is a legal override; firing
            # against no baseline would crash the loop)
            self._baseline = self._ew
            return (False, ev, None)
        ev["baseline_steps_per_s"] = round(self._baseline, 4)
        firing = self._ew < (1.0 - self.th["drop_frac"]) * self._baseline
        return (firing, ev, None)


@register_detector(
    "compile_storm", severity="critical", source="beat",
    thresholds={"on_count": 1, "off_count": 2})
class CompileStormDetector(_Detector):
    """Compile storm: the compile sentinel's steady-state recompile
    counter (obs/compile_watch.py — builds after a program's warmup
    window) advancing between beats. Every steady recompile silently
    re-pays the multi-second compile the scan-chunk design amortizes;
    one is an anomaly, a stream of them is a storm (the episode)."""

    def __init__(self, th, num_workers):
        super().__init__(th, num_workers)
        self._prev = 0

    def update_beat(self, step, extra, ctx):
        steady = extra.get("steady_recompiles")
        if not isinstance(steady, (int, float)):
            return None
        delta = float(steady) - self._prev
        self._prev = float(steady)
        return (delta > 0, {"steady_recompiles": float(steady),
                            "new_recompiles": delta}, None)


@register_detector(
    "starvation", severity="warn", source="beat",
    thresholds={"depth_beats": 3, "on_count": 1, "off_count": 1})
class StarvationDetector(_Detector):
    """Prefetch starvation: a supervised prefetcher restart since the last
    beat (a worker crashed/stalled and was rebuilt —
    resilience/supervisor.py), or the queue-depth signal the tracer
    counters track (the heartbeat's prefetch_depth extra) pinned at zero
    for ``depth_beats`` consecutive beats mid-run (the device outrunning
    the host: nothing in flight when a chunk was due)."""

    def __init__(self, th, num_workers):
        super().__init__(th, num_workers)
        self._prev_restarts = 0.0
        self._zero_streak = 0

    def update_beat(self, step, extra, ctx):
        depth = extra.get("prefetch_depth")
        restarts = extra.get("prefetch_restarts")
        if depth is None and restarts is None:
            return None
        delta = 0.0
        if isinstance(restarts, (int, float)):
            delta = float(restarts) - self._prev_restarts
            self._prev_restarts = float(restarts)
        if isinstance(depth, (int, float)) and depth <= 0:
            self._zero_streak += 1
        else:
            self._zero_streak = 0
        firing = delta > 0 or self._zero_streak >= int(self.th["depth_beats"])
        return (firing, {"prefetch_depth": depth,
                         "restarts": self._prev_restarts,
                         "zero_depth_beats": self._zero_streak}, None)


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------


class _Hyst:
    """Per-detector hysteresis state + the open episode, if any."""

    __slots__ = ("hot", "quiet", "first_hot", "open")

    def __init__(self):
        self.hot = 0
        self.quiet = 0
        self.first_hot: Optional[int] = None
        self.open: Optional[dict] = None


class IncidentEngine:
    """Folds observed records/beats into incident episodes.

    ``out_path``: incidents.jsonl (lazily opened on the first event — a
    clean run writes nothing). ``thresholds``: ``"det.key" -> value``
    overrides (parse_thresholds). ``clock``: injectable monotonic clock
    for the beat detectors' wall-rate math (tests).
    """

    def __init__(self, num_workers: Optional[int] = None,
                 out_path: Optional[str] = None,
                 thresholds: Optional[Dict[str, float]] = None,
                 clock=time.monotonic):
        overrides = dict(thresholds or {})
        self.clock = clock
        self.num_workers = num_workers
        # the engine's OWN ledger (trust detector input): self-contained,
        # so the offline replay needs nothing but the record stream
        self.ledger = (AccusationLedger(num_workers)
                       if num_workers else None)
        self.detectors: Dict[str, _Detector] = {}
        self._hyst: Dict[str, _Hyst] = {}
        for name, spec in DETECTORS.items():
            th = dict(spec.thresholds)
            for key, val in overrides.items():
                det, tkey = key.split(".", 1)
                if det == name:
                    th[tkey] = val
            self.detectors[name] = spec.factory(th, num_workers)
            self._hyst[name] = _Hyst()
        # the NON-DEFAULT overrides actually in effect — stamped into the
        # status block so the offline replay (tools/incident_report.py)
        # rebuilds with the run's own thresholds (make_engine's implicit
        # cyclic_tol <- guard_residual_tol included), not the registry
        # defaults
        self.overrides = {
            k: v for k, v in overrides.items()
            if DETECTORS.get(k.split(".", 1)[0]) is not None
            and DETECTORS[k.split(".", 1)[0]].thresholds.get(
                k.split(".", 1)[1]) != v}
        self.episodes: List[dict] = []  # closed, in closure order
        self.total_onsets = 0
        self._out_path = out_path
        self._fh = None
        self._seq = 0
        self._last_step: Optional[int] = None
        # per-record unpacked forensics masks (observe() refreshes)
        self.current_masks: Optional[dict] = None
        # workers the autopilot (control/autopilot.py) has excluded via
        # the present-mask schedule: their absence is POLICY, so the
        # straggle detector must not read it as telemetry
        self.quarantined: set = set()

    # ---- folding ---------------------------------------------------------
    def observe(self, record: dict) -> None:
        """One materialized train record — the heartbeat observer hook."""
        # unpack the packed forensics masks ONCE per record; the engine's
        # ledger fold and every consuming detector (+ _accused_workers)
        # read this cache
        self.current_masks = (record_masks(record, self.num_workers)
                              if self.num_workers else None)
        if self.ledger is not None:
            self.ledger.observe(record, masks=self.current_masks)
        step = int(record.get("step", (self._last_step or 0) + 1))
        self._last_step = step
        for name, det in self.detectors.items():
            if DETECTORS[name].source != "record":
                continue
            sig = det.update(record, self)
            if sig is not None:
                self._advance(name, step, sig)

    def observe_beat(self, step: int, extra: Optional[dict] = None) -> None:
        """One heartbeat flush boundary, fed the beat extras the loops
        already assemble (prefetch depth/restarts, compile counters)."""
        self._last_step = int(step)
        extra = extra or {}
        for name, det in self.detectors.items():
            if DETECTORS[name].source != "beat":
                continue
            sig = det.update_beat(int(step), extra, self)
            if sig is not None:
                self._advance(name, int(step), sig)

    def _advance(self, name: str, step: int, sig) -> None:
        firing, evidence, workers = sig
        st = self._hyst[name]
        spec = DETECTORS[name]
        if firing:
            st.quiet = 0
            st.hot += 1
            if st.first_hot is None:
                st.first_hot = step
            if st.open is not None:
                ep = st.open
                ep["last_step"] = step
                ep["steps"] += 1
                ep["evidence"] = evidence
                if workers:
                    ep["workers"] = sorted(set(ep["workers"] or ())
                                           | set(workers))
            elif st.hot >= int(self.detectors[name].th["on_count"]):
                st.open = {
                    "type": name, "severity": spec.severity,
                    "source": spec.source, "onset_step": st.first_hot,
                    "last_step": step, "steps": st.hot,
                    "workers": sorted(workers) if workers else None,
                    "evidence": evidence,
                }
                self.total_onsets += 1
                self._emit("onset", st.open)
        else:
            st.hot = 0
            st.first_hot = None
            if st.open is not None:
                st.quiet += 1
                if st.quiet >= int(self.detectors[name].th["off_count"]):
                    ep = st.open
                    st.open = None
                    st.quiet = 0
                    ep["offset_step"] = step
                    self.episodes.append(ep)
                    self._emit("offset", ep)

    # ---- emission --------------------------------------------------------
    def _line(self, event: str) -> Optional[dict]:
        """Start an event line on the (lazily opened) stream, or None when
        the engine has no out_path."""
        if self._out_path is None:
            return None
        if self._fh is None:
            os.makedirs(os.path.dirname(self._out_path) or ".",
                        exist_ok=True)
            self._fh = open(self._out_path, "a")
        # wall-clock stamp (ISSUE 19): onset→remediation latency (MTTR)
        # is only computable offline if every event carries real time —
        # step indices alone cannot price a stalled run's response lag
        line = {"v": INCIDENT_SCHEMA, "event": event, "seq": self._seq,
                "ts": time.time()}
        self._seq += 1
        return line

    def _emit(self, event: str, ep: dict) -> None:
        line = self._line(event)
        if line is None:
            return
        line.update({k: ep[k] for k in
                     ("type", "severity", "source", "onset_step",
                      "last_step", "steps", "workers", "evidence")})
        if event == "offset":
            line["offset_step"] = ep["offset_step"]
        # one fsync-free write+flush per event: incidents are rare, and a
        # torn tail (killed mid-write) is tolerated by every reader
        self._fh.write(json.dumps(line) + "\n")
        self._fh.flush()

    def remediation(self, rem: dict) -> None:
        """Append an autopilot remediation (control/autopilot.py) to the
        SAME event stream, same seq counter: every runtime-control
        decision is an attributed line in the run's incident ledger,
        interleaved in decision order with the episodes that triggered
        it. Offline consumers (tools/incident_report.py) carry these
        through — runtime control state is not recomputable from metric
        columns alone."""
        line = self._line("remediation")
        if line is None:
            return
        line.update(rem)
        self._fh.write(json.dumps(line) + "\n")
        self._fh.flush()

    def open_episodes(self) -> List[dict]:
        return [self._hyst[n].open for n in sorted(self._hyst)
                if self._hyst[n].open is not None]

    def all_episodes(self) -> List[dict]:
        """Closed episodes (closure order) + still-open tails."""
        return ([dict(e, open=False) for e in self.episodes]
                + [dict(e, open=True) for e in self.open_episodes()])

    def status_block(self) -> dict:
        """The ``incidents`` status.json block (STATUS_SCHEMA 4): open
        episodes, per-type totals, last onset."""
        counts: Dict[str, int] = {}
        eps = self.all_episodes()
        for ep in eps:
            counts[ep["type"]] = counts.get(ep["type"], 0) + 1
        last = max(eps, key=lambda e: e["onset_step"]) if eps else None
        return {
            "total": self.total_onsets,
            "open": [{"type": e["type"], "severity": e["severity"],
                      "onset_step": e["onset_step"],
                      "last_step": e["last_step"],
                      "workers": e["workers"]}
                     for e in self.open_episodes()],
            "by_type": counts,
            "thresholds": dict(self.overrides),
            "last": ({"type": last["type"], "severity": last["severity"],
                      "onset_step": last["onset_step"],
                      "workers": last["workers"],
                      "open": last.get("open", True)}
                     if last else None),
        }

    def finalize(self) -> None:
        """Flush + close the event stream (the terminal heartbeat write
        calls this). Open episodes stay open — an incident whose condition
        never cleared must not fabricate an offset."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def make_engine(cfg, is_main: bool = True) -> Optional[IncidentEngine]:
    """The one construction rule both production loops share: an engine
    only when ``cfg.incident_watch == "on"``, there is a train_dir to
    stream into, and this is the metrics-emitting process; threshold
    overrides from ``cfg.incident_thresholds``, with the cyclic residual
    tolerance defaulting to the step guard's ``cfg.guard_residual_tol``
    (one loudness definition across guard and detector) plus the narrow
    wire's residual slack (ISSUE 15 — same widening guards.assess applies:
    quantization noise on a bf16/int8 wire is the dtype's normal state,
    not residual drift; 0 on the f32 wire)."""
    if getattr(cfg, "incident_watch", "off") != "on" or not cfg.train_dir \
            or not is_main:
        return None
    from draco_tpu.obs.numerics import wire_residual_slack

    slack = wire_residual_slack(getattr(cfg, "wire_dtype", "f32"))
    thresholds = {"decode_residual.cyclic_tol":
                  cfg.guard_residual_tol + slack,
                  "decode_residual.slack": slack}
    thresholds.update(parse_thresholds(
        getattr(cfg, "incident_thresholds", "")))
    return IncidentEngine(
        num_workers=cfg.num_workers,
        out_path=os.path.join(cfg.train_dir, "incidents.jsonl"),
        thresholds=thresholds)
