"""Wire & numerics observatory: dynamic-range telemetry and the
shadow-quantized coded wire (ISSUE 10).

ROADMAP item 4 wants the worker→aggregator wire narrowed to bf16/int8 (the
reference shipped blosc-compressed gradients, ``compress_gradient.py``; the
communication-efficient coding line — PAPERS.md arXiv:1802.03475,
CodedReduce arXiv:1902.01981 — makes wire bytes the scaling bottleneck at
large n). Before any dtype change lands, this module MEASURES it, under the
telemetry spine's standing invariant: zero extra device fetches, zero
retraces, and the f32 training path bit-for-bit untouched.

Three instruments, all riding the existing (K, m) metric block:

**Numerics columns** (``cfg.numerics_watch == "on"``) — per-step dynamic-
range statistics of three pipeline stages: the pre-encode per-worker
gradients (``grad``), the post-encode codewords that would cross the wire
(``wire``), and the decoded aggregate (``agg``). Per stage: absmax, rms,
underflow fraction at the bf16-subnormal threshold (values a bf16 wire
would flush to zero), underflow fraction at the int8-per-block-scale
threshold (values a per-block-scaled int8 wire would round to zero),
overflow fraction past bf16 max, the non-finite fraction, and a coarse
base-2 exponent histogram (EXP_EDGES bins, as fractions — fractions rather
than raw counts because an f32-carried count loses integer exactness past
2^24 elements, which d·n already exceeds at LM scale). Every statistic is
computed over the FINITE elements only, so an injected NaN/Inf fault
(resilience/faults.py) yields finite sentinel values plus a loud
``nonfinite`` fraction instead of poisoning the metric block — the
chaos-matrix NaN-safety contract.

**Shadow-quantized wire** (``cfg.shadow_wire ∈ {bf16, int8}``) — inside the
same step body the codewords are rounded to the narrow dtype (int8 with
per-block scales over ``cfg.shadow_block``-element blocks; optional
stochastic rounding via ``cfg.shadow_round``) and decoded ALONGSIDE the f32
path. Only the f32 decode updates parameters, so the K∈{1,4} bitwise
equivalence suites hold with the shadow enabled; the shadow emits:

  shadow_err          relative L2 error of the shadow aggregate vs the f32
                      aggregate — the end-to-end cost of the narrow wire
  shadow_residual     the shadow decode's own health residual (cyclic:
                      fitted-codeword self-consistency at a quantization-
                      aware flag threshold, SHADOW_REL_TOL; approx:
                      measured residual vs the true mean; maj_vote:
                      1 − shadow vote agreement)
  shadow_flag_agree   fraction of present workers whose shadow detection
                      flag equals the f32 flag (1.0 = quantization changed
                      no accusation)
  shadow_det_flagged / shadow_det_tp
                      the shadow flag set scored against the seeded
                      schedules, so detection precision/recall *under
                      quantization* is measured, not assumed

All shadow columns are NaN-sentineled (``SHADOW_SENTINEL``): a fault-
poisoned comparison lands at −1.0, never NaN, so the block stays finite.

**Wire ledger** (:func:`wire_ledger`, jax-free) — logical wire bytes per
worker per step from the program's registered shapes (cyclic ships re+im,
everything else one row of d f32s), with the bf16/int8 candidate sizes, for
``status.json``'s ``wire`` block, ``bench.py``'s ``extra.wire_bytes``, and
``tools/wire_study.py``.

The int8 shadow stores its levels in f32 (every int8 value is exact in
f32): the shadow never leaves the chip, so only the LOGICAL bytes matter —
the ledger tracks those; the program needs no narrow buffer. The bf16
shadow uses real bf16 converts (whitelisted promotion sites under the dtype
lint rule; shadow-watch programs register with ``BF16_DTYPES``).

Like the rest of draco_tpu/obs this module imports WITHOUT jax (in-graph
functions import it lazily), so jax-free tools can use the ledger and the
column-name helpers.
"""

from __future__ import annotations

from typing import Optional

# ---- thresholds (jax-free constants) --------------------------------------

# smallest positive bfloat16 subnormal (2^-126 · 2^-7): an f32 value below
# this flushes to zero when a bf16 wire carries it
BF16_TINY = 2.0 ** -133
# largest finite bfloat16 (0x7F7F): an f32 value above this rounds to inf
# on a bf16 wire
BF16_MAX = 3.3895313892515355e38
# int8 quantization levels per sign (symmetric per-block scale absmax/127)
INT8_LEVELS = 127.0
# default per-block scale granularity (elements per block along the last
# axis) — cfg.shadow_block overrides
DEFAULT_BLOCK = 256

# coarse exponent histogram: bin edges in floor(log2 |x|) over finite
# nonzero elements. Bin i covers [EXP_EDGES[i-1], EXP_EDGES[i]) with the
# open ends below the first and at/above the last edge, i.e.
# (-inf,-32) [-32,-16) [-16,-8) [-8,0) [0,8) [8,+inf) — six bins bracketing
# where bf16/int8 rounding decisions happen for gradient-scale data
EXP_EDGES = (-32, -16, -8, 0, 8)
NUM_EXP_BINS = len(EXP_EDGES) + 1

NUMERICS_STAGES = ("grad", "wire", "agg")
STAT_NAMES = ("absmax", "rms", "uf_bf16", "uf_int8", "of_bf16",
              "nonfinite") + tuple(f"exp{i}" for i in range(NUM_EXP_BINS))
NUMERICS_PREFIX = "nx_"

SHADOW_NAMES = ("shadow_err", "shadow_residual", "shadow_flag_agree",
                "shadow_det_flagged", "shadow_det_tp")
# finite sentinel for a fault-poisoned shadow comparison (real values of
# every shadow column are >= 0, so -1 is unambiguous)
SHADOW_SENTINEL = -1.0

# ---- the REAL narrow wire (ISSUE 15) --------------------------------------
# cfg.wire_dtype picks what the worker→aggregator wire PHYSICALLY carries:
WIRE_DTYPES = ("f32", "bf16", "int8")

# Regularization λ for the cyclic locator solve per wire dtype, scaled to
# the dtype's quantization noise floor on the SIGNAL-normalized Hankel
# system (the λ path divides the syndrome by the received rows' RMS, so a
# pure-quantization syndrome sits at the dtype's relative noise — measured
# ≤ 4.6e-3 bf16 / ≤ 1.6e-2 int8 at n=32 s=3, tools/wire_study.py locator
# cells). λ sits ~2× above each measured floor and acts twice, both
# branchless: (1) the syndrome-significance GATE — relative syndrome below
# λ certifies no corruption, collapsing the locator magnitudes to uniform
# so the spread-rank bias (coding/cyclic.SPREAD_PHI) pins the
# well-conditioned honest subset, instead of the noise-driven subset whose
# exact codeword fit extrapolates quantization noise ~4e4× (the PR 10
# n=32 s=3 blocker); (2) the solve's noise-floor cutoff — singular
# directions with σ ≤ λ are dropped outright (coding/linalg.truncated_lstsq
# λ semantics). λ=0 (the f32 wire) is the exact historical path, bitwise.
WIRE_LOCATOR_LAMBDA = {"f32": 0.0, "bf16": 2.0 ** -8, "int8": 2.0 ** -6}

# Per-(n, s, dtype) cyclic flag thresholds for the REAL narrow wire,
# DERIVED by tools/wire_study.py's locator-margin cells (committed in
# wire_study.json's threshold_table and re-verified by --check): each
# entry sits between the measured worst honest-row deviation (quantization
# noise through the λ-regularized locator/fit solves) and the measured
# smallest adversary-row deviation at the in-scope attack magnitudes.
# Shapes not in the table fall back to the per-dtype SHADOW_REL_TOL
# calibration band — run wire_study at the target shape before shipping a
# narrow wire there (wire_rel_tol docstring).
WIRE_REL_TOL_TABLE = {
    # study shapes (n=8): the PR 10 shadow calibration band holds
    (8, 1, "bf16"): 5e-2, (8, 1, "int8"): 1.5e-1,
    # the PR 10 blocker shape: UNUSABLE unregularized (no-adversary honest
    # deviations amplified to 29–137× the row RMS — past any threshold);
    # usable with the λ-regularized locator, whose measured no-adversary
    # honest deviations sit under 0.047/0.24 vs adversary deviations above
    # 0.33 (wire_study.py locator cells, re-verified by --check). Measured
    # limit: WITH live adversaries at this shape, honest rows extrapolated
    # through the locator fit deviate up to 0.79/7.5 — past these
    # thresholds — so detection recall holds but flag precision degrades
    # in the adversary regime (honest_dev_max_adv in the committed cells;
    # PERF.md §17). The certificate these entries carry is the
    # no-adversary one the PR 10 blocker was about.
    (32, 3, "bf16"): 2e-1, (32, 3, "int8"): 2.8e-1,
}

# Guard/incident residual slack per wire dtype: on a narrow wire the
# UNFLAGGED honest rows deviate from the fitted codeword by rounding noise
# (not f32 noise), and the approx family's measured residual carries the
# end-to-end quantization error on top of its analytic bound (which prices
# drops only). guards.assess and the decode_residual incident detector add
# this to their tolerances so a clean narrow-wire step is not a trip —
# sized ~3× the committed shadow-study maxima (bf16 err ≤0.6%, int8 ≤3.5%).
WIRE_RESIDUAL_SLACK = {"f32": 0.0, "bf16": 2e-2, "int8": 1e-1}

# f32-ward widening ladder (the autopilot's wire_widen remediation walks
# it one step at a time; wire_narrow walks back toward the configured
# dtype): int8 -> bf16 -> f32
WIRE_WIDEN = {"int8": "bf16", "bf16": "f32", "f32": "f32"}


def wire_rel_tol(n: int, s: int, dtype: str) -> float:
    """The cyclic flag threshold a REAL narrow wire decodes with at
    (n, s): the committed per-shape table entry, else — inside the
    s ≤ 2 band PR 10 measured — the per-dtype calibration default
    (SHADOW_REL_TOL). Outside both, ``inf``: no usable threshold is
    KNOWN, and config.validate routes such shapes to the approx family
    (whose decode has no locator to amplify the quantization noise,
    arXiv:1802.03475) until tools/wire_study.py measures them. f32 keeps
    HEALTH_REL_TOL — resolved by the caller, not here."""
    key = (int(n), int(s), dtype)
    if key in WIRE_REL_TOL_TABLE:
        return WIRE_REL_TOL_TABLE[key]
    if int(s) <= 2:
        return SHADOW_REL_TOL[dtype]
    return float("inf")


def wire_locator_lambda(dtype: str) -> float:
    return WIRE_LOCATOR_LAMBDA[dtype]


def wire_residual_slack(dtype: str) -> float:
    return WIRE_RESIDUAL_SLACK.get(dtype, 0.0)


def narrow_toward(current: str, target: str) -> str:
    """One narrowing step from ``current`` toward ``target`` (the
    autopilot's wire_narrow ladder): f32 -> bf16 -> int8, never past the
    configured target."""
    order = ("f32", "bf16", "int8")
    ci, ti = order.index(current), order.index(target)
    return order[min(ci + 1, ti)] if ci < ti else current


# quantization-aware flag threshold for the SHADOW cyclic decode (relative
# amplitude, same role as coding/cyclic.HEALTH_REL_TOL = 1e-3): honest rows
# on a quantized wire deviate from the fitted codeword by the rounding
# noise (~2^-9 relative for bf16, ~1/254 of the block absmax for int8)
# AMPLIFIED through the locator/fit solves — loudest in the no-live-
# adversary regime, where the locator system is rank-deficient and the
# truncated solve spreads the noise (measured worst honest deviation at
# n≤9, s≤2: 0.03 relative for bf16, 0.1 for int8 — vs f32's ~1e-6).
# These thresholds cover that band with ~2× margin while sitting two
# orders under the in-scope attack payloads (O(100×) amplitude). They are
# the thresholds a REAL narrow wire would ship with at these shapes;
# at larger (n, s) the amplification grows further — run
# tools/wire_study.py at the target shape before narrowing the wire
# (ROADMAP item 4), that measurement being this module's whole point.
SHADOW_REL_TOL = {"bf16": 5e-2, "int8": 1.5e-1}


def watch_enabled(cfg) -> bool:
    """True when the step bodies should compute any observatory columns."""
    return cfg.numerics_watch == "on" or cfg.shadow_wire != "off"


def numerics_metric_names() -> tuple:
    """Column order of the numerics block: 3 stages × STAT_NAMES."""
    return tuple(f"{NUMERICS_PREFIX}{stage}_{stat}"
                 for stage in NUMERICS_STAGES for stat in STAT_NAMES)


def watch_metric_names(cfg) -> tuple:
    """The observatory's contribution to a route's metric schema — the one
    name source for step bodies and the host flush (same contract as
    forensics.mask_metric_names)."""
    names = ()
    if cfg.numerics_watch == "on":
        names += numerics_metric_names()
    if cfg.shadow_wire != "off":
        names += SHADOW_NAMES
    return names


# --------------------------------------------------------------------------
# wire ledger (jax-free)
# --------------------------------------------------------------------------


def wire_rows(approach: str) -> int:
    """f32 words per gradient element on the wire: the cyclic code ships a
    complex codeword (re + im row pair); every other family ships one real
    row per worker."""
    return 2 if approach == "cyclic" else 1


# Segment quantum for the streaming segmented wire (ISSUE 16): cuts land
# on multiples of this so every segment is a whole number of kernel d-tiles.
# Mirrors ops/coded.TILE_D — pinned equal by tests/test_segments.py; kept a
# literal here so the ledger (and wire_study --check) stays jax-free.
SEGMENT_QUANTUM = 4096


def wire_segment_bounds(d: int, segments: int, block: int = 1) -> tuple:
    """Jax-free cut points for the streaming segmented wire: ``(b_0=0 <
    b_1 < ... < b_S=d)`` splitting the d axis into at most ``segments``
    pieces, every interior cut a multiple of the segment quantum
    (SEGMENT_QUANTUM when ``block`` divides it, else ``block`` itself).

    Quantum alignment is the bitwise-invariance contract: the int8
    per-block scales (one per ``block`` elements) and the (d,)-shaped
    shared stochastic-rounding draws never straddle an interior cut, so
    quantize-the-full-row-then-slice equals quantize-per-segment
    bit-for-bit — the narrow buffers are segment-invariant and only the
    decode is segmented. A ``d`` smaller than ``segments`` quanta yields
    fewer (possibly one) segments rather than sub-quantum slivers."""
    d = int(d)
    segments = max(int(segments), 1)
    block = max(int(block), 1)
    if d <= 0:
        return (0, 0)
    quantum = SEGMENT_QUANTUM if SEGMENT_QUANTUM % block == 0 else block
    units = -(-d // quantum)  # whole quanta covering d
    s_eff = max(min(segments, units), 1)
    per, rem = divmod(units, s_eff)
    bounds = [0]
    for i in range(s_eff):
        step = (per + (1 if i < rem else 0)) * quantum
        bounds.append(min(bounds[-1] + step, d))
    bounds[-1] = d
    # dedupe (clamping can only collapse trailing cuts onto d)
    out = [bounds[0]]
    for b in bounds[1:]:
        if b > out[-1]:
            out.append(b)
    return tuple(out)


def cfg_segment_bounds(cfg, dim: int) -> tuple:
    """The segment bounds a config induces at flat-gradient size ``dim``
    — THE one bounds source for the in-graph decode seams, the ledger and
    the tools, so they cannot drift. int8 wires align cuts to the
    per-block scale granularity; f32/bf16 only to the kernel d-tile."""
    block = (int(getattr(cfg, "shadow_block", DEFAULT_BLOCK))
             if getattr(cfg, "wire_dtype", "f32") == "int8" else 1)
    return wire_segment_bounds(dim, getattr(cfg, "wire_segments", 1),
                               block)


def _segment_bytes(bounds: tuple, rows: int, dtype: str,
                   block: int) -> list:
    """Per-segment wire bytes for one worker at ``dtype`` — the physical
    bytes of each [a, b) slice of the narrow buffers. Because interior
    cuts are block-aligned, per-segment int8 scale counts sum exactly to
    the unsegmented ledger's count (no padding hidden at the seams)."""
    out = []
    for a, b in zip(bounds[:-1], bounds[1:]):
        w = rows * (b - a)
        if dtype == "f32":
            out.append(4 * w)
        elif dtype == "bf16":
            out.append(2 * w)
        else:  # int8: 1 byte/elem + f32 per-block scales
            out.append(w + 4 * rows * (-(-(b - a) // block)))
    return out


def wire_ledger(cfg, dim: int) -> dict:
    """Logical worker→aggregator wire bytes per step at the program's
    registered shapes, per dtype candidate — and, since ISSUE 15, the
    MATERIALIZED wire: ``wire_dtype`` names the dtype the step body
    actually rounds the codewords into (real bf16/int8 buffers crossing
    the sharding boundary) and ``physical_bytes_per_worker`` /
    ``physical_bytes_per_step`` are that candidate's bytes — equal BY
    CONSTRUCTION to the logical candidate row (the narrow buffers carry
    exactly 1 byte/elem + f32 per-block scales for int8, 2 bytes/elem for
    bf16), which is what wire_study --check re-verifies. int8 adds one
    f32 scale per ``cfg.shadow_block`` elements (per row)."""
    n = int(cfg.num_workers)
    rows = wire_rows(cfg.approach)
    words = rows * int(dim)
    block = max(int(getattr(cfg, "shadow_block", DEFAULT_BLOCK)), 1)
    blocks = rows * ((int(dim) + block - 1) // block)
    per_worker = {
        "f32": 4 * words,
        "bf16": 2 * words,
        "int8": words + 4 * blocks,  # 1 byte/elem + f32 per-block scales
    }
    wire_dtype = getattr(cfg, "wire_dtype", "f32")
    bounds = cfg_segment_bounds(cfg, dim)
    seg_worker = _segment_bytes(bounds, rows, wire_dtype, block)
    ledger = {
        "family": cfg.approach,
        "dim": int(dim),
        "num_workers": n,
        "wire_words_per_worker": words,
        "bytes_per_worker": per_worker,
        "bytes_per_step": {k: v * n for k, v in per_worker.items()},
        "wire_dtype": wire_dtype,
        "physical_bytes_per_worker": per_worker[wire_dtype],
        "physical_bytes_per_step": per_worker[wire_dtype] * n,
        "shadow_wire": cfg.shadow_wire,
        "shadow_block": block,
        # streaming segmented wire (ISSUE 16): the per-segment physical
        # bytes MUST sum to the per-worker/per-step rows above — block-
        # aligned cuts hide no padding at the seams (wire_study --check
        # re-verifies the sum on the committed matrix)
        "segments": {
            "count": len(bounds) - 1,
            "bounds": list(bounds),
            "physical_bytes_per_worker": seg_worker,
            "physical_bytes_per_step": [v * n for v in seg_worker],
        },
    }
    # hierarchical tree wire (ISSUE 17): per-level ingest bytes. Level 0
    # (leaves) carries the same n physical codewords as the flat wire —
    # level_bytes_per_step[0] == physical_bytes_per_step EXACTLY — and
    # each parent level carries one f32 decoded partial per child group
    # (perf_watch pins the sum identity on the committed study).
    if getattr(cfg, "topology", "flat") == "tree":
        from draco_tpu.coding.topology import tree_ledger_block

        ledger["tree"] = tree_ledger_block(
            n, int(cfg.tree_fanout), int(getattr(cfg, "tree_levels", 0)),
            int(dim), per_worker[wire_dtype])
    return ledger


# --------------------------------------------------------------------------
# in-graph numerics statistics (lazy jax imports)
# --------------------------------------------------------------------------


def _block_absmax(af, block: int):
    """Per-block absmax along the last axis (blocks pad with 0), broadcast
    back to ``af``'s shape — the int8 per-block scale basis. ``af`` must
    already be the finite-masked |x|."""
    import jax.numpy as jnp

    d = af.shape[-1]
    nb = (d + block - 1) // block
    pad = nb * block - d
    if pad:
        padding = [(0, 0)] * (af.ndim - 1) + [(0, pad)]
        af = jnp.pad(af, padding)
    blocked = af.reshape(af.shape[:-1] + (nb, block))
    bmax = jnp.max(blocked, axis=-1, keepdims=True)
    out = jnp.broadcast_to(bmax, blocked.shape)
    out = out.reshape(af.shape[:-1] + (nb * block,))
    return out[..., :d]


def _part_counts(x, block: int) -> dict:
    """Raw accumulators for one tensor (any shape): everything needed to
    combine multiple wire parts (cyclic re+im) without materializing their
    concatenation. All values are finite by construction."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    total = float(x.size)  # static
    a = jnp.abs(x)
    finite = jnp.isfinite(x)
    af = jnp.where(finite, a, 0.0)
    nonzero = finite & (a > 0)
    counts = {
        "total": total,
        "n_finite": jnp.sum(finite.astype(jnp.float32)),
        "sumsq": jnp.sum(jnp.where(finite, x * x, 0.0)),
        "absmax": jnp.max(af) if x.size else jnp.float32(0.0),
        "uf_bf16": jnp.sum((nonzero & (a < BF16_TINY)).astype(jnp.float32)),
        "of_bf16": jnp.sum((finite & (a > BF16_MAX)).astype(jnp.float32)),
    }
    thr = _block_absmax(af, block) / (2.0 * INT8_LEVELS)
    counts["uf_int8"] = jnp.sum((nonzero & (af < thr)).astype(jnp.float32))
    # exponent histogram over finite nonzero elements (log2 of the masked
    # |x| with zeros excluded by the nonzero gate)
    e = jnp.where(nonzero, jnp.log2(jnp.where(nonzero, af, 1.0)), 0.0)
    edges = (-float("inf"),) + tuple(float(v) for v in EXP_EDGES) \
        + (float("inf"),)
    counts["exp"] = [
        jnp.sum((nonzero & (e >= lo) & (e < hi)).astype(jnp.float32))
        for lo, hi in zip(edges[:-1], edges[1:])
    ]
    return counts


def stage_columns(stage: str, parts, block: int = DEFAULT_BLOCK) -> dict:
    """The ``nx_{stage}_*`` columns for one pipeline stage, combined over
    ``parts`` (a list of arrays — the cyclic wire is its (re, im) pair).
    Fractions are over ALL elements; absmax/rms over the finite ones, so a
    NaN/Inf fault yields finite sentinels plus a loud ``nonfinite``."""
    import jax.numpy as jnp

    acc = [_part_counts(p, block) for p in parts]
    total = sum(c["total"] for c in acc)
    n_finite = sum(c["n_finite"] for c in acc)
    sumsq = sum(c["sumsq"] for c in acc)
    absmax = acc[0]["absmax"]
    for c in acc[1:]:
        absmax = jnp.maximum(absmax, c["absmax"])
    denom = max(total, 1.0)
    cols = {
        f"{NUMERICS_PREFIX}{stage}_absmax": absmax,
        f"{NUMERICS_PREFIX}{stage}_rms": jnp.sqrt(
            sumsq / jnp.maximum(n_finite, 1.0)),
        f"{NUMERICS_PREFIX}{stage}_uf_bf16": sum(
            c["uf_bf16"] for c in acc) / denom,
        f"{NUMERICS_PREFIX}{stage}_uf_int8": sum(
            c["uf_int8"] for c in acc) / denom,
        f"{NUMERICS_PREFIX}{stage}_of_bf16": sum(
            c["of_bf16"] for c in acc) / denom,
        f"{NUMERICS_PREFIX}{stage}_nonfinite": (total - n_finite) / denom,
    }
    for i in range(NUM_EXP_BINS):
        cols[f"{NUMERICS_PREFIX}{stage}_exp{i}"] = sum(
            c["exp"][i] for c in acc) / denom
    return cols


def numerics_columns(cfg, grad_parts, wire_parts, agg) -> dict:
    """All three stages' columns (numerics_metric_names order)."""
    block = max(int(cfg.shadow_block), 1)
    cols = {}
    cols.update(stage_columns("grad", list(grad_parts), block))
    cols.update(stage_columns("wire", list(wire_parts), block))
    cols.update(stage_columns("agg", [agg], block))
    return cols


# --------------------------------------------------------------------------
# shadow quantizers (lazy jax imports)
# --------------------------------------------------------------------------


def _round_step_key(cfg, step, offset: int):
    """Per-step PRNG key for stochastic rounding — None under nearest
    rounding (the default), so the deterministic path adds no PRNG ops.
    Folded from (seed, step) like every other schedule; the noise draw is
    shared across wire rows (shape (d,)), so bitwise-identical rows
    (maj_vote's soundness condition) quantize bitwise-identically.
    ``offset`` separates the shadow and real-wire streams."""
    if cfg.shadow_round != "stochastic":
        return None
    import jax

    s = 0 if step is None else step
    return jax.random.fold_in(jax.random.key(cfg.seed + offset), s)


def shadow_step_key(cfg, step=None):
    """The shadow quantizer's stochastic-rounding key (_round_step_key)."""
    return _round_step_key(cfg, step, 11)


def _bf16_stochastic(x, key):
    """Stochastic bf16 rounding via the +rand16-truncate bit trick: f32 in,
    the exactly-bf16-representable f32 values out. ONE implementation for
    the shadow quantizer and the real wire — the calibration transfers
    because the arithmetic cannot drift (pinned bitwise in
    tests/test_wire.py)."""
    import jax
    import jax.numpy as jnp

    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    r = jax.random.bits(key, (x.shape[-1],), jnp.uint32) \
        & jnp.uint32(0xFFFF)
    bits = (bits + r) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(bits, jnp.float32)


def _int8_levels_and_scale(x, block: int, key):
    """Symmetric per-block int8 quantization core — f32 rows in,
    ``(q, scale)`` out with ``q`` the integer levels in [-127, 127] held
    in f32 (exact) and ``scale`` the per-ELEMENT f32 scale (absmax/127
    over ``block``-element blocks along the last axis, constant within a
    block). Round-to-nearest, or floor(x/s + u) stochastic under ``key``;
    non-finite inputs map to 0 — a narrow integer wire has no NaN
    encoding, and non-finite attribution belongs to the ingest-row
    forensics (obs/forensics.nonfinite_rows), not the wire. ONE
    implementation for the shadow quantizer and the real wire."""
    import jax
    import jax.numpy as jnp

    block = max(int(block), 1)
    d = x.shape[-1]
    finite = jnp.isfinite(x)
    af = jnp.where(finite, jnp.abs(x), 0.0)
    bmax = _block_absmax(af, block)
    scale = jnp.where(bmax > 0, bmax / INT8_LEVELS, 1.0)
    y = jnp.where(finite, x, 0.0) / scale
    if key is None:
        q = jnp.round(y)
    else:
        u = jax.random.uniform(key, (d,), jnp.float32)
        q = jnp.floor(y + u)
    return jnp.clip(q, -INT8_LEVELS, INT8_LEVELS), scale


def quantize_rows(x, mode: str, block: int = DEFAULT_BLOCK, key=None):
    """Round wire rows to the narrow dtype, returning the DEQUANTIZED f32
    tensor the shadow decode consumes.

    ``bf16``: round-to-nearest-even via real bf16 converts (or stochastic
    via :func:`_bf16_stochastic` when ``key`` is set). ``int8``:
    :func:`_int8_levels_and_scale` — the SAME cores the real wire
    (narrow_wire_rows) quantizes with, so the shadow calibration
    transfers by construction."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    if mode == "bf16":
        if key is None:
            return x.astype(jnp.bfloat16).astype(jnp.float32)
        return _bf16_stochastic(x, key)
    if mode != "int8":
        raise ValueError(f"unknown shadow wire dtype: {mode!r}")
    q, scale = _int8_levels_and_scale(x, block, key)
    # int8 levels are exact in f32 — the shadow never leaves the chip, so
    # no narrow buffer is materialized (module docstring); the LOGICAL
    # bytes live in wire_ledger
    return q * scale


# --------------------------------------------------------------------------
# the REAL narrow wire (ISSUE 15): actual bf16/int8 buffers cross the
# sharding boundary; f32 exists again only inside the decode
# --------------------------------------------------------------------------


def wire_step_key(cfg, step=None):
    """Per-step PRNG key for the REAL wire's stochastic rounding
    (``cfg.shadow_round`` doubles as the wire rounding mode — the
    observatory knob it was calibrated with). Distinct stream from the
    shadow's (seed + 17 vs + 11, _round_step_key)."""
    return _round_step_key(cfg, step, 17)


def narrow_wire_rows(x, mode: str, block: int = DEFAULT_BLOCK, key=None):
    """Round (..., d) f32 wire rows into REAL narrow buffers — the arrays
    that physically cross the worker→aggregator sharding boundary.

    Returns a dict of narrow arrays:
      bf16: {"q": bfloat16 (..., d)}
      int8: {"q": int8 (..., d), "scale": f32 (..., ceil(d/block))}
            symmetric per-block scales (absmax/127 over ``block``-element
            blocks along the last axis, per row); non-finite inputs map
            to 0 (an integer wire has no NaN encoding — non-finite
            attribution belongs to the pre-encode ingest forensics).
    Rounding: nearest by default; ``key`` enables the shared-draw
    stochastic rounding (wire_step_key). The quantization cores
    (:func:`_bf16_stochastic`, :func:`_int8_levels_and_scale`) are THE
    SAME ones the shadow quantizer runs — the calibration transfers by
    construction, pinned bitwise in tests/test_wire.py."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    if mode == "bf16":
        if key is None:
            return {"q": x.astype(jnp.bfloat16)}
        # the stochastic-rounded value is exactly bf16-representable: the
        # narrowing cast is exact
        return {"q": _bf16_stochastic(x, key).astype(jnp.bfloat16)}
    if mode != "int8":
        raise ValueError(f"unknown wire dtype: {mode!r}")
    q, scale = _int8_levels_and_scale(x, block, key)
    # blocked scale buffer: within-block values are identical, so strided
    # slicing at the block starts yields the (..., nb) per-block scales
    return {"q": q.astype(jnp.int8),
            "scale": scale[..., ::max(int(block), 1)]}


def widen_wire_rows(buf: dict, mode: str, block: int = DEFAULT_BLOCK):
    """Narrow wire buffers -> the f32 rows the decode consumes (f32
    accumulation throughout). This is the ONLY widening site: on the XLA
    path the convert fuses into the consuming matmul; on TPU the
    narrow-ingest Pallas kernels (ops/decode_kernels) run the same
    arithmetic in-tile on VMEM blocks, so the widened (n, d) f32 matrix
    never round-trips HBM."""
    import jax.numpy as jnp

    q = jnp.asarray(buf["q"])
    if mode == "bf16":
        return q.astype(jnp.float32)
    if mode != "int8":
        raise ValueError(f"unknown wire dtype: {mode!r}")
    block = max(int(block), 1)
    d = q.shape[-1]
    scale = jnp.asarray(buf["scale"])
    wide = jnp.repeat(scale, block, axis=-1)[..., :d]
    return q.astype(jnp.float32) * wide


def wire_decode_params(cfg, n=None, s=None):
    """(rel_tol, lam) the cyclic decode runs with at ``cfg``'s wire dtype:
    (None, 0.0) on the f32 wire — the caller keeps HEALTH_REL_TOL and the
    exact λ=0 solve bitwise — else the committed per-(n, s, dtype)
    threshold and the dtype's locator λ. ``n``/``s`` override the flat
    (num_workers, worker_fail) shape — the tree route decodes each leaf
    group at the GROUP shape (fanout, s_g), so its thresholds come from
    that row of the table, not the flat one."""
    dtype = getattr(cfg, "wire_dtype", "f32")
    if dtype == "f32":
        return None, 0.0
    n = cfg.num_workers if n is None else n
    s = cfg.worker_fail if s is None else s
    return wire_rel_tol(n, s, dtype), wire_locator_lambda(dtype)


def narrow_wire_pair(cfg, enc_re, enc_im, step=None, constrain=None):
    """Apply the REAL narrow wire to a cyclic (re, im) codeword pair:
    quantize into narrow buffers — THE arrays that cross the sharding
    boundary (``constrain`` pins each to the worker axis) — then widen to
    f32 for the decode. Returns ``(enc_re, enc_im, wire)`` where ``wire``
    is ``(mode, buf_re, buf_im, block)`` for the narrow-ingest decode
    kernels, or None on the f32 wire (identity — no ops added)."""
    dtype = getattr(cfg, "wire_dtype", "f32")
    if dtype == "f32":
        return enc_re, enc_im, None
    import jax

    key = wire_step_key(cfg, step)
    k_im = None if key is None else jax.random.fold_in(key, 1)
    buf_re = narrow_wire_rows(enc_re, dtype, cfg.shadow_block, key)
    buf_im = narrow_wire_rows(enc_im, dtype, cfg.shadow_block, k_im)
    if constrain is not None:
        buf_re = {k: constrain(v) for k, v in buf_re.items()}
        buf_im = {k: constrain(v) for k, v in buf_im.items()}
    return (widen_wire_rows(buf_re, dtype, cfg.shadow_block),
            widen_wire_rows(buf_im, dtype, cfg.shadow_block),
            (dtype, buf_re, buf_im, int(cfg.shadow_block)))


def narrow_wire_single(cfg, rows, step=None, constrain=None):
    """The single-row-block variant (approx partial sums / maj_vote raw
    gradient rows): returns ``(rows_f32, wire)`` with ``wire`` =
    ``(mode, buf, block)`` or None on the f32 wire."""
    dtype = getattr(cfg, "wire_dtype", "f32")
    if dtype == "f32":
        return rows, None
    buf = narrow_wire_rows(rows, dtype, cfg.shadow_block,
                           wire_step_key(cfg, step))
    if constrain is not None:
        buf = {k: constrain(v) for k, v in buf.items()}
    return (widen_wire_rows(buf, dtype, cfg.shadow_block),
            (dtype, buf, int(cfg.shadow_block)))


# --------------------------------------------------------------------------
# shadow comparison columns
# --------------------------------------------------------------------------


def _finite_or(v, sentinel: float = SHADOW_SENTINEL):
    import jax.numpy as jnp

    v = jnp.asarray(v, jnp.float32)
    return jnp.where(jnp.isfinite(v), v, jnp.float32(sentinel))


def shadow_columns(agg, shadow_agg, shadow_residual, flags, shadow_flags,
                   adv_mask, present) -> dict:
    """The SHADOW_NAMES columns from one step's f32 + shadow decode pair
    (module docstring). The detection counts reimplement the present-gated
    scoring of training/step._detection_metrics on the SHADOW flag set (a
    straggling adversary is neither detectable nor ground truth)."""
    import jax.numpy as jnp

    agg = jnp.asarray(agg, jnp.float32)
    shadow_agg = jnp.asarray(shadow_agg, jnp.float32)
    n = int(jnp.asarray(flags).shape[0])
    pres = (jnp.ones((n,), bool) if present is None
            else jnp.asarray(present, bool))
    f = jnp.asarray(flags, bool) & pres
    sf = jnp.asarray(shadow_flags, bool) & pres
    adv = jnp.asarray(adv_mask, bool)
    err = jnp.sqrt(jnp.sum((shadow_agg - agg) ** 2)) / jnp.maximum(
        jnp.sqrt(jnp.sum(agg ** 2)), 1e-30)
    agree = jnp.sum(((f == sf) & pres).astype(jnp.float32)) / jnp.maximum(
        jnp.sum(pres.astype(jnp.float32)), 1.0)
    return {
        "shadow_err": _finite_or(err),
        "shadow_residual": _finite_or(shadow_residual),
        "shadow_flag_agree": _finite_or(agree),
        "shadow_det_flagged": jnp.sum(sf.astype(jnp.int32)),
        "shadow_det_tp": jnp.sum((sf & adv & pres).astype(jnp.int32)),
    }


# --------------------------------------------------------------------------
# per-family shadow drivers (one place, so the CNN bodies and the LM tail
# cannot drift on quantize/decode/compare semantics)
# --------------------------------------------------------------------------


def cyclic_shadow(cfg, code, enc_re, enc_im, agg, health, rand_factor,
                  leaf_offsets, present, adv_mask, step=None) -> dict:
    """Shadow decode of the quantized cyclic wire (both complex halves
    rounded), at the quantization-aware flag threshold SHADOW_REL_TOL.
    Decode granularity follows the live f32 decode so the flag sets
    compare apples to apples."""
    import jax

    from draco_tpu.coding import cyclic as cyclic_mod

    key = shadow_step_key(cfg, step)
    k_im = None if key is None else jax.random.fold_in(key, 1)
    q_re = quantize_rows(enc_re, cfg.shadow_wire, cfg.shadow_block, key)
    q_im = quantize_rows(enc_im, cfg.shadow_wire, cfg.shadow_block, k_im)
    rel_tol = SHADOW_REL_TOL[cfg.shadow_wire]
    if cfg.decode_granularity == "layer":
        sagg, _honest, sh = cyclic_mod.decode_layers(
            code, q_re, q_im, rand_factor, leaf_offsets, present=present,
            with_health=True, rel_tol=rel_tol)
    else:
        sagg, _honest, sh = cyclic_mod.decode(
            code, q_re, q_im, rand_factor, present=present,
            with_health=True, rel_tol=rel_tol)
    return shadow_columns(agg, sagg, sh["residual"], health["flagged"],
                          sh["flagged"], adv_mask, present)


def majvote_shadow(cfg, rep_code, grads, voted, vhealth, vkey, present,
                   adv_mask, step=None) -> dict:
    """Shadow vote over the quantized gradient rows (the repetition code's
    wire IS the raw rows). Deterministic quantization preserves within-
    group bitwise equality, so the vote's soundness condition holds on the
    shadow wire by construction; the columns verify it per step. The
    residual slot carries 1 − shadow vote agreement (the family's decode-
    health analogue)."""
    from draco_tpu.coding import repetition as rep_mod

    key = shadow_step_key(cfg, step)
    qg = quantize_rows(grads, cfg.shadow_wire, cfg.shadow_block, key)
    voted_s, sh = rep_mod.majority_vote(rep_code, qg, present=present,
                                        key=vkey, method=cfg.vote_check,
                                        with_health=True)
    return shadow_columns(voted, voted_s, 1.0 - sh["vote_agree"],
                          vhealth["flagged"], sh["flagged"], adv_mask,
                          present)


def approx_shadow(cfg, code, rows, grads, decoded, present,
                  adv_mask, step=None) -> dict:
    """Shadow partial-recovery decode of the quantized approx wire. This
    family has no located-error set (no Byzantine certificate), so the
    flag comparison is over the non-finite WIRE rows — meaningful under
    fault injection, identically empty on clean runs. The residual slot is
    the shadow decode's measured relative error vs the true batch-gradient
    mean (same units as the family's decode_residual column)."""
    from draco_tpu.coding import approx as approx_mod
    from draco_tpu.obs.forensics import nonfinite_rows

    key = shadow_step_key(cfg, step)
    q = quantize_rows(rows, cfg.shadow_wire, cfg.shadow_block, key)
    dec_s, _v, sh = approx_mod.decode(code, q, present=present,
                                      with_health=True, batch_grads=grads)
    return shadow_columns(decoded, dec_s, sh["residual"],
                          nonfinite_rows(rows), nonfinite_rows(q),
                          adv_mask, present)
