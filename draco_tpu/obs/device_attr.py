"""Device-time attribution: fold a jax.profiler capture into per-phase and
per-collective ledgers (ISSUE 9 — the device-side half of the telemetry
spine).

PR 4 planted ``jax.named_scope`` phases (``draco_comp`` / ``draco_encode`` /
``draco_decode`` / ``draco_update``) in every step body and ``--profile-dir``
captures jax.profiler traces, but nothing parsed them: all attribution was
host-side spans around opaque jitted dispatches. This module closes the gap
**without importing jax** — it is pure artifact folding, importable from the
jax-free tools (tools/device_profile.py, tools/trace_report.py) and usable on
a laptop against capture dirs scp'd from a chip job.

Capture shapes handled
----------------------

jax.profiler writes ``profile_dir/plugins/profile/<ts>/*.trace.json.gz`` — a
Chrome-trace-event dump. Two event shapes exist:

* **XLA:CPU fallback (this container, PERF.md §8c):** each executed HLO op
  is one complete event whose ``args`` carry only ``hlo_module`` (e.g.
  ``jit_many_body``) and ``hlo_op`` (the *optimized*-HLO instruction name,
  e.g. ``dot.2`` / ``fusion.17``). The named-scope path is NOT in the event —
  it lives in the compiled executable's HLO metadata
  (``metadata={op_name="jit(f)/.../draco_decode/dot_general"}``). Attribution
  therefore needs a **scope map**: optimized-instruction name → draco phase,
  parsed from ``compiled.as_text()`` by :func:`scope_map_from_hlo` and dumped
  next to the capture (``device_scope_map.json``) by the profiled run
  (tools/device_profile.py ``--run-cell``). Because XLA:CPU compilation is
  deterministic for a fixed program, the re-compiled text names match the
  executed trace's names — and a drift would be loud, not silent: unmatched
  ops land in the ``unattributed`` row, never in a phase.
* **TPU (XProf) traces** carry the full scope path in the event itself; ops
  whose name/args embed a ``draco_*`` segment attribute directly, scope map
  optional.

Accounting rule (the "provably sums" contract)
----------------------------------------------

Device op events NEST (a ``call`` computation event wraps its body's op
events on the same thread) and run CONCURRENTLY across executor threads, so
naive duration sums double-count. Attribution uses per-thread **self time**:
each event's duration minus the durations of events nested inside it on the
same thread. Per program, the ledger rows

  draco_comp + draco_encode + draco_decode + draco_update
  + other (mapped op, no draco scope) + unattributed (op not in the map)

sum EXACTLY to the program's total device self-time in the profiled window —
the residual is carried explicitly (``other`` / ``unattributed``), never
absorbed into a phase. ``wall_us`` (envelope of the module's events) is
reported separately; on a multi-threaded executor total self-time > wall is
normal (it is core-time, the chip analogue of busy lanes).

Collective cross-check
----------------------

The PR 3 linter pins each program's *explicit* collective counts
(shard_map psum/ppermute rings) in its ``Manifest``; GSPMD-inserted
collectives materialize only inside the SPMD partitioner and are exempt
(analysis/registry.py docstring). In the compiled HLO the two are separable
by metadata: an explicit collective's ``op_name`` path ends in the jax
primitive that lowered it (``.../psum``, ``.../ppermute``), a GSPMD-inserted
one carries the compute op it was inserted for (``.../dot_general``,
``.../reduce_sum``). The runtime cross-check — :func:`cross_check` — demands
that the distinct explicit collective instructions OBSERVED EXECUTING in the
trace equal the manifest counts per kind; any mismatch is a hard
:class:`CollectiveMismatchError` (the static audit and the runtime trace
must agree). GSPMD collectives are folded into their own ledger row for
observability, never counted against the manifest.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import re
from typing import Optional

# the named-scope phases every step body carries (PR 4; training/step.py +
# parallel/common.py) — ledger row order
PHASES = ("draco_comp", "draco_encode", "draco_decode", "draco_update")
# residual rows: "other" = op mapped by the scope map but under no draco
# scope (optimizer glue, schedule slicing, metric folds), "unattributed" =
# op absent from the scope map entirely (post-scheduling copies, or a
# scope-map drift)
RESIDUAL_ROWS = ("other", "unattributed")

# optimized-HLO opcode -> manifest collective kind (analysis/registry.py
# COLLECTIVE_KINDS spelling)
HLO_COLLECTIVES = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "all-to-all": "all_to_all",
    "collective-permute": "collective_permute",
    "reduce-scatter": "reduce_scatter",
    # async pairs (TPU lowers collectives to start/done) — counted on start
    "all-reduce-start": "all_reduce",
    "all-gather-start": "all_gather",
    "collective-permute-start": "collective_permute",
}
COLLECTIVE_KINDS = ("all_reduce", "all_gather", "all_to_all",
                    "collective_permute", "reduce_scatter")

# jax primitive (the last op_name path segment of an EXPLICIT collective)
# -> manifest kind; a collective whose metadata ends elsewhere is
# GSPMD-inserted
PRIM_COLLECTIVES = {
    "psum": "all_reduce",
    "ppermute": "collective_permute",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "psum_scatter": "reduce_scatter",
}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_SCOPE_RE = re.compile(r"draco_\w+")
_HLO_LINE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*.*?\s([\w\-]+)\(")
_META_RE = re.compile(r'metadata=\{[^}]*?op_name="([^"]*)"')
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


class CollectiveMismatchError(RuntimeError):
    """The runtime trace's explicit-collective structure disagrees with the
    program's linted Manifest — the hard-error contract of ISSUE 9."""


# --------------------------------------------------------------------------
# scope map: optimized-HLO text -> {op: phase}, collective classification
# --------------------------------------------------------------------------

def _shape_bytes(type_text: str) -> int:
    """Byte size of an HLO result type (sums tuple elements); 0 when no
    sized array appears (token/opaque)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_text):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def phase_of(op_name: Optional[str]) -> str:
    """First ``draco_*`` segment of a metadata op_name path ('' if none)."""
    if not op_name:
        return ""
    m = _SCOPE_RE.search(op_name)
    return m.group(0) if m else ""


def scope_map_from_hlo(hlo_text: str) -> dict:
    """Parse ``compiled.as_text()`` into the attribution scope map.

    Returns ``{"module", "ops": {instr: phase|""}, "collectives":
    {instr: {kind, bytes, explicit, phase}}}``. Pure text parsing — callable
    without jax (the profiled runner dumps the text; tests feed fixtures).
    """
    m = re.match(r"HloModule\s+([\w.\-]+)", hlo_text)
    module = m.group(1).rstrip(",") if m else ""
    ops: dict = {}
    collectives: dict = {}
    for line in hlo_text.splitlines():
        hm = _HLO_LINE_RE.match(line)
        if not hm:
            continue
        instr, opcode = hm.group(1), hm.group(2)
        meta = _META_RE.search(line)
        op_name = meta.group(1) if meta else ""
        ops[instr] = phase_of(op_name)
        kind = HLO_COLLECTIVES.get(opcode)
        if kind is not None:
            tail = op_name.rsplit("/", 1)[-1] if op_name else ""
            explicit = PRIM_COLLECTIVES.get(tail) == kind
            # result type text sits between '=' and the opcode
            type_text = line.split("=", 1)[1].split(opcode + "(", 1)[0]
            collectives[instr] = {
                "kind": kind,
                "bytes": _shape_bytes(type_text),
                "explicit": bool(explicit),
                "phase": ops[instr],
            }
    return {"module": module, "ops": ops, "collectives": collectives}


# --------------------------------------------------------------------------
# capture loading
# --------------------------------------------------------------------------

def find_capture(profile_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` (or ``.trace.json``) under the jax
    profiler layout ``profile_dir/plugins/profile/<ts>/``; None when the
    directory holds no capture (tolerated, like a missing metrics.jsonl)."""
    pats = (os.path.join(profile_dir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(profile_dir, "plugins", "profile", "*",
                         "*.trace.json"))
    hits = [p for pat in pats for p in glob.glob(pat)]
    return max(hits, key=os.path.getmtime) if hits else None


def load_trace(path: str) -> "tuple[list, dict]":
    """(events, top-level payload) from a Chrome-trace JSON (.gz or plain;
    tolerates the bare event-array form)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        payload = json.load(fh)
    if isinstance(payload, list):
        return payload, {}
    return payload.get("traceEvents", []) or [], payload


def load_json(path: str) -> Optional[dict]:
    try:
        with open(path) as fh:
            out = json.load(fh)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def load_scope_map(profile_dir: str) -> Optional[dict]:
    """The runner-dumped ``device_scope_map.json`` (None when absent — a
    plain ``--profile-dir`` run never dumps one; attribution then degrades
    to module totals with everything unattributed)."""
    return load_json(os.path.join(profile_dir, "device_scope_map.json"))


def load_anchor(profile_dir: str) -> Optional[dict]:
    """``host_anchor.json`` stamped by obs.profiling.profiler_window at
    start/stop — the shared-clock anchor the merged timeline needs."""
    return load_json(os.path.join(profile_dir, "host_anchor.json"))


def _module_of(ev: dict) -> Optional[str]:
    args = ev.get("args")
    return args.get("hlo_module") if isinstance(args, dict) else None


def _op_of(ev: dict) -> str:
    args = ev.get("args") or {}
    return args.get("hlo_op") or ev.get("name", "")


# --------------------------------------------------------------------------
# per-thread self-time (the anti-double-count accounting)
# --------------------------------------------------------------------------

def self_times(events: list) -> "list[tuple[dict, float]]":
    """[(event, self_dur_us)] — each complete event's duration minus the
    durations of events nested inside it on the SAME thread (a ``call``
    computation event wraps its body ops; summing both would double-count).
    Partial overlaps (distinct executor work items) stay independent."""
    out = []
    by_tid: dict = collections.defaultdict(list)
    for ev in events:
        if ev.get("ph") != "X":
            continue
        by_tid[ev.get("tid", 0)].append(ev)
    for evs in by_tid.values():
        evs.sort(key=lambda e: (float(e.get("ts", 0.0)),
                                -float(e.get("dur", 0.0))))
        stack: list = []  # [ev, end_ts, child_dur]
        for ev in evs:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
            while stack and stack[-1][1] <= ts + 1e-9:
                top = stack.pop()
                out.append((top[0], max(float(top[0].get("dur", 0.0))
                                        - top[2], 0.0)))
            if stack and ts + dur <= stack[-1][1] + 1e-6:
                stack[-1][2] += dur  # nested: parent pays the child's time
            stack.append([ev, ts + dur, 0.0])
        while stack:
            top = stack.pop()
            out.append((top[0], max(float(top[0].get("dur", 0.0))
                                    - top[2], 0.0)))
    return out


# --------------------------------------------------------------------------
# per-phase ledger
# --------------------------------------------------------------------------

def _module_events(events: list, module: str) -> list:
    """One selection rule for both ledgers: complete events tagged
    ``args.hlo_module == module``, plus untagged events carrying a
    ``draco_*`` segment in their name/op path (the TPU scope-in-name
    shape)."""
    out = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        evm = _module_of(ev)
        if evm is not None:
            if evm == module:
                out.append(ev)
        elif (_SCOPE_RE.search(_op_of(ev))
              or _SCOPE_RE.search(ev.get("name", ""))):
            # scope-in-name (TPU) shape — _op_of prefers args.hlo_op, so
            # also search the event name the scope path actually rides in
            out.append(ev)
    return out


def _phase_rows(pairs: list, scope: dict) -> dict:
    """Per-phase ledger rows from precomputed (event, self_us) pairs —
    each pair lands in exactly one row (phase / other / unattributed), so
    the rows sum to the total device self-time by construction."""
    ops = scope.get("ops", {})
    rows = {k: {"time_us": 0.0, "events": 0}
            for k in PHASES + RESIDUAL_ROWS}
    t_lo, t_hi = float("inf"), float("-inf")
    for ev, self_us in pairs:
        op = _op_of(ev)
        ph = ops.get(op)
        if ph is None:
            ph = phase_of(op)  # TPU shape: the path is the event name
            key = ph if ph else "unattributed"
        else:
            key = ph if ph else "other"
        if key not in rows:
            # a draco_* token outside the ledger rows — e.g. "draco_tpu"
            # matched from a repo file path in a python-tracer frame name,
            # or a future named scope this ledger predates: residual, loud
            key = "unattributed"
        rows[key]["time_us"] += self_us
        rows[key]["events"] += 1
        ts = float(ev.get("ts", 0.0))
        t_lo = min(t_lo, ts)
        t_hi = max(t_hi, ts + float(ev.get("dur", 0.0)))
    total = sum(r["time_us"] for r in rows.values())
    for r in rows.values():
        r["frac"] = (r["time_us"] / total) if total else 0.0
    return {
        "module": scope.get("module", ""),
        "phases": rows,
        "total_device_us": total,
        "wall_us": (t_hi - t_lo) if t_hi > t_lo else 0.0,
        "matched_events": len(pairs),
    }


def attribute_phases(events: list, scope: dict) -> dict:
    """Fold one program's device events into the per-phase ledger.

    ``scope``: a :func:`scope_map_from_hlo` dict. Events are selected by
    :func:`_module_events`; each selected event's SELF time lands in
    exactly one row (phase / other / unattributed), so the rows sum to
    ``total_device_us`` by construction. Ops with no module tag but a
    ``draco_*`` segment in their name/op path (TPU trace shape) attribute
    directly.
    """
    pairs = self_times(_module_events(events, scope.get("module", "")))
    return _phase_rows(pairs, scope)


# --------------------------------------------------------------------------
# collective comms ledger + manifest cross-check
# --------------------------------------------------------------------------

def collective_ledger(events: list, scope: dict) -> dict:
    """Per-kind count/bytes/time ledger of the program's collectives.

    ``explicit`` rows carry ``instructions`` (DISTINCT collective
    instructions observed executing — the static quantity the Manifest
    pins), ``events`` (executions: instructions × devices × scan trips ×
    profiled dispatches), ``bytes`` (result bytes × executions) and device
    self-time. GSPMD-inserted collectives fold into one ``gspmd`` row per
    kind — real traffic worth seeing, but exempt from the manifest
    (analysis/registry.py: a manifest pins the *explicit* ICI structure)."""
    pairs = self_times(_module_events(events, scope.get("module", "")))
    return _collective_rows(pairs, scope)


def _collective_rows(pairs: list, scope: dict) -> dict:
    """Collective ledger from precomputed (event, self_us) pairs."""
    coll = scope.get("collectives", {})
    explicit = {k: {"instructions": 0, "events": 0, "bytes": 0,
                    "time_us": 0.0} for k in COLLECTIVE_KINDS}
    gspmd = {k: {"instructions": 0, "events": 0, "bytes": 0, "time_us": 0.0}
             for k in COLLECTIVE_KINDS}
    seen: dict = collections.defaultdict(set)
    for ev, self_us in pairs:
        op = _op_of(ev)
        info = coll.get(op)
        if info is None:
            continue
        side = explicit if info["explicit"] else gspmd
        row = side[info["kind"]]
        row["events"] += 1
        row["bytes"] += int(info.get("bytes", 0))
        row["time_us"] += self_us
        bucket = ("explicit", info["kind"]) if info["explicit"] \
            else ("gspmd", info["kind"])
        if op not in seen[bucket]:
            seen[bucket].add(op)
            row["instructions"] += 1
    return {"explicit": explicit, "gspmd": gspmd}


def cross_check(ledger: dict, manifest_counts: Optional[dict],
                program: str) -> dict:
    """The hard-error reconciliation: distinct explicit collective
    instructions observed in the runtime trace must equal the program's
    linted Manifest counts per kind (missing kinds default to 0). Returns
    ``{"ok": True, "expected": ..., "observed": ...}`` or raises
    :class:`CollectiveMismatchError` naming every drifted kind. A program
    whose manifest skips the rule (``None``) cross-checks nothing."""
    observed = {k: ledger["explicit"][k]["instructions"]
                for k in COLLECTIVE_KINDS}
    if manifest_counts is None:
        return {"ok": True, "skipped": True, "observed": observed}
    expected = {k: int(manifest_counts.get(k, 0)) for k in COLLECTIVE_KINDS}
    if observed != expected:
        diff = {k: {"manifest": expected[k], "trace": observed[k]}
                for k in COLLECTIVE_KINDS if expected[k] != observed[k]}
        raise CollectiveMismatchError(
            f"{program}: runtime trace's explicit collective structure "
            f"disagrees with the linted Manifest — {diff}. The static audit "
            f"and the runtime trace must agree: either the program changed "
            f"without relinting (run tools/program_lint.py) or the scope "
            f"map drifted from the executed program (PERF.md §12)")
    return {"ok": True, "expected": expected, "observed": observed}


# --------------------------------------------------------------------------
# roofline join (PR 5 cost_analysis columns from program_lint.json)
# --------------------------------------------------------------------------

def roofline(total_device_us: float, steps_profiled: int, lint_row: dict,
             peak_flops: Optional[float] = None,
             peak_bytes_per_s: Optional[float] = None) -> dict:
    """Join measured device time with the program's analytic cost columns
    (``rules.memory_budget``: cost_analysis flops + memory byte columns;
    PERF.md §8). ``flops`` of a K-fused row counts the scan body ONCE
    (rules._cost_flops), so it is the per-step figure either way. Fractions
    are reported only when a peak is supplied (on the XLA:CPU fallback there
    is no honest hardware peak — PERF.md §8c; chip runs pass the chip
    numbers)."""
    mb = (lint_row.get("rules") or {}).get("memory_budget") or {}
    flops = mb.get("flops")
    mem = mb.get("memory") or {}
    # bytes the program touches per execution: argument + output + temp —
    # the working-set proxy, not a DMA count
    touched = sum(int(mem.get(k, 0)) for k in
                  ("argument_bytes", "output_bytes", "temp_bytes"))
    out: dict = {"flops_per_step": flops, "touched_bytes_per_step": touched}
    secs = total_device_us / 1e6
    if flops and secs > 0 and steps_profiled:
        out["achieved_flops_per_s"] = flops * steps_profiled / secs
        if peak_flops:
            out["achieved_flops_frac"] = out["achieved_flops_per_s"] / peak_flops
            out["peak_flops"] = peak_flops
    if touched and secs > 0 and steps_profiled:
        out["achieved_bytes_per_s"] = touched * steps_profiled / secs
        if peak_bytes_per_s:
            out["achieved_bw_frac"] = (out["achieved_bytes_per_s"]
                                       / peak_bytes_per_s)
            out["peak_bytes_per_s"] = peak_bytes_per_s
    return out


# --------------------------------------------------------------------------
# merged host+device timeline
# --------------------------------------------------------------------------

# pid offset for re-emitted device lanes (host tracer uses the real pid)
DEVICE_PID_BASE = 1 << 20

_START_TRACE_RE = re.compile(r"start_trace")


def _start_trace_end(events: list) -> Optional[float]:
    """Device-trace timestamp (µs) of the moment ``start_trace`` RETURNED.
    jax's python tracer emits a ``$profiler.py:<line> start_trace`` event
    whose END is exactly that moment; None when the capture has no such
    event (the quiet capture — obs/profiling._quiet_start_trace disables
    the python tracer — or the TPU shape)."""
    best = None
    for ev in events:
        if ev.get("ph") == "X" and _START_TRACE_RE.search(ev.get("name", "")):
            end = float(ev.get("ts", 0.0)) + float(ev.get("dur", 0.0))
            best = end if best is None else min(best, end)
    return best


def _event_span(events: list) -> "tuple[Optional[float], Optional[float]]":
    """(earliest start, latest end) of the capture's complete events."""
    lo, hi = None, None
    for ev in events:
        if ev.get("ph") != "X":
            continue
        ts = float(ev.get("ts", 0.0))
        end = ts + float(ev.get("dur", 0.0))
        lo = ts if lo is None else min(lo, ts)
        hi = end if hi is None else max(hi, end)
    return lo, hi


def device_time_origin(events: list) -> float:
    """The device-trace timestamp (µs) of the profiler's start-time anchor:
    the ``start_trace`` frame END when the python tracer recorded one, else
    the earliest event (which over-shifts by at most the capture lead-in)."""
    best = _start_trace_end(events)
    if best is not None:
        return best
    lo, _ = _event_span(events)
    return lo if lo is not None else 0.0


def merge_timeline(host_events: list, device_events: list,
                   scope: Optional[dict] = None,
                   anchor: Optional[dict] = None,
                   max_device_events: int = 0) -> dict:
    """One Perfetto-loadable payload: the PR 4 host tracer lanes plus the
    capture's device lanes on a shared clock.

    The device timebase is shifted onto the host tracer clock through the
    best anchor pair available (obs/profiling.py stamps both ends):

    * the capture's ``start_trace`` frame END paired with
      ``anchor["tracer_ts_us"]`` (python-tracer captures — exact);
    * else the capture's LAST event END paired with
      ``anchor["drained_tracer_ts_us"]`` — the quiet capture has no start
      event, but the devices were provably idle at the drain stamp, so the
      final device event ends at that host instant (the drain-stamp anchor
      profiling.stop() exists to provide);
    * else the earliest event paired with ``tracer_ts_us``, over-shifting
      the device lanes EARLY by at most the start-to-first-dispatch
      lead-in.

    Device events are
    re-emitted under ``pid += DEVICE_PID_BASE`` with their draco phase (from
    the scope map) in ``args.phase`` and ``cat="device"`` — so one trace
    answers "is the gap host prefetch or chip decode". Without an anchor
    (no host tracer was running) the device lanes keep their own origin at
    ts 0.

    ``max_device_events`` > 0 bounds the device lanes to the LONGEST that
    many complete events (XLA:CPU conv thunks emit hundreds of thousands of
    sub-ms slices — an unbounded merge is a viewer-killing multi-100MB
    file); the drop count is carried explicitly in ``mergedTimeline`` —
    never a silent cap. Metadata/counter events always survive."""
    tracer_ts = (anchor or {}).get("tracer_ts_us")
    drained_ts = (anchor or {}).get("drained_tracer_ts_us")
    start_end = _start_trace_end(device_events)
    span_lo, span_hi = _event_span(device_events)
    if tracer_ts is not None and start_end is not None:
        anchor_kind = "start_trace"
        offset = tracer_ts - start_end
    elif drained_ts is not None and span_hi is not None:
        anchor_kind = "drain"
        offset = drained_ts - span_hi
    elif tracer_ts is not None:
        anchor_kind = "start_stamp"
        offset = tracer_ts - (span_lo if span_lo is not None else 0.0)
    else:
        anchor_kind = None
        offset = -(span_lo if span_lo is not None else 0.0)
    ops = (scope or {}).get("ops", {})
    merged = list(host_events)
    seen_pids = set()
    dropped = 0
    if max_device_events > 0:
        xs = [ev for ev in device_events if ev.get("ph") == "X"]
        if len(xs) > max_device_events:
            xs.sort(key=lambda e: -float(e.get("dur", 0.0)))
            keep = set(map(id, xs[:max_device_events]))
            dropped = len(xs) - max_device_events
            device_events = [ev for ev in device_events
                             if ev.get("ph") != "X" or id(ev) in keep]
    for ev in device_events:
        ph = ev.get("ph")
        if ph not in ("X", "M", "C", "i"):
            continue
        out = dict(ev)
        pid = int(ev.get("pid", 0)) + DEVICE_PID_BASE
        out["pid"] = pid
        if ph != "M":
            out["ts"] = round(float(ev.get("ts", 0.0)) + offset, 3)
            out["cat"] = "device"
            phase = ops.get(_op_of(ev)) or phase_of(_op_of(ev))
            if phase:
                out.setdefault("args", {})
                out["args"] = dict(out["args"], phase=phase)
        elif ev.get("name") == "process_name":
            out["args"] = {"name": "device: "
                           + str((ev.get("args") or {}).get("name", ""))}
        merged.append(out)
        seen_pids.add(pid)
    for pid in sorted(seen_pids):
        merged.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                       "args": {"sort_index": pid}})
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "mergedTimeline": {"device_offset_us": round(offset, 3),
                               "anchored": anchor_kind is not None,
                               "anchor_kind": anchor_kind,
                               "droppedDeviceEvents": dropped}}


# --------------------------------------------------------------------------
# one-call fold (tools/trace_report.py + tools/device_profile.py entry)
# --------------------------------------------------------------------------

def fold_capture(profile_dir: str, strict: bool = False) -> Optional[dict]:
    """Fold a profile dir (capture + runner-dumped scope map) into the
    device report: per-program phase ledger + collective ledger. None when
    no capture exists; a capture without a scope map folds with every op
    unattributed (still honest — the residual carries it). A torn/corrupt
    capture (a run killed mid-flush) returns None too unless ``strict`` —
    the same partial-artifact tolerance metrics.jsonl consumers follow."""
    trace_path = find_capture(profile_dir)
    if trace_path is None:
        return None
    try:
        events, payload = load_trace(trace_path)
    except (OSError, ValueError, EOFError):
        if strict:
            raise
        return None
    sm = load_scope_map(profile_dir)
    meta = {k: sm[k] for k in ("cell", "steps_profiled", "steps_per_call")
            if sm and k in sm}
    programs = (sm or {}).get("programs")
    if not programs:
        # no scope map: fold the busiest module so the report still shows
        # device time, all of it unattributed
        mods = collections.Counter(m for m in map(_module_of, events) if m)
        programs = [{"module": m, "ops": {}, "collectives": {}}
                    for m, _ in mods.most_common(1)]
    out_programs = []
    for scope in programs:
        # one selection + self-time pass feeds both ledgers (captures run
        # to ~1M events and this fold also runs inline at window close via
        # heartbeat.observe_device — don't pay the O(n log n) pass twice)
        pairs = self_times(_module_events(events, scope.get("module", "")))
        row = _phase_rows(pairs, scope)
        row["collectives"] = _collective_rows(pairs, scope)
        for k in ("lint_row", "flops_per_step"):
            if isinstance(scope, dict) and k in scope:
                row[k] = scope[k]
        out_programs.append(row)
    return {"trace": trace_path, "programs": out_programs,
            "anchor": load_anchor(profile_dir), **meta}


def device_status_block(fold: dict) -> Optional[dict]:
    """The heartbeat's ``device`` status.json block from a folded capture
    (obs/heartbeat.RunHeartbeat.observe_device): the last profiled window's
    phase fractions, decode share, attribution coverage, and — when the
    scope map carries the program's analytic flops (stamped by
    tools/device_profile.py) — the achieved-FLOPs rate. On the XLA:CPU
    fallback there is no honest hardware peak (PERF.md §8c), so
    ``achieved_flops_frac`` stays None unless a peak was supplied."""
    programs = (fold or {}).get("programs") or []
    if not programs:
        return None
    totals = {k: 0.0 for k in PHASES + RESIDUAL_ROWS}
    total_us = 0.0
    flops = 0.0
    for row in programs:
        for k, r in row.get("phases", {}).items():
            totals[k] = totals.get(k, 0.0) + float(r.get("time_us", 0.0))
        total_us += float(row.get("total_device_us", 0.0))
        if isinstance(row.get("flops_per_step"), (int, float)):
            flops += float(row["flops_per_step"])
    anchor = fold.get("anchor") or {}
    steps = anchor.get("steps_profiled")
    block = {
        "profiled_steps": steps,
        "total_device_us": round(total_us, 1),
        "phase_fracs": {k: (round(v / total_us, 4) if total_us else 0.0)
                        for k, v in totals.items()},
        "decode_share": (round(totals["draco_decode"] / total_us, 4)
                         if total_us else 0.0),
        # share of device time the scope map could attribute at all — a
        # plain --profile-dir run has no scope map and reads 0.0 here
        # (everything in the unattributed row), which is the honest state
        "attributed_frac": (round(1.0 - totals["unattributed"] / total_us, 4)
                            if total_us else 0.0),
        "achieved_flops_per_s": None,
        "achieved_flops_frac": None,
    }
    if flops and steps and total_us > 0:
        block["achieved_flops_per_s"] = flops * steps / (total_us / 1e6)
    return block
