"""Compile/retrace sentinel: the compiler-facing half of the telemetry spine.

The scan-chunk wins of PR 1–2 assume each registered program compiles ONCE
and then replays: a mid-run recompilation (a shape-polymorphic batch, a
schedule array that flips between committed and uncommitted, a carry whose
dtype drifts) silently re-pays the multi-second XLA compile on every
affected dispatch — the exact cost class the chunked loops exist to hide —
and no output-level test can see it (losses stay bitwise identical). This
module makes every compilation an observable event:

* **Ledger** — every XLA executable build becomes one JSON line in
  ``<dir>/compiles.jsonl`` (program label when the build happened inside a
  registered dispatch scope, lowering + backend-compile seconds, a
  steady-state flag) and a ``compile``-category lane event in the existing
  ``trace.json`` (obs/tracer.py), so Perfetto shows compiles nested inside
  the dispatch span that paid for them.
* **Steady-state guard** — each labelled program is allowed ``warmup``
  *compiling dispatch windows* (default 1: the first dispatch of each
  (program, chunk shape) traces and compiles, possibly paying several
  sub-builds for operand fills); any build after that is a steady-state
  recompile. ``guard="warn"`` (production default) emits a
  ``RetraceWarning``; ``guard="raise"`` (the test/CI mode) raises
  :class:`RetraceError` at the dispatch site, which makes "0 steady-state
  recompiles" an assertable property of the K ∈ {1, 4} equivalence suites
  at zero extra training runs.

Event sourcing: ``jax.monitoring`` (jax 0.4.x). The reliable per-build
event is ``jaxpr_to_mlir_module_duration`` — lowering runs on every
executable-cache miss, whereas ``backend_compile_duration`` is skipped when
the persistent XLA compile cache hits (tools enable it via
``runtime.enable_compile_cache``); the backend event, when it fires, attaches
the true compile seconds to the pending build row. jax's listener registry
has no per-listener removal, so ONE module-level dispatcher is installed
forever and fans out to the currently-active watches (a watch's lifetime is
``start()``/``stop()``, tied to its loop); the dispatcher also accumulates
process-wide totals (:func:`global_stats`) that jax-free consumers like
``tools/host_loop_overhead.py`` diff around a run to split compile from
steady-state wall-clock.

Attribution: jax events carry no program name, so the loops label their
dispatch windows (the ISSUE's wrap-the-entry-points fallback) —
``with watch.expect("train_many", key=k): ...`` pushes a thread-local label;
a build that fires inside the scope belongs to that program. Compilation is
synchronous on the dispatching thread, so the scope is exact. Builds outside
any scope (eval steps, checkpoint codecs, jnp utility fills) are recorded
with ``program: null`` and never guarded.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import warnings
from typing import Optional

from draco_tpu.obs.tracer import NULL_TRACER

# the jax.monitoring duration events this sentinel understands (jax 0.4.x)
LOWER_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"
BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"

GUARD_MODES = ("off", "warn", "raise")


class RetraceError(RuntimeError):
    """A registered program recompiled in steady state under guard="raise"."""


class RetraceWarning(UserWarning):
    """A registered program recompiled in steady state under guard="warn"."""


# ---------------------------------------------------------------------------
# module-level dispatcher (installed once; jax has no listener removal)
# ---------------------------------------------------------------------------

_LOCK = threading.RLock()
_ACTIVE: list = []  # watches currently receiving events
_GLOBAL = {"builds": 0, "backend_compiles": 0, "lower_s": 0.0,
           "compile_s": 0.0}
_INSTALLED = False


def _dispatch(event: str, duration: float, **_kw) -> None:
    if event == LOWER_EVENT:
        with _LOCK:
            _GLOBAL["builds"] += 1
            _GLOBAL["lower_s"] += duration
            active = list(_ACTIVE)
        for w in active:
            w._on_build(duration)
    elif event == BACKEND_EVENT:
        with _LOCK:
            _GLOBAL["backend_compiles"] += 1
            _GLOBAL["compile_s"] += duration
            active = list(_ACTIVE)
        for w in active:
            w._on_backend(duration)


def install() -> None:
    """Idempotently register the module dispatcher with jax.monitoring.
    Called by CompileWatch.start(); call directly (before the compiles you
    want counted) when only :func:`global_stats` is needed."""
    global _INSTALLED
    with _LOCK:
        if _INSTALLED:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_dispatch)
        _INSTALLED = True


def global_stats() -> dict:
    """Process-wide executable-build totals since :func:`install`:
    ``builds`` (lowerings = executable-cache misses), ``backend_compiles``
    (persistent-cache misses that paid real XLA compile), ``lower_s``,
    ``compile_s``. Diff two snapshots around a run to split its compile cost
    from steady-state wall-clock (tools/host_loop_overhead.py)."""
    with _LOCK:
        return dict(_GLOBAL)


# ---------------------------------------------------------------------------
# the per-run watch
# ---------------------------------------------------------------------------

class CompileWatch:
    """One run's compile ledger + steady-state retrace guard.

    Lifecycle: ``start()`` activates event delivery, ``stop()`` detaches and
    closes the ledger (loops call them from __init__/close). An unstarted
    watch is inert — safe as a default telemetry object.

    ``expect(name, key=...)`` labels the calling thread's dispatch window;
    ``key`` distinguishes legitimate shape variants of one program (the
    chunked loops pass the chunk length k, so a remainder chunk's first
    build is warmup for *its* shape, not a retrace of the main one).

    Warmup is counted in dispatch *windows*, not raw builds: a single cold
    dispatch may pay several executable builds (the program itself plus
    utility fills for its operands), and that is one warmup unit. A build
    firing after ``warmup`` windows of the same label have already paid
    builds is a steady-state recompile.
    """

    def __init__(self, ledger_dir: Optional[str] = None, tracer=NULL_TRACER,
                 warmup: int = 1, guard: str = "warn"):
        if guard not in GUARD_MODES:
            raise ValueError(f"guard must be one of {GUARD_MODES}, "
                             f"got {guard!r}")
        self.path = (os.path.join(ledger_dir, "compiles.jsonl")
                     if ledger_dir else None)
        self._tracer = tracer
        self.warmup = max(int(warmup), 0)
        self.guard = guard
        self.builds = 0  # executable builds seen while active
        self.backend_compiles = 0
        self.lower_s = 0.0
        self.compile_s = 0.0
        self.steady_recompiles = 0
        self.builds_by_program: dict = {}  # raw builds per label
        self._compiled_windows: dict = {}  # label -> windows that built
        self._tls = threading.local()
        self._fh = None
        self._lock = threading.Lock()

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "CompileWatch":
        install()
        with _LOCK:
            if self not in _ACTIVE:
                _ACTIVE.append(self)
        return self

    def stop(self) -> None:
        with _LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        self._flush_pending()
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "CompileWatch":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # ---- labelling -------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    @contextlib.contextmanager
    def expect(self, name: str, key=None):
        """Label this thread's dispatch window: builds firing inside belong
        to ``name`` (``key`` appended for shape variants, e.g. chunk k)."""
        label = f"{name}[{key}]" if key is not None else name
        stack = self._stack()
        entry = [label, False]  # fired flag set by _on_build
        stack.append(entry)
        try:
            yield self
        finally:
            stack.pop()
            # the window is over: a build still pending (persistent-cache
            # hit, so no backend event arrived) belongs to this label —
            # finalize before the label goes away
            self._flush_pending()
            if entry[1]:
                with self._lock:
                    self._compiled_windows[label] = (
                        self._compiled_windows.get(label, 0) + 1)

    # ---- event sinks (called by the module dispatcher) -------------------
    def _on_build(self, lower_s: float) -> None:
        self._flush_pending()  # previous build on this thread, if any
        stack = self._stack()
        entry = stack[-1] if stack else None
        label = entry[0] if entry is not None else None
        with self._lock:
            self.builds += 1
            self.lower_s += lower_s
            n = self.builds_by_program.get(label, 0) + 1
            if label is not None:
                self.builds_by_program[label] = n
            # steady iff `warmup` prior dispatch windows of this label have
            # already paid builds — this window's own earlier builds (a cold
            # dispatch compiles the program plus operand fills) don't count
            steady = (label is not None
                      and self._compiled_windows.get(label, 0) >= self.warmup)
        if entry is not None:
            entry[1] = True
        row = {
            "time": time.time(),
            "program": label,
            "n_for_program": n if label is not None else None,
            "lower_s": round(lower_s, 6),
            "steady_recompile": steady,
        }
        if not steady:
            self._tls.pending = row  # backend event may still attach cost
            return
        with self._lock:
            self.steady_recompiles += 1
        if self.guard == "raise":
            # raising here aborts the compilation, so no backend event will
            # ever attach — emit the ledger row now, then fail the dispatch
            self._emit(row)
            raise RetraceError(self._retrace_msg(label, n))
        # warn/off: compilation proceeds; keep the row pending so the
        # backend event attaches its compile seconds to THIS row instead of
        # orphaning them on a program-less duplicate
        self._tls.pending = row
        if self.guard == "warn":
            warnings.warn(self._retrace_msg(label, n), RetraceWarning,
                          stacklevel=2)

    def _retrace_msg(self, label, n) -> str:
        return (f"steady-state recompilation of registered program "
                f"{label!r} (build #{n}, after "
                f"{self._compiled_windows.get(label, 0)} compiled dispatch "
                f"windows, warmup={self.warmup}): the program "
                f"re-paid trace+lower+compile mid-run — a shape/dtype/"
                f"structure change in its arguments is defeating the "
                f"compile-once contract (obs/compile_watch.py, PERF.md §8)")

    def _on_backend(self, compile_s: float) -> None:
        with self._lock:
            self.backend_compiles += 1
            self.compile_s += compile_s
        row = getattr(self._tls, "pending", None)
        if row is not None:
            row["compile_s"] = round(compile_s, 6)
            self._tls.pending = None
            self._emit(row)
        else:  # backend compile with no observed lowering on this thread
            self._emit({"time": time.time(), "program": None,
                        "compile_s": round(compile_s, 6)})

    def _flush_pending(self) -> None:
        row = getattr(self._tls, "pending", None)
        if row is not None:
            self._tls.pending = None
            self._emit(row)

    # ---- emission --------------------------------------------------------
    def _emit(self, row: dict) -> None:
        dur = row.get("lower_s", 0.0) + row.get("compile_s", 0.0)
        self._tracer.complete("compile", dur, cat="compile",
                              program=row.get("program"),
                              steady_recompile=row.get("steady_recompile",
                                                       False))
        if self.path is None:
            return
        with self._lock:
            if self._fh is None:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write(json.dumps(row) + "\n")
            self._fh.flush()  # compiles are rare; keep the ledger live

    # ---- surface ---------------------------------------------------------
    def snapshot(self) -> dict:
        """The heartbeat extra both production loops merge into status.json:
        how many executable builds this run has paid, the wall-clock they
        cost, and whether any happened in steady state (must stay 0)."""
        with self._lock:
            return {
                "compiles": self.builds,
                "compile_s": round(self.lower_s + self.compile_s, 3),
                "steady_recompiles": self.steady_recompiles,
            }


def make_compile_watch(cfg, tracer=NULL_TRACER, is_main: bool = True
                       ) -> CompileWatch:
    """The one construction rule both production loops share: ledger next to
    the trace (cfg.trace_dir) when tracing, else next to metrics.jsonl
    (cfg.train_dir); guard/warmup from config; only the metrics-emitting
    process writes a ledger (counters stay live everywhere)."""
    ledger_dir = (cfg.trace_dir or cfg.train_dir or None) if is_main else None
    watch = CompileWatch(ledger_dir=ledger_dir, tracer=tracer,
                         warmup=cfg.compile_warmup, guard=cfg.compile_guard)
    return watch.start()
