"""Host-side span tracer emitting Chrome trace events (Perfetto-loadable).

The chunked training regimes (trainer._run_chunked / token_loop._run_chunked)
deliberately removed every per-step host sync, so the per-step Segments
timers see nothing: all host wall-clock now happens in a handful of
per-chunk phases — gather, upload, dispatch, sync, flush, eval, ckpt — plus
the prefetcher worker threads racing the device. This tracer makes those
phases a loadable artifact: ``trace_dir/trace.json`` in the Chrome trace
event format (the same format ``chrome://tracing`` and https://ui.perfetto.dev
open directly), with one lane per thread and counter tracks for prefetch
queue depth.

Design constraints (the PR 1–2 invariant):

* **No device fetches.** Spans time host phases with ``time.perf_counter``
  only; nothing here ever touches a jax array. Device-side phase attribution
  is jax.profiler's job (``--profile-dir``) — the step programs carry
  ``jax.named_scope`` annotations so both views share Draco's phase names.
* **Zero overhead when disabled.** The disabled path is ``NULL_TRACER``, a
  module singleton whose ``span()`` returns one shared no-op context
  manager — no allocation, no clock read, no branch beyond the method call.
  Loops hold a tracer unconditionally and never test ``enabled``.
* **Thread-safe.** Prefetcher worker threads emit spans from their own
  threads; events append under a lock and carry the emitting thread's id,
  so each worker gets its own lane (``name_thread`` labels it).

Event kinds used (Chrome trace event format spec):

  ph="X"  complete event — one span with ``ts``/``dur`` (microseconds)
  ph="C"  counter event — e.g. prefetch queue depth over time
  ph="M"  metadata — process/thread names for the lane headers
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class _NullSpan:
    """The shared no-op context manager the disabled tracer hands out."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every call is a no-op, ``span`` returns one shared
    context manager (no allocation, no clock read)."""

    __slots__ = ()
    enabled = False
    last_span = None  # no spans recorded, ever

    def span(self, name: str, **args) -> _NullSpan:
        return _NULL_SPAN

    def complete(self, name: str, dur_s: float, cat: str = "host",
                 **args) -> None:
        pass

    def counter(self, name: str, value) -> None:
        pass

    def instant(self, name: str, **args) -> None:
        pass

    def name_thread(self, label: str) -> None:
        pass

    def now_us(self):
        """No tracer clock — anchor consumers treat None as "no shared
        timebase" (obs/profiling.profiler_window)."""
        return None

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NULL_TRACER = NullTracer()


class _Span:
    """One live span: records ts on __enter__, appends the complete event
    on __exit__ (so nesting falls out of wall-clock containment)."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        tr = self._tracer
        t1 = time.perf_counter()
        ev = {
            "name": self._name,
            "ph": "X",
            "ts": round((self._t0 - tr._t0) * 1e6, 3),
            "dur": round((t1 - self._t0) * 1e6, 3),
            "pid": tr._pid,
            "tid": threading.get_ident(),
            "cat": "host",
        }
        if self._args:
            ev["args"] = self._args
        tr._append(ev)
        return False


class SpanTracer:
    """Collects Chrome trace events in memory; ``flush()`` rewrites the
    JSON file atomically (a crash keeps the last flushed window),
    ``close()`` flushes and disarms.

    The buffer is BOUNDED: past ``max_events`` the oldest non-metadata
    events are dropped (metadata lane labels are kept, and the written
    payload carries a top-level ``droppedEvents`` count), so an
    arbitrarily long chip job holds a sliding window of its newest spans
    at O(max_events) memory and O(max_events) bytes per flush — "where is
    the wall-clock going NOW", never an unbounded rewrite."""

    enabled = True

    def __init__(self, path: str, process_name: str = "draco_tpu host",
                 max_events: int = 100_000):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._pid = os.getpid()
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._max_events = max(int(max_events), 16)
        self._dropped = 0
        self._events: list = [
            {"name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
             "args": {"name": process_name}},
        ]
        self.name_thread("main")

    def _append(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self._max_events:
                # drop the oldest half of the non-metadata events; lane
                # labels (ph=M) survive so the remaining window renders
                meta = [e for e in self._events if e.get("ph") == "M"]
                rest = [e for e in self._events if e.get("ph") != "M"]
                keep = len(rest) // 2
                self._dropped += len(rest) - keep
                self._events = meta + rest[-keep:]

    # ---- emission --------------------------------------------------------
    def span(self, name: str, **args) -> _Span:
        """Context manager timing one host phase on the calling thread."""
        return _Span(self, name, args or None)

    def complete(self, name: str, dur_s: float, cat: str = "host",
                 **args) -> None:
        """Append an already-measured span ending now (duration in seconds)
        on the calling thread's lane — how externally-timed phases (e.g. XLA
        compiles observed via jax.monitoring, obs/compile_watch.py) land in
        the trace without a context manager around them."""
        t1 = time.perf_counter()
        ev = {
            "name": name,
            "ph": "X",
            "ts": round((t1 - self._t0 - dur_s) * 1e6, 3),
            "dur": round(dur_s * 1e6, 3),
            "pid": self._pid,
            "tid": threading.get_ident(),
            "cat": cat,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name: str, value) -> None:
        """One sample of a counter track (e.g. prefetch queue depth)."""
        ev = {"name": name, "ph": "C",
              "ts": round((time.perf_counter() - self._t0) * 1e6, 3),
              "pid": self._pid, "args": {name: value}}
        self._append(ev)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker on the calling thread's lane."""
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": round((time.perf_counter() - self._t0) * 1e6, 3),
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._append(ev)

    def name_thread(self, label: str) -> None:
        """Label the calling thread's lane (prefetcher workers call this
        once so their spans render under a named track)."""
        ev = {"name": "thread_name", "ph": "M", "pid": self._pid,
              "tid": threading.get_ident(), "args": {"name": label}}
        self._append(ev)

    def now_us(self) -> float:
        """Current tracer-relative timestamp (µs) — the shared clock the
        profiler window's anchor stamps so device captures can be shifted
        onto the host lanes (obs/profiling.py + obs/device_attr.py)."""
        return round((time.perf_counter() - self._t0) * 1e6, 3)

    @property
    def last_span(self) -> Optional[str]:
        """Name of the newest completed span — the 'what was happening
        last' breadcrumb error paths attach (e.g. PrefetchStallError)."""
        with self._lock:
            for ev in reversed(self._events):
                if ev.get("ph") == "X":
                    return ev.get("name")
        return None

    # ---- persistence -----------------------------------------------------
    def flush(self) -> None:
        """Rewrite ``path`` with everything collected so far (atomic:
        tmp + rename, so a monitor never reads a torn file)."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        payload = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            payload["droppedEvents"] = dropped
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, self.path)

    def close(self) -> None:
        self.flush()


def make_tracer(trace_dir: Optional[str], is_main: bool = True):
    """The one construction rule both production loops share: a real tracer
    iff a trace_dir is configured on the metrics-emitting process, else the
    shared no-op singleton (callers never branch)."""
    if trace_dir and is_main:
        return SpanTracer(os.path.join(trace_dir, "trace.json"))
    return NULL_TRACER
