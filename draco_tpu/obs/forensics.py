"""Per-worker Byzantine forensics: packed accusation masks + the host ledger.

DRACO's value proposition is *identifying and removing* adversarial workers
(PAPER.md), yet until this module the telemetry folded the per-worker
``flagged`` accusation vectors both codes already compute in-graph
(coding/cyclic._locate_v, coding/repetition.majority_vote) down to scalar
detection counts. This module keeps the attribution:

In-graph half — :func:`pack_mask_columns` packs each per-step (n,) bool mask
(the accusation set, the present set, and the seeded-adversary ground truth)
into ``ceil(n/32)`` uint32 words bit-cast to float32, so they ride the
existing (K, m) float32 metric block with ZERO extra device fetches:

  * n <= 32  -> one packed column per mask kind
  * n <= 64  -> two columns per kind (word 0 = workers 0..31, word 1 = 32..63)
  * n  > 64  -> a named error (the schema stays bounded; grow MAX_WORKERS
                together with a third column family when a real mesh needs it)

Host half — the float payload is bit-identical to the uint32 word all the way
to the host fetch (bitcast + pure data movement; XLA never runs arithmetic on
it), but a Python ``float()`` / JSON round trip is NOT bit-safe: words whose
bit pattern is a float32 NaN (any mask with workers 23..30 all accused and
worker 31 variable) would collapse to a payload-free ``NaN`` in
metrics.jsonl. :func:`record_value` therefore re-views mask columns as
integers at record-materialization time (utils/metrics.DeferredMetricWriter
and both eager loops route every record value through it), so the JSONL
carries exact integer words and :func:`unpack_bits` is pure int bit-twiddling
— usable from jax-free tools (tools/forensics_report.py).

:class:`AccusationLedger` folds the per-step masks (at flush boundaries, via
the existing DeferredMetricWriter -> RunHeartbeat observer hook — no new
fetch, no new callback) into per-worker counters (accused / present /
true-positive / false-positive vs the seeded schedule), an
exponentially-weighted trust score, and attack **episodes** — maximal runs of
consecutive accusations per worker, so "worker 3 was adversarial for steps
120..400" is a first-class object. Absence is an erasure, never evidence: an
absent worker is neither accused nor exonerated, so a straggler cannot open,
extend toward closure, or close an episode.

This module is importable WITHOUT jax (the pack side imports it lazily), the
same discipline as the rest of draco_tpu/obs — tools fold committed
artifacts on machines with no accelerator stack.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

MASK_WORD_BITS = 32
MAX_WORKERS = 64

# column-name stem per packed mask kind; a step's forensics columns are
# f"{MASK_PREFIX}{kind}{word}" for word in range(num_mask_words(n))
MASK_PREFIX = "wmask_"
MASK_KINDS = ("accused", "present", "adv")

# EW trust-score step: trust <- (1-alpha)*trust + alpha*(not accused), only
# on steps the worker is present. 0.2 makes ~10 consecutive accusations pull
# a fresh worker below 0.2 and ~10 clean steps pull it back above 0.85 —
# fast enough to rank suspects inside one flush window, slow enough that a
# single false accusation cannot tank a worker
TRUST_ALPHA = 0.2


def num_mask_words(num_workers: int) -> int:
    """ceil(n/32) packed words per mask kind; bounded by MAX_WORKERS."""
    if num_workers < 1:
        raise ValueError(f"num_workers must be >= 1, got {num_workers}")
    if num_workers > MAX_WORKERS:
        raise ValueError(
            f"forensics mask columns support num_workers <= {MAX_WORKERS} "
            f"(got {num_workers}); grow MAX_WORKERS and the column family "
            f"together (PERF.md §10)"
        )
    return (num_workers + MASK_WORD_BITS - 1) // MASK_WORD_BITS


def mask_metric_names(num_workers: int) -> tuple:
    """Column order of the packed forensics block for an n-worker config —
    the single schema source for step bodies and the host flush (same
    contract as parallel/common.token_metric_names)."""
    words = num_mask_words(num_workers)
    return tuple(f"{MASK_PREFIX}{kind}{w}"
                 for kind in MASK_KINDS for w in range(words))


def is_mask_column(name: str) -> bool:
    """True for packed-bitmask metric columns (f32-carried uint32 words) —
    every record-materialization site must route these through
    :func:`record_value` instead of ``float()``."""
    return name.startswith(MASK_PREFIX)


# --------------------------------------------------------------------------
# in-graph packing (lazy jax import: the module stays jax-free for tools)
# --------------------------------------------------------------------------


def pack_bits(mask):
    """(n,) bool -> (num_mask_words(n),) float32 carrying the uint32 words.

    Bit j of word w is worker ``32*w + j``. The float32 is a pure bitcast of
    the uint32 word: no arithmetic ever touches it downstream (stack, scan
    stacking, device->host copy are data movement), so the bits survive to
    the host fetch exactly. In-graph only — the host direction is
    :func:`unpack_bits` on the integer view.

    Deliberately formulated as masked-weight sums over the ORIGINAL (n,)
    axis — no pad-concat, no reshape. The obvious
    ``concat(mask, zeros) -> reshape(words, 32) -> dot(2**j)`` packs a
    mesh-SHARDED mask off by one bit position under the GSPMD partitioner
    (observed on the folded w×tp CPU mesh: worker 3's accusation landed on
    bit 4; the fetched mask itself was correct, only the packed word
    shifted — the pad-concat's per-shard offsets are what go wrong).
    Elementwise ops + a full reduction partition correctly, and the
    equivalence suites + the tp chaos cell pin it per mesh.
    """
    import jax
    import jax.numpy as jnp

    n = int(mask.shape[0])
    words = num_mask_words(n)
    bits = jnp.asarray(mask, jnp.uint32)
    j = jnp.arange(n, dtype=jnp.uint32)
    packed = []
    for w in range(words):
        lo = jnp.uint32(w * MASK_WORD_BITS)
        in_word = (j >= lo) & (j < lo + MASK_WORD_BITS)
        weights = jnp.where(in_word,
                            jnp.left_shift(jnp.uint32(1), j - lo),
                            jnp.uint32(0))
        packed.append(jnp.sum(bits * weights, dtype=jnp.uint32))
    return jax.lax.bitcast_convert_type(jnp.stack(packed), jnp.float32)


def pack_mask_columns(accused, present, adv_mask) -> dict:
    """The per-step packed forensics columns (mask_metric_names order).

    ``accused``: the step's (n,) accusation set — a present-gated union of
    the code's own flag set and the forensic-only signals (loud rows,
    non-finite ingest rows); ``present``: (n,) bool or None (all present);
    ``adv_mask``: the seeded-adversary schedule row, the in-graph ground
    truth. An absent worker is never an accused worker: ``accused`` is
    re-gated by ``present`` here so no call site can forget.
    """
    import jax.numpy as jnp

    accused = jnp.asarray(accused, bool)
    n = int(accused.shape[0])
    pres = (jnp.ones((n,), bool) if present is None
            else jnp.asarray(present, bool))
    cols = {}
    for kind, mask in (("accused", accused & pres), ("present", pres),
                       ("adv", jnp.asarray(adv_mask, bool))):
        packed = pack_bits(mask)
        for w in range(int(packed.shape[0])):
            cols[f"{MASK_PREFIX}{kind}{w}"] = packed[w]
    return cols


def nonfinite_rows(grads):
    """(n, ...) per-worker gradient stack -> (n,) bool: rows containing any
    non-finite value. The ingest-health check a real aggregator runs on
    every received row, evaluated on the RAW per-worker gradients after
    fault injection and BEFORE encode — under ``redundancy="shared"`` the
    algebraic encode smears a NaN across every codeword (0·NaN = NaN in the
    masked matmul), so the wire rows cannot attribute a non-finite fault but
    the ingest rows can (row k <-> worker k in shared mode)."""
    import jax.numpy as jnp

    g = jnp.asarray(grads)
    return ~jnp.all(jnp.isfinite(g).reshape(g.shape[0], -1), axis=1)


# --------------------------------------------------------------------------
# host-side materialization + unpack (numpy/stdlib only)
# --------------------------------------------------------------------------


def record_value(name: str, value):
    """Materialize one metric value for a host record: mask columns become
    the exact integer word (the f32 payload re-viewed as uint32 — safe
    through JSON, where a float NaN would drop its payload), everything else
    the usual float."""
    if not is_mask_column(name):
        return float(value)
    import numpy as np

    arr = np.asarray(value)
    if arr.dtype.kind in "ui":  # already an integer word (re-folded record)
        return int(arr)
    return int(arr.astype(np.float32, copy=False).reshape(()).view(np.uint32))


def unpack_bits(words: Sequence[int], num_workers: int) -> Tuple[bool, ...]:
    """Integer words -> (num_workers,) bools. Pure int bit-twiddling (no
    numpy): usable from jax-free artifact tools."""
    out = []
    for i in range(num_workers):
        w, j = divmod(i, MASK_WORD_BITS)
        word = int(words[w]) if w < len(words) else 0
        out.append(bool((word >> j) & 1))
    return tuple(out)


def record_masks(record: dict, num_workers: int) -> Optional[Dict[str, tuple]]:
    """kind -> (n,) bool tuples from one materialized record, or None when
    the record carries no forensics columns (baseline routes, eval records,
    mixed-route train dirs)."""
    if f"{MASK_PREFIX}accused0" not in record:
        return None
    words = num_mask_words(num_workers)
    out = {}
    for kind in MASK_KINDS:
        vals = [int(record.get(f"{MASK_PREFIX}{kind}{w}", 0))
                for w in range(words)]
        out[kind] = unpack_bits(vals, num_workers)
    return out


# --------------------------------------------------------------------------
# AccusationLedger — the host fold
# --------------------------------------------------------------------------


class AccusationLedger:
    """Folds per-step packed masks into per-worker forensics state.

    Fed one materialized record at a time (:meth:`observe`) — wired through
    the existing DeferredMetricWriter observer / RunHeartbeat hook, so it
    sees exactly the records the flush materializes anyway (every step in
    the chunked regime, the logged steps in the eager LM regime). Records
    without forensics columns are ignored, so mixed-route train dirs cannot
    poison the counters.
    """

    def __init__(self, num_workers: int, trust_alpha: float = TRUST_ALPHA):
        self.n = int(num_workers)
        num_mask_words(self.n)  # validate the bound early
        self.alpha = float(trust_alpha)
        self.steps = 0
        self.accused = [0] * self.n
        self.present = [0] * self.n
        self.tp = [0] * self.n  # accused ∧ adversarial (∧ present)
        self.fp = [0] * self.n  # accused ∧ honest (∧ present)
        self.fn = [0] * self.n  # adversarial ∧ present ∧ not accused
        self.trust = [1.0] * self.n
        self.episodes: List[dict] = []  # closed, in closure order
        self._open: Dict[int, dict] = {}  # worker -> open episode

    # ---- fold ------------------------------------------------------------
    def observe(self, record: dict, masks: Optional[dict] = None) -> bool:
        """Fold one record; returns True iff it carried forensics columns.
        ``masks``: the record's already-unpacked mask dict, when the caller
        holds one (the incident engine's per-record cache) — skips the
        redundant bit-unpack on the hot observer path."""
        if masks is None:
            masks = record_masks(record, self.n)
        if masks is None:
            return False
        step = int(record.get("step", self.steps + 1))
        accused, present, adv = (masks["accused"], masks["present"],
                                 masks["adv"])
        self.steps += 1
        for w in range(self.n):
            if not present[w]:
                # erasure: no vote either way — trust and episodes hold
                continue
            self.present[w] += 1
            if accused[w]:
                self.accused[w] += 1
                if adv[w]:
                    self.tp[w] += 1
                else:
                    self.fp[w] += 1
                ep = self._open.get(w)
                if ep is None:
                    self._open[w] = {"worker": w, "start": step, "end": step,
                                     "steps": 1}
                else:
                    ep["end"] = step
                    ep["steps"] += 1
            else:
                if adv[w]:
                    self.fn[w] += 1
                ep = self._open.pop(w, None)
                if ep is not None:
                    self.episodes.append(ep)
            self.trust[w] = ((1.0 - self.alpha) * self.trust[w]
                             + self.alpha * (0.0 if accused[w] else 1.0))
        return True

    # ---- views -----------------------------------------------------------
    @property
    def active(self) -> bool:
        return self.steps > 0

    def open_episodes(self) -> List[dict]:
        """Episodes still running at the last observed step (sorted by
        worker), marked ``open``."""
        return [dict(self._open[w], open=True) for w in sorted(self._open)]

    def all_episodes(self) -> List[dict]:
        """Closed episodes (closure order) + the still-open tails."""
        return [dict(e, open=False) for e in self.episodes] \
            + self.open_episodes()

    def worker_rows(self) -> List[dict]:
        """One forensics row per worker: counters, detection precision /
        recall vs the seeded schedule (1.0 on the empty-denominator healthy
        states), trust, episode count."""
        rows = []
        n_eps = [0] * self.n
        for ep in self.all_episodes():
            n_eps[ep["worker"]] += 1
        for w in range(self.n):
            adv_seen = self.tp[w] + self.fn[w]
            rows.append({
                "worker": w,
                "present": self.present[w],
                "accused": self.accused[w],
                "tp": self.tp[w],
                "fp": self.fp[w],
                "fn": self.fn[w],
                "precision": (self.tp[w] / self.accused[w]
                              if self.accused[w] else 1.0),
                "recall": (self.tp[w] / adv_seen) if adv_seen else 1.0,
                "trust": round(self.trust[w], 4),
                "episodes": n_eps[w],
            })
        return rows

    def forgive(self, worker: int, trust: float = 0.75) -> None:
        """Re-admission parole (control/autopilot.py): reset the worker's
        EW trust to ``trust`` so a readmitted worker is judged on fresh
        evidence instead of its pre-quarantine collapse — without this the
        trust detector re-fires on the first present step and the
        quarantine/readmit pair would flap forever. Accusation counters
        are NOT reset: the history stays in the ledger."""
        self.trust[worker] = float(trust)

    def summary(self, top: int = 3) -> dict:
        """The compact ``forensics`` block for status.json: top suspects by
        accusation count (ties broken toward lower trust), the per-worker
        trust vector, and the episode counts."""
        order = sorted(range(self.n),
                       key=lambda w: (-self.accused[w], self.trust[w], w))
        suspects = [{"worker": w, "accused": self.accused[w],
                     "trust": round(self.trust[w], 4)}
                    for w in order[:top] if self.accused[w] > 0]
        return {
            "num_workers": self.n,
            "steps": self.steps,
            "top_suspects": suspects,
            "trust": [round(t, 4) for t in self.trust],
            "accused_total": sum(self.accused),
            "open_episodes": len(self._open),
            "episodes_total": len(self.episodes) + len(self._open),
        }

    def to_dict(self) -> dict:
        """The full fold (tools/forensics_report.py's forensics.json body)."""
        return {
            "num_workers": self.n,
            "steps": self.steps,
            "workers": self.worker_rows(),
            "episodes": self.all_episodes(),
            "summary": self.summary(),
        }
