"""Coded data parallelism × sequence parallelism: the 2-D-mesh training step.

Composition (SURVEY.md §5.7): ring attention makes each logical worker's
sequence span the ``sp`` axis; the per-shard gradients psum over ``sp`` into
exact whole per-worker gradients; Draco's coding/aggregation then acts on the
(n, d) gradient matrix over ``w`` exactly as in the CNN path
(draco_tpu/training/step.py) — Byzantine resilience is oblivious to how each
worker's compute was sharded.

Supported approaches here: ``baseline`` (mean / geo-median / krum) and
``cyclic`` with either redundancy mode — ``simulate`` (reference-parity
2s+1-lane redundant compute per worker, cyclic_worker.py:122-146) or
``shared`` (each batch gradient computed once, rows formed algebraically).
(maj_vote's bitwise-equality vote is specified over identical lanes; under
SP a group member is a whole mesh row, which the batching layer does not
replicate — use the CNN path for it.)
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from draco_tpu import optim, rng as drng
from draco_tpu.coding import cyclic as cyclic_mod
from draco_tpu.runtime import shard_map
from draco_tpu.config import TrainConfig
from draco_tpu.models.transformer import TransformerLM
from draco_tpu.parallel.a2a_attention import a2a_attention
from draco_tpu.parallel.common import (
    TOKEN_METRIC_NAMES,
    aggregate_flat_grads,
    build_code_from_cfg,
    decode_health_metrics,
    finish_flat_step,
    make_token_train_many,
    masked_loss_metric,
    token_metric_names,
)
from draco_tpu.parallel.mesh import SEQ_AXIS
from draco_tpu.parallel.partition import (
    REPLICATED, SP_STEP_RULES, WORKER_ROWS, WORKER_ROWS3, sharding,
)
from draco_tpu.parallel.ring_attention import ring_attention
from draco_tpu.runtime import WORKER_AXIS
from draco_tpu.training.step import TrainState, _flatten_tree, _make_unravel


class SPTrainSetup(NamedTuple):
    model: TransformerLM
    state: TrainState
    # (state, tokens (n,B,T), adv_mask (n,)) -> (state, metrics)
    train_step: any
    eval_step: any  # (params, tokens) -> loss (no donation, no update)
    code: Optional[cyclic_mod.CyclicCode]
    unravel: any
    dim: int
    # K fused LM steps in ONE device program (parallel/common.py):
    # (state, toks (K,n,B,T) | steps (K,), masks (K,n), presents (K,n)|None)
    #   -> (state, metrics (K, len(metric_names)) float32)
    train_token_many: any = None
    metric_names: tuple = TOKEN_METRIC_NAMES


def synthetic_text(seed: int, step: int, n: int, batch: int,
                   seq_len: int, vocab: int):
    """Deterministic learnable token stream: ramps t_{i+1} = t_i + stride
    with
    per-sequence stride ∈ {1, 2}. Same (seed, step) ⇒ same batch everywhere."""
    r = np.random.RandomState((seed * 1_000_003 + step) % (2**31 - 1))
    start = r.randint(0, vocab, size=(n, batch, 1))
    stride = r.randint(1, 3, size=(n, batch, 1))
    idx = np.arange(seq_len)[None, None, :]
    return ((start + stride * idx) % vocab).astype(np.int32)


def synthetic_text_in_graph(seed: int, step, n: int, batch: int, seq_len: int,
                            vocab: int):
    """In-graph counterpart of :func:`synthetic_text` (cfg.token_gen ==
    "device"): the same ramp construction (start + stride·i mod vocab,
    stride ∈ {1, 2}), generated INSIDE the jitted program from the scalar
    (seed, step) — ``step`` may be traced, so a scanned K-step driver feeds
    it per-iteration from the (K,) step vector and the host never assembles
    or uploads a token block at all (the discipline of
    rng.random_projection_factors_in_graph). Values come from the jax PRNG,
    not numpy's MT19937, so the two streams differ draw-by-draw while
    sharing distribution and the property that matters: every participant
    derives the identical batch from (seed, step)."""
    key = jax.random.fold_in(jax.random.key(seed), step)
    k_start, k_stride = jax.random.split(key)
    start = jax.random.randint(k_start, (n, batch, 1), 0, vocab)
    stride = jax.random.randint(k_stride, (n, batch, 1), 1, 3)
    idx = jnp.arange(seq_len)[None, None, :]
    return ((start + stride * idx) % vocab).astype(jnp.int32)


def token_fn_from_cfg(cfg: TrainConfig):
    """The in-graph per-step token generator for cfg.token_gen == "device"
    (None for the default host-generated stream) — shared by every LM route
    builder so the scanned drivers can't disagree on the stream."""
    if cfg.token_gen != "device":
        return None
    return lambda step: synthetic_text_in_graph(
        cfg.seed, step, cfg.num_workers, cfg.batch_size, cfg.seq_len,
        cfg.vocab,
    )


def build_sp_train_setup(cfg: TrainConfig, mesh) -> SPTrainSetup:
    """mesh must have axes (w, sp) — see make_mesh_2d."""
    cfg.validate()
    if cfg.approach not in ("baseline", "cyclic", "approx"):
        raise ValueError(
            f"SP path supports baseline|cyclic|approx, got {cfg.approach}")
    n = cfg.num_workers
    sp = mesh.shape[SEQ_AXIS]
    # logical workers fold onto the available w-axis devices in equal
    # lane blocks (same discipline as tp_step / runtime.make_mesh): a
    # single chip can still run the n-lane coded step, vmapped
    if n % mesh.shape[WORKER_AXIS]:
        raise ValueError(
            f"num_workers {n} must be a multiple of the mesh's w axis "
            f"({mesh.shape[WORKER_AXIS]})"
        )
    if cfg.seq_len % sp:
        raise ValueError(f"seq_len {cfg.seq_len} not divisible by sp={sp}")
    t_local = cfg.seq_len // sp

    from draco_tpu.ops.flash_attention import attn_impl_fn

    flash = attn_impl_fn(cfg)
    if flash is not None and sp == 1:
        # single-shard long-context path: the Pallas blockwise kernel
        # (per-device inside shard_map — no GSPMD partitioning involved)
        attn = flash
    elif flash is not None and cfg.sp_attn == "ring":
        # ring + flash: the kernel attends each visiting K/V block
        # (causal self hop, unmasked past hops, future hops skipped) and
        # per-hop outputs merge by differentiable lse weights
        from draco_tpu.parallel.ring_attention import ring_flash_attention

        attn = functools.partial(ring_flash_attention, axis_name=SEQ_AXIS)
    elif flash is not None:
        # Ulysses + flash: head-scatter a2a, then the flash kernel on each
        # device's full-sequence head group
        attn = functools.partial(a2a_attention, axis_name=SEQ_AXIS,
                                 inner=flash)
    else:
        attn_impl = ring_attention if cfg.sp_attn == "ring" else a2a_attention
        attn = functools.partial(
            attn_impl, axis_name=SEQ_AXIS if sp > 1 else None
        )
    cdtype = jnp.dtype(cfg.compute_dtype)
    model = TransformerLM(
        vocab=cfg.vocab, dim=cfg.model_dim, heads=cfg.model_heads,
        layers=cfg.model_layers, attn_fn=attn, experts=cfg.moe_experts,
        dtype=cdtype, remat=cfg.remat, scan_layers=cfg.scan_layers,
    )
    # init single-shard (dense attention) — parameter shapes are identical
    init_model = TransformerLM(
        vocab=cfg.vocab, dim=cfg.model_dim, heads=cfg.model_heads,
        layers=cfg.model_layers, attn_fn=None, experts=cfg.moe_experts,
        dtype=cdtype, scan_layers=cfg.scan_layers,
    )
    root = jax.random.key(cfg.seed)
    init_toks = jnp.zeros((1, min(cfg.seq_len, 8)), jnp.int32)
    params = init_model.init({"params": root}, init_toks, train=True)["params"]

    opt = optim.build_optimizer_from_cfg(cfg)
    unravel, dim, leaf_offsets = _make_unravel(params)

    repl = sharding(mesh, REPLICATED)
    shard_w = sharding(mesh, WORKER_ROWS)
    state = TrainState(
        params=jax.device_put(params, repl),
        opt_state=jax.device_put(opt.init(params), repl),
        batch_stats=None,
        step=jax.device_put(jnp.asarray(1, jnp.int32), repl),
    )

    # ---- per-device worker-gradient computation (manual SPMD) -------------
    def _shard_objective(params, toks, train: bool):
        """This shard's masked next-token CE contribution (scalar); the
        psum over sp equals the single-shard mean CE: each shard also
        predicts its successor shard's first token (fetched with one
        ppermute hop), the global last position is masked, and per-shard
        sums are normalised by the global (T−1)·B — so sp is
        trajectory-invariant (asserted in tests/test_parallel_sp.py)."""
        idx = lax.axis_index(SEQ_AXIS)
        off = idx * t_local
        # shard i receives shard (i+1)'s first token (garbage on the last
        # shard, masked below)
        nxt_first = lax.ppermute(
            toks[:, :1], SEQ_AXIS, [(j, (j - 1) % sp) for j in range(sp)]
        )
        # (B, t_local)
        targets = jnp.concatenate([toks[:, 1:], nxt_first], axis=1)
        pos_valid = jnp.where(
            idx == sp - 1,
            (jnp.arange(t_local) < t_local - 1).astype(jnp.float32),
            jnp.ones((t_local,), jnp.float32),
        )
        denom = toks.shape[0] * (cfg.seq_len - 1)
        logits = model.apply({"params": params}, toks, pos_offset=off,
                             train=train)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.sum(nll * pos_valid[None, :]) / denom

    def device_grads(params, tokens):
        """tokens: (lanes, B, t_local) — this device's shard of its workers'
        batches (lanes = num_workers / mesh w-axis; 1 on a full mesh).
        Returns (flat_grads (lanes, d), losses (lanes,)) — each worker's FULL
        gradient, psum-assembled over sp and replicated along it."""
        def one_lane(toks):
            loss, g = jax.value_and_grad(
                lambda p: _shard_objective(p, toks, train=True)
            )(params)
            return _flatten_tree(g), loss

        g, loss = jax.vmap(one_lane)(tokens)
        # exact per-worker grad: cotangents already routed through the ring's
        # transpose; psum folds the shard contributions
        g = lax.psum(g, SEQ_AXIS)
        loss = lax.psum(loss, SEQ_AXIS)
        return g, loss

    def device_loss(params, tokens):
        """Forward-only held-out loss (no backward, no gradient ICI
        traffic)."""
        loss = jax.vmap(
            lambda toks: _shard_objective(params, toks, train=False)
        )(tokens)
        return lax.psum(loss, SEQ_AXIS)

    grads_fn = shard_map(
        device_grads,
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, SEQ_AXIS)),
        out_specs=(P(WORKER_AXIS, None), P(WORKER_AXIS)),
        check_vma=False,
    )

    def device_grads_sim(params, tokens):
        """Reference-parity r× redundant compute under SP: tokens
        (lanes, hat_s, B, t_local) — each lane worker really evaluates its
        hat_s = 2s+1 assigned batch rows (cyclic_worker.py:122-146).
        Returns ((lanes, hat_s, d), (lanes, hat_s))."""
        def one_row(toks):
            loss, g = jax.value_and_grad(
                lambda p: _shard_objective(p, toks, train=True)
            )(params)
            return _flatten_tree(g), loss

        g, loss = jax.vmap(jax.vmap(one_row))(tokens)
        return lax.psum(g, SEQ_AXIS), lax.psum(loss, SEQ_AXIS)

    grads_fn_sim = shard_map(
        device_grads_sim,
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, None, SEQ_AXIS)),
        out_specs=(P(WORKER_AXIS, None, None), P(WORKER_AXIS, None)),
        check_vma=False,
    )

    # ---- aggregation over w (identical machinery to the CNN path) ---------
    code = build_code_from_cfg(cfg)
    simulate = cfg.approach == "cyclic" and cfg.redundancy == "simulate"
    batch_ids = jnp.asarray(code.batch_ids) if simulate else None
    shard_w3 = sharding(mesh, WORKER_ROWS3)

    def step_body(state: TrainState, tokens, adv_mask, present=None):
        with jax.named_scope("draco_comp"):
            if simulate:
                # gather each worker's redundant rows (n, hat_s, B, T); GSPMD
                # inserts the w-axis collective for the cross-worker rows
                toks_w = tokens[batch_ids]
                grads, losses = grads_fn_sim(state.params, toks_w)
                grads = lax.with_sharding_constraint(grads, shard_w3)
                losses = jnp.mean(losses, axis=1)
            else:
                grads, losses = grads_fn(state.params, tokens)
                grads = lax.with_sharding_constraint(grads, shard_w)
        # in-graph decode projection — no d-length program constant
        # (rng.random_projection_factors_in_graph docstring); the approx
        # decode is projection-free (real least squares, no syndrome)
        rand_factor = (drng.random_projection_factors_in_graph(cfg.seed, dim)
                       if cfg.approach == "cyclic" else None)
        agg, health = aggregate_flat_grads(grads, adv_mask, cfg, code,
                                           rand_factor, present=present,
                                           leaf_offsets=leaf_offsets,
                                           step=state.step)
        new_state, guard_cols = finish_flat_step(cfg, state, agg, health,
                                                 opt, unravel,
                                                 present=present)
        metrics = {"loss": masked_loss_metric(losses, present)}
        metrics.update(decode_health_metrics(health, adv_mask, present))
        metrics.update(guard_cols)
        return new_state, metrics

    loss_fn = shard_map(
        device_loss,
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, SEQ_AXIS)),
        out_specs=P(WORKER_AXIS),
        check_vma=False,
    )

    def eval_body(params, tokens):
        return jnp.mean(loss_fn(params, tokens))

    metric_names = token_metric_names(cfg)
    with mesh:
        train_step = jax.jit(step_body, donate_argnums=(0,))
        eval_step = jax.jit(eval_body)
        train_token_many = jax.jit(
            make_token_train_many(step_body, token_fn_from_cfg(cfg),
                                  metric_names=metric_names),
            donate_argnums=(0,),
        )

    return SPTrainSetup(
        model=model, state=state, train_step=train_step, eval_step=eval_step,
        code=code, unravel=unravel, dim=dim,
        train_token_many=train_token_many, metric_names=metric_names,
    )


# ---- program-lint registration (draco_tpu/analysis) -----------------------

# The route's explicit-collective budget at the audited shape (1 layer,
# sp=2): each layer's ring attention is sp-1 ppermute hops plus the
# target-handoff hop, and the per-worker gradient/loss assembly is two
# psums over sp. Static op counts — layout-independent (the 16-device
# chip audit and the folded 8-device CI mesh observe the same counts), so
# tools/tpu_parallel_lowering_check.py imports this same constant. A
# legitimate schedule change updates it HERE, once (PERF.md §6).
LINT_COLLECTIVES = {"all_reduce": 2, "collective_permute": 5}


def lint_programs():
    """The SP route's chip-bound programs. This is the explicit-collective
    route (LINT_COLLECTIVES above). An extra all_gather here means GSPMD
    started resharding the ring, exactly the drift the budget exists to
    catch."""
    from draco_tpu.analysis.registry import (
        BF16_DTYPES, LintProgram, Manifest, built_token_program,
        ci_lm_config,
    )
    from draco_tpu.parallel.mesh import make_mesh_2d

    # every explicit collective in the route lowers over the sp axis (ring
    # hops + the two gradient/loss psums); a w- or cross-axis collective
    # here means the coding tail stopped being pure GSPMD
    LINT_COLLECTIVE_AXES = {"sp": dict(LINT_COLLECTIVES)}

    manifest = Manifest(collectives=LINT_COLLECTIVES,
                        collective_axes=LINT_COLLECTIVE_AXES)
    # the shadow-watch program's bf16 rounds are whitelisted converts;
    # everything else in its manifest matches the ring budget exactly
    manifest_bf16 = Manifest(collectives=LINT_COLLECTIVES,
                             collective_axes=LINT_COLLECTIVE_AXES,
                             allowed_dtypes=BF16_DTYPES)

    def _build(name, many, mf=None, **overrides):
        cfg = ci_lm_config(seq_shards=2, **overrides)
        mesh = make_mesh_2d(4, 2)  # 8 CI devices; n=8 folds 2 lanes/device
        setup = build_sp_train_setup(cfg, mesh)
        return built_token_program(name, cfg, mesh, setup, mf or manifest,
                                   many=many, partition_rules=SP_STEP_RULES)

    return [
        LintProgram("lm_sp_ring_step", route="sp",
                    build=lambda: _build("lm_sp_ring_step", False)),
        LintProgram("lm_sp_ring_many_k2", route="sp",
                    build=lambda: _build("lm_sp_ring_many_k2", True)),
        # guarded production program (ISSUE 6): the step guard must not
        # change the ring's explicit-collective budget or donation
        LintProgram("lm_sp_ring_many_guard_k2", route="sp",
                    build=lambda: _build("lm_sp_ring_many_guard_k2", True,
                                         step_guard="on")),
        # the approx family on the ring (ISSUE 8): swapping the cyclic
        # decode for the optimal-decoding least squares must leave the
        # ring's explicit-collective budget untouched — the coding tail is
        # pure GSPMD either way, so extra collectives here would mean the
        # (n, n) solve started resharding
        LintProgram("lm_sp_ring_approx_many_k2", route="sp",
                    build=lambda: _build("lm_sp_ring_approx_many_k2", True,
                                         approach="approx", worker_fail=0,
                                         code_redundancy=1.5,
                                         step_guard="on")),
        # the fused-decode lowering of the same program (ISSUE 12):
        # decode_impl="pallas" resolves to the kernels' fused reference
        # path on the CPU host — the restructured O(n·d) decode tail must
        # keep the identical ring budget, donation and zero host traffic,
        # and this row is the device-profile join row for the
        # lm_sp_approx_pallas_k4 cell (tools/device_profile.py).
        # fast=False: an impl variant of the fast-swept approx row — the
        # full tool covers it without growing the --fast sweep budget
        LintProgram("lm_sp_ring_approx_pallas_many_k2", route="sp",
                    fast=False,
                    build=lambda: _build("lm_sp_ring_approx_pallas_many_k2",
                                         True,
                                         approach="approx", worker_fail=0,
                                         code_redundancy=1.5,
                                         step_guard="on",
                                         decode_impl="pallas")),
        # shadow-watch production program (obs/numerics.py, ISSUE 10): the
        # numerics columns + bf16 shadow decode ride the shared flat-grad
        # tail — the ring's explicit-collective budget and donation must
        # not move (the shadow is reductions + a second GSPMD decode of
        # already-gathered rows, never a shard_map collective)
        LintProgram("lm_sp_ring_shadow_many_k2", route="sp",
                    build=lambda: _build("lm_sp_ring_shadow_many_k2", True,
                                         mf=manifest_bf16,
                                         numerics_watch="on",
                                         shadow_wire="bf16",
                                         step_guard="on")),
        # REAL narrow-wire production program (ISSUE 15): the flat-grad
        # tail's codewords cross the sharding boundary as actual bf16
        # buffers and the λ-regularized locator decodes them — ring
        # budget, donation and host traffic unchanged, and the manifest
        # REQUIRES bf16 in the module (a silently-f32 "narrow" ring
        # program trips the dtype rule)
        LintProgram("lm_sp_ring_wire_bf16_many_k2", route="sp",
                    build=lambda: _build(
                        "lm_sp_ring_wire_bf16_many_k2", True,
                        mf=Manifest(collectives=LINT_COLLECTIVES,
                                    collective_axes=LINT_COLLECTIVE_AXES,
                                    allowed_dtypes=BF16_DTYPES,
                                    required_dtypes=frozenset({"bf16"})),
                        wire_dtype="bf16", step_guard="on")),
    ]


def train_sp(cfg: TrainConfig, mesh, steps: Optional[int] = None,
             quiet: bool = False, profile_dir: Optional[str] = None):
    """SP training loop on the synthetic text stream; returns the final state
    and last-step metrics. Checkpoint/eval/resume/chunking semantics live in
    the shared token loop (parallel/token_loop.py); ``profile_dir`` captures
    a jax.profiler device trace there (chunk-snapped under K>1)."""
    from draco_tpu.parallel.token_loop import run_token_loop

    return run_token_loop(build_sp_train_setup(cfg, mesh), cfg, steps, quiet,
                          tag="sp", profile_dir=profile_dir,
                          # autopilot family swaps rebuild the route setup
                          # for the new regime cfg (warm-cached per regime)
                          rebuild=lambda c: build_sp_train_setup(c, mesh))
