"""The shared LM training loop — one host driver for all five token routes
(single-shard, sp, tp, pp, ep; anything exposing ``.state``, ``.train_step``,
``.eval_step``, ``.train_token_many``).

Two execution regimes, selected by ``cfg.steps_per_call`` — the same contract
as the CNN ``Trainer`` (training/trainer.py):

* K=1 (default): the eager per-step loop — one ``synthetic_text`` host
  generation, one fresh upload, one dispatch per step. The bitwise reference
  for the chunked path, and honest on local CPU.
* K>1: the scan-chunked loop — ``train_token_many`` (parallel/common.py)
  fuses K full LM coded steps (token-batch slice → vmapped lane fwd/bwd →
  encode → aggregate/decode → update) into ONE jitted ``lax.scan`` with the
  state carry donated and the adversary/straggler schedules sliced on device
  from (K, n) blocks. Per-step losses accumulate into a (K, m) device block
  fetched once per flush window (``DeferredMetricWriter``); the next chunk's
  (K, n·B, T) token block is assembled on a background thread while the
  device runs the current one (``TokenChunkPrefetcher``). Per K steps the
  host pays ONE dispatch instead of K × (host token gen + device_put +
  dispatch) — this is what hides the ~70 ms/dispatch RTT of remote backends
  (PERF.md §0/§4b) on the LM routes, where it was ~70 % of the flagship
  step (PERF.md §1b).

``cfg.token_gen == "device"`` removes the host token path entirely: the
scanned program regenerates each step's batch in-graph from the scalar
(seed, step) (``sp_step.synthetic_text_in_graph``, the same discipline as
``rng.random_projection_factors_in_graph``), so a chunk's upload is K int32
scalars. The device stream is a distinct PRNG draw from the host stream, so
the flag selects WHICH deterministic stream trains — both regimes of a given
stream stay bitwise-equivalent (K=1 runs the scanned driver too in this
mode).

Eval/checkpoint cadence snaps to chunk boundaries via explicit remainder
chunks (``batching.chunk_ranges`` — the one snapping rule, shared with
``Trainer._run_chunked``), so ``max_steps`` need not divide by K and a
resumed run re-enters the exact chunk grid. Held-out eval needs only
``eval_freq`` (the metric writer prints when there is no ``train_dir``);
checkpoints need only ``train_dir`` — a run with ``eval_freq=0`` still saves
its final state (previously both hid behind one ``eval_freq and train_dir``
guard and checkpointing without eval was impossible).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from draco_tpu import rng as drng
from draco_tpu.config import TrainConfig
from draco_tpu.data.batching import chunk_ranges
from draco_tpu.obs import (
    NULL_TRACER,
    CompileWatch,
    RunHeartbeat,
    profiler_window,
)
from draco_tpu.obs.forensics import record_value
from draco_tpu.resilience import faults as faults_mod
from draco_tpu.resilience.supervisor import (
    GracefulStop,
    ImmediateStopError,
    SupervisedPrefetcher,
    restore_with_walkback,
)


class _LoopTelemetry(NamedTuple):
    """Telemetry + resilience context threaded through both regimes'
    drivers (defaults = everything disabled, so direct driver calls need no
    setup)."""

    tracer: Any = NULL_TRACER
    heartbeat: RunHeartbeat = RunHeartbeat(None)
    total_end: int = 0  # last step of the run (heartbeat ETA denominator)
    profile_dir: Optional[str] = None
    profile_steps: tuple = (3, 8)
    # compile/retrace sentinel; the default is an unstarted (inert) watch
    compile_watch: CompileWatch = CompileWatch(guard="off")
    # deterministic host-fault injector (inert without cfg.fault_spec) and
    # the graceful-stop holder run_token_loop installs (ISSUE 6)
    injector: Any = faults_mod.NULL_INJECTOR
    stop: Optional[GracefulStop] = None
    # mutable {"state", "step"} holder the eager loop refreshes per step —
    # the escalated-stop (ImmediateStopError) checkpoint source there
    latest: Any = None


def _stop_requested(obs: _LoopTelemetry, step: int) -> bool:
    """True when the loop should stop after ``step`` — a SIGTERM/SIGINT
    arrived, or the fault plan injects one here (delivered through the
    real handler path; the shared poll lives in supervisor.stop_requested,
    one implementation for both production loops)."""
    from draco_tpu.resilience.supervisor import stop_requested

    return stop_requested(obs.stop, obs.injector, step)


def _snap_stop(cfg, state, step: int, obs: _LoopTelemetry,
               already_saved: bool = False) -> None:
    """Honor a graceful stop: snap a resumable boundary checkpoint and
    record where (the terminal "preempted" heartbeat reports it).
    ``already_saved``: the boundary path just checkpointed this exact step
    — don't pay the device_get + write twice."""
    from draco_tpu.utils import checkpoint as ckpt_mod

    if cfg.train_dir and not already_saved:
        with obs.tracer.span("ckpt", at_step=step):
            ckpt_mod.save(cfg.train_dir, step, state,
                          compress=cfg.compress_ckpt,
                          keep=cfg.keep_checkpoints)
    if obs.stop is not None:
        obs.stop.stopped_step = step


def run_token_loop(setup, cfg: TrainConfig, steps: Optional[int] = None,
                   quiet: bool = False, tag: str = "mp",
                   profile_dir: Optional[str] = None,
                   profile_steps: tuple = (3, 8), rebuild=None):
    """Train ``steps or cfg.max_steps`` steps on the synthetic token stream.

    Same operational contract as the CNN Trainer: step-indexed Orbax
    checkpoints + held-out eval every ``eval_freq`` steps (reference:
    baseline_master.py:142-144), resume via ``cfg.checkpoint_step``.
    ``tag`` labels the route in error messages only; metric records carry
    the step number. Returns (state, last metrics).

    Telemetry (draco_tpu/obs, same contract as Trainer.run): ``profile_dir``
    captures a jax.profiler device trace of steps [profile_steps) — under
    the chunked regime capture snaps to the chunks containing those steps,
    exactly like ``Trainer._run_chunked``; ``cfg.trace_dir`` writes the
    host-span ``trace.json``; ``cfg.train_dir`` gets the ``status.json``
    heartbeat at every flush boundary.
    """
    from draco_tpu.obs import make_compile_watch, make_tracer
    from draco_tpu.parallel.sp_step import synthetic_text
    from draco_tpu.utils import checkpoint as ckpt_mod
    from draco_tpu.utils.metrics import MetricWriter

    state = setup.state
    start = 1
    if cfg.checkpoint_step > 0 or cfg.checkpoint_step == -1:
        # walk-back restore (resilience/supervisor.py): a corrupt
        # checkpoint is skipped, not fatal; -1 means "newest loadable" —
        # and, for restart controllers, an EMPTY train_dir means a fresh
        # start rather than a crash loop
        try:
            state, loaded, _skipped = restore_with_walkback(
                cfg.train_dir, cfg.checkpoint_step,
                jax.tree.map(lambda x: x, state))
            start = loaded + 1
        except FileNotFoundError:
            if cfg.checkpoint_step != -1:
                raise
            print(f"checkpoint_step=-1: no checkpoints in "
                  f"{cfg.train_dir!r}; starting fresh", flush=True)
    total = steps or cfg.max_steps
    last_step = start + total - 1
    # live adversaries may be fewer than the code parameter s when decode
    # budget is reserved for stragglers (config.adversary_count); the
    # fault plan's over_budget events (cfg.fault_spec) push their steps'
    # rows past the s budget — deterministically, like everything else
    fault_plan = faults_mod.plan_from_cfg(cfg)
    adv = faults_mod.apply_adversary(
        faults_mod.apply_over_budget(
            drng.adversary_schedule(cfg.seed, start + total + 1,
                                    cfg.num_workers, cfg.num_adversaries),
            fault_plan, cfg.worker_fail,
        ), fault_plan)
    # straggle events (sustained per-worker drops, faults.apply_straggle)
    # overlay the seeded schedule — or materialize one from scratch
    straggle = faults_mod.apply_straggle(
        drng.straggler_schedule(cfg.seed, start + total + 1, cfg.num_workers,
                                cfg.straggle_count)
        if cfg.straggle_mode == "drop" and cfg.straggle_count > 0
        else None,
        fault_plan, cfg.num_workers, start + total + 1,
    )
    if getattr(cfg, "autopilot", "off") == "on" and straggle is None:
        # autopilot quarantine actuates through the present-mask schedule:
        # materialize an all-present table so exclusion is a host array
        # write, never a program-signature change (same rule as Trainer)
        straggle = np.zeros((start + total + 1, cfg.num_workers), dtype=bool)
    is_main = jax.process_index() == 0
    writer = MetricWriter(cfg.train_dir or None, quiet=quiet)
    tracer = make_tracer(cfg.trace_dir, is_main)
    # num_workers keys the heartbeat's per-worker accusation ledger
    # (obs/forensics.AccusationLedger), fed by the same observer hook; the
    # incident engine (obs/incidents.py, ISSUE 13) rides the same hook +
    # the beat when cfg.incident_watch is on — host-side only, bitwise-
    # transparent to training
    from draco_tpu.obs import incidents as incidents_mod

    heartbeat = RunHeartbeat(cfg.train_dir or None, enabled=is_main,
                             num_workers=cfg.num_workers,
                             incidents=incidents_mod.make_engine(cfg,
                                                                 is_main),
                             job_name=getattr(cfg, "job_name", "") or None)
    # static logical wire-bytes ledger (obs/numerics.wire_ledger, ISSUE
    # 10): the ``wire`` status block, from the route's flat-grad dimension
    from draco_tpu.obs import numerics as numerics_mod

    heartbeat.set_wire(numerics_mod.wire_ledger(cfg, setup.dim))
    compile_watch = make_compile_watch(cfg, tracer, is_main)
    eval_toks = None
    if cfg.eval_freq:
        # held-out stream: step 0 is never trained on
        eval_toks = jnp.asarray(
            synthetic_text(cfg.seed + 1, 0, cfg.num_workers, cfg.batch_size,
                           cfg.seq_len, cfg.vocab)
        )

    def boundary_eval_ckpt(step, st):
        if eval_toks is not None:
            with tracer.span("eval"):
                eval_loss = float(setup.eval_step(st.params, eval_toks))
            writer.write({"step": step, "split": "eval", "loss": eval_loss})
            writer.flush()
        if cfg.train_dir:
            with tracer.span("ckpt"):
                ckpt_mod.save(cfg.train_dir, step, st,
                              compress=cfg.compress_ckpt,
                              keep=cfg.keep_checkpoints)

    # resilience envelope (ISSUE 6), mirroring Trainer.run: SIGTERM/SIGINT
    # become a cooperative stop honored at step/chunk boundaries (boundary
    # checkpoint + "preempted" terminal heartbeat state); an unhandled
    # exception stamps a "crashed" terminal status.json before re-raising.
    # ``engine_ref``/``latest`` track the newest dispatched state + step so
    # a second signal (ImmediateStopError) can checkpoint immediately
    engine_ref: list = []
    latest = {"state": state, "step": None}
    try:
        with GracefulStop() as stop:
            obs = _LoopTelemetry(tracer=tracer, heartbeat=heartbeat,
                                 total_end=last_step,
                                 profile_dir=(profile_dir if is_main
                                              else None),
                                 profile_steps=profile_steps,
                                 compile_watch=compile_watch,
                                 injector=faults_mod.HostFaultInjector(
                                     fault_plan),
                                 stop=stop, latest=latest)
            K = max(cfg.steps_per_call, 1)
            if K > 1 or cfg.token_gen == "device":
                # the device-generated stream exists only inside the
                # scanned program, so that mode runs the chunked driver
                # even at K=1
                state, metrics = _run_chunked(setup, cfg, state, start,
                                              last_step, adv, straggle,
                                              writer, boundary_eval_ckpt,
                                              tag, obs, rebuild=rebuild,
                                              engine_ref=engine_ref)
            else:
                state, metrics = _run_eager(setup, cfg, state, start,
                                            last_step, adv, straggle,
                                            writer, boundary_eval_ckpt, obs)
            if (cfg.train_dir and not cfg.eval_freq
                    and stop.stopped_step is None):
                # checkpointing without eval: no cadence boundaries exist,
                # so save the final state (with eval_freq set the boundary
                # saves stand alone, preserving the historical
                # on-boundary-only layout); a preempted run already snapped
                # its resumable checkpoint at the stop point
                with tracer.span("ckpt"):
                    ckpt_mod.save(cfg.train_dir, last_step, state,
                                  compress=cfg.compress_ckpt,
                                  keep=cfg.keep_checkpoints)
        if stop.stopped_step is not None:
            heartbeat.terminal(
                "preempted", cause=f"graceful stop on {stop.signame}",
                resumable_step=(stop.stopped_step if cfg.train_dir
                                else None))
        else:
            heartbeat.terminal("done")
    except ImmediateStopError as e:
        # second SIGTERM during a chunk (resilience/supervisor.py):
        # checkpoint the newest dispatched state NOW — blocking on the
        # in-flight chunk if one is executing — and end with the terminal
        # "preempted" status instead of finishing the chunk grid
        eng = engine_ref[0] if engine_ref else None
        if eng is not None and eng.state is not None:
            state, step_now = eng.state, eng.last_end
        else:
            state, step_now = latest["state"], latest["step"]
        if cfg.train_dir and step_now is not None:
            with tracer.span("ckpt", at_step=step_now):
                ckpt_mod.save(cfg.train_dir, step_now, state,
                              compress=cfg.compress_ckpt,
                              keep=cfg.keep_checkpoints)
        heartbeat.terminal(
            "preempted", cause=str(e),
            resumable_step=(step_now if cfg.train_dir
                            and step_now is not None else None))
        metrics = {}
    except BaseException as e:
        heartbeat.terminal("crashed", cause=f"{type(e).__name__}: {e}")
        raise
    finally:
        writer.close()
        compile_watch.stop()
        tracer.close()
    return state, metrics


def _run_eager(setup, cfg, state, start, last_step, adv, straggle, writer,
               boundary_eval_ckpt, obs=_LoopTelemetry()):
    """One dispatch per step — the K=1 bitwise reference."""
    from draco_tpu.parallel.sp_step import synthetic_text

    tracer, heartbeat, watch = obs.tracer, obs.heartbeat, obs.compile_watch
    total_end = obs.total_end
    # shared capture window (obs/profiling.py): start/stop + the
    # drain-before-stop fix + the merged-timeline anchor, one
    # implementation for all four loop sites (ISSUE 9); on stop the capture
    # folds into the heartbeat's ``device`` status block
    win = profiler_window(obs.profile_dir, obs.profile_steps, tracer=tracer,
                          on_stop=heartbeat.observe_device)
    metrics = {}
    for step in range(start, last_step + 1):
        win.maybe_start(step)
        with tracer.span("gather"):
            toks = jnp.asarray(
                synthetic_text(cfg.seed, step, cfg.num_workers,
                               cfg.batch_size, cfg.seq_len, cfg.vocab)
            )
        with tracer.span("dispatch"), watch.expect("train_step"):
            if straggle is None:
                state, metrics = setup.train_step(state, toks,
                                                  jnp.asarray(adv[step]))
            else:
                state, metrics = setup.train_step(
                    state, toks, jnp.asarray(adv[step]),
                    jnp.asarray(~straggle[step]),
                )
        win.maybe_stop(step, state.params)
        if obs.latest is not None:  # escalated-stop checkpoint cursor
            obs.latest["state"], obs.latest["step"] = state, step
        # materialize metrics at log boundaries only — the eager loop's
        # historical device-sync cadence; fetching every step for the
        # heartbeat would re-serialize the async-dispatch pipeline. The
        # heartbeat therefore aggregates the LOGGED steps in this regime
        # (the chunked driver observes every step for free at its flush)
        if step % cfg.log_every == 0:
            with tracer.span("sync"):
                # record_value: forensics bitmask columns materialize as
                # exact integer words (obs/forensics docstring)
                record = {"step": step}
                record.update({k: record_value(k, v)
                               for k, v in metrics.items()})
            heartbeat.observe(record)
            writer.write(record)
        boundary = cfg.eval_freq and step % cfg.eval_freq == 0
        if boundary or step == last_step:
            with tracer.span("flush"):
                writer.flush()
                heartbeat.beat(step, total_end, extra=watch.snapshot())
                tracer.flush()
        if boundary:
            boundary_eval_ckpt(step, state)
        if _stop_requested(obs, step):
            with tracer.span("flush"):
                writer.flush()
            _snap_stop(cfg, state, step, obs, already_saved=bool(boundary))
            break
    win.stop(state.params)  # loop ended inside the window
    return state, metrics


def _run_chunked(setup, cfg, state, start, last_step, adv, straggle, writer,
                 boundary_eval_ckpt, tag="mp", obs=_LoopTelemetry(),
                 rebuild=None, engine_ref=None):
    """One dispatch per chunk of up to K steps, driven by the shared
    ``ChunkedEngine`` (control/engine.py — one implementation with the CNN
    Trainer loop): metrics deferred to flush boundaries, next chunk
    assembled while the device runs the current one."""
    from draco_tpu.control.clients import TokenChunkClient
    from draco_tpu.control.engine import ChunkedEngine
    from draco_tpu.data.prefetch import TokenChunkPrefetcher
    from draco_tpu.parallel.sp_step import synthetic_text

    if setup.train_token_many is None:
        raise ValueError(
            f"{tag} route setup lacks train_token_many — rebuild it with "
            "the current route builders (parallel/{sp,tp,ep,pp}_step.py)"
        )
    ranges = chunk_ranges(start, last_step, cfg.steps_per_call, cfg.eval_freq)
    if not ranges:
        return state, {}
    prefetch = None
    if cfg.token_gen != "device":
        # generation fn wrapped by the fault injector (inert by default),
        # prefetcher wrapped by restart supervision with a bounded queue
        # wait — a dead/hung worker thread is retried with backoff, then
        # surfaces as the named PrefetchStallError, never a silent hang
        gen_fn = obs.injector.wrap_step_fn(
            lambda step: synthetic_text(cfg.seed, step, cfg.num_workers,
                                        cfg.batch_size, cfg.seq_len,
                                        cfg.vocab))
        factory = lambda: TokenChunkPrefetcher(  # noqa: E731
            gen_fn, tracer=obs.tracer, timeout_s=cfg.prefetch_timeout_s)
        prefetch = (SupervisedPrefetcher(factory,
                                         restarts=cfg.prefetch_restarts,
                                         tracer=obs.tracer)
                    if cfg.prefetch_restarts > 0 else factory())
    client = TokenChunkClient(setup, cfg, adv, straggle, prefetch, obs,
                              boundary_eval_ckpt, rebuild=rebuild)
    autopilot = None
    if getattr(cfg, "autopilot", "off") == "on":
        from draco_tpu.control.autopilot import make_autopilot

        autopilot = make_autopilot(cfg, obs.heartbeat, dim=setup.dim)
    engine = ChunkedEngine(
        client, eval_freq=cfg.eval_freq, total_end=obs.total_end,
        tracer=obs.tracer, heartbeat=obs.heartbeat,
        compile_watch=obs.compile_watch, writer=writer,
        autopilot=autopilot, profile_dir=obs.profile_dir,
        profile_steps=obs.profile_steps)
    if engine_ref is not None:
        engine_ref.append(engine)  # the escalated-stop checkpoint source
    state, last = engine.run(state, ranges)
    return state, ({"loss": last["loss"]} if "loss" in last else {})
