"""2-D device meshes: coded worker axis × sequence axis."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from draco_tpu.runtime import WORKER_AXIS

SEQ_AXIS = "sp"
TP_AXIS = "tp"
EP_AXIS = "ep"
PP_AXIS = "pp"


def make_mesh_2d(
    num_workers: int,
    seq_shards: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh of shape (num_workers, seq_shards) with axes (w, sp).

    Lay the sequence axis innermost so its ring rides neighbouring ICI links;
    the worker-axis gather crosses the slower dimension once per step.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = num_workers * seq_shards
    if len(devices) < need:
        raise ValueError(
            f"make_mesh_2d({num_workers}, {seq_shards}) needs {need} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(num_workers, seq_shards)
    return Mesh(grid, (WORKER_AXIS, SEQ_AXIS))


def make_folded_wtp_mesh(num_workers: int) -> Mesh:
    """(w, tp=1) mesh with the logical workers FOLDED onto the available
    devices (runtime.make_mesh discipline: equal lane blocks per device, warns
    when devices idle). The trivial tp axis makes the GSPMD LM builder
    (tp_step.build_tp_train_setup) applicable on any device count — the
    single-chip n-lane vmapped regime the perf/convergence tools run in.
    Distinct from make_mesh_wtp, which demands num_workers × shards physical
    devices for real tensor sharding."""
    from draco_tpu.runtime import make_mesh

    fold = make_mesh(num_workers).devices.ravel()
    return Mesh(np.asarray(fold).reshape(len(fold), 1), (WORKER_AXIS, TP_AXIS))


def _make_mesh_w2(axis2: str, num_workers: int, shards: int,
                  devices: Optional[Sequence[jax.Device]]) -> Mesh:
    """(num_workers, shards) mesh with axes (w, axis2); the model-parallel
    axis is innermost, riding the fastest ICI links (its collectives fire
    several times per step; the worker-axis gather once)."""
    devices = list(devices if devices is not None else jax.devices())
    need = num_workers * shards
    if len(devices) < need:
        raise ValueError(
            f"(w={num_workers}, {axis2}={shards}) mesh needs {need} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(num_workers, shards)
    return Mesh(grid, (WORKER_AXIS, axis2))


def make_mesh_wtp(
    num_workers: int,
    tensor_shards: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh of shape (num_workers, tensor_shards) with axes (w, tp)."""
    return _make_mesh_w2(TP_AXIS, num_workers, tensor_shards, devices)


def make_mesh_wep(
    num_workers: int,
    expert_shards: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh of shape (num_workers, expert_shards) with axes (w, ep)."""
    return _make_mesh_w2(EP_AXIS, num_workers, expert_shards, devices)


def make_mesh_wpp(
    num_workers: int,
    pipeline_shards: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh of shape (num_workers, pipeline_shards) with axes (w, pp)."""
    return _make_mesh_w2(PP_AXIS, num_workers, pipeline_shards, devices)
