"""2-D device meshes: coded worker axis × sequence axis."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from draco_tpu.runtime import WORKER_AXIS

SEQ_AXIS = "sp"
TP_AXIS = "tp"


def make_mesh_2d(
    num_workers: int,
    seq_shards: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh of shape (num_workers, seq_shards) with axes (w, sp).

    Lay the sequence axis innermost so its ring rides neighbouring ICI links;
    the worker-axis gather crosses the slower dimension once per step.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = num_workers * seq_shards
    if len(devices) < need:
        raise ValueError(
            f"make_mesh_2d({num_workers}, {seq_shards}) needs {need} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(num_workers, seq_shards)
    return Mesh(grid, (WORKER_AXIS, SEQ_AXIS))


def make_mesh_wtp(
    num_workers: int,
    tensor_shards: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh of shape (num_workers, tensor_shards) with axes (w, tp).

    Tensor-parallel all-reduces fire at every row-parallel layer boundary
    (several per step), the worker-axis gather once per step — so ``tp``
    is innermost, riding the fastest ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = num_workers * tensor_shards
    if len(devices) < need:
        raise ValueError(
            f"make_mesh_wtp({num_workers}, {tensor_shards}) needs {need} "
            f"devices, have {len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(num_workers, tensor_shards)
    return Mesh(grid, (WORKER_AXIS, TP_AXIS))
