"""All-to-all (Ulysses-style) sequence-parallel exact attention.

The second of the two standard sequence-parallelism strategies (ring
attention being the first — draco_tpu/parallel/ring_attention.py): instead
of streaming K/V blocks around a ring, one ``lax.all_to_all`` trades the
sequence shard for a head shard — every device then holds the FULL sequence
for ``H/sp`` heads, runs ordinary dense attention locally (heads are
embarrassingly parallel), and a second all_to_all restores the sequence
layout. Two collectives total, independent of sequence length, vs the
ring's ``sp`` ppermute hops — the better trade when heads are plentiful and
the per-device full-sequence score block fits memory; ring wins at extreme
T where O(T·T/sp) scores must never materialise.

Both strategies are exact (bitwise-comparable to dense attention up to f32
reduction order) and reverse-differentiable: all_to_all is linear and its
transpose is the inverse all_to_all, so per-shard gradients psum into exact
per-worker gradients for the coded-DP layer above (sp_step.py), same as the
ring.

No reference counterpart: the reference is CNN-only (SURVEY.md §5.7); this
axis is the TPU build's long-context capability.
"""

from __future__ import annotations

from typing import Optional

from jax import lax

from draco_tpu.parallel.ring_attention import dense_attention

from draco_tpu.runtime import axis_size


def a2a_attention(
    q,
    k,
    v,
    axis_name: Optional[str],
    causal: bool = True,
    inner=None,
):
    """Exact attention over sequence shards via head-scatter all_to_all.

    q, k, v: (B, T_local, H, Dh) — this shard's block of the sequence, all
    H heads. H must be divisible by the ``axis_name`` mesh-axis size. Must
    be called inside ``shard_map``; with ``axis_name=None`` it degrades to
    single-shard dense attention.

    ``inner``: the full-sequence attention run on each device's head group
    after the scatter — defaults to dense causal attention; pass the flash
    kernel (ops/flash_attention.py) to remove the (T, T) score block this
    strategy otherwise materialises (causal-only contract: (q, k, v) -> o).
    """
    if axis_name is None:
        return (inner(q, k, v) if inner is not None
                else dense_attention(q, k, v, causal=causal))

    sp = axis_size(axis_name)
    h = q.shape[2]
    if h % sp:
        raise ValueError(f"a2a_attention: heads {h} not divisible by sp={sp}")

    # sequence-sharded, all heads  ->  full sequence, H/sp heads.
    # tiled all_to_all splits axis 2 (heads) into sp chunks, one per peer,
    # and concatenates the received chunks along axis 1 (sequence); peers
    # arrive in axis order, so concatenation restores sequence order.
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)

    # full-sequence attention on this device's head group. With the dense
    # default the whole (T, T) score block materialises per head group —
    # the strategy's known memory trade; the flash inner removes it.
    if inner is not None:
        oh = inner(qh, kh, vh)
    else:
        oh = dense_attention(qh, kh, vh, causal=causal)

    # full sequence, H/sp heads  ->  sequence-sharded, all heads
    return lax.all_to_all(oh, axis_name, split_axis=1, concat_axis=2, tiled=True)
