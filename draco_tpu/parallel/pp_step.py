"""Coded data parallelism × pipeline parallelism: the (w, pp) GPipe step.

Pipeline parallelism the TPU-native way: the TransformerLM's blocks are a
``nn.scan`` stack whose stacked parameters shard their leading layer axis
over mesh axis ``pp`` (each device holds ``layers / pp`` consecutive
blocks = one stage), and the classic GPipe schedule is an explicit
``lax.scan`` over ``M + S - 1`` ticks inside ``shard_map``: each tick a
stage runs its blocks on the activation in flight and hands the result to
its successor with ONE ``ppermute`` hop.  Backward needs no hand-written
schedule — the pipeline loop is traced, ``ppermute`` is linear, and
``jax.grad`` transposes the whole thing into the reverse-flowing backward
pipeline automatically (cotangents ride the same ring, reversed).

Composition with Draco (SURVEY.md §2.3): parameters are broadcast along a
leading worker axis sharded over ``w`` (free: each worker column just uses
its replica), so ``jax.grad`` yields *per-worker* gradients laid out
(n, ...) over ``w`` with stage slices over ``pp``; flattening to the (n, d)
gradient matrix re-lays them over ``w`` (XLA inserts the pp-gather) and the
coding / robust-aggregation machinery is unchanged, exactly as in the tp
path.

No reference counterpart: the reference's *Split* models stream per-layer
gradients over MPI but every worker holds the full model
(/root/reference/src/model_ops/resnet_split.py:210-234 — grad streaming,
not pipeline stages; SURVEY.md §2.3 "Pipeline parallelism: absent"). This
axis is part of the TPU build's scale-out surface: models deeper than one
chip's HBM span the ``pp`` axis.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from draco_tpu import optim, rng as drng
from draco_tpu.coding import cyclic as cyclic_mod
from draco_tpu.runtime import shard_map
from draco_tpu.config import TrainConfig
from draco_tpu.models.transformer import Block
from draco_tpu.parallel.common import (
    TOKEN_METRIC_NAMES,
    aggregate_flat_grads,
    build_code_from_cfg,
    finish_flat_step,
    decode_health_metrics,
    make_token_train_many,
    masked_loss_metric,
    token_metric_names,
)
from draco_tpu.parallel.mesh import PP_AXIS
from draco_tpu.parallel.partition import PP_STEP_RULES
from draco_tpu.parallel.tp_step import _constrain_params, shard_params
from draco_tpu.runtime import WORKER_AXIS
from draco_tpu.training.step import TrainState, _make_unravel


class _PipeBlock(nn.Module):
    """scan cell: one transformer block, (carry, broadcast args) contract."""

    dim: int
    heads: int
    dtype: Any
    remat: bool = False
    attn_fn: Any = None

    @nn.compact
    def __call__(self, x, positions):
        # static_argnums counts self as 0, so `train` is 3; CSE prevention
        # is unnecessary inside nn.scan (flax checkpoint docs) and would
        # put a barrier in every scanned body
        blk_cls = Block if not self.remat else nn.remat(
            Block, static_argnums=(3,), prevent_cse=False
        )
        x = blk_cls(self.dim, self.heads, attn_fn=self.attn_fn,
                    dtype=self.dtype, name="b")(x, positions, True)
        return x, None


class StageBlocks(nn.Module):
    """``layers`` transformer blocks as one scanned stack.

    Parameters carry a leading ``layers`` axis, so a contiguous slice of the
    full stack IS a pipeline stage's parameter tree: the same module class
    applies the full model (layers=L) and a stage (layers=L/S) alike.
    """

    dim: int
    heads: int
    layers: int
    dtype: Any = jnp.float32
    remat: bool = False
    attn_fn: Any = None

    @nn.compact
    def __call__(self, x, positions):
        scan = nn.scan(
            _PipeBlock,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            length=self.layers,
            in_axes=nn.broadcast,
        )
        x, _ = scan(self.dim, self.heads, self.dtype, self.remat,
                    self.attn_fn, name="loop")(x, positions)
        return x


class PPTrainSetup(NamedTuple):
    state: TrainState
    # (state, tokens (n,B,T), adv_mask (n,)) -> (state, metrics)
    train_step: any
    eval_step: any  # (params, tokens) -> mean loss
    per_worker_loss: any  # (params, tokens (n,B,T)) -> (n,) losses
    # (params, tokens) -> ((n, d) flat grads, (n,) losses)
    per_worker_grads: any
    code: Optional[cyclic_mod.CyclicCode]
    unravel: any
    dim: int
    # K fused LM steps in ONE device program (parallel/common.py):
    # (state, toks (K,n,B,T) | steps (K,), masks (K,n), presents (K,n)|None)
    #   -> (state, metrics (K, len(metric_names)) float32)
    train_token_many: any = None
    metric_names: tuple = TOKEN_METRIC_NAMES


def _flatten_rows(tree) -> jnp.ndarray:
    """(n, ...)-leaved tree -> (n, d), same leaf order as _make_unravel."""
    leaves = jax.tree.leaves(tree)
    n = leaves[0].shape[0]
    return jnp.concatenate([jnp.reshape(x, (n, -1)) for x in leaves], axis=1)


def build_pp_train_setup(cfg: TrainConfig, mesh) -> PPTrainSetup:
    """mesh must have axes (w, pp) — see make_mesh_wpp."""
    cfg.validate()
    if cfg.approach not in ("baseline", "cyclic", "approx"):
        raise ValueError(
            f"PP path supports baseline|cyclic|approx, got {cfg.approach}")
    n = cfg.num_workers
    S = mesh.shape[PP_AXIS]
    # logical workers fold onto the available w-axis devices in equal
    # lane blocks (same discipline as tp_step / runtime.make_mesh): a
    # single chip can still run the n-lane coded step, vmapped
    if n % mesh.shape[WORKER_AXIS]:
        raise ValueError(
            f"num_workers {n} must be a multiple of the mesh's w axis "
            f"({mesh.shape[WORKER_AXIS]})"
        )
    if cfg.approach == "cyclic" and cfg.redundancy == "simulate":
        # sp/tp/ep carry true 2s+1-lane redundant compute; here the r×
        # regime would multiply the whole pipeline schedule per lane for
        # no semantic difference (per-batch gradients are deterministic, so
        # the shared encode is algebraically identical) — say so instead of
        # silently reinterpreting the config
        import warnings

        warnings.warn(
            "pp path: redundancy='simulate' is not implemented; using the "
            "algebraically-identical 'shared' encode",
            stacklevel=2,
        )
    L = cfg.model_layers
    if L % S:
        raise ValueError(f"model_layers {L} not divisible by pp={S}")
    l_loc = L // S
    M = cfg.pp_microbatches or S
    if cfg.batch_size % M:
        raise ValueError(
            f"microbatches {M} must divide batch_size {cfg.batch_size}")
    mb = cfg.batch_size // M
    # the pipeline carries all T positions and the loss drops the last
    # logit row (identical next-token math — causal rows < T-1 cannot see
    # token T-1); a T-1 carry would break the flash kernel's t%8 tiling
    # (1023 at T=1024) and silently ride the dense fallback
    t_in = cfg.seq_len

    cdtype = jnp.dtype(cfg.compute_dtype)
    from draco_tpu.ops.flash_attention import attn_impl_fn

    attn_fn = attn_impl_fn(cfg)
    embed = nn.Embed(cfg.vocab, cfg.model_dim, name="embed")
    blocks_full = StageBlocks(cfg.model_dim, cfg.model_heads, layers=L,
                              dtype=cdtype, remat=cfg.remat, attn_fn=attn_fn)
    blocks_stage = StageBlocks(cfg.model_dim, cfg.model_heads, layers=l_loc,
                               dtype=cdtype, remat=cfg.remat, attn_fn=attn_fn)
    final_ln = nn.LayerNorm(use_bias=False, name="final_ln")

    root = jax.random.key(cfg.seed)
    k_emb, k_blk, k_ln = jax.random.split(root, 3)
    init_toks = jnp.zeros((1, min(t_in, 8)), jnp.int32)
    init_x = jnp.zeros((1, min(t_in, 8), cfg.model_dim), cdtype)
    init_pos = jnp.arange(init_x.shape[1])
    params = {
        "embed": embed.init(k_emb, init_toks)["params"],
        "blocks": blocks_full.init(k_blk, init_x, init_pos)["params"],
        "final_ln": final_ln.init(k_ln, init_x.astype(jnp.float32))["params"],
    }

    opt = optim.build_optimizer_from_cfg(cfg)
    unravel, dim, leaf_offsets = _make_unravel(params)

    # parameter residence between steps: stage stacks shard their leading
    # layer axis over pp, everything else replicated
    def _leaf_spec(path):
        # membership, not names[0]: opt_state paths reach the stage stacks
        # as 0/momentum_buf/blocks/... — a leading-name test left every
        # momentum slot replicated at rest while the compiled step emitted
        # it pp-sharded, i.e. a resharding retrace on the second dispatch
        # (the exact PR 6 failure mode, caught by lint rule 7)
        names = [getattr(k, "key", str(k)) for k in path]
        if "blocks" in names:
            return P(PP_AXIS)
        return P()

    def _leaf_spec_n(path):
        """Same, with the per-worker broadcast axis leading."""
        return P(WORKER_AXIS, *_leaf_spec(path))

    params = shard_params(params, mesh, _leaf_spec)
    state = TrainState(
        params=params,
        opt_state=shard_params(opt.init(params), mesh, _leaf_spec),
        batch_stats=None,
        step=jax.device_put(jnp.asarray(1, jnp.int32),
                            NamedSharding(mesh, P())),
    )

    params_n_specs = jax.tree_util.tree_map_with_path(
        lambda path, _: _leaf_spec_n(path), params
    )

    def device_loss(params_n_local, tokens_local):
        """One device = one (worker-block, stage) cell of the mesh.

        params_n_local: this device's worker replicas, this stage's block
        slice — leaves (lanes, [l_loc,] ...) where lanes = num_workers /
        mesh w-axis (1 on a full mesh). tokens_local: (lanes, B, T).
        Returns each lane worker's mean next-token CE, replicated over pp,
        shape (lanes,)."""
        return jax.vmap(_lane_loss)(params_n_local, tokens_local)

    def _lane_loss(p, toks):
        inp, tgt = toks, toks[:, 1:]
        my = lax.axis_index(PP_AXIS)
        positions = jnp.arange(t_in)

        # stage 0's injections: embedded microbatches, padded with S-1
        # bubble ticks (every stage computes the embedding locally — it is
        # one gather; only stage 0's enters the pipeline, so only stage 0
        # contributes its cotangent)
        x = embed.apply({"params": p["embed"]}, inp).astype(cdtype)
        x_mb = x.reshape(M, mb, t_in, cfg.model_dim)
        feed = jnp.concatenate(
            [x_mb, jnp.zeros((S - 1, mb, t_in, cfg.model_dim), cdtype)], axis=0
        ) if S > 1 else x_mb

        def stage(xin):
            return blocks_stage.apply({"params": p["blocks"]}, xin, positions)

        if S == 1:
            outs = jax.vmap(stage)(x_mb)
        else:
            def tick(carry, t):
                cur, outs = carry
                xin = lax.dynamic_index_in_dim(feed, t, 0, keepdims=False)
                xin = jnp.where(my == 0, xin, cur)
                out = stage(xin)
                # hand to the successor stage; stage 0 receives nothing
                # (ppermute leaves unaddressed receivers zero)
                nxt = lax.ppermute(
                    out, PP_AXIS, [(i, i + 1) for i in range(S - 1)]
                )
                idx = t - (S - 1)
                upd = lax.dynamic_update_index_in_dim(
                    outs, out, jnp.clip(idx, 0, M - 1), 0
                )
                outs = jnp.where(idx >= 0, upd, outs)
                return (nxt, outs), None

            outs0 = jnp.zeros((M, mb, t_in, cfg.model_dim), cdtype)
            (_, outs), _ = lax.scan(
                tick, (jnp.zeros((mb, t_in, cfg.model_dim), cdtype), outs0),
                jnp.arange(M + S - 1),
            )

        # head on the last stage (all stages run it SPMD-uniformly; the
        # where selects, and non-last contributions are exact zeros)
        h = final_ln.apply({"params": p["final_ln"]},
                           outs.astype(jnp.float32))
        logits = embed.apply({"params": p["embed"]}, h, method="attend")
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))[:, :, :-1]
        tgt_mb = tgt.reshape(M, mb, t_in - 1)
        nll = -jnp.take_along_axis(logp, tgt_mb[..., None], axis=-1)[..., 0]
        loss = jnp.where(my == S - 1, jnp.mean(nll), 0.0)
        return lax.psum(loss, PP_AXIS)

    losses_fn = shard_map(
        device_loss,
        mesh=mesh,
        in_specs=(params_n_specs, P(WORKER_AXIS, None, None)),
        out_specs=P(WORKER_AXIS),
        check_vma=False,
    )

    def _broadcast_n(params):
        bcast = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), params
        )
        return _constrain_params(bcast, mesh, _leaf_spec_n)

    def per_worker_loss(params, tokens):
        return losses_fn(_broadcast_n(params), tokens)

    def per_worker_grads(params, tokens):
        def total(params_n):
            losses = losses_fn(params_n, tokens)
            return jnp.sum(losses), losses

        grads_n, losses = jax.grad(total, has_aux=True)(_broadcast_n(params))
        flat = _flatten_rows(grads_n)
        return lax.with_sharding_constraint(
            flat, NamedSharding(mesh, P(WORKER_AXIS))
        ), losses

    code = build_code_from_cfg(cfg)

    def step_body(state: TrainState, tokens, adv_mask, present=None):
        with jax.named_scope("draco_comp"):
            grads, losses = per_worker_grads(state.params, tokens)
        # in-graph decode projection — no d-length program constant
        # (rng.random_projection_factors_in_graph docstring); the approx
        # decode is projection-free
        rand_factor = (drng.random_projection_factors_in_graph(cfg.seed, dim)
                       if cfg.approach == "cyclic" else None)
        agg, health = aggregate_flat_grads(grads, adv_mask, cfg, code,
                                           rand_factor, present=present,
                                           leaf_offsets=leaf_offsets,
                                           step=state.step)
        new_state, guard_cols = finish_flat_step(
            cfg, state, agg, health, opt, unravel, present=present,
            constrain=lambda p: _constrain_params(p, mesh, _leaf_spec),
        )
        metrics = {"loss": masked_loss_metric(losses, present)}
        metrics.update(decode_health_metrics(health, adv_mask, present))
        metrics.update(guard_cols)
        return new_state, metrics

    def eval_body(params, tokens):
        return jnp.mean(per_worker_loss(params, tokens))

    from draco_tpu.parallel.sp_step import token_fn_from_cfg

    metric_names = token_metric_names(cfg)
    # state-in == state-out at the JIT boundary, tp_step-style: the carry
    # pin stops GSPMD from electing a different at-rest layout for the
    # momentum stacks than shard_params installed (lint rule 7 audits this
    # contract on every registered program)
    state_shardings = jax.tree.map(lambda x: x.sharding, state)
    with mesh:
        train_step = jax.jit(step_body, donate_argnums=(0,),
                             out_shardings=(state_shardings, None))
        eval_step = jax.jit(eval_body)
        loss_jit = jax.jit(per_worker_loss)
        grads_jit = jax.jit(per_worker_grads)
        train_token_many = jax.jit(
            make_token_train_many(step_body, token_fn_from_cfg(cfg),
                                  metric_names=metric_names),
            donate_argnums=(0,),
            out_shardings=(state_shardings, None),
        )

    return PPTrainSetup(
        state=state, train_step=train_step, eval_step=eval_step,
        per_worker_loss=loss_jit, per_worker_grads=grads_jit,
        code=code, unravel=unravel, dim=dim,
        train_token_many=train_token_many, metric_names=metric_names,
    )


# ---- program-lint registration (draco_tpu/analysis) -----------------------

# The route's explicit-collective budget at the audited shape (2 stages,
# 2 microbatches, 2 layers): the forward tick loop plus its transposed
# backward ride 2 collective_permute ops, and the loss/grad psums over pp
# contribute 4 all_reduce. Static op counts — layout-independent (same on
# the 16-device chip audit and the folded 8-device CI mesh), shared with
# tools/tpu_parallel_lowering_check.py; a legitimate schedule change
# updates it HERE, once (PERF.md §6).
LINT_COLLECTIVES = {"all_reduce": 4, "collective_permute": 2}


def lint_programs():
    """The GPipe pipeline route's chip-bound programs. The schedule's hop
    structure is explicit (shard_map + ppermute inside the traced pipeline
    loop), so the manifest pins it (LINT_COLLECTIVES above). A count drift
    here means the pipeline schedule itself changed."""
    from draco_tpu.analysis.registry import (
        LintProgram, Manifest, built_token_program, ci_lm_config,
    )
    from draco_tpu.parallel.mesh import make_mesh_wpp

    # all explicit hops and psums lower over the pp axis — a w-axis
    # collective here would mean the coding tail left pure GSPMD
    manifest = Manifest(collectives=LINT_COLLECTIVES,
                        collective_axes={"pp": dict(LINT_COLLECTIVES)})

    def _build(name, many):
        cfg = ci_lm_config(pipeline_shards=2, pp_microbatches=2,
                           model_layers=2)
        mesh = make_mesh_wpp(4, 2)  # 8 CI devices; n=8 folds 2 lanes/device
        setup = build_pp_train_setup(cfg, mesh)
        return built_token_program(name, cfg, mesh, setup, manifest,
                                   many=many,
                                   partition_rules=PP_STEP_RULES)

    return [
        LintProgram("lm_pp_step", route="pp",
                    build=lambda: _build("lm_pp_step", False)),
        LintProgram("lm_pp_many_k2", route="pp",
                    build=lambda: _build("lm_pp_many_k2", True)),
    ]


def train_pp(cfg: TrainConfig, mesh, steps: Optional[int] = None,
             quiet: bool = False, profile_dir: Optional[str] = None):
    """PP training loop; returns (state, last metrics)."""
    from draco_tpu.parallel.token_loop import run_token_loop

    setup = build_pp_train_setup(cfg, mesh)
    return run_token_loop(setup, cfg, steps, quiet, tag="pp",
                          profile_dir=profile_dir)
