"""Coded data parallelism × expert parallelism: the (w, ep) GSPMD step.

Expert parallelism for the Switch-MoE TransformerLM
(draco_tpu/models/moe.py), same GSPMD idiom as the tensor-parallel path
(tp_step.py): expert weight stacks carry ``NamedSharding`` annotations over
mesh axis ``ep`` on their leading E axis, the step is one plain jit, and
XLA's partitioner localises each expert's FFN to its shard with
dispatch/combine resharding at the einsum boundaries. Router and all
non-expert parameters stay replicated.

Draco composition is identical to the tp path: per-worker flat gradients
over ``w``, then the shared coding/robust-aggregation tail
(parallel/common.py).

No reference counterpart (CNN-only zoo, single-axis DP).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec as P

from draco_tpu.config import TrainConfig
from draco_tpu.parallel.mesh import EP_AXIS
from draco_tpu.parallel.partition import EP_STEP_RULES
from draco_tpu.parallel.token_loop import run_token_loop
from draco_tpu.parallel.tp_step import (
    TPTrainSetup,
    _build_gspmd_train_setup,
)

EXPERT_PARAMS = ("w1", "w2", "b1", "b2")


def ep_partition_spec(path) -> P:
    """Expert weight stacks shard their leading E axis over ``ep``; the
    router and every non-MoE parameter stay replicated."""
    names = [getattr(k, "key", str(k)) for k in path]
    if len(names) >= 2 and names[-2] == "moe" and names[-1] in EXPERT_PARAMS:
        # scan_layers stacks block params under "blocks" with a leading
        # layer axis — the E axis moves to position 1 (same shift as
        # tp_step.param_partition_spec)
        if "blocks" in names:
            return P(None, EP_AXIS)
        return P(EP_AXIS)
    return P()


def build_ep_train_setup(cfg: TrainConfig, mesh) -> TPTrainSetup:
    """mesh must have axes (w, ep) — see make_mesh_wep."""
    return _build_gspmd_train_setup(
        cfg, mesh, mp_axis=EP_AXIS, mp_size=max(cfg.expert_shards, 1),
        partition_fn=ep_partition_spec, experts=cfg.moe_experts,
    )


# ---- program-lint registration (draco_tpu/analysis) -----------------------


def lint_programs():
    """The Switch-MoE expert-parallel route's chip-bound programs. Like the
    tp route this is pure GSPMD (dispatch/combine resharding is inserted by
    the SPMD partitioner, post-export), so the manifest pins zero explicit
    collectives — shard_map leaking into the MoE path would show up here."""
    from draco_tpu.analysis.registry import (
        LintProgram, Manifest, built_token_program, ci_lm_config,
    )
    from draco_tpu.parallel.mesh import make_mesh_wep

    def _build(name, many):
        cfg = ci_lm_config(moe_experts=4, expert_shards=2)
        mesh = make_mesh_wep(4, 2)  # 8 CI devices; n=8 folds 2 lanes/device
        setup = build_ep_train_setup(cfg, mesh)
        return built_token_program(name, cfg, mesh, setup,
                                   Manifest(collectives={},
                                            collective_axes={}),
                                   many=many,
                                   partition_rules=EP_STEP_RULES)

    return [
        LintProgram("lm_ep_step", route="ep",
                    build=lambda: _build("lm_ep_step", False)),
        LintProgram("lm_ep_many_k2", route="ep",
                    build=lambda: _build("lm_ep_many_k2", True)),
    ]


def train_ep(cfg: TrainConfig, mesh, steps: Optional[int] = None,
             quiet: bool = False, profile_dir: Optional[str] = None):
    """EP training loop; returns (state, last metrics)."""
    return run_token_loop(build_ep_train_setup(cfg, mesh), cfg, steps, quiet,
                          tag="ep", profile_dir=profile_dir)
