"""Coded data parallelism × tensor parallelism: the (w, tp) GSPMD step.

Megatron-style tensor parallelism for the TransformerLM, expressed the
TPU-native way: parameters carry ``NamedSharding`` annotations over mesh
axis ``tp`` (column-parallel qkv/mlp_in, row-parallel proj/mlp_out) and the
training step is ONE plain ``jit`` — no manual collectives, no shard_map;
XLA's SPMD partitioner inserts the all-reduces at the row-parallel
boundaries and shards every matmul. This is deliberately the other
idiomatic-JAX parallelism style from the ``sp`` path (sp_step.py uses
explicit shard_map + ppermute/all_to_all; this path uses sharding
propagation), so the framework demonstrates both.

Composition with Draco (SURVEY.md §2.3): per-worker gradients inherit the
``tp`` shardings leaf-by-leaf; flattening to the (n, d) gradient matrix
re-lays them out over ``w`` (XLA inserts the tp-gather), and the coding /
robust-aggregation machinery is unchanged. After the update the new
parameters are constrained back onto their ``tp`` shards.

No reference counterpart (the reference is CNN-only, single-axis DP);
this axis is part of the TPU build's scale-out surface.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from draco_tpu import optim, rng as drng
from draco_tpu.coding import cyclic as cyclic_mod
from draco_tpu.config import TrainConfig
from draco_tpu.models.transformer import TransformerLM
from draco_tpu.parallel.common import (
    TOKEN_METRIC_NAMES,
    aggregate_flat_grads,
    build_code_from_cfg,
    finish_flat_step,
    decode_health_metrics,
    make_token_train_many,
    masked_loss_metric,
    token_metric_names,
)
from draco_tpu.parallel.mesh import TP_AXIS
from draco_tpu.parallel.partition import (
    REPLICATED,
    TP_STEP_RULES,
    WORKER_ROWS,
    WORKER_ROWS3,
    norm_spec,
    override,
    sharding,
)
# re-export: historical home
from draco_tpu.parallel.token_loop import run_token_loop  # noqa: F401
from draco_tpu.runtime import WORKER_AXIS
from draco_tpu.training.step import TrainState, _flatten_tree, _make_unravel


class TPTrainSetup(NamedTuple):
    model: TransformerLM
    state: TrainState
    # (state, tokens (n,B,T), adv_mask (n,)) -> (state, metrics)
    train_step: any
    eval_step: any  # (params, tokens) -> loss
    code: Optional[cyclic_mod.CyclicCode]
    unravel: any
    dim: int
    # K fused LM steps in ONE device program (parallel/common.py):
    # (state, toks (K,n,B,T) | steps (K,), masks (K,n), presents (K,n)|None)
    #   -> (state, metrics (K, len(metric_names)) float32)
    train_token_many: any = None
    metric_names: tuple = TOKEN_METRIC_NAMES


def param_partition_spec(path) -> P:
    """Megatron partitioning by parameter name.

    Column-parallel (output dim sharded): ``qkv``, ``mlp_in``.
    Row-parallel (input dim sharded): ``proj``, ``mlp_out`` — XLA inserts
    the psum over ``tp`` where their outputs meet the residual stream.
    Everything 1-D or shared (embeddings, layer norms, biases of
    row-parallel layers) stays replicated.
    """
    names = [getattr(k, "key", str(k)) for k in path]
    leaf = names[-1]
    layer = names[-2] if len(names) >= 2 else ""
    if leaf == "kernel" and layer in ("qkv", "mlp_in"):
        spec = (None, TP_AXIS)
    elif leaf == "kernel" and layer in ("proj", "mlp_out"):
        spec = (TP_AXIS, None)
    elif leaf == "bias" and layer == "mlp_in":
        spec = (TP_AXIS,)
    else:
        return P()
    # scan_layers stacks block params under a "blocks" subtree with a
    # leading layer axis — the Megatron dims shift right by one
    if "blocks" in names:
        spec = (None,) + spec
    return P(*spec)


# The trailing-None spec normalizer this route's PR 6 fix introduced now
# lives in parallel/partition.norm_spec (the canonical copy every route
# and the static sharding auditor share); re-exported under the old name
# for the retrace-regression tests.
_norm_spec = norm_spec


def shard_params(params, mesh, partition_fn=param_partition_spec):
    """Annotate a parameter pytree with its (w-replicated, mp-sharded)
    placement."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x: jax.device_put(
            x, NamedSharding(mesh, _norm_spec(partition_fn(path)))
        ),
        params,
    )


def _constrain_params(params, mesh, partition_fn):
    return jax.tree_util.tree_map_with_path(
        lambda path, x: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, _norm_spec(partition_fn(path)))
        ),
        params,
    )


def build_tp_train_setup(cfg: TrainConfig, mesh) -> TPTrainSetup:
    """mesh must have axes (w, tp) — see make_mesh_wtp."""
    # experts honoured even at tensor_shards=1 (validate() forbids MoE with
    # tensor_shards>1; at 1 shard the tp rules just replicate expert params)
    return _build_gspmd_train_setup(
        cfg, mesh, mp_axis=TP_AXIS, mp_size=max(cfg.tensor_shards, 1),
        partition_fn=param_partition_spec, experts=cfg.moe_experts,
    )


def _build_gspmd_train_setup(cfg: TrainConfig, mesh, *, mp_axis: str,
                             mp_size: int, partition_fn,
                             experts: int) -> TPTrainSetup:
    """Shared GSPMD builder for the sharding-annotation model-parallel paths
    (tensor parallelism here; expert parallelism in ep_step.py). The paths
    differ only in the mesh axis, the parameter partition rules, and the
    model's expert count."""
    cfg.validate()
    if cfg.approach not in ("baseline", "cyclic", "approx"):
        raise ValueError(
            f"MP path supports baseline|cyclic|approx, got {cfg.approach}")
    n = cfg.num_workers
    # logical workers fold onto the available w-axis devices in equal blocks
    # (same discipline as runtime.make_mesh for the CNN path) — a single
    # chip can still run the n-lane coded step, vmapped
    if n % mesh.shape[WORKER_AXIS]:
        raise ValueError(
            f"num_workers {n} must be a multiple of the mesh's w axis "
            f"({mesh.shape[WORKER_AXIS]})"
        )
    # the mesh defines the actual mp shard count — it must be the one the
    # config's divisibility checks validated, or GSPMD silently pads
    if mesh.shape[mp_axis] != mp_size:
        raise ValueError(
            f"mesh {mp_axis} axis is {mesh.shape[mp_axis]} but the config "
            f"requests {mp_size} shards"
        )

    cdtype = jnp.dtype(cfg.compute_dtype)
    # flash applies in the folded (tp=1) regime the perf/convergence tools
    # run in; real tensor sharding with flash is rejected by cfg.validate()
    # (GSPMD cannot partition the opaque pallas_call across head shards —
    # the sp paths compose it explicitly instead, sp_step.py)
    from draco_tpu.ops.flash_attention import attn_impl_fn

    attn_fn = attn_impl_fn(cfg) if mp_size == 1 else None
    model = TransformerLM(
        vocab=cfg.vocab, dim=cfg.model_dim, heads=cfg.model_heads,
        layers=cfg.model_layers, attn_fn=attn_fn, experts=experts,
        dtype=cdtype, remat=cfg.remat, scan_layers=cfg.scan_layers,
    )
    root = jax.random.key(cfg.seed)
    init_toks = jnp.zeros((1, min(cfg.seq_len, 8)), jnp.int32)
    params = model.init({"params": root}, init_toks, train=True)["params"]

    opt = optim.build_optimizer_from_cfg(cfg)
    unravel, dim, leaf_offsets = _make_unravel(params)

    repl = sharding(mesh, REPLICATED)
    shard_w = sharding(mesh, WORKER_ROWS)
    params = shard_params(params, mesh, partition_fn)
    # opt.init is zeros_like on the sharded params, so the slots inherit
    # the tp layout with no host round-trip (multi-host safe) — but its
    # bookkeeping scalars (schedule count, sgd's initialized flag) come out
    # as fresh single-device arrays. Live they are uncommitted and jit
    # transfers them freely; an Orbax restore however round-trips them
    # COMMITTED to device 0, which jit then rejects next to the
    # mesh-committed params — pin them mesh-replicated up front so the
    # checkpoint template carries a placement that restores clean.
    opt_state = jax.tree.map(
        lambda x: x
        if isinstance(getattr(x, "sharding", None), NamedSharding)
        else jax.device_put(x, repl),
        opt.init(params),
    )
    state = TrainState(
        params=params,
        opt_state=opt_state,
        batch_stats=None,
        step=jax.device_put(jnp.asarray(1, jnp.int32), repl),
    )
    # pin the step's output opt state to the carry's INPUT layout: left
    # unconstrained, GSPMD is free to reshard momentum buffers on the
    # first execution (e.g. a replicated LayerNorm-scale slot coming back
    # tp-sharded), and the K-fused program then RETRACES on its second
    # dispatch against the drifted shardings (_norm_spec docstring)
    opt_shardings = jax.tree.map(lambda x: x.sharding, state.opt_state)
    constrain_opt = lambda o: jax.tree.map(  # noqa: E731
        jax.lax.with_sharding_constraint, o, opt_shardings)

    def lane_loss(params, toks, train: bool):
        """Whole-sequence next-token CE for one worker's (B, T) batch.

        The model sees all T tokens and the last logit row is discarded
        (identical math on the dense/flash attention paths: causal row i
        attends keys <= i, so rows < T-1 cannot see token T-1). Feeding
        toks[:, :-1] instead would hand the attention a T-1-length
        sequence (1023 at T=1024), which fails the flash kernel's t%8
        tiling and silently rode the dense fallback — the kernel never
        actually ran on the LM path before this.

        Deliberate deviation when moe_experts > 0: Switch capacity
        routing (models/moe.py) is cross-token over the flattened B*T
        stream, so the now-included last-position tokens compete for
        arrival-order capacity slots. cap = int(1.25*n_tok/e) scales with
        the stream, so capacity pressure is ~unchanged, but individual
        evictions can differ from the pre-change B*(T-1) stream — a
        routing-statistics perturbation of order 1/T, not an objective
        change (and matches inference, where the last token routes too)."""
        logits = model.apply({"params": params}, toks, train=train)[:, :-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, toks[:, 1:, None], axis=-1)[..., 0]
        return jnp.mean(nll)

    code = build_code_from_cfg(cfg)
    # reference-parity r× redundant compute: each worker really evaluates
    # its hat_s = 2s+1 assigned batch rows (cyclic_worker.py:122-146); the
    # "shared" fast path computes each row once and forms encoded rows
    # algebraically (identical semantics — per-batch gradients are
    # deterministic under XLA)
    simulate = cfg.approach == "cyclic" and cfg.redundancy == "simulate"
    batch_ids = jnp.asarray(code.batch_ids) if simulate else None
    shard_w3 = sharding(mesh, WORKER_ROWS3)

    def step_body(state: TrainState, tokens, adv_mask, present=None):
        def lane(toks):
            loss, g = jax.value_and_grad(lane_loss)(state.params, toks, True)
            return _flatten_tree(g), loss

        with jax.named_scope("draco_comp"):
            if simulate:
                toks_w = tokens[batch_ids]  # (n, hat_s, B, T) redundant rows
                # (n, hat_s, d)
                grads, losses = jax.vmap(jax.vmap(lane))(toks_w)
                grads = jax.lax.with_sharding_constraint(grads, shard_w3)
                losses = jnp.mean(losses, axis=1)
            else:
                grads, losses = jax.vmap(lane)(tokens)  # (n, d), (n,)
                grads = jax.lax.with_sharding_constraint(grads, shard_w)
        # decode projection generated in-graph from the scalar seed — a
        # closed-over (d,) constant serializes into the program (638 MB at
        # d~159M: the remote-compile ceiling, rng.py docstring); the approx
        # decode is projection-free
        rand_factor = (drng.random_projection_factors_in_graph(cfg.seed, dim)
                       if cfg.approach == "cyclic" else None)
        agg, health = aggregate_flat_grads(grads, adv_mask, cfg, code,
                                           rand_factor, present=present,
                                           leaf_offsets=leaf_offsets,
                                           step=state.step)
        new_state, guard_cols = finish_flat_step(
            cfg, state, agg, health, opt, unravel, present=present,
            constrain=lambda p: _constrain_params(p, mesh, partition_fn),
            constrain_opt=constrain_opt,
        )
        metrics = {"loss": masked_loss_metric(losses, present)}
        metrics.update(decode_health_metrics(health, adv_mask, present))
        metrics.update(guard_cols)
        return new_state, metrics

    def eval_body(params, tokens):
        return jnp.mean(
            jax.vmap(lambda t: lane_loss(params, t, False))(tokens))

    from draco_tpu.parallel.sp_step import token_fn_from_cfg

    metric_names = token_metric_names(cfg)
    # the carry's layout is pinned at the JIT boundary: out_shardings for
    # the state output = the state input's shardings. A with_sharding_
    # constraint inside the scanned body does not win the scan carry's
    # unified layout — GSPMD still resharded replicated momentum slots to
    # tp-sharded on the real tp mesh, and the second dispatch then
    # retraced against the drifted input (_norm_spec docstring). The
    # boundary pin makes state-in == state-out by construction (and lets
    # donation alias cleanly). The metrics output stays compiler-chosen.
    state_shardings = jax.tree.map(lambda x: x.sharding, state)
    with mesh:
        train_step = jax.jit(step_body, donate_argnums=(0,),
                             out_shardings=(state_shardings, None))
        eval_step = jax.jit(eval_body)
        train_token_many = jax.jit(
            make_token_train_many(step_body, token_fn_from_cfg(cfg),
                                  metric_names=metric_names),
            donate_argnums=(0,),
            out_shardings=(state_shardings, None),
        )

    return TPTrainSetup(
        model=model, state=state, train_step=train_step, eval_step=eval_step,
        code=code, unravel=unravel, dim=dim,
        train_token_many=train_token_many, metric_names=metric_names,
    )


# ---- program-lint registration (draco_tpu/analysis) -----------------------


def lint_programs():
    """The GSPMD tensor-parallel route's chip-bound programs, plus the
    folded single-shard regime every perf/convergence tool runs in.

    All-zero explicit-collective manifests are the POINT here: this route
    is pure sharding propagation (module docstring) — the tp all-reduces
    exist only after the XLA SPMD partitioner runs, so any explicit
    collective in the exported module means shard_map leaked in.

    ``lm_fold_big_bf16_many_k2`` is the constant-bloat guard at a d where a
    closed-over (d,) constant would dominate (d ≈ 3.3 M → +13 MB against a
    ~0.2 MB honest module): the round-5 wedge generalized from
    tests/test_program_size.py to the production K-fused program. It builds
    a real 3.3M-param state, so it is not in the --fast subset, and exports
    for cpu (its rule is serialized bytes, not TPU lowering).
    """
    from draco_tpu.analysis.registry import (
        BF16_DTYPES, LintProgram, Manifest, built_token_program,
        ci_lm_config,
    )
    from draco_tpu.parallel.mesh import make_folded_wtp_mesh, make_mesh_wtp

    # the devgen program's token input is the (K,) step vector, not a
    # host batch — it rides replicated (partition.override docstring)
    devgen_rules = override(TP_STEP_RULES, (r"^tokens$", REPLICATED))

    def _tp2(name, many, **overrides):
        cfg = ci_lm_config(tensor_shards=2, **overrides)
        mesh = make_mesh_wtp(4, 2)  # 8 CI devices; n=8 folds 2 lanes/device
        setup = build_tp_train_setup(cfg, mesh)
        return built_token_program(name, cfg, mesh, setup,
                                   Manifest(collectives={},
                                            collective_axes={}),
                                   many=many,
                                   partition_rules=TP_STEP_RULES)

    def _fold(name, many, **overrides):
        cfg = ci_lm_config(tensor_shards=1, **overrides)
        mesh = make_folded_wtp_mesh(cfg.num_workers)
        setup = build_tp_train_setup(cfg, mesh)
        allowed = (BF16_DTYPES if cfg.compute_dtype == "bfloat16"
                   else Manifest.allowed_dtypes)
        rules = (devgen_rules if cfg.token_gen == "device"
                 else TP_STEP_RULES)
        return built_token_program(
            name, cfg, mesh, setup,
            Manifest(collectives={}, collective_axes={},
                     allowed_dtypes=allowed), many=many,
            partition_rules=rules)

    def _fold_big(name):
        cfg = ci_lm_config(
            tensor_shards=1, compute_dtype="bfloat16", remat=True,
            seq_len=64, vocab=512, model_dim=256, model_heads=4,
            model_layers=4, batch_size=1,
        )
        mesh = make_folded_wtp_mesh(cfg.num_workers)
        setup = build_tp_train_setup(cfg, mesh)
        if setup.dim < 3_000_000:  # guard only meaningful if d is CI-large
            raise ValueError(
                f"big-d lint program built d={setup.dim} < 3M — the "
                f"constant-bloat guard no longer covers a d-dominating "
                f"constant; grow the config")
        # a closed-over (d,) f32 would add 4*d bytes; the honest program is
        # a few hundred KB. 2*d sits far from both (test_program_size
        # lineage).
        manifest = Manifest(collectives={}, collective_axes={},
                            allowed_dtypes=BF16_DTYPES,
                            max_module_bytes=2 * setup.dim,
                            max_constant_bytes=1 << 20)
        return built_token_program(name, cfg, mesh, setup, manifest,
                                   many=True, partition_rules=TP_STEP_RULES)

    mk = lambda name, build, **kw: LintProgram(  # noqa: E731
        name=name, route="tp", build=build, **kw)
    return [
        mk("lm_tp2_step", lambda: _tp2("lm_tp2_step", False)),
        mk("lm_tp2_many_k2", lambda: _tp2("lm_tp2_many_k2", True)),
        mk("lm_fold_bf16_step",
           lambda: _fold("lm_fold_bf16_step", False,
                         compute_dtype="bfloat16")),
        # the production chunked driver with the in-graph token stream: the
        # program whose whole input is K int32 scalars (token_loop.py)
        mk("lm_fold_devgen_many_k2",
           lambda: _fold("lm_fold_devgen_many_k2", True, token_gen="device",
                         steps_per_call=2)),
        # guarded production program (ISSUE 6): the in-graph step guard on
        # the GSPMD route — still zero explicit collectives, no host traffic
        mk("lm_tp2_many_guard_k2",
           lambda: _tp2("lm_tp2_many_guard_k2", True, step_guard="on")),
        # the approx family on the real tp mesh, xla + fused decode
        # lowerings (ISSUE 12): the optimal-decoding tail must stay pure
        # GSPMD under BOTH impls (zero explicit collectives, donation,
        # zero host traffic); these are the device-profile join rows for
        # the lm_tp_approx_k4 / lm_tp_approx_pallas_k4 claim cells.
        # fast=False: impl/family variants of the fast-swept tp rows —
        # the full tool covers them without growing the --fast budget
        mk("lm_tp2_approx_many_k2",
           lambda: _tp2("lm_tp2_approx_many_k2", True, approach="approx",
                        worker_fail=0, code_redundancy=1.5,
                        step_guard="on"),
           fast=False),
        mk("lm_tp2_approx_pallas_many_k2",
           lambda: _tp2("lm_tp2_approx_pallas_many_k2", True,
                        approach="approx", worker_fail=0,
                        code_redundancy=1.5, step_guard="on",
                        decode_impl="pallas"),
           fast=False),
        mk("lm_fold_big_bf16_many_k2",
           lambda: _fold_big("lm_fold_big_bf16_many_k2"),
           fast=False, export_platforms=("cpu",)),
    ]


def train_tp(cfg: TrainConfig, mesh, steps: Optional[int] = None,
             quiet: bool = False, profile_dir: Optional[str] = None):
    """TP training loop; returns (state, last metrics)."""
    return run_token_loop(build_tp_train_setup(cfg, mesh), cfg, steps, quiet,
                          profile_dir=profile_dir,
                          tag="tp")
