"""Shared tail of the flat-gradient training paths (sp_step / tp_step):
attack injection → coded decode or robust aggregation → optimizer update.

One implementation so a fix to injection, decode, or the update convention
cannot silently diverge between the parallelism paths. (The CNN path in
training/step.py keeps its own tail: it additionally handles straggler
presence masks, layer-granularity decode, and per-worker batch stats.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from draco_tpu import aggregation, attacks
from draco_tpu.coding import cyclic as cyclic_mod


def aggregate_flat_grads(grads: jnp.ndarray, adv_mask, cfg, code, rand_factor,
                         present=None, leaf_offsets=None):
    """(n, d) per-worker flat gradients → one aggregated (d,) gradient.

    cyclic: shared-redundancy encode, adversarial injection on the encoded
    rows, exact decode. Otherwise: injection on the raw rows, then the
    configured robust aggregation (mean / geo-median / krum).

    ``present`` ((n,) bool, optional): straggler rows marked False never
    arrive — cyclic decodes around them as erasures (known-missing, one
    redundancy unit each), the robust rules aggregate over present rows
    only. Same semantics as the CNN path (training/step.py).

    ``leaf_offsets``: static per-tensor segment boundaries from
    _make_unravel — required when ``cfg.decode_granularity == "layer"`` so
    the cyclic decode runs one locator per parameter tensor like the
    reference (cyclic_master.py:125-129), matching the CNN path.
    """
    if cfg.approach == "cyclic":
        if grads.ndim == 3:
            # (n, hat_s, d): true per-worker redundant lanes
            # (cfg.redundancy == "simulate" — the reference's r× compute,
            # cyclic_worker.py:122-146); each worker encodes its own rows
            enc_re, enc_im = cyclic_mod.encode(code, grads)
        else:
            # (n, d): one-copy batch gradients, rows formed algebraically
            # (cfg.redundancy == "shared", the TPU-native fast path)
            enc_re, enc_im = cyclic_mod.encode_shared(code, grads)
        enc_re, enc_im = attacks.inject_cyclic(
            enc_re, enc_im, adv_mask, cfg.err_mode, cfg.adversarial
        )
        if present is not None:
            pw = present[:, None].astype(enc_re.dtype)
            enc_re, enc_im = enc_re * pw, enc_im * pw
        if cfg.decode_granularity == "layer":
            if leaf_offsets is None:
                raise ValueError(
                    "decode_granularity='layer' needs leaf_offsets from "
                    "_make_unravel"
                )
            agg, _honest = cyclic_mod.decode_layers(
                code, enc_re, enc_im, rand_factor, leaf_offsets,
                present=present,
            )
        else:
            agg, _honest = cyclic_mod.decode(code, enc_re, enc_im,
                                             rand_factor, present=present)
        return agg
    grads = attacks.inject_plain(grads, adv_mask, cfg.err_mode, cfg.adversarial,
                                 n_mal=cfg.num_adversaries)
    return aggregation.aggregate(
        grads, cfg.mode, s=cfg.worker_fail,
        geomedian_iters=cfg.geomedian_iters, present=present,
    )


def masked_loss_metric(losses, present):
    """Mean loss over received rows only — a straggler's loss was never
    observed (mirrors the CNN path's _metrics, training/step.py)."""
    if present is None:
        return jnp.mean(losses)
    w = present.astype(losses.dtype)
    return jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1.0)


def apply_flat_update(state, agg: jnp.ndarray, opt, unravel):
    """Aggregated flat gradient → (new_params, new_opt_state) via the
    grads-as-argument optimizer convention (reference sgd_modified.py:53)."""
    grads_tree = unravel(agg)
    updates, new_opt = opt.update(grads_tree, state.opt_state, state.params)
    new_params = jax.tree.map(lambda p, u: p + u, state.params, updates)
    return new_params, new_opt


# column order of the (K, m) metric block train_token_many returns — the LM
# step bodies emit exactly one scalar metric today; extend here (and in every
# step_body) if the routes ever grow more
TOKEN_METRIC_NAMES = ("loss",)


def make_token_train_many(step_body, token_fn=None,
                          metric_names=TOKEN_METRIC_NAMES):
    """K fused LM coded steps in ONE ``lax.scan`` — the token-route analogue
    of the CNN path's ``train_many`` (training/step.py).

    ``step_body(state, tokens, adv_mask, present) -> (state, metrics)`` is
    any route's single-step body (sp/tp/ep share the flat-gradient tail in
    this module; pp brings its pipeline schedule). The returned
    ``many_body(state, tokens, masks, presents)`` scans it over the leading
    K axis of every operand and stacks the per-step metrics into a (K, m)
    float32 block the host fetches once per flush window. ``presents=None``
    threads through as an empty pytree, exactly like ``train_many``.

    ``token_fn`` (optional): in-graph token generator ``step -> (n, B, T)``
    (cfg.token_gen == "device"). When set, the first scanned operand is the
    (K,) int32 step-index vector instead of the (K, n, B, T) token block —
    the host uploads K scalars per chunk and the device synthesizes the
    tokens itself, the same closed-over-constant-free discipline as
    rng.random_projection_factors_in_graph.

    Callers jit with ``donate_argnums=(0,)`` inside the route's mesh context
    so the K-step state carry reuses the input buffers.
    """

    def many_body(state, tokens, masks, presents):
        def body(st, operand):
            toks, adv_mask, present = operand
            if token_fn is not None:
                toks = token_fn(toks)
            st, metrics = step_body(st, toks, adv_mask, present)
            row = jnp.stack(
                [jnp.asarray(metrics[k], jnp.float32) for k in metric_names]
            )
            return st, row

        return jax.lax.scan(body, state, (tokens, masks, presents))

    return many_body
