"""Shared tail of the flat-gradient training paths (sp_step / tp_step):
attack injection → coded decode or robust aggregation → optimizer update.

One implementation so a fix to injection, decode, or the update convention
cannot silently diverge between the parallelism paths. (The CNN path in
training/step.py keeps its own tail: it additionally handles straggler
presence masks, layer-granularity decode, and per-worker batch stats.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from draco_tpu import aggregation, attacks
from draco_tpu.coding import approx as approx_mod
from draco_tpu.coding import cyclic as cyclic_mod


def build_code_from_cfg(cfg):
    """The route-shared code constructor: CyclicCode for approach="cyclic",
    ApproxCode for "approx", None otherwise — one place so the CNN path and
    every LM route build the identical code from a config. Under
    ``topology == "tree"`` (ISSUE 17) the constructor returns a TreeCode
    wrapping ONE small group code at the (fanout, s_g) shape — the
    aggregation tails below dispatch on the code type, so every route gets
    the hierarchical path through the same seam."""
    if (cfg.approach in ("cyclic", "approx")
            and getattr(cfg, "topology", "flat") == "tree"):
        from draco_tpu.coding import topology as topology_mod

        return topology_mod.build_tree_code(cfg)
    if cfg.approach == "cyclic":
        return cyclic_mod.build_cyclic_code(cfg.num_workers, cfg.worker_fail)
    if cfg.approach == "approx":
        return approx_mod.build_approx_code(
            cfg.num_workers, cfg.code_redundancy, cfg.assignment_scheme)
    return None


def _is_tree(code) -> bool:
    """Code-type dispatch for the aggregation tails (lazy import so the
    flat path's import graph is untouched)."""
    from draco_tpu.coding import topology as topology_mod

    return isinstance(code, topology_mod.TreeCode)


def segment_decode_bounds(cfg, dim: int, leaf_offsets=None):
    """The decode partition the streaming segmented wire induces (ISSUE
    16): the quantum-aligned segment cuts (obs/numerics.cfg_segment_bounds
    — THE bounds source the ledger and tools share), refined by the static
    leaf boundaries when the decode runs at layer granularity so every
    parameter tensor keeps its own locator."""
    from draco_tpu.obs import numerics as numerics_mod

    bounds = list(numerics_mod.cfg_segment_bounds(cfg, dim))
    if leaf_offsets is not None:
        cuts = sorted({int(o) for o in leaf_offsets}
                      | {int(b) for b in bounds})
        bounds = [c for c in cuts if 0 <= c <= dim]
    return bounds


def approx_aggregate(code, grads: jnp.ndarray, present=None, constrain=None,
                     cfg=None, adv_mask=None, step=None):
    """The approx family's whole aggregation sequence — ingest forensics →
    weighted-partial-sum encode → present mask → optimal-decoding partial
    recovery → residual-vs-bound health — in ONE place, shared by the CNN
    step body (training/step.py) and the LM routes' flat-gradient tail
    below, so the accusation/masking semantics cannot drift between loops.

    No adversary injection: config.validate rejects live adversaries under
    this family (no Byzantine certificate); stragglers are the fault model
    and the only per-worker accusation signal is the non-finite ingest
    check. ``constrain``: optional sharding-constraint hook applied to the
    encoded (n, d) rows (the CNN path pins them to the worker axis).

    ``cfg``/``adv_mask``/``step`` (optional, passed by both call sites):
    enable the numerics observatory (obs/numerics.py, ISSUE 10) — dynamic-
    range columns for grads/wire/aggregate and the shadow-quantized decode
    — stashed under ``health["watch"]`` for ``decode_health_metrics`` to
    merge into the metric row. Identity (no added ops) when the watch is
    off."""
    from draco_tpu.obs import forensics as forensics_mod
    from draco_tpu.obs import numerics as numerics_mod
    from draco_tpu.ops.decode_kernels import resolve_decode_impl

    decode_impl = resolve_decode_impl(
        getattr(cfg, "decode_impl", "xla") if cfg is not None else "xla")
    tree = _is_tree(code)
    bad_rows = forensics_mod.nonfinite_rows(grads)
    with jax.named_scope("draco_encode"):
        if tree:
            from draco_tpu.coding import topology as topology_mod

            rows = topology_mod.encode_tree(code, grads)
        else:
            rows = approx_mod.encode_shared(code, grads)
        if present is not None:
            rows = jnp.where(jnp.asarray(present).astype(bool)[:, None],
                             rows, jnp.zeros_like(rows))
        # the REAL narrow wire (ISSUE 15): quantize the partial-sum rows
        # into narrow buffers — THE arrays that cross the sharding
        # boundary — and widen to f32 only for the decode; identity (no
        # ops) on the f32 wire
        wire = None
        if cfg is not None and getattr(cfg, "wire_dtype", "f32") != "f32":
            rows, wire = numerics_mod.narrow_wire_single(
                cfg, rows, step=step, constrain=constrain)
        elif constrain is not None:
            rows = constrain(rows)
    segments = (int(getattr(cfg, "wire_segments", 1))
                if cfg is not None else 1)
    with jax.named_scope("draco_decode"):
        if tree:
            # hierarchical tree aggregation (ISSUE 17): per-group optimal
            # decoding at the (g, d) block, level-structured combine, root
            # residual + Cauchy-Schwarz-folded bound (decode_tree_approx)
            from draco_tpu.coding import topology as topology_mod

            bounds = (numerics_mod.cfg_segment_bounds(
                cfg, int(rows.shape[-1])) if segments > 1 else None)
            agg, _v, health = topology_mod.decode_tree_approx(
                code, rows, present=present, batch_grads=grads,
                impl=decode_impl, wire=wire, bounds=bounds)
        elif segments > 1:
            # streaming segmented wire (ISSUE 16): the presence-only
            # weight solve runs once; each segment combines on arrival and
            # the residual accumulators fold to one per-step verdict
            bounds = numerics_mod.cfg_segment_bounds(
                cfg, int(rows.shape[-1]))
            agg, _v, health = approx_mod.decode_segments(
                code, rows, bounds, present=present, with_health=True,
                batch_grads=grads, impl=decode_impl, wire=wire)
        else:
            agg, _v, health = approx_mod.decode(
                code, rows, present=present, with_health=True,
                batch_grads=grads, impl=decode_impl, wire=wire)
    health["bad_rows"] = bad_rows
    if cfg is not None:
        from draco_tpu.obs import numerics as numerics_mod

        if numerics_mod.watch_enabled(cfg):
            watch = {}
            if cfg.numerics_watch == "on":
                watch.update(numerics_mod.numerics_columns(
                    cfg, [grads], [rows], agg))
            if cfg.shadow_wire != "off":
                amask = (jnp.zeros((code.n,), bool) if adv_mask is None
                         else adv_mask)
                watch.update(numerics_mod.approx_shadow(
                    cfg, code, rows, grads, agg, present, amask, step))
            health["watch"] = watch
    return agg, health


def aggregate_flat_grads(grads: jnp.ndarray, adv_mask, cfg, code, rand_factor,
                         present=None, leaf_offsets=None, step=None):
    """(n, d) per-worker flat gradients → ``(aggregated (d,), health)``.

    ``step`` (optional traced scalar): the training step, threaded so the
    deterministic fault plan (``cfg.fault_spec``,
    resilience/faults.corrupt_grads) can inject its in-graph NaN/Inf
    worker-gradient faults — identity (no added ops) when no plan is
    configured.

    cyclic: shared-redundancy encode, adversarial injection on the encoded
    rows, exact decode — ``health`` is the in-graph decode-health dict
    (coding/cyclic.decode ``with_health``: scalar ``residual`` ≈ 0 iff the
    decode is self-consistent, (n,) bool ``flagged`` of located-error
    rows). Otherwise: injection on the raw rows, then the configured robust
    aggregation (mean / geo-median / krum) — approximate rules carry no
    exactness certificate, so ``health`` is None and the telemetry layer
    emits no decode-health columns for them.

    ``present`` ((n,) bool, optional): straggler rows marked False never
    arrive — cyclic decodes around them as erasures (known-missing, one
    redundancy unit each), the robust rules aggregate over present rows
    only. Same semantics as the CNN path (training/step.py).

    ``leaf_offsets``: static per-tensor segment boundaries from
    _make_unravel — required when ``cfg.decode_granularity == "layer"`` so
    the cyclic decode runs one locator per parameter tensor like the
    reference (cyclic_master.py:125-129), matching the CNN path.

    The encode/decode phases run under ``jax.named_scope`` so XProf device
    traces group ops by Draco's reference phase names (the device-side
    counterpart of the host SpanTracer, draco_tpu/obs).
    """
    from draco_tpu.obs import forensics as forensics_mod
    from draco_tpu.resilience import faults as faults_mod

    grads = faults_mod.corrupt_grads(grads, cfg, step)
    if cfg.approach == "approx":
        # approximate family (coding/approx.py; ISSUE 8): the shared
        # sequence above — health is the residual-vs-bound certificate
        return approx_aggregate(code, grads, present=present, cfg=cfg,
                                adv_mask=adv_mask, step=step)
    if cfg.approach == "cyclic":
        # ingest-row health, BEFORE encode: a non-finite per-worker gradient
        # row attributes to its worker here, where row k still means worker
        # k — the shared-redundancy encode below smears any NaN across every
        # codeword (0·NaN = NaN in the masked matmul), so the wire rows
        # cannot (obs/forensics.nonfinite_rows docstring)
        bad_rows = forensics_mod.nonfinite_rows(grads)
        tree = _is_tree(code)
        with jax.named_scope("draco_encode"):
            if tree:
                # hierarchical tree encode (ISSUE 17): each leaf group
                # encodes with the ONE shared small code — rows stay
                # worker-indexed (n, d), so injection/presence/wire below
                # are byte-identical to flat
                from draco_tpu.coding import topology as topology_mod

                enc_re, enc_im = topology_mod.encode_tree(code, grads)
            elif grads.ndim == 3:
                # (n, hat_s, d): true per-worker redundant lanes
                # (cfg.redundancy == "simulate" — the reference's r× compute,
                # cyclic_worker.py:122-146); each worker encodes its own rows
                enc_re, enc_im = cyclic_mod.encode(code, grads)
            else:
                # (n, d): one-copy batch gradients, rows formed algebraically
                # (cfg.redundancy == "shared", the TPU-native fast path)
                enc_re, enc_im = cyclic_mod.encode_shared(code, grads)
            enc_re, enc_im = attacks.inject_cyclic(
                enc_re, enc_im, adv_mask, cfg.err_mode, cfg.adversarial,
                step=step, seed=cfg.seed
            )
            if present is not None:
                pw = present[:, None].astype(enc_re.dtype)
                enc_re, enc_im = enc_re * pw, enc_im * pw
        from draco_tpu.obs import numerics as numerics_mod
        from draco_tpu.ops.decode_kernels import resolve_decode_impl

        decode_impl = resolve_decode_impl(cfg.decode_impl)
        # the REAL narrow wire (ISSUE 15): the codeword pair is rounded
        # into narrow buffers that cross the sharding boundary; the decode
        # widens to f32 and runs the quantization-aware flag threshold +
        # Tikhonov-regularized locator. Identity on the f32 wire.
        enc_re, enc_im, wire = numerics_mod.narrow_wire_pair(
            cfg, enc_re, enc_im, step=step)
        if tree:
            # the tree decodes each leaf group at the GROUP shape — its
            # narrow-wire thresholds come from the (fanout, s_g) table row
            wire_tol, wire_lam = numerics_mod.wire_decode_params(
                cfg, n=code.plan.fanout, s=code.group_code.s)
        else:
            wire_tol, wire_lam = numerics_mod.wire_decode_params(cfg)
        rel_tol = (cyclic_mod.HEALTH_REL_TOL if wire_tol is None
                   else wire_tol)
        segments = int(getattr(cfg, "wire_segments", 1))
        with jax.named_scope("draco_decode"):
            if tree:
                # hierarchical decode (ISSUE 17): per-group small-n decode
                # (segmented when the streaming wire is on), level-
                # structured combine, PR 16-style health fold — same
                # health keys as flat, so every consumer below is shared
                from draco_tpu.coding import topology as topology_mod

                bounds = (numerics_mod.cfg_segment_bounds(
                    cfg, int(grads.shape[-1])) if segments > 1 else None)
                agg, _honest, health = topology_mod.decode_tree_cyclic(
                    code, enc_re, enc_im, rand_factor, present=present,
                    rel_tol=rel_tol, impl=decode_impl, lam=wire_lam,
                    wire=wire, bounds=bounds)
            elif cfg.decode_granularity == "layer":
                if leaf_offsets is None:
                    raise ValueError(
                        "decode_granularity='layer' needs leaf_offsets from "
                        "_make_unravel"
                    )
                if segments > 1:
                    # streaming segmented wire (ISSUE 16) at layer
                    # granularity: the decode partition is the REFINEMENT
                    # of the leaf boundaries by the quantum-aligned segment
                    # cuts — every layer still gets (at least) its own
                    # locator, and the health fold is unchanged (max /
                    # union over a finer partition)
                    bounds = segment_decode_bounds(cfg, int(grads.shape[-1]),
                                                   leaf_offsets)
                    agg, _honest, health = cyclic_mod.decode_segments(
                        code, enc_re, enc_im, rand_factor, bounds,
                        present=present, with_health=True, impl=decode_impl,
                        rel_tol=rel_tol, lam=wire_lam, wire=wire)
                else:
                    agg, _honest, health = cyclic_mod.decode_layers(
                        code, enc_re, enc_im, rand_factor, leaf_offsets,
                        present=present, with_health=True, impl=decode_impl,
                        rel_tol=rel_tol, lam=wire_lam,
                    )
            elif segments > 1:
                # streaming segmented wire (ISSUE 16): per-segment
                # syndromes/locators, one folded verdict per step
                from draco_tpu.obs import numerics as numerics_mod

                bounds = numerics_mod.cfg_segment_bounds(
                    cfg, int(grads.shape[-1]))
                agg, _honest, health = cyclic_mod.decode_segments(
                    code, enc_re, enc_im, rand_factor, bounds,
                    present=present, with_health=True, impl=decode_impl,
                    rel_tol=rel_tol, lam=wire_lam, wire=wire)
            else:
                agg, _honest, health = cyclic_mod.decode(
                    code, enc_re, enc_im, rand_factor, present=present,
                    with_health=True, impl=decode_impl, rel_tol=rel_tol,
                    lam=wire_lam, wire=wire)
        health["bad_rows"] = bad_rows

        if numerics_mod.watch_enabled(cfg):
            # numerics observatory (obs/numerics.py, ISSUE 10): dynamic-
            # range columns + the shadow-quantized decode, stashed under
            # health["watch"] for decode_health_metrics to merge — the f32
            # decode above alone feeds the update
            watch = {}
            if cfg.numerics_watch == "on":
                watch.update(numerics_mod.numerics_columns(
                    cfg, [grads], [enc_re, enc_im], agg))
            if cfg.shadow_wire != "off":
                watch.update(numerics_mod.cyclic_shadow(
                    cfg, code, enc_re, enc_im, agg, health, rand_factor,
                    leaf_offsets, present, adv_mask, step))
            health["watch"] = watch
        return agg, health
    with jax.named_scope("draco_decode"):
        grads = attacks.inject_plain(grads, adv_mask, cfg.err_mode,
                                     cfg.adversarial,
                                     n_mal=cfg.num_adversaries,
                                     step=step, seed=cfg.seed)
        agg = aggregation.aggregate(
            grads, cfg.mode, s=cfg.worker_fail,
            geomedian_iters=cfg.geomedian_iters, present=present,
        )
    return agg, None


def masked_loss_metric(losses, present):
    """Mean loss over received rows only — a straggler's loss was never
    observed (mirrors the CNN path's _metrics, training/step.py)."""
    if present is None:
        return jnp.mean(losses)
    w = present.astype(losses.dtype)
    return jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1.0)


def apply_flat_update(state, agg: jnp.ndarray, opt, unravel):
    """Aggregated flat gradient → (new_params, new_opt_state) via the
    grads-as-argument optimizer convention (reference sgd_modified.py:53)."""
    with jax.named_scope("draco_update"):
        grads_tree = unravel(agg)
        updates, new_opt = opt.update(grads_tree, state.opt_state,
                                      state.params)
        new_params = jax.tree.map(lambda p, u: p + u, state.params, updates)
    return new_params, new_opt


def finish_flat_step(cfg, state, agg, health, opt, unravel, present=None,
                     constrain=None, constrain_opt=None):
    """The shared flat-gradient step tail: optimizer update → optional
    param/opt-state sharding constraints → advance the carry, with the
    in-graph step guard folded in when ``cfg.step_guard == "on"``
    (resilience/guards.guard_update: untrusted steps keep the previous
    params/opt_state via branch-free carry passthrough, the step counter
    still advances). One implementation for every LM route (sp / tp / ep /
    pp) so the guard semantics cannot diverge between them. Returns
    ``(new_state, guard_metric_columns)`` — the columns dict is empty when
    the guard is off, so the metric schema only grows for guarded configs
    (token_metric_names).

    ``constrain_opt``: routes whose carry must hold a GSPMD-stable layout
    (the real tp/ep meshes) pin the new opt state to the input layout here
    — otherwise the partitioner is free to reshard momentum buffers on the
    first execution and the SECOND dispatch of the K-fused program
    retraces against the drifted shardings (a silent steady-state
    recompile the PR 5 sentinel flags)."""
    new_params, new_opt = apply_flat_update(state, agg, opt, unravel)
    if constrain is not None:
        new_params = constrain(new_params)
    if constrain_opt is not None:
        new_opt = constrain_opt(new_opt)
    new_state = state._replace(params=new_params, opt_state=new_opt,
                               step=state.step + 1)
    if cfg.step_guard != "on":
        return new_state, {}
    from draco_tpu.resilience import guards

    return guards.guard_update(cfg, state, new_state, agg, health, present)


# column order of the (K, m) metric block train_token_many returns on the
# non-coded routes; cyclic routes append DECODE_HEALTH_NAMES and guarded
# configs (cfg.step_guard == "on") append GUARD_METRIC_NAMES — use
# token_metric_names(cfg), never these tuples directly, so the step bodies
# and the host flush can't disagree on the column order
TOKEN_METRIC_NAMES = ("loss",)

# per-step guard columns (resilience/guards.py): guard_trips = health
# signals fired, skipped_steps = 1 iff the update was passthrough-skipped
from draco_tpu.resilience.guards import GUARD_METRIC_NAMES  # noqa: E402

# per-step decode-health columns (in-graph scalars; coding/cyclic.py):
#   decode_residual  self-consistency residual, ≈ 0 iff decode exact
#   located_errors   present rows flagged as corrupt by the decode
#   det_tp           flagged ∧ adversarial ∧ present (true positives)
#   det_adv          adversarial ∧ present (the detectable ground truth)
# flush boundaries derive detection precision = Σdet_tp/Σlocated_errors and
# recall = Σdet_tp/Σdet_adv from these (obs/heartbeat.py) — the seeded
# schedules are step inputs, so the comparison runs in-graph with no host
# traffic.
DECODE_HEALTH_NAMES = ("decode_residual", "located_errors", "det_tp",
                       "det_adv")

# per-step health columns of the approx family (coding/approx.py; ISSUE 8):
#   decode_residual        measured relative decode error vs the TRUE batch-
#                          gradient sum (available in-graph — the fleet is
#                          simulated in one SPMD program), dimensionless
#   decode_residual_bound  the arrived support's analytic optimal-decoding
#                          bound ‖u − 1‖₂ (arXiv:2006.09638); residual ≤
#                          bound is algebra, so any violation is a fault
#   recovered_fraction     fraction of batches with ≥ 1 present worker —
#                          1.0 is full coverage, the redundancy payoff
APPROX_HEALTH_NAMES = ("decode_residual", "decode_residual_bound",
                       "recovered_fraction")


def metric_family_names(cfg) -> tuple:
    """The OPTIONAL column families a route's metric schema appends after
    its base columns, declared once for every consumer (ISSUE 10 satellite):
    the CNN path's ``metric_names`` (training/step.py) and every LM route's
    ``token_metric_names`` below both call this, so a new column family —
    decode health, packed forensics masks, the numerics observatory, guard
    columns, whatever comes next — is declared HERE once and both loops'
    step bodies and host flushes agree on the order by construction.

    Family order: per-approach health columns → packed forensics masks →
    numerics/shadow observatory columns (cfg.numerics_watch /
    cfg.shadow_wire, obs/numerics.py) → guard columns. The baseline
    approach contributes nothing before the guard block — no exactness
    certificate, no accusation set, no coded wire (the PR 4 invariant)."""
    from draco_tpu.obs import numerics as numerics_mod
    from draco_tpu.obs.forensics import mask_metric_names

    names = ()
    if cfg.approach == "cyclic":
        names += DECODE_HEALTH_NAMES + mask_metric_names(cfg.num_workers)
    elif cfg.approach == "approx":
        names += APPROX_HEALTH_NAMES + mask_metric_names(cfg.num_workers)
    elif cfg.approach == "maj_vote":
        names += ("vote_agree", "flagged_groups", "det_flagged", "det_tp",
                  "det_adv") + mask_metric_names(cfg.num_workers)
    names += numerics_mod.watch_metric_names(cfg)
    if cfg.step_guard == "on":
        names += GUARD_METRIC_NAMES
    return names


def token_metric_names(cfg) -> tuple:
    """Column order of the (K, m) metric block for an LM route at ``cfg``
    — every route builder stores this on its setup so the shared token
    loop flushes the right schema. The optional families (health masks /
    forensics / numerics / guard) come from the one shared assembly
    (:func:`metric_family_names`); baseline routes emit only the base
    columns."""
    return TOKEN_METRIC_NAMES + metric_family_names(cfg)


def accusation_mask(health, present=None):
    """The step's per-worker accusation set from a coded health dict: the
    code's own flag set ∪ the forensic-only signals — magnitude-outlier
    ``loud`` rows (cyclic LOUD_REL_TOL: the attribution that survives the
    beyond-budget regime) and non-finite ingest ``bad_rows``. The approx
    family carries no ``flagged`` set at all (no Byzantine certificate —
    its only signal is the non-finite ingest check), so the union starts
    empty there; a *scheduled* straggler is in particular never accused.
    Present-gated at pack time too (forensics.pack_mask_columns): an absent
    worker is never an accused worker."""
    import jax.numpy as jnp

    accused = None
    for key in ("flagged", "loud", "bad_rows"):
        if key in health:
            m = jnp.asarray(health[key], bool)
            accused = m if accused is None else accused | m
    if accused is None:
        raise ValueError("health dict carries no per-worker accusation "
                         "signal (flagged/loud/bad_rows)")
    if present is not None:
        accused = accused & present
    return accused


def decode_health_metrics(health, adv_mask, present) -> dict:
    """The DECODE_HEALTH_NAMES columns + the packed per-worker forensics
    masks from a decode-health dict + the step's seeded schedules ({} when
    the route has no exactness certificate, i.e. health is None). The
    present-gated counting is the one shared implementation
    (training/step._detection_metrics — a straggling adversary's row never
    arrives, so it is neither detectable nor ground truth); only the column
    name differs: the cyclic flag count ships as ``located_errors``. The
    scalar detection counts keep their historical meaning (the decode's own
    flag set, feeding the guard and the P/R fold); the packed ``accused``
    mask is the wider forensic union (accusation_mask)."""
    from draco_tpu.obs import forensics as forensics_mod
    from draco_tpu.training.step import _detection_metrics

    if health is None:
        return {}
    # numerics-observatory columns (obs/numerics.py, ISSUE 10) stashed by
    # the aggregation tails — already final column-name -> scalar pairs
    watch = health.pop("watch", {})
    if "bound" in health:
        # approx family (APPROX_HEALTH_NAMES docstring): the certificate is
        # residual ≤ bound, there is no located-error set — the packed
        # accused mask is the non-finite ingest rows only, and the present/
        # adv masks ride along so the AccusationLedger folds this family
        # with the same absent≠accused semantics as the exact codes
        out = {
            "decode_residual": health["residual"],
            "decode_residual_bound": health["bound"],
            "recovered_fraction": health["recovered_fraction"],
        }
        out.update(forensics_mod.pack_mask_columns(
            accusation_mask(health, present), present, adv_mask))
        out.update(watch)
        return out
    det = _detection_metrics(health["flagged"], adv_mask, present)
    out = {
        "decode_residual": health["residual"],
        "located_errors": det["det_flagged"],
        "det_tp": det["det_tp"],
        "det_adv": det["det_adv"],
    }
    out.update(forensics_mod.pack_mask_columns(
        accusation_mask(health, present), present, adv_mask))
    out.update(watch)
    return out


def make_token_train_many(step_body, token_fn=None,
                          metric_names=TOKEN_METRIC_NAMES):
    """K fused LM coded steps in ONE ``lax.scan`` — the token-route analogue
    of the CNN path's ``train_many`` (training/step.py).

    ``step_body(state, tokens, adv_mask, present) -> (state, metrics)`` is
    any route's single-step body (sp/tp/ep share the flat-gradient tail in
    this module; pp brings its pipeline schedule). The returned
    ``many_body(state, tokens, masks, presents)`` scans it over the leading
    K axis of every operand and stacks the per-step metrics into a (K, m)
    float32 block the host fetches once per flush window. ``presents=None``
    threads through as an empty pytree, exactly like ``train_many``.

    ``token_fn`` (optional): in-graph token generator ``step -> (n, B, T)``
    (cfg.token_gen == "device"). When set, the first scanned operand is the
    (K,) int32 step-index vector instead of the (K, n, B, T) token block —
    the host uploads K scalars per chunk and the device synthesizes the
    tokens itself, the same closed-over-constant-free discipline as
    rng.random_projection_factors_in_graph.

    Callers jit with ``donate_argnums=(0,)`` inside the route's mesh context
    so the K-step state carry reuses the input buffers.
    """

    def many_body(state, tokens, masks, presents):
        def body(st, operand):
            toks, adv_mask, present = operand
            if token_fn is not None:
                toks = token_fn(toks)
            st, metrics = step_body(st, toks, adv_mask, present)
            row = jnp.stack(
                [jnp.asarray(metrics[k], jnp.float32) for k in metric_names]
            )
            return st, row

        return jax.lax.scan(body, state, (tokens, masks, presents))

    return many_body
