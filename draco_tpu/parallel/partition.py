"""Declarative partition-rule tables: the single source of sharding truth.

Before this module, every route carried its own ad-hoc ``P(...)`` literals
(``repl`` / ``shard_w`` in training/step.py and sp_step.py, the Megatron
``param_partition_spec`` in tp_step.py, the stage-stack ``_leaf_spec`` in
pp_step.py, the tree ``row_spec`` in coding/topology.py) and its own copy
of the trailing-``None`` spec normalizer that PR 6's retrace-on-reshard
bug forced into tp_step. Both GSPMD defects the chaos harness has caught
(PR 6: an unnormalized ``P('tp', None)`` carry spec retraced every second
dispatch; PR 7: a sharded bitmask pack shifting every bit) were *runtime*
catches of *statically decidable* properties — so the sharding layer
becomes declared-and-audited here instead of scattered-and-hoped:

- :func:`norm_spec` — THE canonical normalizer (PR 6 fix, deduped out of
  tp/ep); every spec a table declares must be its own ``norm_spec``.
- :func:`match_partition_rules` — the fmengine/EasyLM regex-table pattern
  (SNIPPETS.md [3]): first matching rule wins, scalars map to ``P()``,
  unmatched array leaves raise.
- Per-route rule tables (``CNN_STEP_RULES`` … ``tree_combine_rules``):
  params, opt-state slots, token/batch operands, codeword/wire buffers and
  tree partials — written DISJOINT (each path matches exactly one rule) so
  the static auditor (analysis/sharding.py, lint rules 7–9) can hold every
  chip-bound program to them.

A table spec declares *axis membership* — which mesh axes a leaf is
distributed over. Multi-dim kernels under a scanned ``blocks/`` stack
shift the sharded dim right (tp_step.param_partition_spec stays the
placement authority for device_put); the auditor checks the declared axes
appear in the compiled sharding, not the exact dim index.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

from draco_tpu.parallel.mesh import EP_AXIS, PP_AXIS, SEQ_AXIS, TP_AXIS
from draco_tpu.runtime import WORKER_AXIS

# ---- canonical specs (the migrated ad-hoc literals) -----------------------

REPLICATED = P()
# per-worker row blocks: flat grads (n, d), codeword/wire buffers, masks
WORKER_ROWS = P(WORKER_AXIS)
# simulate-lane batches (n, B, ...) with trailing dims explicit
WORKER_ROWS3 = P(WORKER_AXIS, None, None)
# ring-sequence tokens (n, B, T): workers over w, sequence over sp
SEQ_TOKENS = P(WORKER_AXIS, None, SEQ_AXIS)


def sharding(mesh, spec: P):
    """NamedSharding helper so routes write ``sharding(mesh, WORKER_ROWS)``
    instead of re-spelling ``NamedSharding(mesh, P(...))`` literals."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, spec)


# ---- the canonical normalizer (PR 6's _norm_spec, deduped) ----------------

def norm_spec(spec: Optional[P]) -> P:
    """Strip trailing ``None`` entries from a PartitionSpec.

    XLA reports shardings in normalized form (``P('tp')``, never
    ``P('tp', None)``). Pinning a jit boundary or comparing carry
    shardings with an UNnormalized spec is the PR 6 bug: the specs
    compare unequal, the second dispatch silently retraces and reshards,
    and the route pays a full compile + all-to-all every step. Idempotent:
    ``norm_spec(norm_spec(s)) == norm_spec(s)``.
    """
    if spec is None:
        return P()
    entries = tuple(spec)
    while entries and entries[-1] is None:
        entries = entries[:-1]
    return P(*entries)


def spec_axes(spec: Optional[P]) -> frozenset:
    """The set of mesh axis names a spec distributes over (flattening
    tuple entries like ``P(('tl2', 'tl1'))``)."""
    axes = set()
    for entry in tuple(spec or ()):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.update(a for a in entry if a is not None)
        else:
            axes.add(entry)
    return frozenset(axes)


# ---- path utilities -------------------------------------------------------

def _key_str(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def leaf_paths(tree, prefix: str) -> "list[tuple[str, Any]]":
    """``[(path, leaf), ...]`` with '/'-joined path strings rooted at
    ``prefix`` — the naming vocabulary the rule tables match against
    (``state/params/block0/qkv/kernel``, ``state/opt_state/0/
    momentum_buf/...``, ``tokens``)."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        parts = [prefix] + [_key_str(k) for k in path]
        out.append(("/".join(p for p in parts if p), leaf))
    return out


def arg_leaf_paths(args: Sequence, arg_names: Optional[Sequence[str]]
                   ) -> "list[tuple[str, Any]]":
    """Leaf paths across a program's positional args tuple."""
    out = []
    for i, arg in enumerate(args):
        name = (arg_names[i] if arg_names is not None and i < len(arg_names)
                else f"arg{i}")
        out.extend(leaf_paths(arg, name))
    return out


def _is_scalar_like(leaf) -> bool:
    import numpy as np

    try:
        return int(np.size(leaf)) <= 1
    except Exception:
        return False


# ---- the matcher (SNIPPETS.md [3] pattern) --------------------------------

def match_partition_rules(rules: Sequence[Tuple[str, P]], tree,
                          prefix: str = "") -> Any:
    """Map a pytree to a pytree of PartitionSpecs via a regex rule table.

    Precedence is first-match-wins (``re.search``) in table order; scalar
    and size-1 leaves map to ``P()`` without consulting the table (they
    are replicated by construction); an unmatched array leaf raises
    ``ValueError`` naming the path — a partition table that does not cover
    its tree is a lint failure, not a silent default.
    """
    import jax

    def assign(path, leaf):
        if _is_scalar_like(leaf):
            return P()
        name = "/".join(p for p in ([prefix] if prefix else [])
                        + [_key_str(k) for k in path])
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        raise ValueError(
            f"no partition rule matches leaf {name!r} "
            f"(shape {getattr(leaf, 'shape', ())}) — extend the table")

    return jax.tree_util.tree_map_with_path(assign, tree)


def match_report(rules: Sequence[Tuple[str, P]],
                 paths_and_leaves: Sequence[Tuple[str, Any]]
                 ) -> "list[dict]":
    """The lint-facing coverage report: for every array leaf, how many
    table rules match it, the claimed spec, and whether that spec is
    normalized. Scalar/size-1 leaves are implicitly ``P()`` and excluded
    (same convention as :func:`match_partition_rules`)."""
    report = []
    for path, leaf in paths_and_leaves:
        if _is_scalar_like(leaf):
            continue
        matches = [(pat, spec) for pat, spec in rules
                   if re.search(pat, path)]
        spec = matches[0][1] if matches else None
        report.append({
            "path": path,
            "shape": tuple(getattr(leaf, "shape", ())),
            "n_matches": len(matches),
            "spec": str(spec) if matches else None,
            "normalized": (spec == norm_spec(spec)) if matches else None,
        })
    return report


# ---- per-route rule tables ------------------------------------------------
# Paths: state/params/..., state/opt_state/<i>/momentum_buf/..., and the
# operand names built_token_program / the CNN _build register. Tables are
# DISJOINT by construction (negative lookaheads complement the sharded
# leaf patterns) so rule 7's exactly-one-match check holds.

# CNN coded-DP route (cyclic/approx, seg-wire and tree-combine variants):
# LeNet state fully replicated; the CI-shape compiler replicates the image
# batch too (every device redundantly computes all workers' grads — the
# honest n=8-on-8-devices fold); only the adversary mask rides the w axis.
CNN_STEP_RULES: Tuple[Tuple[str, P], ...] = (
    (r"^state/batch_stats/", WORKER_ROWS),  # per-worker BN stats (has_bn)
    (r"^state/(?!batch_stats/)", REPLICATED),
    (r"^(?:x|y)$", REPLICATED),
    (r"^adv_mask$", WORKER_ROWS),
)

# Sequence-ring route: replicated state, tokens sharded (w, _, sp).
SP_STEP_RULES: Tuple[Tuple[str, P], ...] = (
    (r"^state/", REPLICATED),
    (r"^tokens$", SEQ_TOKENS),
    (r"^adv_mask$", WORKER_ROWS),
)

# Megatron TP route (and the folded w×1 fold_* family): the five sharded
# leaf kinds of param_partition_spec; momentum slots inherit the layout
# (opt.init zeros_like), so the patterns are prefix-insensitive.
_TP_SHARDED = (r"(?:(?:qkv|mlp_in)/kernel|(?:proj|mlp_out)/kernel"
               r"|mlp_in/bias)$")
TP_STEP_RULES: Tuple[Tuple[str, P], ...] = (
    (r"^state/.*(?:qkv|mlp_in)/kernel$", P(None, TP_AXIS)),
    (r"^state/.*(?:proj|mlp_out)/kernel$", P(TP_AXIS)),
    (r"^state/.*mlp_in/bias$", P(TP_AXIS)),
    (rf"^state/(?!.*{_TP_SHARDED})", REPLICATED),
    (r"^tokens$", WORKER_ROWS),
    (r"^adv_mask$", WORKER_ROWS),
)

# Expert-parallel route: expert stacks over ep, router/backbone replicated.
_EP_SHARDED = r"moe/(?:w1|w2|b1|b2)$"
EP_STEP_RULES: Tuple[Tuple[str, P], ...] = (
    (rf"^state/.*{_EP_SHARDED}", P(EP_AXIS)),
    (rf"^state/(?!.*{_EP_SHARDED})", REPLICATED),
    (r"^tokens$", WORKER_ROWS),
    (r"^adv_mask$", WORKER_ROWS),
)

# GPipe route: every blocks/ stage stack (params AND momentum) over pp.
PP_STEP_RULES: Tuple[Tuple[str, P], ...] = (
    (r"^state/.*/blocks/", P(PP_AXIS)),
    (r"^state/(?!.*/blocks/)", REPLICATED),
    (r"^tokens$", WORKER_ROWS),
    (r"^adv_mask$", WORKER_ROWS),
)


def override(rules: Sequence[Tuple[str, P]],
             *overrides: Tuple[str, P]) -> Tuple[Tuple[str, P], ...]:
    """A table with specific patterns re-declared (keeps disjointness:
    the overridden pattern's original row is dropped, not shadowed). The
    devgen rows use it — their ``tokens`` operand is the (K,) step-index
    vector, which rides replicated instead of the host token batch."""
    pats = {p for p, _ in overrides}
    return tuple(overrides) + tuple(r for r in rules if r[0] not in pats)


def tree_rows(level_axes: Sequence[str]) -> P:
    """Worker-row spec on a tree-combine mesh: dim 0 folded over the
    REVERSED level axes, so C-order places leaf group j at grid
    multi-index unravel(j) (coding/topology.tree_mesh docstring)."""
    return P(tuple(reversed(tuple(level_axes))))


def tree_combine_rules(level_axes: Sequence[str]
                       ) -> Tuple[Tuple[str, P], ...]:
    """Partition table for a CodedReduce tree-combine program
    (coding/topology.make_tree_decode_shmap): codeword partials and the
    presence mask ride the worker rows while the projection factors stay
    replicated."""
    rows = tree_rows(level_axes)
    return (
        (r"^r_(?:re|im)$", rows),
        (r"^present$", rows),
        (r"^rand_factor$", REPLICATED),
    )
