"""Ring attention — sequence-parallel exact attention over a device ring.

Each ``sp``-shard holds a contiguous block of the sequence. Q stays put; K/V
blocks travel the ring via ``lax.ppermute`` (one ICI hop per step), and every
shard folds each visiting block into a numerically-stable streaming softmax
(flash-attention accumulators m/l/o). After ``sp`` steps every query has seen
every key exactly once — exact attention, O(T/sp) memory per chip, comm
overlapped by XLA with the block einsums.

Written with ``lax.scan`` so the whole ring is reverse-differentiable
(``ppermute`` is linear; its transpose is the inverted ring), which is what
lets per-shard gradients psum over ``sp`` into exact per-worker gradients for
the coded-DP layer above (draco_tpu/parallel/sp_step.py).

No reference counterpart: the reference is CNN-only (SURVEY.md §5.7); this
axis is the TPU build's long-context capability.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from draco_tpu.runtime import axis_size

NEG_INF = -1e30


def _block_attn(q, k, v, q_pos, k_pos, scale, causal, o, m, l):
    """Fold one K/V block into the streaming-softmax accumulators.

    q: (B, Tq, H, Dh); k, v: (B, Tk, H, Dh); q_pos: (Tq,), k_pos: (Tk,)
    o: (B, Tq, H, Dh) accumulator, m, l: (B, Tq, H) running max / normaliser.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # (Tq, Tk)
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_blk = jnp.max(s, axis=-1)  # (B, H, Tq)
    m_blk = jnp.moveaxis(m_blk, 1, 2)  # (B, Tq, H)
    m_new = jnp.maximum(m, m_blk)
    # exp of masked-everything rows stays 0 through the NEG_INF offset
    p = jnp.exp(s - jnp.moveaxis(m_new, 1, 2)[:, :, :, None])  # (B, H, Tq, Tk)
    corr = jnp.exp(m - m_new)  # (B, Tq, H)
    l_new = l * corr + jnp.moveaxis(jnp.sum(p, axis=-1), 1, 2)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32)
    o_new = o * corr[..., None] + pv
    return o_new, m_new, l_new


def dense_attention(q, k, v, q_offset=0, k_offset=0, causal: bool = True):
    """Single-shard exact attention with the same streaming accumulators.

    Used as the sp=1 fallback and as the oracle in tests.
    """
    return dense_attention_lse(q, k, v, q_offset, k_offset, causal)[0]


def dense_attention_lse(q, k, v, q_offset=0, k_offset=0, causal: bool = True):
    """dense_attention that also returns the per-row log-sum-exp (B, T, H)
    f32 — the dense counterpart of ops/flash_attention.flash_attention_with_lse
    (its off-TPU / non-tiling fallback, and the small-shape oracle)."""
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    scale = 1.0 / (dh**0.5)
    q_pos = q_offset + jnp.arange(tq)
    k_pos = k_offset + jnp.arange(tk)
    o = jnp.zeros((b, tq, h, dh), jnp.float32)
    m = jnp.full((b, tq, h), NEG_INF, jnp.float32)
    l = jnp.zeros((b, tq, h), jnp.float32)
    o, m, l = _block_attn(q, k, v, q_pos, k_pos, scale, causal, o, m, l)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype), lse


def ring_flash_attention(
    q,
    k,
    v,
    axis_name: Optional[str],
    causal: bool = True,
    attn_with_lse=None,
):
    """Ring attention with a blockwise-kernel inner: O(T_local·Dh) memory at
    BOTH levels. The plain ring (ring_attention) streams K/V blocks across
    chips but each hop still materialises the (T_local, T_local) score block
    on-chip; here every hop runs the flash kernel (ops/flash_attention) —
    causal for the self hop, non-causal for fully-visible past-owner hops,
    skipped entirely (lax.cond) for future owners — and the normalized
    per-hop (o, lse) pairs merge by log-sum-exp weights. The kernel's lse
    output is differentiable, so the merge backpropagates exactly.

    Same contract as ring_attention: (B, T_local, H, Dh) per shard, called
    inside shard_map; axis_name=None degrades to the single-shard kernel.
    """
    if attn_with_lse is None:
        from draco_tpu.ops.flash_attention import flash_attention_with_lse

        attn_with_lse = flash_attention_with_lse
    if axis_name is None:
        o, _ = attn_with_lse(q, k, v, causal=causal)
        return o

    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # hop 0: this shard's own block (the only hop needing the causal mask)
    o0, lse0 = attn_with_lse(q, k, v, causal=causal)

    def hop(carry, r):
        o_acc, lse_acc, k_prev, v_prev = carry
        # permute at hop START: after r hops this shard holds the block
        # owned by (idx - r) mod sp, and the final hop's blocks are used
        # (a trailing permute would be sp-th = wasted ICI traffic)
        k_blk = lax.ppermute(k_prev, axis_name, perm)
        v_blk = lax.ppermute(v_prev, axis_name, perm)
        owner = (idx - r) % sp
        # causal ring: a visiting block is visible iff its owner precedes
        # this shard (then it is FULLY visible — no mask needed); the
        # non-causal ring sees every block
        visible = (owner < idx) | jnp.asarray(not causal)

        def seen(_):
            o_h, lse_h = attn_with_lse(q, k_blk, v_blk, causal=False)
            return o_h.astype(jnp.float32), lse_h

        def skipped(_):
            return (jnp.zeros(q.shape, jnp.float32),
                    jnp.full(q.shape[:2] + (q.shape[2],), NEG_INF,
                             jnp.float32))

        o_h, lse_h = lax.cond(visible, seen, skipped, None)
        lse_new = jnp.logaddexp(lse_acc, lse_h)
        w1 = jnp.exp(lse_acc - lse_new)
        w2 = jnp.exp(lse_h - lse_new)
        o_new = o_acc * w1[..., None] + o_h * w2[..., None]
        return (o_new, lse_new, k_blk, v_blk), None

    carry = (o0.astype(jnp.float32), lse0, k, v)
    (o, _, _, _), _ = lax.scan(hop, carry, jnp.arange(1, sp))
    return o.astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    axis_name: Optional[str],
    causal: bool = True,
):
    """Exact attention over sequence shards laid out on mesh axis ``axis_name``.

    q, k, v: (B, T_local, H, Dh) — this shard's block of the sequence. Must be
    called inside ``shard_map`` (or any context where ``axis_name`` is bound).
    With ``axis_name=None`` it degrades to single-shard dense attention.
    """
    if axis_name is None:
        return dense_attention(q, k, v, causal=causal)

    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, t, h, dh = q.shape
    scale = 1.0 / (dh**0.5)
    q_pos = idx * t + jnp.arange(t)

    o0 = jnp.zeros((b, t, h, dh), jnp.float32)
    m0 = jnp.full((b, t, h), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, t, h), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # hop 0 (own block) outside the scan so every scan iteration permutes
    # FIRST and the final hop's blocks are used — no trailing wasted permute
    o0, m0, l0 = _block_attn(q, k, v, q_pos, q_pos, scale, causal, o0, m0, l0)

    def ring_step(carry, r):
        o, m, l, k_prev, v_prev = carry
        k_blk = lax.ppermute(k_prev, axis_name, perm)
        v_blk = lax.ppermute(v_prev, axis_name, perm)
        # after r hops this shard holds the block owned by (idx - r) mod sp
        owner = (idx - r) % sp
        k_pos = owner * t + jnp.arange(t)
        o, m, l = _block_attn(q, k_blk, v_blk, q_pos, k_pos, scale, causal, o, m, l)
        return (o, m, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = lax.scan(ring_step, (o0, m0, l0, k, v),
                                  jnp.arange(1, sp))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
