"""Parallelism strategies beyond the coded worker axis.

The reference implements exactly one strategy — synchronous coded data
parallelism over MPI ranks (SURVEY.md §2.3); its workloads are fixed-size
CNNs, so it owes nothing for long sequences. This package makes the axes the
reference lacks first-class in the TPU build:

  * ``sp`` — sequence/context parallelism, both standard strategies: ring
    attention (blockwise flash attention with K/V blocks rotating over ICI
    via ``ppermute``) and Ulysses-style all-to-all head-scatter attention —
    so one logical worker's sequence can span many chips
    (``config.sp_attn`` selects).
  * 2-D meshes ``(w, sp)`` where the coded worker axis composes with
    sequence parallelism: per-worker gradients are psum-reduced over ``sp``
    first, then Draco's coding/aggregation acts on whole per-worker
    gradients over ``w`` — exactly the composition note in SURVEY.md §5.7.
  * ``tp`` — Megatron-style tensor parallelism on ``(w, tp)`` meshes,
    written the GSPMD way (parameter sharding annotations, one plain jit,
    XLA inserts the collectives) as the counterpart to the SP path's
    explicit shard_map style (tp_step.py).
  * ``ep`` — expert parallelism for the Switch-MoE TransformerLM on
    ``(w, ep)`` meshes: expert weight stacks shard their leading E axis,
    router and shared weights stay replicated (ep_step.py, models/moe.py).
  * ``pp`` — GPipe-style pipeline parallelism on ``(w, pp)`` meshes: the
    block stack splits into pp stages, microbatch activations flow
    stage-to-stage over ``ppermute`` inside a ``lax.scan`` schedule, and
    ``jax.grad`` transposes the loop into the backward pipeline
    (pp_step.py).
"""

from draco_tpu.parallel.a2a_attention import a2a_attention
from draco_tpu.parallel.ep_step import build_ep_train_setup
from draco_tpu.parallel.mesh import (
    EP_AXIS,
    PP_AXIS,
    SEQ_AXIS,
    TP_AXIS,
    make_mesh_2d,
    make_mesh_wep,
    make_mesh_wpp,
    make_mesh_wtp,
)
from draco_tpu.parallel.pp_step import build_pp_train_setup
from draco_tpu.parallel.ring_attention import dense_attention, ring_attention
from draco_tpu.parallel.sp_step import build_sp_train_setup
from draco_tpu.parallel.tp_step import build_tp_train_setup

__all__ = [
    "EP_AXIS",
    "PP_AXIS",
    "SEQ_AXIS",
    "TP_AXIS",
    "make_mesh_2d",
    "make_mesh_wep",
    "make_mesh_wpp",
    "make_mesh_wtp",
    "a2a_attention",
    "ring_attention",
    "dense_attention",
    "build_sp_train_setup",
    "build_tp_train_setup",
    "build_ep_train_setup",
    "build_pp_train_setup",
]
