"""Parallelism strategies beyond the coded worker axis.

The reference implements exactly one strategy — synchronous coded data
parallelism over MPI ranks (SURVEY.md §2.3); its workloads are fixed-size
CNNs, so it owes nothing for long sequences. This package makes the axes the
reference lacks first-class in the TPU build:

  * ``sp`` — sequence/context parallelism, both standard strategies: ring
    attention (blockwise flash attention with K/V blocks rotating over ICI
    via ``ppermute``) and Ulysses-style all-to-all head-scatter attention —
    so one logical worker's sequence can span many chips
    (``config.sp_attn`` selects).
  * 2-D meshes ``(w, sp)`` where the coded worker axis composes with
    sequence parallelism: per-worker gradients are psum-reduced over ``sp``
    first, then Draco's coding/aggregation acts on whole per-worker
    gradients over ``w`` — exactly the composition note in SURVEY.md §5.7.
"""

from draco_tpu.parallel.a2a_attention import a2a_attention
from draco_tpu.parallel.mesh import SEQ_AXIS, make_mesh_2d
from draco_tpu.parallel.ring_attention import dense_attention, ring_attention
from draco_tpu.parallel.sp_step import build_sp_train_setup

__all__ = [
    "SEQ_AXIS",
    "make_mesh_2d",
    "a2a_attention",
    "ring_attention",
    "dense_attention",
    "build_sp_train_setup",
]
