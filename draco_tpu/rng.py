"""Deterministic randomness discipline.

The reference makes every process agree on "who is adversarial at step t" and
"which group shuffles with which seed" by seeding numpy's global RNG with
SEED_=428 on every rank (reference: src/util.py:17,79-103). We keep the
*property* (every participant derives the identical schedule) with
``jax.random`` keys folded from the experiment seed — no global RNG state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def adversary_schedule(seed: int, max_steps: int, num_workers: int, num_fail: int) -> np.ndarray:
    """Boolean mask of shape (max_steps + 1, num_workers).

    ``mask[t, i]`` is True iff logical worker i behaves Byzantine at step t.
    Exactly ``num_fail`` workers per step, sampled without replacement, from a
    schedule every participant can recompute (reference semantics:
    src/util.py:100-103 pre-generates per-step adversary index lists from a
    fixed seed so all ranks agree).
    """
    mask = np.zeros((max_steps + 1, num_workers), dtype=bool)
    if num_fail == 0:
        return mask
    rng = np.random.RandomState(seed)
    for t in range(max_steps + 1):
        idx = rng.choice(num_workers, size=num_fail, replace=False)
        mask[t, idx] = True
    return mask


def straggler_schedule(seed: int, max_steps: int, num_workers: int,
                       num_straggle: int) -> np.ndarray:
    """Boolean mask (max_steps + 1, num_workers): True = worker misses the
    step's deadline (its gradient never arrives).

    The reference only sketched straggler handling (the unreferenced tag-77
    kill switch, resnet_split.py:625-737); here missing workers are
    first-class *erasures* — known positions, unlike Byzantine rows — and the
    schedule is deterministic for the same every-participant-agrees reason as
    :func:`adversary_schedule`. Salted so adversary and straggler draws are
    independent streams.
    """
    mask = np.zeros((max_steps + 1, num_workers), dtype=bool)
    if num_straggle == 0:
        return mask
    rng = np.random.RandomState(seed ^ 0x5A5A5A)
    for t in range(max_steps + 1):
        idx = rng.choice(num_workers, size=num_straggle, replace=False)
        mask[t, idx] = True
    return mask


def group_seeds(seed: int, num_groups: int) -> np.ndarray:
    """Per-group shuffle seeds, identical on every participant.

    Mirrors util.py:79-87: members of a repetition group share a shuffle seed
    so they draw identical batches (that is what makes the bitwise majority
    vote sound, reference: rep_worker.py:89, rep_master.py:162).
    """
    rng = np.random.RandomState(seed)
    return rng.randint(0, 20000, size=num_groups)


def epoch_permutation(seed: int, epoch: int, n: int) -> np.ndarray:
    """Shuffle of ``n`` sample indices for a given epoch from a shared seed.

    Reference re-seeds torch at every epoch with seed+factor*epoch
    (rep_worker.py:89, cyclic_worker.py:88); we fold (seed, epoch) into one
    stream the same agreed-upon way.
    """
    rng = np.random.RandomState((seed * 100003 + epoch * 23) % (2**31 - 1))
    return rng.permutation(n)


def fold(key: jax.Array, *data: int) -> jax.Array:
    """Fold a sequence of ints into a key (step ids, batch ids, worker ids)."""
    for d in data:
        key = jax.random.fold_in(key, d)
    return key


def random_projection_factors_in_graph(seed: int, dim: int) -> jnp.ndarray:
    """The decode-side random projection vector (reference:
    cyclic_master.py:58-61, np.random.normal(loc=1.0) per layer) — same
    distribution (normal, loc=1), deterministic in ``seed``, generated
    from a scalar key INSIDE the jitted step instead of being closed over
    as a d-length host constant.

    Why it exists: a closed-over (d,) float32 array is serialized into the
    XLA program — at the d≈159M LM flagship that is a 638 MB module
    (baselines_out/tpu_lm_scan_lowering.json), which is what the tunnel's
    remote-compile service choked on for four straight attempts (PERF.md
    §4). Generated in-graph, the program carries only the scalar seed and
    regenerates the identical vector each step (~one HBM pass over d —
    noise vs the step cost). Values differ from the numpy stream (jax
    PRNG, not MT19937); decode is projection-value-agnostic (exact
    recovery for ≤s corruptions regardless of the projection draw), and
    every participant still derives the identical vector, which is the
    property the reference pins (cyclic_master.py:58-61).
    """
    key = jax.random.fold_in(jax.random.key(seed), 7919)
    return 1.0 + jax.random.normal(key, (dim,), jnp.float32)
