"""Resilience layer (ISSUE 6): deterministic fault injection, in-graph step
guards, and preemption-safe graceful degradation.

DRACO's contract is exact recovery from ≤ s Byzantine workers; production
runs die to faults the contract does not model. This package is the
detect → degrade-boundedly → keep-training posture:

  faults.py      seeded fault-injection plan (``cfg.fault_spec``) — the
                 chaos counterpart of attacks.py's adversary schedules
  guards.py      branchless in-graph step guard: fold decode-health +
                 global-finite signals, skip untrusted updates via carry
                 passthrough, emit guard_trips/skipped_steps columns
  supervisor.py  host-side half: prefetcher restart supervision with
                 backoff, checkpoint walk-back past corruption, and the
                 SIGTERM → boundary-checkpoint → "preempted" status path

``tools/chaos_run.py`` drives the fault × loop matrix and commits
``baselines_out/chaos_matrix.json``; ``tools/perf_watch.py`` gates on a
fault class flipping from masked to crashed.
"""

# guards.py is deliberately NOT imported here: it needs jax, while this
# package surface (faults/supervisor) stays importable from jax-free
# contexts (config.validate parses fault specs; tools fold artifacts).
# Step bodies import draco_tpu.resilience.guards directly.
from draco_tpu.resilience.faults import (
    FaultEvent,
    FaultPlan,
    HostFaultInjector,
    InjectedFaultError,
    NULL_INJECTOR,
    plan_from_cfg,
)
from draco_tpu.resilience.supervisor import (
    GracefulStop,
    SupervisedPrefetcher,
    restore_with_walkback,
)

__all__ = [
    "FaultEvent", "FaultPlan", "GracefulStop", "HostFaultInjector",
    "InjectedFaultError", "NULL_INJECTOR", "SupervisedPrefetcher",
    "plan_from_cfg", "restore_with_walkback",
]
