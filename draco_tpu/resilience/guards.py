"""Branchless in-graph step guard: skip the optimizer update when a step is
untrusted, keep training.

DRACO's decode is *exact* only inside its contract (≤ s Byzantine rows,
erasures within budget, finite arithmetic). Outside it — a
faulty-but-honest worker emitting NaN/Inf, corruption past the locator
budget, a vote with no honest majority — the decoded "gradient" is silently
poisoned. The PR 4 decode-health columns already *detect* these states
in-graph; this module *acts* on them (the detect → degrade-boundedly →
keep-training posture of the Stochastic Gradient Coding line, PAPERS.md
arXiv:1905.05383):

  signal                         trips when
  ------                         ----------
  nonfinite                      any non-finite value in the aggregated /
                                 decoded flat gradient (all approaches)
  residual_loud                  cyclic decode_residual > cfg.guard_residual_tol
                                 (clean decodes sit at f32 solve noise ~1e-6;
                                 a mislocated beyond-budget decode is O(1));
                                 NaN residual counts as loud. Under the
                                 approx family the certificate is partial
                                 recovery, not exactness: the trip condition
                                 becomes residual > bound + tol — a step
                                 whose measured decode error exceeds its own
                                 analytic optimal-decoding bound
                                 (coding/approx.py) is the fault, while any
                                 within-bound residual is the family's
                                 normal operating state
  over_budget                    located/flagged present rows > s — more
                                 corruption than the code can certify
                                 (cyclic locator roots; maj_vote out-voted
                                 rows, i.e. vote disagreement past budget)

When any signal trips the step's update is SKIPPED via carry passthrough:
``jnp.where`` selects the previous params/opt_state/batch_stats while the
step counter still advances — branch-free, so the compiled program is the
same every step (zero retraces under the PR 5 compile guard) and bitwise
identical to the unguarded program on trusted steps (``where(True, new,
old)`` is a select). The per-step verdict ships as two new metric columns
(``guard_trips``/``skipped_steps``) riding the existing (K, m) block — zero
extra device fetches.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

# column order of the guard's metric-block contribution; appended to a
# route's metric_names iff cfg.step_guard == "on" (parallel/common.
# token_metric_names and the CNN path's metric_names both consume this)
GUARD_METRIC_NAMES = ("guard_trips", "skipped_steps")


class GuardVerdict(NamedTuple):
    ok: jnp.ndarray  # scalar bool — the step's update is trusted
    trips: jnp.ndarray  # scalar int32 — how many signals fired


def assess(cfg, agg: jnp.ndarray, health: Optional[dict] = None,
           present=None) -> GuardVerdict:
    """Fold the step's health signals into one trust verdict (docstring
    table). ``health`` is the in-graph decode-health dict the coded paths
    already produce (coding/cyclic.decode with_health; the maj_vote path
    passes its ``flagged`` row set) — None for routes with no exactness
    certificate (baseline robust aggregation), where only the finite check
    applies. All comparisons are NaN-safe in the conservative direction:
    a NaN residual or a NaN gradient is never trusted."""
    from draco_tpu.obs.numerics import wire_residual_slack

    # narrow-wire residual slack (ISSUE 15): on a bf16/int8 wire the
    # unflagged honest rows deviate by rounding noise and the approx
    # residual carries the end-to-end quantization error — both are the
    # dtype's normal operating state, not a fault; the tolerance widens
    # by the committed per-dtype slack (0 on the f32 wire: bitwise)
    tol = cfg.guard_residual_tol + wire_residual_slack(
        getattr(cfg, "wire_dtype", "f32"))
    trips = []
    # <= so a NaN (any comparison False) lands on the untrusted side
    finite = jnp.all(jnp.isfinite(agg))
    trips.append(~finite)
    if health is not None:
        if "bound" in health:
            # approx partial-recovery certificate (docstring table): the
            # residual is allowed up to its analytic bound; exceeding it
            # (or a NaN on either side) is the trip
            loud = ~(health["residual"] <= health["bound"] + tol)
            trips.append(loud)
        elif "residual" in health:
            loud = ~(health["residual"] <= tol)
            trips.append(loud)
        if "flagged" in health:
            flagged = health["flagged"]
            if present is not None:
                flagged = flagged & present
            located = jnp.sum(flagged.astype(jnp.int32))
            trips.append(located > cfg.worker_fail)
    trip_vec = jnp.stack(trips)
    n_trips = jnp.sum(trip_vec.astype(jnp.int32))
    return GuardVerdict(ok=~jnp.any(trip_vec), trips=n_trips)


def select_state(ok, new_state, prev_state) -> Any:
    """Carry passthrough: the new state when trusted, the previous state
    (step counter still advanced) when not — a branch-free per-leaf select,
    bitwise-transparent on trusted steps."""
    passthrough = prev_state._replace(step=new_state.step)
    return jax.tree.map(lambda a, b: jnp.where(ok, a, b), new_state,
                        passthrough)


def metric_columns(verdict: GuardVerdict) -> dict:
    """The GUARD_METRIC_NAMES columns for the step's metrics dict."""
    return {
        "guard_trips": verdict.trips,
        "skipped_steps": (~verdict.ok).astype(jnp.int32),
    }


def guard_update(cfg, prev_state, new_state, agg, health=None,
                 present=None):
    """One-call wrapper for step bodies: assess + select + columns.
    Returns ``(state, metric_columns_dict)`` — the unguarded
    ``(new_state, {})`` when cfg.step_guard is off, so call sites stay
    branch-free too."""
    if cfg.step_guard != "on":
        return new_state, {}
    verdict = assess(cfg, agg, health, present)
    return select_state(verdict.ok, new_state, prev_state), \
        metric_columns(verdict)
